"""Optional ``jax.profiler`` hook: trace a configurable window of steps.

The bench already knows ``--profile-dir``; this gives the TRAINING loop
(and any other stepped workload) the same capability without hand-editing
the loop: construct a :class:`ProfilerHook` (or let
:func:`profiler_from_env` build one from ``KATATPU_OBS_PROFILE_DIR`` /
``KATATPU_OBS_PROFILE_START`` / ``KATATPU_OBS_PROFILE_STEPS``) and call
``on_step(step)`` once per step — the hook starts ``jax.profiler`` at
``start_step``, stops it ``num_steps`` later, and dumps the xplane trace
into the directory. ``stop()`` is idempotent and also runs on ``close``,
so an exception mid-window cannot leave the profiler running.

jax is imported lazily at start time; a host-side process that never
crosses the start step never loads it.

``jax.profiler`` allows ONE trace per process — two hooks can
legitimately race for it (an env-armed ``profiler_from_env`` window and
the watchdog's auto-opened alert window, ISSUE 17's bug-risk fix). A
hook that loses the race — the process-wide owner guard below, or
``start_trace`` itself raising over a trace some other caller started
raw — marks itself done and emits one ``profiler_busy`` event instead
of raising out of the serving loop.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

from ..utils import log
from . import events

LOG = log.get("obs.profiler")

# Process-wide trace ownership: jax.profiler.start_trace raises on a
# second concurrent start, so hooks claim the slot under this lock
# before touching jax at all.
_trace_lock = threading.Lock()
_trace_owner: Optional["ProfilerHook"] = None

_ENV_DIR = ("KATATPU_OBS_PROFILE_DIR", "KATA_TPU_OBS_PROFILE_DIR")
_ENV_START = ("KATATPU_OBS_PROFILE_START", "KATA_TPU_OBS_PROFILE_START")
_ENV_STEPS = ("KATATPU_OBS_PROFILE_STEPS", "KATA_TPU_OBS_PROFILE_STEPS")


def _env(names: tuple, default: str = "") -> str:
    for n in names:
        v = os.environ.get(n, "")
        if v:
            return v
    return default


class ProfilerHook:
    """Start/stop ``jax.profiler`` around steps
    ``[start_step, start_step + num_steps)`` (1-indexed, matching the
    trainer's step numbering)."""

    def __init__(self, profile_dir: str, start_step: int = 2,
                 num_steps: int = 3):
        if start_step < 1:
            raise ValueError(f"start_step must be >= 1, got {start_step}")
        if num_steps < 1:
            raise ValueError(f"num_steps must be >= 1, got {num_steps}")
        self.profile_dir = profile_dir
        self.start_step = start_step
        self.stop_after = start_step + num_steps - 1
        self._active = False
        self._done = False

    def on_step(self, step: int) -> None:
        """Call AFTER step ``step`` completes (the trainer's on_step
        convention; the trainer also primes the hook with the step it
        RESUMES from, so ``start_step=1`` — and a resume landing inside
        the window — both work): the window opens once ``start_step - 1``
        has completed and covers through ``stop_after``, i.e. by default
        starting at step 2, past the compile+execute first step that
        would drown the steady state. A resume already past the window
        never starts it (a partial trace would masquerade as the
        configured window)."""
        if (
            not self._done
            and not self._active
            and self.start_step - 1 <= step < self.stop_after
        ):
            self._start()
        elif self._active and step >= self.stop_after:
            self.stop()

    def _busy(self, reason: str) -> None:
        """Lost the process-wide trace slot: give up this hook's window
        for good (``_done`` — a later step must not retry into the same
        running trace) and record why, instead of raising out of the
        caller's loop."""
        self._done = True
        events.emit(
            "profile", "profiler_busy",
            dir=self.profile_dir, start_step=self.start_step,
            stop_step=self.stop_after, reason=reason,
        )
        LOG.warning(
            "profiler window skipped: trace already running",
            extra=log.kv(dir=self.profile_dir, reason=reason),
        )

    def _start(self) -> None:
        global _trace_owner
        with _trace_lock:
            if _trace_owner is not None:
                owner = _trace_owner
            else:
                owner, _trace_owner = None, self
        if owner is not None:
            self._busy(f"owned:{owner.profile_dir}")
            return
        import jax

        os.makedirs(self.profile_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(self.profile_dir)
        except Exception as exc:
            # Someone started jax.profiler without a hook (bench
            # --profile-dir, user code): same degrade, and the slot is
            # released — this hook never owned a running trace.
            with _trace_lock:
                if _trace_owner is self:
                    _trace_owner = None
            self._busy(f"start_trace:{type(exc).__name__}")
            return
        self._active = True
        LOG.info(
            "profiler trace started",
            extra=log.kv(dir=self.profile_dir, start=self.start_step,
                         stop=self.stop_after),
        )

    def stop(self) -> None:
        global _trace_owner
        if not self._active:
            return
        import jax

        try:
            jax.profiler.stop_trace()
        finally:
            self._active = False
            self._done = True
            with _trace_lock:
                if _trace_owner is self:
                    _trace_owner = None
        events.emit(
            "profile", "jax_trace",
            dir=self.profile_dir,
            start_step=self.start_step,
            stop_step=self.stop_after,
        )
        LOG.info("profiler trace stopped", extra=log.kv(dir=self.profile_dir))

    def close(self) -> None:
        self.stop()

    def __enter__(self) -> "ProfilerHook":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def profiler_from_env() -> Optional[ProfilerHook]:
    """Build a hook from ``KATATPU_OBS_PROFILE_DIR`` (+ optional
    ``_START``/``_STEPS``); None when unset."""
    profile_dir = _env(_ENV_DIR)
    if not profile_dir:
        return None
    return ProfilerHook(
        profile_dir,
        start_step=int(_env(_ENV_START, "2")),
        num_steps=int(_env(_ENV_STEPS, "3")),
    )
