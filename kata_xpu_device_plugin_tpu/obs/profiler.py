"""Optional ``jax.profiler`` hook: trace a configurable window of steps.

The bench already knows ``--profile-dir``; this gives the TRAINING loop
(and any other stepped workload) the same capability without hand-editing
the loop: construct a :class:`ProfilerHook` (or let
:func:`profiler_from_env` build one from ``KATATPU_OBS_PROFILE_DIR`` /
``KATATPU_OBS_PROFILE_START`` / ``KATATPU_OBS_PROFILE_STEPS``) and call
``on_step(step)`` once per step — the hook starts ``jax.profiler`` at
``start_step``, stops it ``num_steps`` later, and dumps the xplane trace
into the directory. ``stop()`` is idempotent and also runs on ``close``,
so an exception mid-window cannot leave the profiler running.

jax is imported lazily at start time; a host-side process that never
crosses the start step never loads it.
"""
from __future__ import annotations

import os
from typing import Optional

from ..utils import log
from . import events

LOG = log.get("obs.profiler")

_ENV_DIR = ("KATATPU_OBS_PROFILE_DIR", "KATA_TPU_OBS_PROFILE_DIR")
_ENV_START = ("KATATPU_OBS_PROFILE_START", "KATA_TPU_OBS_PROFILE_START")
_ENV_STEPS = ("KATATPU_OBS_PROFILE_STEPS", "KATA_TPU_OBS_PROFILE_STEPS")


def _env(names: tuple, default: str = "") -> str:
    for n in names:
        v = os.environ.get(n, "")
        if v:
            return v
    return default


class ProfilerHook:
    """Start/stop ``jax.profiler`` around steps
    ``[start_step, start_step + num_steps)`` (1-indexed, matching the
    trainer's step numbering)."""

    def __init__(self, profile_dir: str, start_step: int = 2,
                 num_steps: int = 3):
        if start_step < 1:
            raise ValueError(f"start_step must be >= 1, got {start_step}")
        if num_steps < 1:
            raise ValueError(f"num_steps must be >= 1, got {num_steps}")
        self.profile_dir = profile_dir
        self.start_step = start_step
        self.stop_after = start_step + num_steps - 1
        self._active = False
        self._done = False

    def on_step(self, step: int) -> None:
        """Call AFTER step ``step`` completes (the trainer's on_step
        convention; the trainer also primes the hook with the step it
        RESUMES from, so ``start_step=1`` — and a resume landing inside
        the window — both work): the window opens once ``start_step - 1``
        has completed and covers through ``stop_after``, i.e. by default
        starting at step 2, past the compile+execute first step that
        would drown the steady state. A resume already past the window
        never starts it (a partial trace would masquerade as the
        configured window)."""
        if (
            not self._done
            and not self._active
            and self.start_step - 1 <= step < self.stop_after
        ):
            self._start()
        elif self._active and step >= self.stop_after:
            self.stop()

    def _start(self) -> None:
        import jax

        os.makedirs(self.profile_dir, exist_ok=True)
        jax.profiler.start_trace(self.profile_dir)
        self._active = True
        LOG.info(
            "profiler trace started",
            extra=log.kv(dir=self.profile_dir, start=self.start_step,
                         stop=self.stop_after),
        )

    def stop(self) -> None:
        if not self._active:
            return
        import jax

        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        events.emit(
            "profile", "jax_trace",
            dir=self.profile_dir,
            start_step=self.start_step,
            stop_step=self.stop_after,
        )
        LOG.info("profiler trace stopped", extra=log.kv(dir=self.profile_dir))

    def close(self) -> None:
        self.stop()

    def __enter__(self) -> "ProfilerHook":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def profiler_from_env() -> Optional[ProfilerHook]:
    """Build a hook from ``KATATPU_OBS_PROFILE_DIR`` (+ optional
    ``_START``/``_STEPS``); None when unset."""
    profile_dir = _env(_ENV_DIR)
    if not profile_dir:
        return None
    return ProfilerHook(
        profile_dir,
        start_step=int(_env(_ENV_START, "2")),
        num_steps=int(_env(_ENV_STEPS, "3")),
    )
