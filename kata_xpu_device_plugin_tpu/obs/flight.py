"""Crash flight recorder: the last N telemetry events, always armed.

The JSONL sink (:mod:`.events`) answers "what happened" only when someone
remembered to set ``KATATPU_OBS=1`` before the incident — which is never
true for the incident that matters. This module keeps a bounded
in-memory ring of the most recent events (spans included — every closed
span is one event) REGARDLESS of the sink switch, and dumps the ring to
a postmortem JSONL file the moment a TERMINAL event passes through:

- ``serving/chip_loss_fatal``   — no degraded mesh rung left; the load
  failed (guest side, ISSUE 10);
- ``serving/fatal_error``       — a non-recoverable exception unwound the
  serving loop (user bug, strict-mode guard trip — the supervisor's
  "not ours to catch" class);
- ``plugin/registration_exhausted`` — the daemon gave up on kubelet
  registration (host side);
- ``serving/drain``             — only when the drain failed requests
  (``failed > 0``): work was shed, the 2 s before matter.

The dump is the answer to "what happened in the 2 seconds before the
mesh shrank": every span/event the process emitted leading up to the
terminal one, trace ids included, with zero configuration. Cost while
armed is one dict append per emitted event (events are emitted at the
scheduling cadence — admissions, retires, checkpoints — never per
token), bounded by the ring; ``KATATPU_FLIGHT=0`` disarms it entirely
and restores the sink-off fast path.

Knobs (env, read when the recorder is (re)configured):

- ``KATATPU_FLIGHT=0``      — kill switch (default armed);
- ``KATATPU_FLIGHT_RING``   — ring capacity in events (default 512);
- ``KATATPU_FLIGHT_DIR``    — dump directory (default: ``artifacts/``
  under the working dir — postmortems join the other telemetry
  artifacts instead of littering the repo/pod root, ISSUE 15).

Dumps are named ``katatpu_flight_<event>_<pid>_<seq>.jsonl`` so several
terminal events (or processes) never clobber each other. The module is
stdlib-only and imported by :mod:`.events` (never the reverse), so the
jax-free host daemon records flights too.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
from collections import deque
from typing import Optional

ENV_ENABLE = "KATATPU_FLIGHT"   # "0" disarms; anything else (or unset) arms
ENV_RING = "KATATPU_FLIGHT_RING"
ENV_DIR = "KATATPU_FLIGHT_DIR"

DEFAULT_RING = 512
DEFAULT_DIR = "artifacts"

# (kind, name) pairs that always trigger a dump. serving/drain is
# conditional (failed > 0) and handled in _is_terminal.
TERMINAL_EVENTS = frozenset({
    ("serving", "chip_loss_fatal"),
    ("serving", "fatal_error"),
    ("plugin", "registration_exhausted"),
})


def enabled() -> bool:
    return os.environ.get(ENV_ENABLE, "1") != "0"


def ring_capacity() -> int:
    raw = os.environ.get(ENV_RING, "")
    try:
        n = int(raw) if raw else DEFAULT_RING
    except ValueError:
        n = DEFAULT_RING
    return max(1, n)


def dump_dir() -> str:
    return os.environ.get(ENV_DIR, "") or DEFAULT_DIR


class FlightRecorder:
    """Bounded ring of recent event dicts + the terminal-event dump.

    Thread-safe: concurrent emitters share the ring under one lock, and
    the dump runs inside it so the postmortem is a consistent snapshot
    (the terminal event is always the ring's last entry — record()
    appends before it checks the trigger)."""

    def __init__(self, capacity: int = DEFAULT_RING):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self.dumps: list[str] = []

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def record(self, event: dict) -> None:
        """Append one event; dump the ring when it is terminal. Never
        raises — the recorder is telemetry of last resort and must not
        add a failure mode to the path that is already failing."""
        with self._lock:
            self._ring.append(event)
            if not self._is_terminal(event):
                return
            try:
                self._dump_locked(str(event.get("name", "event")))
            except Exception:
                pass

    @staticmethod
    def _is_terminal(event: dict) -> bool:
        key = (event.get("kind"), event.get("name"))
        if key in TERMINAL_EVENTS:
            return True
        # A drain that shed work is an incident; a clean drain is not.
        if key == ("serving", "drain"):
            try:
                return int(event.get("failed") or 0) > 0
            except (TypeError, ValueError):
                return False
        return False

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Write the ring to a postmortem JSONL now (the terminal-event
        path calls the locked form itself); returns the path, or None
        when the ring is empty."""
        with self._lock:
            return self._dump_locked(reason)

    def _dump_locked(self, reason: str) -> Optional[str]:
        if not self._ring:
            return None
        d = dump_dir()
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
        # PROCESS-global sequence, not per-recorder: several recorder
        # instances in one process (the per-test fresh ring, a reconfig)
        # must never reuse a filename and overwrite an earlier
        # postmortem in a shared dump dir.
        path = os.path.join(
            d,
            f"katatpu_flight_{safe}_{os.getpid()}_{next(_DUMP_SEQ)}.jsonl",
        )
        # Sanctioned lock-held IO: a postmortem must be a consistent
        # snapshot — recording threads pausing behind the (rare) dump is
        # the cost of a ring that is not torn mid-capture.
        if d and d != ".":
            os.makedirs(d, exist_ok=True)  # jaxguard: allow(JG203) consistent postmortem snapshot
        with open(path, "w", encoding="utf-8") as fh:  # jaxguard: allow(JG203) consistent postmortem snapshot
            for event in self._ring:
                fh.write(json.dumps(event, default=str) + "\n")
        self.dumps.append(path)
        return path


# -- process-default recorder ------------------------------------------------

# Dump-name uniqueness across every recorder instance this process makes
# (itertools.count.__next__ is atomic under the GIL).
_DUMP_SEQ = itertools.count(1)

_default: Optional[FlightRecorder] = None
_configured = False
_lock = threading.Lock()


def configure_from_env(force: bool = False) -> Optional[FlightRecorder]:
    """Resolve the default recorder from the environment (once; ``force``
    re-reads — tests that flip the env or need a fresh ring)."""
    global _default, _configured
    with _lock:
        if _configured and not force:
            return _default
        _configured = True
        _default = FlightRecorder(ring_capacity()) if enabled() else None
        return _default


def recorder() -> Optional[FlightRecorder]:
    """The process-default recorder (None when disarmed)."""
    return configure_from_env()


def set_default_recorder(
    rec: Optional[FlightRecorder],
) -> Optional[FlightRecorder]:
    """Install ``rec`` as the process default (None disarms); returns the
    previous recorder so callers can restore it — the sink-swap contract
    of :func:`..events.set_default_sink`."""
    global _default
    prev = configure_from_env()
    with _lock:
        _default = rec
        return prev
