"""TPU discovery: the native path.

Replaces the reference's single hardcoded path — "vendor == 10de && driver ==
vfio-pci" over /sys/bus/pci/devices (``device_plugin.go:142-160``) — with the
TPU-first scan (SURVEY §7 stage 2a): enumerate ``/dev/accel*`` char devices
(the Cloud TPU kernel driver's nodes), correlate them with vendor-``1ae0``
PCIe endpoints for BDF/NUMA/IOMMU metadata, and derive the host's slice
topology. The VFIO walk lives in :mod:`.vfio` as the generalized path.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from ..topology.slice import HostTopology, detect_accelerator_type
from . import sysfs
from .pciids import GOOGLE_VENDOR, PciIds, resource_suffix


@dataclass(frozen=True)
class TpuChip:
    """One TPU chip on the host: a /dev/accel node plus optional PCI identity.

    ``index`` (the accelN suffix) is the stable host-local chip id — it is the
    CDI device name and the device-plugin device id, replacing the reference's
    fragile global bus-walk counter (SURVEY §Quirks 5).
    """

    index: int
    dev_path: str  # /dev/accel<N>
    major: Optional[int] = None
    minor: Optional[int] = None
    pci_address: Optional[str] = None
    pci_device: Optional[str] = None
    numa_node: Optional[int] = None
    vfio_group: Optional[str] = None  # set when the function is vfio-bound


@dataclass(frozen=True)
class TpuInventory:
    """Everything discovery learned about this host's TPUs."""

    chips: tuple[TpuChip, ...]
    topology: HostTopology
    model_suffix: str  # resource-name suffix, e.g. "TPU_V5E"

    @property
    def count(self) -> int:
        return len(self.chips)

    def chip(self, index: int) -> TpuChip:
        for c in self.chips:
            if c.index == index:
                return c
        raise KeyError(index)


def scan_tpus(
    sysfs_root: str = sysfs.DEFAULT_SYSFS_ROOT,
    dev_root: str = sysfs.DEFAULT_DEV_ROOT,
    env: Optional[dict[str, str]] = None,
    pci_ids: Optional[PciIds] = None,
    accelerator_type: Optional[str] = None,
    resolve_env_identity: bool = True,
) -> TpuInventory:
    """One-shot scan (re-run periodically by the manager; the reference never
    rescans — SURVEY §Quirks 9).

    Chip identity comes from /dev/accel*; PCI metadata is correlated by sorted
    BDF order (the Cloud TPU driver enumerates accel nodes in BDF order). When
    counts disagree, PCI metadata is attached only pairwise-in-order and the
    mismatch is left to the caller's logging.
    """
    environ: dict[str, str] = os.environ if env is None else env  # type: ignore[assignment]
    nodes = [
        n
        for n in sysfs.scan_char_devices(dev_root, "accel")
        if n.name[len("accel"):].isdigit()  # accel<N> only; ignore strays
    ]
    google_funcs = [f for f in sysfs.scan_pci(sysfs_root) if f.vendor == GOOGLE_VENDOR]
    # Prefer the strict filter (known TPU device ids): index↔BDF-order
    # correlation is only sound when the list holds exactly the TPU endpoints.
    # A momentarily-unbound gVNIC sharing vendor 1ae0 must not shift every
    # chip onto the wrong BDF. The heuristic is the fallback for new
    # generations whose ids aren't in the table yet.
    from .pciids import BUILTIN_GOOGLE_DEVICES

    pci_funcs = [f for f in google_funcs if f.device in BUILTIN_GOOGLE_DEVICES]
    if not pci_funcs:
        pci_funcs = [f for f in google_funcs if _is_accel_function(f)]

    chips = []
    for node in nodes:
        index = int(node.name[len("accel"):])
        # Correlate by the chip's stable index, not enumeration position —
        # a missing /dev/accel1 must not shift every later chip onto the
        # wrong PCI function (and hence the wrong BDF/IOMMU group in Kata
        # attach hints).
        pci = pci_funcs[index] if index < len(pci_funcs) else None
        chips.append(
            TpuChip(
                index=index,
                dev_path=node.path,
                major=node.major,
                minor=node.minor,
                pci_address=pci.address if pci else None,
                pci_device=pci.device if pci else None,
                numa_node=pci.numa_node if pci else None,
                vfio_group=pci.iommu_group if pci and pci.driver == "vfio-pci" else None,
            )
        )

    accel_type = accelerator_type or detect_accelerator_type(
        environ,
        chip_count=len(chips),
        pci_device_id=next((c.pci_device for c in chips if c.pci_device), None),
    )
    # Worker identity from env is parsed by the multihost resolver — the one
    # parser of TPU_WORKER_ID/TPU_WORKER_HOSTNAMES. The manager disables
    # this (resolve_env_identity=False) because its membership overlay
    # re-resolves env with the proper --node-name; resolving here too would
    # duplicate the work and warn with the wrong (pod) hostname.
    from ..multihost.resolver import env_hostnames, from_env

    mem = (
        from_env(environ, hostname=environ.get("HOSTNAME", ""))
        if resolve_env_identity
        else None
    )
    # When no id is derivable the peer list still passes through (worker 0):
    # dropping TPU_WORKER_HOSTNAMES from the topology would hide the slice's
    # membership from direct scan_tpus callers.
    hostnames = (
        mem.hostnames
        if mem
        else (env_hostnames(environ) if resolve_env_identity else ())
    )
    topo = HostTopology.from_accelerator_type(
        accel_type,
        worker_id=mem.worker_id if mem else 0,
        worker_hostnames=hostnames,
    )
    device_id = next((c.pci_device for c in chips if c.pci_device), None)
    suffix = resource_suffix(GOOGLE_VENDOR, device_id, pci_ids) if device_id else "TPU"
    return TpuInventory(chips=tuple(chips), topology=topo, model_suffix=suffix)


def _is_accel_function(f: sysfs.PciFunction) -> bool:
    """Google endpoints that are accelerators (filters out e.g. gVNIC which
    shares the vendor id): accept known-TPU device ids and anything not bound
    to a networking driver."""
    from .pciids import BUILTIN_GOOGLE_DEVICES

    if f.device in BUILTIN_GOOGLE_DEVICES:
        return True
    return f.driver not in ("gve", "virtio-pci")
