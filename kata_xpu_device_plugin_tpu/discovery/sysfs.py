"""sysfs/devfs access primitives.

Counterpart of the reference's raw file helpers (``device_plugin.go:183-206``:
``readIDFromFile`` strips the ``0x`` prefix, ``readLink`` takes the basename of
the symlink target). The reference makes these swappable package-level function
vars for testability (SURVEY §4); here the same seam is the ``sysfs_root`` /
``dev_root`` parameters, so tests point discovery at a tempdir fake tree.
"""
from __future__ import annotations

import os
import stat
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_SYSFS_ROOT = "/sys"
DEFAULT_DEV_ROOT = "/dev"

PCI_DEVICES_SUBDIR = "bus/pci/devices"
ACCEL_CLASS_SUBDIR = "class/accel"


def read_id_file(path: str) -> Optional[str]:
    """Read a sysfs id file (``vendor``/``device``), normalizing ``0x1ae0`` -> ``1ae0``."""
    try:
        with open(path) as f:
            val = f.read().strip().lower()
    except OSError:
        return None
    return val[2:] if val.startswith("0x") else val


def read_link_base(path: str) -> Optional[str]:
    """Basename of a sysfs symlink target (``driver`` -> ``vfio-pci``,
    ``iommu_group`` -> group id)."""
    try:
        return os.path.basename(os.readlink(path))
    except OSError:
        return None


def read_text(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


@dataclass(frozen=True)
class PciFunction:
    """One PCI function under ``<sysfs>/bus/pci/devices`` (the unit the
    reference walks; ``device_plugin.go:132-180``)."""

    address: str  # e.g. "0000:00:05.0"
    vendor: Optional[str]  # 4-hex-digit, lowercase, no 0x
    device: Optional[str]
    driver: Optional[str]  # bound kernel driver name, or None
    iommu_group: Optional[str]  # group id as string, or None
    numa_node: Optional[int] = None

    @property
    def bdf(self) -> str:
        return self.address


def scan_pci(sysfs_root: str = DEFAULT_SYSFS_ROOT) -> list[PciFunction]:
    """Enumerate all PCI functions, sorted by address for deterministic output.

    The reference's ``filepath.Walk`` over ``/sys/bus/pci/devices``
    (``device_plugin.go:126-180``) with the vendor filter *removed* — filtering
    is the caller's job (vendor-table-driven; SURVEY §7 stage 2), not baked in.
    """
    base = os.path.join(sysfs_root, PCI_DEVICES_SUBDIR)
    try:
        entries = sorted(os.listdir(base))
    except OSError:
        return []
    out: list[PciFunction] = []
    for addr in entries:
        devdir = os.path.join(base, addr)
        if not os.path.isdir(devdir):
            continue
        numa = read_text(os.path.join(devdir, "numa_node"))
        out.append(
            PciFunction(
                address=addr,
                vendor=read_id_file(os.path.join(devdir, "vendor")),
                device=read_id_file(os.path.join(devdir, "device")),
                driver=read_link_base(os.path.join(devdir, "driver")),
                iommu_group=read_link_base(os.path.join(devdir, "iommu_group")),
                numa_node=int(numa) if numa not in (None, "", "-1") else None,
            )
        )
    return out


@dataclass(frozen=True)
class CharDevice:
    """A character device node (``/dev/accel<N>`` or ``/dev/vfio/<group>``)."""

    path: str
    major: Optional[int] = None
    minor: Optional[int] = None

    @property
    def name(self) -> str:
        return os.path.basename(self.path)


def scan_char_devices(dev_root: str, prefix: str) -> list[CharDevice]:
    """List char devices directly under ``dev_root`` whose name starts with
    ``prefix`` (e.g. ``accel``), sorted by the numeric suffix when present.

    In tests the fake ``/dev`` holds regular files; those are accepted (no
    mknod in CI), with major/minor only populated for real char devices.
    """
    try:
        names = os.listdir(dev_root)
    except OSError:
        return []
    found: list[CharDevice] = []
    for name in names:
        if not name.startswith(prefix):
            continue
        path = os.path.join(dev_root, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        if stat.S_ISDIR(st.st_mode):
            continue
        if stat.S_ISCHR(st.st_mode):
            found.append(
                CharDevice(path=path, major=os.major(st.st_rdev), minor=os.minor(st.st_rdev))
            )
        else:
            found.append(CharDevice(path=path))

    def sort_key(d: CharDevice):
        suffix = d.name[len(prefix):]
        return (0, int(suffix)) if suffix.isdigit() else (1, suffix)

    return sorted(found, key=sort_key)


@dataclass
class FakeSysfsBuilder:
    """Helper for building fake sysfs/dev trees in tests (SURVEY §4's
    "discovery against a tempdir fake sysfs tree"). Lives in the package (not
    tests/) so downstream users get the same harness."""

    root: str
    _groups: set = field(default_factory=set)

    @property
    def sysfs(self) -> str:
        return os.path.join(self.root, "sys")

    @property
    def dev(self) -> str:
        return os.path.join(self.root, "dev")

    def add_pci_function(
        self,
        address: str,
        vendor: str,
        device: str,
        driver: Optional[str] = None,
        iommu_group: Optional[str] = None,
        numa_node: Optional[int] = None,
    ) -> str:
        devdir = os.path.join(self.sysfs, PCI_DEVICES_SUBDIR, address)
        os.makedirs(devdir, exist_ok=True)
        with open(os.path.join(devdir, "vendor"), "w") as f:
            f.write(f"0x{vendor}\n")
        with open(os.path.join(devdir, "device"), "w") as f:
            f.write(f"0x{device}\n")
        if numa_node is not None:
            with open(os.path.join(devdir, "numa_node"), "w") as f:
                f.write(f"{numa_node}\n")
        if driver:
            drv_dir = os.path.join(self.sysfs, "bus/pci/drivers", driver)
            os.makedirs(drv_dir, exist_ok=True)
            _force_symlink(drv_dir, os.path.join(devdir, "driver"))
        if iommu_group is not None:
            grp_dir = os.path.join(self.sysfs, "kernel/iommu_groups", iommu_group)
            os.makedirs(grp_dir, exist_ok=True)
            _force_symlink(grp_dir, os.path.join(devdir, "iommu_group"))
            if iommu_group not in self._groups:
                self._groups.add(iommu_group)
                self.add_dev_node(f"vfio/{iommu_group}")
        return devdir

    def add_dev_node(self, rel_path: str) -> str:
        path = os.path.join(self.dev, rel_path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write("")
        return path

    def add_accel_chip(self, index: int) -> str:
        """A TPU chip: /dev/accel<N> plus its /sys/class/accel entry."""
        node = self.add_dev_node(f"accel{index}")
        class_dir = os.path.join(self.sysfs, ACCEL_CLASS_SUBDIR, f"accel{index}")
        os.makedirs(class_dir, exist_ok=True)
        return node

    def remove_dev_node(self, rel_path: str) -> None:
        try:
            os.unlink(os.path.join(self.dev, rel_path))
        except FileNotFoundError:
            pass


def _force_symlink(target: str, link: str) -> None:
    try:
        os.unlink(link)
    except FileNotFoundError:
        pass
    os.symlink(target, link)
