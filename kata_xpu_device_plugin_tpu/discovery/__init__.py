"""Device discovery: TPU-native (/dev/accel* + vendor 1ae0) and generalized
VFIO paths, plus pci.ids naming (counterpart of the reference's
``pkg/device_plugin/device_plugin.go`` discovery layer)."""
from .pciids import GOOGLE_VENDOR, NVIDIA_VENDOR, PciIds, resource_suffix, sanitize_name
from .sysfs import CharDevice, FakeSysfsBuilder, PciFunction, scan_char_devices, scan_pci
from .tpu import TpuChip, TpuInventory, scan_tpus
from .vfio import VfioDevice, VfioInventory, scan_vfio

__all__ = [
    "GOOGLE_VENDOR",
    "NVIDIA_VENDOR",
    "PciIds",
    "resource_suffix",
    "sanitize_name",
    "CharDevice",
    "FakeSysfsBuilder",
    "PciFunction",
    "scan_char_devices",
    "scan_pci",
    "TpuChip",
    "TpuInventory",
    "scan_tpus",
    "VfioDevice",
    "VfioInventory",
    "scan_vfio",
]
