"""Generalized VFIO passthrough discovery.

The reference's entire discovery, kept as the *generalized* second path
(SURVEY §7 stage 2b): walk PCI functions, keep those bound to ``vfio-pci``
whose vendor is in the configured vendor table (the reference hardcodes
``10de``; ``device_plugin.go:19,149``), and group them by IOMMU group — the
co-allocation unit for whole-VM passthrough (a group's functions share an
IOMMU domain and must move together into the guest).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from . import sysfs
from .pciids import PciIds, resource_suffix

VFIO_DRIVER = "vfio-pci"


@dataclass(frozen=True)
class VfioDevice:
    """One vfio-bound PCI function (ref ``NvidiaGpuDevice{addr,index}``,
    device_plugin.go:24-28 — but keyed by address, not a global counter)."""

    address: str
    vendor: str
    device: str
    iommu_group: str
    numa_node: int | None = None

    @property
    def vfio_node(self) -> str:
        return f"/dev/vfio/{self.iommu_group}"


@dataclass
class VfioInventory:
    """IOMMU-group-keyed view of vfio-bound devices.

    ``groups``: group id → functions in the group (ref ``iommuMap``,
    device_plugin.go:31). ``models``: (vendor, device) → group ids containing
    that model (ref ``deviceMap``, :34 — one plugin is spawned per model).
    """

    groups: dict[str, list[VfioDevice]] = field(default_factory=dict)
    models: dict[tuple[str, str], list[str]] = field(default_factory=dict)

    def model_suffix(self, key: tuple[str, str], db: PciIds | None = None) -> str:
        return resource_suffix(key[0], key[1], db)


def scan_vfio(
    sysfs_root: str = sysfs.DEFAULT_SYSFS_ROOT,
    vendors: tuple[str, ...] = (),
) -> VfioInventory:
    """Build the inventory; ``vendors`` empty means accept every vendor
    (vendor-table-driven rather than hardcoded; SURVEY §7 stage 2)."""
    inv = VfioInventory()
    for f in sysfs.scan_pci(sysfs_root):
        if f.driver != VFIO_DRIVER or f.iommu_group is None:
            continue
        if vendors and f.vendor not in vendors:
            continue
        if f.vendor is None or f.device is None:
            continue
        dev = VfioDevice(
            address=f.address,
            vendor=f.vendor,
            device=f.device,
            iommu_group=f.iommu_group,
            numa_node=f.numa_node,
        )
        inv.groups.setdefault(f.iommu_group, []).append(dev)
        key = (f.vendor, f.device)
        if f.iommu_group not in inv.models.setdefault(key, []):
            inv.models[key].append(f.iommu_group)
    return inv
