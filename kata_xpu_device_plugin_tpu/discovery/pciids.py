"""PCI id → human-readable resource-name translation.

Counterpart of the reference's ``getDeviceName``/``locateVendor``
(``device_plugin.go:208-275``), which seeks through a vendored 38k-line
``pci.ids`` at ``/usr/pci.ids`` and upper-cases the marketing name into a
resource-name suffix. Differences here:

- the database path is config, with a ladder of fallbacks (explicit path →
  system locations → the small authored table shipped in ``data/pci.ids``);
- a built-in TPU table covers Google vendor ``1ae0``, whose Cloud TPU device
  ids are *absent* from the public pci.ids (SURVEY §L0: only the Pixel Edge
  TPU is listed) — the exact gap the reference's lookup would fall into;
- the parser reads the whole (small) file instead of a byte-seek state machine.
"""
from __future__ import annotations

import os
import re
from typing import Optional

GOOGLE_VENDOR = "1ae0"
NVIDIA_VENDOR = "10de"

# Built-in fallback names for Google accelerator endpoints. Public pci.ids has
# no Cloud TPU device ids, and GKE nodes may not ship a database at all, so
# these guarantee a stable resource name on exactly the hardware we target.
# Generation names follow the TPU_ACCELERATOR_TYPE families.
BUILTIN_GOOGLE_DEVICES = {
    "0027": "TPU_V2",
    "0056": "TPU_V3",
    "005e": "TPU_V4",
    "0062": "TPU_V5P",
    "0063": "TPU_V5E",
    "006f": "TPU_V6E",
}
BUILTIN_GOOGLE_FALLBACK = "TPU"

SYSTEM_PCIIDS_PATHS = (
    "/usr/pci.ids",  # where the reference's image installs it (Dockerfile:66)
    "/usr/share/misc/pci.ids",
    "/usr/share/hwdata/pci.ids",
)

_SANITIZE_RE = re.compile(r"[^A-Za-z0-9_]+")


def sanitize_name(name: str) -> str:
    """Uppercase and restrict to ``[A-Za-z0-9_]`` (ref device_plugin.go:241-251),
    collapsing runs and trimming edges so names are clean resource suffixes."""
    return _SANITIZE_RE.sub("_", name.strip()).strip("_").upper()


class PciIds:
    """Parsed pci.ids database: vendor id → (vendor name, {device id → name})."""

    def __init__(self) -> None:
        self._vendors: dict[str, tuple[str, dict[str, str]]] = {}

    @classmethod
    def parse(cls, text: str) -> "PciIds":
        db = cls()
        current: Optional[str] = None
        for line in text.splitlines():
            if not line or line.lstrip().startswith("#"):
                continue
            if line.startswith("\t\t"):  # subsystem lines — not needed
                continue
            if line.startswith("\t"):
                if current is None:
                    continue
                body = line[1:]
                dev_id, _, dev_name = body.partition("  ")
                dev_id = dev_id.strip().lower()
                if re.fullmatch(r"[0-9a-f]{4}", dev_id):
                    db._vendors[current][1][dev_id] = dev_name.strip()
                continue
            if line[:1].upper() == "C" and line[1:2] == " ":  # device-class section
                current = None
                continue
            ven_id, _, ven_name = line.partition("  ")
            ven_id = ven_id.strip().lower()
            if re.fullmatch(r"[0-9a-f]{4}", ven_id):
                current = ven_id
                db._vendors.setdefault(ven_id, (ven_name.strip(), {}))
            else:
                current = None
        return db

    @classmethod
    def load(cls, path: Optional[str] = None) -> "PciIds":
        """Load from ``path`` if given (errors if it doesn't exist — an
        explicit path silently falling through to a different database would
        ignore the operator's curated names), else the first existing system
        path, else the authored table shipped with the package; else empty."""
        if path:
            with open(path, errors="replace") as f:
                return cls.parse(f.read())
        candidates = list(SYSTEM_PCIIDS_PATHS)
        candidates.append(
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "data", "pci.ids")
        )
        for cand in candidates:
            if cand and os.path.isfile(cand):
                try:
                    with open(cand, errors="replace") as f:
                        return cls.parse(f.read())
                except OSError:
                    continue
        return cls()

    def vendor_name(self, vendor: str) -> Optional[str]:
        entry = self._vendors.get(vendor.lower())
        return entry[0] if entry else None

    def device_name(self, vendor: str, device: str) -> Optional[str]:
        entry = self._vendors.get(vendor.lower())
        return entry[1].get(device.lower()) if entry else None


def resource_suffix(vendor: str, device: str, db: Optional[PciIds] = None) -> str:
    """Resource-name suffix for a (vendor, device) pair.

    Resolution order: built-in Google TPU table → pci.ids database → raw hex
    device id (the reference's fallback, device_plugin.go:100-103).
    """
    vendor = vendor.lower()
    device = device.lower()
    if vendor == GOOGLE_VENDOR:
        name = BUILTIN_GOOGLE_DEVICES.get(device)
        if name:
            return name
        if db:
            from_db = db.device_name(vendor, device)
            if from_db:
                return sanitize_name(from_db)
        return BUILTIN_GOOGLE_FALLBACK
    if db:
        from_db = db.device_name(vendor, device)
        if from_db:
            return sanitize_name(from_db)
    return device
