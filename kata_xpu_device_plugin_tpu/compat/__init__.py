"""Version-compat shims for fast-moving dependency surfaces.

:mod:`.jaxapi` is the single place the repo touches JAX symbols that have
moved (or will move) between release lines. Everything else imports them
from here; ``tools.lint`` rule JX001 enforces that.
"""
from . import jaxapi

__all__ = ["jaxapi"]
