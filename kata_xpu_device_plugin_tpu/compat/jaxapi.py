"""One version-gated shim for every JAX symbol that has drifted between
release lines.

The seed of this repo could not even *import*: nine modules used APIs from
a newer JAX (``from jax import shard_map``, ``jax.sharding.AxisType``) that
do not exist in the installed 0.4.x, so 19 of ~30 test files died at pytest
collection. The accelerator runtime moves faster than the framework — the
fix is to resolve each moved symbol HERE, once, against whatever JAX is
installed, and let ``tools.lint`` (rule JX001) make any direct import of a
drifted symbol outside this package a lint error at PR time instead of a
collection crash at run time.

Supported range: jax >= 0.4.26 (``jax.tree``, ``jax.experimental.shard_map``
with partial-auto) through the current stable line (``jax.shard_map``,
typed mesh axes). Export table — see ``docs/compat_and_lint.md``:

==================  ============================  ===========================
symbol              0.4.x resolution              newer resolution
==================  ============================  ===========================
``shard_map``       ``jax.experimental.shard_map``  ``jax.shard_map``
                    (``check_vma``→``check_rep``,
                    ``axis_names``→``auto``)
``AxisType``        fallback enum (Auto only       ``jax.sharding.AxisType``
                    honorable)
``make_mesh``       drops ``axis_types``           passes ``axis_types``
``Mesh`` etc.       ``jax.sharding``               ``jax.sharding``
``pvary``           no-op                          ``lax.pvary``/``pcast``
``tree_map`` etc.   ``jax.tree`` / ``jax.tree_util``  same
==================  ============================  ===========================

Every resolver takes the ``jax`` module as a parameter so the unit tests
can drive both sides of each gate with a fake old/new module surface
(``tests/test_compat_jaxapi.py``) regardless of the JAX actually installed.
"""
from __future__ import annotations

import enum
import importlib
import inspect
import os
import re
import sys
import threading
import warnings
from contextlib import ExitStack, contextmanager
from typing import Any, Callable, Optional, Sequence


class JaxCompatError(ImportError):
    """A JAX symbol this repo depends on is unavailable in the installed
    version. Names the symbol, what was searched, and the minimum version
    that provides it."""

    def __init__(self, symbol: str, detail: str, min_version: str):
        self.symbol = symbol
        self.min_version = min_version
        super().__init__(
            f"jax compat: cannot resolve {symbol!r} ({detail}); "
            f"this repo needs jax >= {min_version} — "
            f"see kata_xpu_device_plugin_tpu/compat/jaxapi.py"
        )


def parse_version(version: str) -> tuple[int, int, int]:
    """``"0.4.37"`` / ``"0.5.0.dev20250101"`` → ``(0, 4, 37)`` (non-numeric
    tails dropped; missing fields are 0)."""
    nums = []
    for part in version.split(".")[:3]:
        m = re.match(r"\d+", part)
        nums.append(int(m.group()) if m else 0)
    while len(nums) < 3:
        nums.append(0)
    return tuple(nums)  # type: ignore[return-value]


# ----- shard_map ------------------------------------------------------------


def resolve_shard_map(jax_mod: Any) -> tuple[Callable, str]:
    """Find the raw shard_map: ``jax.shard_map`` on the stable line,
    ``jax.experimental.shard_map.shard_map`` on 0.4.x. Returns
    ``(fn, style)`` with style ``"stable"`` or ``"experimental"``."""
    fn = getattr(jax_mod, "shard_map", None)
    if callable(fn):
        return fn, "stable"
    # Fakes/tests expose the submodule as an attribute; the real package
    # needs an import to materialize it.
    exp = getattr(jax_mod, "experimental", None)
    sub = getattr(exp, "shard_map", None) if exp is not None else None
    if sub is None:
        try:
            sub = importlib.import_module(
                f"{jax_mod.__name__}.experimental.shard_map"
            )
        except ImportError:
            sub = None
    fn = getattr(sub, "shard_map", None) if sub is not None else None
    if callable(fn):
        return fn, "experimental"
    raise JaxCompatError(
        "shard_map",
        "neither jax.shard_map nor jax.experimental.shard_map.shard_map "
        f"exists in jax {getattr(jax_mod, '__version__', '?')}",
        min_version="0.4.26",
    )


def build_shard_map(raw: Callable, style: str) -> Callable:
    """Wrap the raw shard_map behind ONE calling convention — the stable
    line's: ``shard_map(f, mesh=, in_specs=, out_specs=, check_vma=,
    axis_names=)``, where ``None`` for either optional means "the
    version's own default" on BOTH lines (the stable jax.shard_map would
    otherwise receive a literal None where its default is True). On the
    experimental line, ``check_vma`` maps to its older spelling
    ``check_rep`` and ``axis_names`` (the set of MANUAL axes) maps to its
    complement ``auto`` (the set of axes GSPMD keeps)."""

    def shard_map(
        f: Callable,
        *,
        mesh: Any,
        in_specs: Any,
        out_specs: Any,
        check_vma: Optional[bool] = None,
        axis_names: Optional[Any] = None,
        **kw: Any,
    ) -> Callable:
        if style == "stable":
            if check_vma is not None:
                kw.setdefault("check_vma", check_vma)
            if axis_names is not None:
                kw.setdefault("axis_names", set(axis_names))
        else:
            if check_vma is not None:
                kw.setdefault("check_rep", check_vma)
            if axis_names is not None:
                manual = frozenset(axis_names)
                kw.setdefault("auto", frozenset(mesh.axis_names) - manual)
        return raw(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

    return shard_map


# ----- mesh axis types ------------------------------------------------------


class _FallbackAxisType(enum.Enum):
    """Stand-in for ``jax.sharding.AxisType`` on lines that predate typed
    mesh axes. Only ``Auto`` is honorable there: untyped meshes ARE
    all-auto (GSPMD partitions every axis unless a shard_map takes it
    manual), so requesting ``Auto`` is a no-op and anything else raises at
    :func:`make_mesh` time."""

    Auto = "auto"
    Explicit = "explicit"
    Manual = "manual"


def resolve_axis_type(jax_mod: Any) -> Any:
    sharding = getattr(jax_mod, "sharding", None)
    native = getattr(sharding, "AxisType", None) if sharding is not None else None
    return native if native is not None else _FallbackAxisType


def resolve_sharding_types(jax_mod: Any) -> tuple[Any, Any, Any]:
    """``(Mesh, NamedSharding, PartitionSpec)`` — stable across the
    supported range, re-exported so call sites have one import home."""
    sharding = getattr(jax_mod, "sharding", None)
    out = []
    for name in ("Mesh", "NamedSharding", "PartitionSpec"):
        sym = getattr(sharding, name, None) if sharding is not None else None
        if sym is None:
            raise JaxCompatError(
                name, f"jax.sharding.{name} missing", min_version="0.4.26"
            )
        out.append(sym)
    return tuple(out)  # type: ignore[return-value]


def build_make_mesh(jax_mod: Any, axis_type: Any) -> Callable:
    """``make_mesh(axis_shapes, axis_names, *, axis_types=None, devices=None)``.

    Newer JAX forwards ``axis_types`` natively. 0.4.x has no axis type
    system: ``AxisType.Auto`` is dropped (untyped == all-auto there) and
    any other requested type raises — silently ignoring ``Explicit`` would
    change sharding semantics, not just syntax."""
    native = getattr(jax_mod, "make_mesh", None)
    native_takes_types = False
    if native is not None:
        try:
            native_takes_types = "axis_types" in inspect.signature(
                native
            ).parameters
        except (TypeError, ValueError):  # pragma: no cover - C impls
            native_takes_types = False

    def make_mesh(
        axis_shapes: Sequence[int],
        axis_names: Sequence[str],
        *,
        axis_types: Optional[Sequence[Any]] = None,
        devices: Optional[Sequence[Any]] = None,
    ) -> Any:
        if native is not None and native_takes_types:
            kw: dict = {"devices": devices}
            if axis_types is not None:
                kw["axis_types"] = tuple(axis_types)
            return native(tuple(axis_shapes), tuple(axis_names), **kw)
        auto = getattr(axis_type, "Auto", None)
        if axis_types is not None and any(t is not auto for t in axis_types):
            raise JaxCompatError(
                "make_mesh(axis_types=...)",
                f"installed jax {getattr(jax_mod, '__version__', '?')} has "
                "untyped mesh axes; only AxisType.Auto can be honored",
                min_version="0.6.0",
            )
        if native is not None:
            return native(tuple(axis_shapes), tuple(axis_names), devices=devices)
        # Pre-make_mesh fallback: row-major device grid.
        import numpy as np

        devs = list(devices if devices is not None else jax_mod.devices())
        mesh_cls = jax_mod.sharding.Mesh
        return mesh_cls(
            np.asarray(devs).reshape(tuple(axis_shapes)), tuple(axis_names)
        )

    return make_mesh


# ----- device-variance marking ---------------------------------------------


def resolve_pvary(jax_mod: Any) -> Callable:
    """``pvary(x, axes)``: mark ``x`` device-varying over ``axes`` under
    shard_map's varying-axis type system. No-op on lines without one (the
    experimental shard_map's ``check_rep`` analysis needs no marking)."""
    lax = getattr(jax_mod, "lax", None)
    pcast = getattr(lax, "pcast", None) if lax is not None else None
    if pcast is not None:
        return lambda x, axes: pcast(x, tuple(axes), to="varying")
    pv = getattr(lax, "pvary", None) if lax is not None else None
    if pv is not None:
        return lambda x, axes: pv(x, tuple(axes))
    return lambda x, axes: x


# ----- axis introspection ---------------------------------------------------


def resolve_axis_size(jax_mod: Any) -> Callable:
    """``axis_size(name)`` inside a shard_map/pmap body. Newer JAX exposes
    ``lax.axis_size``; on 0.4.x the idiom is ``lax.psum(1, name)``, which
    evaluates to a concrete Python int at trace time (the operand is a
    non-tracer constant), so callers can build static permutation lists."""
    lax = getattr(jax_mod, "lax", None)
    native = getattr(lax, "axis_size", None) if lax is not None else None
    if native is not None:
        return native
    psum = getattr(lax, "psum", None) if lax is not None else None
    if psum is None:
        raise JaxCompatError(
            "axis_size", "jax.lax.{axis_size,psum} both missing",
            min_version="0.4.26",
        )
    return lambda name: psum(1, name)


# ----- pallas TPU compiler params -------------------------------------------


def resolve_pallas_compiler_params(pltpu_mod: Any) -> Any:
    """The pallas-TPU compiler params class: newer pallas renamed
    ``TPUCompilerParams`` → ``CompilerParams``."""
    cls = getattr(pltpu_mod, "CompilerParams", None)
    if cls is None:
        cls = getattr(pltpu_mod, "TPUCompilerParams", None)
    if cls is None:
        raise JaxCompatError(
            "pallas tpu CompilerParams",
            "neither CompilerParams nor TPUCompilerParams exists on "
            "jax.experimental.pallas.tpu",
            min_version="0.4.26",
        )
    return cls


def pallas_tpu_compiler_params(**kwargs: Any) -> Any:
    """Build pallas-TPU compiler params under either name (lazy import:
    pallas is heavy and only kernel modules need it)."""
    from jax.experimental import pallas as _pl  # noqa: F401 - registers submodule
    from jax.experimental.pallas import tpu as _pltpu

    return resolve_pallas_compiler_params(_pltpu)(**kwargs)


# ----- RNG partitioning semantics -------------------------------------------


def normalize_rng_config(jax_mod: Any) -> bool:
    """Make sharded-jit RNG match the stable line's semantics.

    0.4.x defaults ``jax_threefry_partitionable=False``, under which
    ``jax.random.normal`` inside a jit with sharded ``out_shardings``
    produces DIFFERENT values than the same call run eagerly — so
    ``init_sharded_params`` would silently initialize a different model
    than ``init_params``. Newer JAX defaults the flag to True (and later
    removes it), where sharded == unsharded. Flip it when present-and-off;
    returns whether a change was made.

    Runs at package import ON PURPOSE (unlike
    :func:`enable_cpu_multiprocess_collectives`, which is call-site
    scoped): on 0.4.x the flag also changes the threefry STREAM, so the
    only safe flip point is before any random draw in the process —
    flipping lazily at the first sharded init would desync values drawn
    earlier in the same program. Consequence: every process of a
    multi-process run must import this package before drawing data
    (tests/test_distributed_init.py shows the pattern)."""
    config = getattr(jax_mod, "config", None)
    if config is None or not hasattr(config, "jax_threefry_partitionable"):
        return False
    if config.jax_threefry_partitionable:
        return False
    config.update("jax_threefry_partitionable", True)
    return True


# ----- CPU cross-process collectives ----------------------------------------


def enable_cpu_multiprocess_collectives(jax_mod: Any) -> bool:
    """Let multi-process CPU meshes actually communicate.

    Newer JAX ships CPU cross-process collectives on by default; 0.4.x
    defaults ``jax_cpu_collectives_implementation`` to ``"none"``, so any
    computation spanning processes dies with "Multiprocess computations
    aren't implemented on the CPU backend". Flip it to gloo when the option
    exists and is still unset. Must run BEFORE the CPU client is created —
    call it on the distributed-init path, not at import. Returns whether a
    change was made."""
    config = getattr(jax_mod, "config", None)
    if config is None:
        return False
    # On 0.4.x the option is a flag: update() accepts it but it is NOT
    # readable as a config attribute, so probe by updating, not hasattr.
    current = getattr(config, "jax_cpu_collectives_implementation", None)
    if current not in (None, "none"):
        return False  # newer line: already defaulted on
    try:
        config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - option or gloo build absent
        return False
    return True


# ----- persistent compilation cache ----------------------------------------


def enable_compilation_cache(
    cache_dir: str = "",
    jax_mod: Any = None,
    min_compile_time_s: float = 1.0,
) -> str:
    """Switch on JAX's persistent (on-disk) compilation cache, best-effort.

    The multi-second per-executable XLA compile cost (visible in bench.py's
    compile-phase breakdown) is paid once per MACHINE instead of once per
    process: compiled executables are keyed by (HLO, compile options,
    backend) and written under ``cache_dir``, so a fresh interpreter tracing
    the same program loads the binary instead of recompiling.

    Resolution ladder: explicit ``cache_dir`` argument > env
    ``KATA_TPU_COMPILE_CACHE_DIR`` > ``~/.cache/kata-tpu/xla-cache``.
    ``KATA_TPU_COMPILE_CACHE=0`` disables entirely (kill switch for cache
    corruption or read-only filesystems). Returns the directory in use, or
    ``""`` when disabled/unsupported — callers never need to branch.

    ``min_compile_time_s`` maps to ``jax_persistent_cache_min_compile_time_secs``
    (skip caching executables cheaper to rebuild than to read); tests pass 0
    so tiny CPU executables round-trip. Each config option is applied
    independently under try/except — on a JAX line missing one knob the
    others still apply, and a line missing the cache entirely returns ``""``
    rather than raising (the option set drifted across 0.4.x)."""
    if os.environ.get("KATA_TPU_COMPILE_CACHE", "").lower() in ("0", "false", "no"):
        return ""
    jax_mod = jax_mod if jax_mod is not None else _jax
    cache_dir = (
        cache_dir
        or os.environ.get("KATA_TPU_COMPILE_CACHE_DIR", "")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "kata-tpu", "xla-cache"
        )
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax_mod.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # pragma: no cover - unwritable dir / ancient jax
        return ""
    for option, value in (
        ("jax_persistent_cache_min_compile_time_secs", min_compile_time_s),
        # Cache every size of executable: the default floor exists to bound
        # metadata churn on shared filesystems; a per-machine local dir has
        # no such concern and small serving executables add up.
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax_mod.config.update(option, value)
        except Exception:  # pragma: no cover - knob absent on this line
            pass
    # The cache singleton initializes lazily on the FIRST compile and then
    # memoizes — a process that already compiled anything (a test suite, a
    # server enabling the cache late) would silently keep running
    # cache-less. Reset so the new dir takes effect from the next compile.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
    except Exception:  # pragma: no cover - layout drifted on this line
        pass
    return cache_dir


# ----- strict mode: runtime enforcement of the jaxguard contract ------------
#
# tools/analyze (jaxguard) proves statically that no implicit host sync,
# donation misuse, or rank surprise sits on the hot path — for the code it
# can resolve. strict mode is the runtime side of the same contract, for
# the code it cannot: under `jax.transfer_guard("disallow")` every
# IMPLICIT host↔device transfer raises at its call site (numpy arrays or
# Python scalars silently uploaded into a jitted dispatch — exactly the
# host round-trip the overlapped serving loop exists to avoid), while
# EXPLICIT transfers (jax.device_put / jnp.asarray / jax.device_get) stay
# legal, so the sanctioned sync regions — DeviceFence retire, the
# admission host read, kv resume staging/prefetch-miss re-land, and
# arena (re)placement on mesh changes — pass through `allow_transfer()`
# hatches instead of weakening the whole guard. The same hatch feeds the
# compile/reshard tripwire below: jaxguard JG403 proves the static dual
# (every serving-reachable device_put is lexically or transitively
# inside a hatch), and `compile_tripwire` proves the runtime one.

_STRICT_ENV = "KATA_TPU_STRICT"
_strict_warned = False

# Sanction depth for the compile/reshard tripwire: >0 while the current
# thread is inside at least one `allow_transfer` region. Thread-local so
# a daemon thread's sanctioned spill never masks a serving-thread reshard.
_tw_local = threading.local()


def _allow_depth() -> int:
    return getattr(_tw_local, "allow_depth", 0)


def strict_enabled(env: Optional[dict] = None) -> bool:
    """Is the ``KATA_TPU_STRICT=1`` env gate on? Serving reads this at
    server construction (overridable per instance); the tier-1 CI job
    exports it so transfer-guard violations fail tests, not just lint."""
    src = env if env is not None else os.environ
    return str(src.get(_STRICT_ENV, "")).lower() in ("1", "true", "yes", "on")


def _strict_noop_warn(jax_mod: Any) -> None:
    global _strict_warned
    if not _strict_warned:
        _strict_warned = True
        warnings.warn(
            f"jax {getattr(jax_mod, '__version__', '?')} lacks "
            "transfer_guard — KATA_TPU_STRICT mode is a no-op on this "
            "line (needs jax >= 0.3.18)",
            RuntimeWarning,
            stacklevel=3,
        )


@contextmanager
def allow_transfer(reason: str = "", jax_mod: Any = None):
    """Escape hatch inside :func:`strict_mode`: re-allow transfers for a
    SANCTIONED synchronous region. ``reason`` documents the sanction at
    the call site (it is not recorded — the point is the code reads like
    the jaxguard pragma grammar). No-op when the guard is unsupported or
    no strict scope is active (``transfer_guard("allow")`` is the
    default level).

    Also maintains the thread-local sanction depth the
    :func:`compile_tripwire` reads: a ``device_put`` issued outside any
    ``allow_transfer`` region counts as a reshard near-miss even when
    strict mode is off — the tripwire is the guard's always-on
    observability twin."""
    del reason
    jm = jax_mod if jax_mod is not None else _jax
    guard = getattr(jm, "transfer_guard", None)
    _tw_local.allow_depth = _allow_depth() + 1
    try:
        if guard is None:
            yield
        else:
            with guard("allow"):
                yield
    finally:
        _tw_local.allow_depth = _allow_depth() - 1


def _looks_like_guard_trip(err: BaseException) -> bool:
    text = f"{type(err).__name__}: {err}"
    return "transfer" in text.lower() and (
        "disallow" in text.lower() or "guard" in text.lower()
    )


@contextmanager
def strict_mode(
    jax_mod: Any = None,
    *,
    transfer: str = "disallow",
    rank_promotion: Optional[str] = "raise",
    debug_nans: bool = False,
    scope: str = "strict",
):
    """Enforce the jaxguard contract at runtime within this scope:

    - ``jax.transfer_guard_{host_to_device,device_to_host}(transfer)`` —
      implicit host↔device transfers raise (explicit ``device_put``/
      ``device_get``/``jnp.asarray`` stay legal; see
      :func:`allow_transfer` for sanctioned regions; device→device stays
      free — see the inline comment);
    - ``jax.numpy_rank_promotion(rank_promotion)`` — silent rank
      promotion becomes an error (pass ``None`` to leave it alone);
    - ``debug_nans=True`` adds ``jax.debug_nans`` (test-suite use: a NaN
      produced under strict mode fails the test that made it).

    On a JAX line without ``transfer_guard`` the whole context is a
    warn-once no-op — old-JAX users lose enforcement, not serving.

    A guard trip emits one ``strict``/``guard_trip`` event to the obs
    sink (``scope`` names the guarded region) before the error
    propagates, so production telemetry records WHERE the contract broke
    even when the exception is swallowed upstream.

    NOTE: the rank-promotion and debug-nans configs participate in jit's
    trace context, so the first strict-scoped call of an executable
    retraces it once; steady-state cost is zero.
    """
    jm = jax_mod if jax_mod is not None else _jax
    guard = getattr(jm, "transfer_guard", None)
    if guard is None:
        _strict_noop_warn(jm)
        yield
        return
    # Guard the HOST boundary only: host→device and device→host are the
    # transfers that serialize the pipelined round (the contract JG101
    # mirrors statically). Device→device stays allowed — under tensor-
    # parallel serving, GSPMD replicates small dispatch inputs across the
    # mesh (an intra-accelerator placement move, not a host sync), and
    # disallowing it would outlaw mesh serving itself.
    h2d = getattr(jm, "transfer_guard_host_to_device", None)
    d2h = getattr(jm, "transfer_guard_device_to_host", None)
    with ExitStack() as stack:
        if h2d is not None and d2h is not None:
            stack.enter_context(h2d(transfer))
            stack.enter_context(d2h(transfer))
        else:  # pragma: no cover - pre-granular-guard line
            stack.enter_context(guard(transfer))
        rank_ctx = getattr(jm, "numpy_rank_promotion", None)
        if rank_promotion is not None and rank_ctx is not None:
            stack.enter_context(rank_ctx(rank_promotion))
        nan_ctx = getattr(jm, "debug_nans", None)
        if debug_nans and nan_ctx is not None:
            stack.enter_context(nan_ctx(True))
        try:
            yield
        except Exception as err:
            if _looks_like_guard_trip(err):
                try:
                    from .. import obs

                    obs.emit(
                        "strict", "guard_trip",
                        scope=scope,
                        error=f"{type(err).__name__}: {err}"[:300],
                    )
                except Exception:  # pragma: no cover - obs must not mask
                    pass
            raise


# ----- compile/reshard tripwire ---------------------------------------------
#
# The runtime twin of jaxguard's JG401/JG403 census: once the serving loop
# is warm, EVERY decode round must hit the executable cache (zero new XLA
# compilations) and issue zero unsanctioned explicit transfers. The census
# proves the dispatch surface is finite statically; the tripwire proves
# the process actually stays on it — a nonzero steady-state count means a
# static arg is varying per round (bucket churn, knob flip, layout flip)
# and the contract broke at runtime even though lint passed.

_compile_count = 0
_compile_listener = {"registered": False, "available": False}


def _on_event_duration(event: str, *args: Any, **kw: Any) -> None:
    # jax.monitoring fires `/jax/core/compile/backend_compile_duration`
    # exactly once per XLA backend compile and never on cache hits —
    # validated against the installed line; other duration events
    # (tracing, whole-program) pass through uncounted.
    if "backend_compile" in event:
        global _compile_count
        _compile_count += 1


def compile_counter(jax_mod: Any = None) -> int:
    """Monotonic count of XLA backend compilations in this process.

    Lazily registers a ``jax.monitoring`` duration listener on first call
    (so merely importing this module never touches jax internals). On a
    line without ``jax.monitoring`` the counter degrades to a constant 0:
    the tripwire then cannot see compiles, only reshards — callers treat
    0 as "clean or unobservable", never as proof.
    """
    jm = jax_mod if jax_mod is not None else _jax
    if not _compile_listener["registered"]:
        _compile_listener["registered"] = True
        mon = getattr(jm, "monitoring", None)
        reg = getattr(
            mon, "register_event_duration_secs_listener", None
        )
        if reg is not None:
            try:
                reg(_on_event_duration)
                _compile_listener["available"] = True
            except Exception:  # pragma: no cover - exotic jax lines
                pass
    return _compile_count


class TripwireCounts:
    """Result of one :func:`compile_tripwire` scope. ``compiles`` and
    ``transfers`` are finalized when the context exits; ``armed`` records
    whether the compile side could observe anything at all."""

    __slots__ = ("compiles", "transfers", "armed")

    def __init__(self) -> None:
        self.compiles = 0
        self.transfers = 0
        self.armed = False

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"TripwireCounts(compiles={self.compiles}, "
            f"transfers={self.transfers}, armed={self.armed})"
        )


@contextmanager
def compile_tripwire(jax_mod: Any = None, enabled: bool = True):
    """Count XLA compilations and unsanctioned explicit transfers within
    this scope.

    Yields a :class:`TripwireCounts`; on exit ``counts.compiles`` is the
    number of backend compiles the scope triggered and
    ``counts.transfers`` the number of ``jax.device_put`` calls issued
    outside any :func:`allow_transfer` region (reshard near-misses — the
    transfer guard only trips IMPLICIT transfers, so an explicit
    ``device_put`` snuck into the decode round would otherwise sail
    through strict mode silently).

    With ``enabled=False`` the scope is a zero-overhead no-op that still
    yields a counts object — callers never branch on the knob.
    """
    counts = TripwireCounts()
    if not enabled:
        yield counts
        return
    jm = jax_mod if jax_mod is not None else _jax
    start = compile_counter(jm)
    counts.armed = _compile_listener["available"]
    orig_put = getattr(jm, "device_put", None)
    patched = False
    if orig_put is not None:
        def _counting_put(*args: Any, **kw: Any):
            if _allow_depth() == 0:
                # Count LEXICAL device_put calls only — the runtime
                # mirror of jaxguard JG403, which flags `device_put`
                # leaves but never `jnp.asarray`. On current lines
                # jnp.asarray routes through jax.device_put internally,
                # so a caller inside jax's own modules is the sanctioned
                # explicit-upload path (round-boundary token/pos
                # uploads), not a reshard near-miss.
                caller = sys._getframe(1).f_globals.get("__name__", "")
                if not caller.startswith("jax"):
                    counts.transfers += 1
            return orig_put(*args, **kw)

        try:
            jm.device_put = _counting_put
            patched = True
        except Exception:  # pragma: no cover - frozen module surface
            pass
    try:
        yield counts
    finally:
        counts.compiles = compile_counter(jm) - start
        if patched:
            jm.device_put = orig_put


# ----- tree utilities -------------------------------------------------------


def resolve_tree_utils(jax_mod: Any) -> dict[str, Callable]:
    """``jax.tree.map`` and friends (0.4.26+) with a ``jax.tree_util``
    fallback; ``tree_map_with_path`` lives in ``jax.tree_util`` on every
    supported line."""
    tree = getattr(jax_mod, "tree", None)
    tu = getattr(jax_mod, "tree_util", None)
    out: dict[str, Callable] = {}
    for short, tu_name in (
        ("map", "tree_map"),
        ("leaves", "tree_leaves"),
        ("flatten", "tree_flatten"),
        ("unflatten", "tree_unflatten"),
    ):
        fn = getattr(tree, short, None) if tree is not None else None
        if fn is None:
            fn = getattr(tu, tu_name, None) if tu is not None else None
        if fn is None:
            raise JaxCompatError(
                f"tree_{short}",
                f"neither jax.tree.{short} nor jax.tree_util.{tu_name} exists",
                min_version="0.4.26",
            )
        out[f"tree_{short}"] = fn
    with_path = getattr(tu, "tree_map_with_path", None) if tu is not None else None
    if with_path is None:
        raise JaxCompatError(
            "tree_map_with_path",
            "jax.tree_util.tree_map_with_path missing",
            min_version="0.4.26",
        )
    out["tree_map_with_path"] = with_path
    return out


# ----- module-level exports (resolved once against the installed jax) -------

import jax as _jax  # noqa: E402

JAX_VERSION: tuple[int, int, int] = parse_version(_jax.__version__)

_raw_shard_map, SHARD_MAP_STYLE = resolve_shard_map(_jax)
shard_map = build_shard_map(_raw_shard_map, SHARD_MAP_STYLE)
AxisType = resolve_axis_type(_jax)
Mesh, NamedSharding, PartitionSpec = resolve_sharding_types(_jax)
P = PartitionSpec
make_mesh = build_make_mesh(_jax, AxisType)
pvary = resolve_pvary(_jax)
axis_size = resolve_axis_size(_jax)
normalize_rng_config(_jax)

_tree = resolve_tree_utils(_jax)
tree_map = _tree["tree_map"]
tree_leaves = _tree["tree_leaves"]
tree_flatten = _tree["tree_flatten"]
tree_unflatten = _tree["tree_unflatten"]
tree_map_with_path = _tree["tree_map_with_path"]

__all__ = [
    "JAX_VERSION",
    "SHARD_MAP_STYLE",
    "AxisType",
    "JaxCompatError",
    "Mesh",
    "NamedSharding",
    "P",
    "PartitionSpec",
    "TripwireCounts",
    "allow_transfer",
    "axis_size",
    "compile_counter",
    "compile_tripwire",
    "strict_enabled",
    "strict_mode",
    "build_make_mesh",
    "build_shard_map",
    "enable_compilation_cache",
    "enable_cpu_multiprocess_collectives",
    "make_mesh",
    "normalize_rng_config",
    "pallas_tpu_compiler_params",
    "parse_version",
    "pvary",
    "resolve_axis_size",
    "resolve_axis_type",
    "resolve_pallas_compiler_params",
    "resolve_pvary",
    "resolve_shard_map",
    "resolve_sharding_types",
    "resolve_tree_utils",
    "shard_map",
    "tree_flatten",
    "tree_leaves",
    "tree_map",
    "tree_map_with_path",
    "tree_unflatten",
]
