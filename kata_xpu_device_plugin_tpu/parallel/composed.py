"""Composed parallelism: pipeline × FSDP × tensor parallelism on ONE mesh.

The BASELINE configs[4] workload (Llama-3-8B on v5p-16) needs all three axes
on the same device set — not the per-axis private meshes the standalone
modules use for their unit semantics. TPU-first composition: the pipeline
axis is *manual* (``shard_map`` over ``pipe`` only: the GPipe schedule is a
``lax.fori_loop`` of compute + ``ppermute`` neighbor hops riding ICI), while
``fsdp``/``model`` stay *automatic* — inside each stage, XLA GSPMD inserts
the all-gathers/reduce-scatters for the FSDP-sharded, tensor-parallel layer
compute exactly as in the unpipelined train step. One mesh, three axes, no
hand-written collectives except the pipeline's own neighbor exchange.

Memory honesty (VERDICT r2): microbatches are sharded over ``pipe`` — each
stage holds M/P microbatches of tokens, embeds its own block, and routes the
activation to stage 0 for its tick (one extra [mb, S, D] hop); stage P-1
routes each finished activation back to the owning stage, which unembeds and
accumulates loss locally. No stage ever materializes all M microbatches of
activations or the replicated [M, mb, S, vocab] logits.

Reference context: the reference's only composition concept is co-allocating
an IOMMU group (device_plugin.go:31,157-175); the parallelism stack itself is
absent (SURVEY §2) and this module is part of the TPU-native capability the
survey's equivalence table demands.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..compat.jaxapi import (
    SHARD_MAP_STYLE,
    AxisType,
    Mesh,
    NamedSharding,
    P,
    make_mesh,
    shard_map,
    tree_map,
    tree_map_with_path,
)
from ..models import transformer as tfm
from .mesh import AXIS_FSDP, AXIS_MODEL
from .pipeline import AXIS_PIPE, _pvary, transformer_stage_fn
from .sharding import PARAM_RULES, make_optimizer


def composed_mesh(
    pipe: int,
    fsdp: int,
    model: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A (pipe, fsdp, model) mesh whose axes are typed Auto so shard_map can
    take ``pipe`` manual while GSPMD keeps handling fsdp/model inside."""
    devices = list(devices if devices is not None else jax.devices())
    n = pipe * fsdp * model
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    return make_mesh(
        (pipe, fsdp, model),
        (AXIS_PIPE, AXIS_FSDP, AXIS_MODEL),
        axis_types=(AxisType.Auto,) * 3,
        devices=devices[:n],
    )


def pp_param_spec(path: str) -> P:
    """Sharding for the stage-major param layout: layer-stacked arrays gain a
    leading ``pipe``-sharded stage axis in front of their PARAM_RULES spec;
    embed/norms keep their rules (replicated over pipe)."""
    rule = PARAM_RULES[path]
    if path.startswith("layers."):
        return P(AXIS_PIPE, *rule)
    return rule


def to_pp_params(params: Any, n_stages: int) -> Any:
    """[L, ...]-stacked layers → [P, L/P, ...] stage-major (a pure reshape:
    stage s holds contiguous layers [s*L/P, (s+1)*L/P))."""
    out = dict(params)
    out["layers"] = tree_map(
        lambda a: a.reshape((n_stages, a.shape[0] // n_stages) + a.shape[1:]),
        params["layers"],
    )
    return out


def pp_param_shardings(params_pp: Any, mesh: Mesh) -> Any:
    from .sharding import _tree_paths

    def spec(path):
        # paths are on the pp tree; the rule table is keyed by the flat tree.
        return NamedSharding(mesh, pp_param_spec(path))

    return tree_map(spec, _tree_paths(params_pp))


def init_pp_params(
    key: jax.Array, cfg: tfm.DecoderConfig, mesh: Mesh, n_stages: int,
    dtype=jnp.float32,
) -> Any:
    """Initialize directly into the stage-major sharded layout."""
    shardings = pp_param_shardings(
        jax.eval_shape(lambda: to_pp_params(tfm.init_params(key, cfg, dtype), n_stages)),
        mesh,
    )
    init = jax.jit(
        lambda k: to_pp_params(tfm.init_params(k, cfg, dtype), n_stages),
        out_shardings=shardings,
    )
    return init(key)


MICROBATCH_SPEC = P(AXIS_PIPE)  # tokens [M, mb, S]: stage s owns block s


def make_pp_loss(
    cfg: tfm.DecoderConfig,
    mesh: Mesh,
    n_stages: int,
    num_microbatches: int,
    attn_fn: Optional[Callable] = None,
):
    """Returns ``loss_fn(params_pp, tokens) -> scalar`` where ``tokens`` is
    [M, mb, S] sharded ``P('pipe')`` on M. Equals
    :func:`..models.transformer.next_token_loss` on the flattened batch."""
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by {n_stages}")
    if cfg.attn_windows or cfg.rope_theta_cycle or cfg.rope_linear_cycle:
        raise ValueError(
            "pipeline stages apply one uniform attention window and rope; "
            "per-layer attn_windows / rope cycles (Gemma-2/3 style) are "
            "not supported here"
        )
    if num_microbatches % n_stages:
        raise ValueError(
            f"num_microbatches={num_microbatches} not divisible by {n_stages} "
            "(each stage owns an equal block)"
        )
    m_local = num_microbatches // n_stages
    total_ticks = num_microbatches + n_stages - 1
    stage_fn = transformer_stage_fn(cfg, attn_fn)

    # Partial-auto (pipe manual, fsdp/model left to GSPMD) is the production
    # shape, but the 0.4.x SPMD partitioner cannot compile this body's
    # manual-subgroup program (CHECK failure on IsManualSubgroup). Fallback
    # there: fully-manual over ALL axes — each (fsdp, model) group member
    # replicates its stage's compute — with the final psum taken over every
    # axis and divided by the replica count. Forward value is identical;
    # gradients stay exact because the P()-input transpose psums cotangents
    # over all axes, cancelling the 1/replicas normalization.
    partial_auto = SHARD_MAP_STYLE == "stable"
    if partial_auto:
        manual_axes, reduce_axes, replicas = {AXIS_PIPE}, AXIS_PIPE, 1
    else:
        reduce_axes = tuple(mesh.axis_names)
        replicas = 1
        for a in mesh.axis_names:
            if a != AXIS_PIPE:
                replicas *= mesh.shape[a]
        manual_axes = None

    def per_stage(
        stage_ids: jax.Array, layers_blk: Any, flat_params: Any,
        tokens_blk: jax.Array,
    ):
        # layers_blk [1, L/P, ...] manual over pipe; flat_params (embed,
        # norms, optional unembed) auto-sharded over fsdp/model; tokens_blk
        # [M/P, mb, S] this stage's microbatch block. stage_ids is a
        # pipe-sharded iota: stage_ids[0] == this stage's index. Using it
        # instead of lax.axis_index keeps the partial-auto body free of the
        # PartitionId op, which 0.4.x GSPMD cannot re-partition (newer JAX
        # handles either spelling).
        stage = stage_ids[0]
        own_layers = tree_map(lambda p: p[0], layers_blk)
        mb, S = tokens_blk.shape[1], tokens_blk.shape[2]
        d = cfg.d_model

        fwd = [(s, 0) for s in range(n_stages)]  # owner → stage 0 (ingest)
        ring = [(s, (s + 1) % n_stages) for s in range(n_stages)]
        back = [(n_stages - 1, s) for s in range(n_stages)]  # egress → owner

        def ingest(t):
            """Owner stage embeds its local microbatch for tick t and routes
            it to stage 0 (zeros elsewhere — ppermute's non-destination)."""
            tt = jnp.clip(t, 0, num_microbatches - 1)
            owner, slot = tt // m_local, tt % m_local
            toks = lax.dynamic_index_in_dim(tokens_blk, slot, 0, keepdims=False)
            # x inherits device-variance over pipe from tokens_blk.
            x = tfm.embed({"embed": flat_params["embed"]}, toks[:, :-1], cfg)
            return lax.switch(
                owner,
                [partial(lambda s, v: lax.ppermute(v, AXIS_PIPE, [fwd[s]]), s)
                 for s in range(n_stages)],
                x,
            )

        def egress(y, t):
            """Route stage P-1's finished activation back to the microbatch's
            owner stage."""
            out_t = jnp.clip(t - (n_stages - 1), 0, num_microbatches - 1)
            owner = out_t // m_local
            return lax.switch(
                owner,
                [partial(lambda s, v: lax.ppermute(v, AXIS_PIPE, [back[s]]), s)
                 for s in range(n_stages)],
                y,
            )

        def tick(t, carry):
            state, outputs = carry
            x_in = ingest(t)
            x = jnp.where(stage == 0, x_in, state)
            y = stage_fn(own_layers, x)
            y_out = egress(y, t)
            out_t = t - (n_stages - 1)
            safe = jnp.clip(out_t, 0, num_microbatches - 1)
            is_mine = jnp.logical_and(out_t >= 0, safe // m_local == stage)
            slot = safe % m_local
            prev = lax.dynamic_index_in_dim(outputs, slot, 0, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(is_mine, y_out, prev), slot, 0
            )
            state = lax.ppermute(y, AXIS_PIPE, ring)
            return state, outputs

        init = tree_map(
            lambda z: _pvary(z, AXIS_PIPE),
            (
                jnp.zeros((mb, S - 1, d), cfg.dtype),
                jnp.zeros((m_local, mb, S - 1, d), cfg.dtype),
            ),
        )
        _, outputs = lax.fori_loop(0, total_ticks, tick, init)

        # Owner-local unembed + loss over this stage's microbatch block.
        logits = tfm.unembed(flat_params, outputs, cfg)  # [M/P, mb, S-1, V]
        nll = tfm.token_nll_sum(logits, tokens_blk[:, :, 1:])
        return lax.psum(nll, reduce_axes) / replicas

    mapped = shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(P(AXIS_PIPE), P(AXIS_PIPE), P(), MICROBATCH_SPEC),
        out_specs=P(),
        axis_names=manual_axes,
        check_vma=None if partial_auto else False,
    )

    def loss_fn(params_pp: Any, tokens: jax.Array) -> jax.Array:
        flat = {k: v for k, v in params_pp.items() if k != "layers"}
        stage_ids = jnp.arange(n_stages, dtype=jnp.int32)
        total = mapped(stage_ids, params_pp["layers"], flat, tokens)
        M, mb, S = tokens.shape
        return total / (M * mb * (S - 1))

    return loss_fn


def make_pp_train_step(
    cfg: tfm.DecoderConfig,
    mesh: Mesh,
    n_stages: int,
    num_microbatches: int,
    optimizer: Optional[optax.GradientTransformation] = None,
    attn_fn: Optional[Callable] = None,
):
    """The composed pp×fsdp×tp training step: ``step(state, tokens[M, mb, S])
    -> (state, loss)``. Gradients flow back through the pipeline's ppermutes
    (their transpose is the reverse permute); GSPMD turns the fsdp-sharded
    param gradients into reduce-scatters exactly as in the unpipelined step."""
    optimizer = optimizer or make_optimizer()
    loss_fn = make_pp_loss(cfg, mesh, n_stages, num_microbatches, attn_fn)

    def init_state(key: jax.Array):
        params = init_pp_params(key, cfg, mesh, n_stages)
        opt_shardings = _pp_opt_shardings(optimizer, params, mesh)
        opt_state = jax.jit(optimizer.init, out_shardings=opt_shardings)(params)
        step_counter = jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        )
        return {"params": params, "opt": opt_state, "step": step_counter}

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], tokens)
        updates, new_opt = optimizer.update(grads, state["opt"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    return init_state, step


def _pp_opt_shardings(optimizer, params_pp, mesh):
    """Optimizer leaves mirror the stage-major param shardings; scalar leaves
    replicate (same longest-suffix match as the unpipelined step)."""
    replicated = NamedSharding(mesh, P())

    def leaf_sharding(path, _leaf):
        names = [p.key for p in path if hasattr(p, "key")]
        for n in range(len(names), 0, -1):
            cand = ".".join(names[-n:])
            if cand in PARAM_RULES:
                return NamedSharding(mesh, pp_param_spec(cand))
        return replicated

    return tree_map_with_path(
        leaf_sharding, jax.eval_shape(optimizer.init, params_pp)
    )


def shard_microbatches(tokens: jax.Array, mesh: Mesh) -> jax.Array:
    """Place [M, mb, S] tokens so stage s owns microbatch block s."""
    return jax.device_put(tokens, NamedSharding(mesh, MICROBATCH_SPEC))
