"""Sharding rules and the sharded training/inference steps.

GSPMD style: parameters and batches get NamedShardings from the rules below;
XLA inserts the collectives (all-gather for fsdp params, reduce-scatter for
grads, all-to-all/psum for tensor-parallel matmuls). No hand-written
collective calls in the train step — that is the TPU-native shape of the
reference's "distributed backend" capability (SURVEY §5: collectives ride
ICI via XLA, not an NCCL port).
"""
from __future__ import annotations

import re
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..compat.jaxapi import (
    Mesh,
    NamedSharding,
    P,
    tree_map,
    tree_map_with_path,
)
from ..models import transformer as tfm
from .mesh import AXIS_DATA, AXIS_FSDP, AXIS_MODEL, AXIS_SEQ

# Parameter PartitionSpecs by param-tree path suffix. Layer-stacked arrays
# carry a leading (layer) axis that is never sharded. Rationale:
# - attention/MLP "wide" matrices shard their wide dim over model (tp) and
#   their d_model dim over fsdp;
# - embed shards vocab over model, d_model over fsdp (logits psum over model);
# - norms are tiny → replicated.
PARAM_RULES: dict[str, P] = {
    "embed": P(AXIS_MODEL, AXIS_FSDP),
    "unembed": P(AXIS_FSDP, AXIS_MODEL),
    "layers.attn_norm": P(None, None),
    "layers.mlp_norm": P(None, None),
    "layers.post_attn_norm": P(None, None),  # Gemma-2 post-sublayer norms
    "layers.post_mlp_norm": P(None, None),
    "layers.q_norm": P(None, None),  # Gemma-3 per-head QK-norms (tiny)
    "layers.k_norm": P(None, None),
    "layers.wq": P(None, AXIS_FSDP, AXIS_MODEL),
    "layers.wk": P(None, AXIS_FSDP, AXIS_MODEL),
    "layers.wv": P(None, AXIS_FSDP, AXIS_MODEL),
    # Qwen2 q/k/v biases: shard the out axis exactly like their matrices
    # so the post-matmul add needs no resharding (GSPMD splits the
    # concatenated fused-bias axis at arbitrary boundaries, like wqkv).
    "layers.bq": P(None, AXIS_MODEL),
    "layers.bk": P(None, AXIS_MODEL),
    "layers.bv": P(None, AXIS_MODEL),
    "layers.bqkv": P(None, AXIS_MODEL),
    "layers.wo": P(None, AXIS_MODEL, AXIS_FSDP),
    "layers.w_gate": P(None, AXIS_FSDP, AXIS_MODEL),
    "layers.w_up": P(None, AXIS_FSDP, AXIS_MODEL),
    "layers.w_down": P(None, AXIS_MODEL, AXIS_FSDP),
    # Fused inference layout (transformer.fuse_decoder_params): the
    # concatenated wide axis shards over model exactly like its parts —
    # GSPMD splits a concatenated axis at arbitrary boundaries without
    # changing values, so fused tensor-parallel serving stays exact.
    "layers.wqkv": P(None, AXIS_FSDP, AXIS_MODEL),
    "layers.w_gateup": P(None, AXIS_FSDP, AXIS_MODEL),
    # MoE layers: experts shard over the model axis (ep replaces tp in the
    # FFN — ops.moe.expert_axis_for), d_model over fsdp; the tiny router is
    # replicated on the expert dim.
    "layers.router": P(None, AXIS_FSDP, None),
    "layers.moe_w_gate": P(None, AXIS_MODEL, AXIS_FSDP, None),
    "layers.moe_w_in": P(None, AXIS_MODEL, AXIS_FSDP, None),
    "layers.moe_w_out": P(None, AXIS_MODEL, None, AXIS_FSDP),
    "final_norm": P(None),
}

BATCH_SPEC = P((AXIS_DATA, AXIS_FSDP), None)  # [batch, seq]


def _seq_size(mesh: Mesh) -> int:
    return mesh.shape.get(AXIS_SEQ, 1) if AXIS_SEQ in mesh.axis_names else 1


def batch_spec(mesh: Mesh) -> P:
    """Token-batch PartitionSpec for this mesh: batch over the data axes,
    and — when the mesh carries a seq axis — the sequence dim over seq, so
    long-context activations are sharded from the embedding onward."""
    return P(
        (AXIS_DATA, AXIS_FSDP), AXIS_SEQ if _seq_size(mesh) > 1 else None
    )


def param_spec(path: str) -> P:
    if path in PARAM_RULES:
        return PARAM_RULES[path]
    raise KeyError(f"no sharding rule for param {path!r}")


def _tree_paths(params: Any, prefix: str = "") -> Any:
    if isinstance(params, dict):
        return {k: _tree_paths(v, f"{prefix}.{k}" if prefix else k) for k, v in params.items()}
    return prefix


def _layout_spec(rule: P, value: Any) -> Any:
    """Expand a weight's PartitionSpec to match its serving layout.

    The inference layouts wrap raw weights in pytree NamedTuples
    (``ops.quant.QTensor``, ``ops.lora.LoRAWeight``); each inner leaf gets
    the spec implied by the weight rule ``[..., in, out]``:

    - QTensor: ``q`` keeps the full rule; ``scale [..., 1, out]`` shards the
      out axis identically (its reduced in-axis stays unsharded), so the
      post-dot scale multiply needs no resharding;
    - LoRAWeight: ``base`` recurses (QLoRA bases are QTensors), ``a [..,
      in, r]`` keeps the in-axis sharding, ``b [.., r, out]`` the out-axis —
      the tiny rank axis replicates, so ``x@a@b`` inserts no collectives
      beyond the base matmul's own.
    """
    from ..ops.lora import LoRAWeight
    from ..ops.quant import QTensor

    if isinstance(value, LoRAWeight):
        lead = tuple(rule)[:-2]
        return LoRAWeight(
            base=_layout_spec(rule, value.base),
            a=P(*lead, tuple(rule)[-2], None),
            b=P(*lead, None, tuple(rule)[-1]),
            scale=P(*lead),
        )
    if isinstance(value, QTensor):
        return QTensor(q=rule, scale=P(*tuple(rule)[:-2], None, tuple(rule)[-1]))
    return rule


def param_specs(params: Any) -> Any:
    """PartitionSpec pytree matching ``params``' structure — training layout
    and the inference layouts (fused wqkv/w_gateup, int8 QTensor, LoRA)."""

    def node(value: Any, path: str) -> Any:
        if isinstance(value, dict):
            return {
                k: node(v, f"{path}.{k}" if path else k) for k, v in value.items()
            }
        return _layout_spec(param_spec(path), value)

    return node(params, "")


# ----- tensor-parallel SERVING rules (ISSUE 9) ------------------------------
#
# Regex → PartitionSpec rules for the in-guest serving mesh (a 1×N slice:
# data=fsdp=1, model=tp — guest.tp_serving.serving_mesh). Distinct from
# PARAM_RULES, which is the TRAINING layout: serving replicates the
# embedding table (decode reads one row per token — sharding vocab would
# turn every embed lookup and every unembed matmul into a collective on
# the latency-critical decode step; at serving batch sizes the replicated
# table is the cheaper trade) and keeps the classic Megatron column/row
# split for the per-layer weights, so each decode layer inserts exactly
# one psum (after wo, after w_down) and no resharding in between.
#
# Matching is `re.search` over the dotted param path, first rule wins —
# the `match_partition_rules` pytree-regex pattern. The rules below cover
# every family in models/ (Gemma/Gemma-2/Gemma-3 post-norms + qk_norm,
# Llama-3, Mistral, Qwen2 qkv biases, Mixtral MoE) in the training layout
# AND the inference layouts: fused wqkv/w_gateup concatenate their parts'
# out axes, which GSPMD splits at arbitrary boundaries without changing
# values; int8 QTensors and LoRA adapters expand through `_layout_spec`
# exactly as in the training rules.
SERVING_RULES: tuple[tuple[str, P], ...] = (
    # Norms and the tiny per-head QK-norms replicate (covers attn_norm,
    # mlp_norm, post_attn_norm, post_mlp_norm, q_norm, k_norm, final_norm).
    (r"norm$", P(None)),
    # Embeddings REPLICATED (see the header note); the tied/untied
    # unembedding reads the same table, so logits need no psum.
    (r"^(embed|unembed)$", P(None, None)),
    # Attention: column-parallel q/k/v (+ fused wqkv, + Qwen2 biases along
    # the same out axis), row-parallel output projection.
    (r"layers\.(wq|wk|wv|wqkv)$", P(None, None, AXIS_MODEL)),
    (r"layers\.(bq|bk|bv|bqkv)$", P(None, AXIS_MODEL)),
    (r"layers\.wo$", P(None, AXIS_MODEL, None)),
    # MLP: column-parallel gate/up (+ fused w_gateup), row-parallel down.
    (r"layers\.(w_gate|w_up|w_gateup)$", P(None, None, AXIS_MODEL)),
    (r"layers\.w_down$", P(None, AXIS_MODEL, None)),
    # MoE: experts over the model axis (ep replaces tp in the FFN); the
    # tiny router replicates.
    (r"layers\.router$", P(None, None, None)),
    (r"layers\.moe_w_(gate|in|out)$", P(None, AXIS_MODEL, None, None)),
)


def decode_attn_specs(cfg, tp: int, quantized: bool,
                      kv_layout: str = "heads"):
    """``shard_map`` PartitionSpecs for the paged-native decode kernel
    (ISSUE 12): ``(q_spec, kv_spec, out_spec)`` over the serving mesh's
    ``model`` axis. A pallas call has no SPMD partitioning rule (the
    SNIPPETS [1] lesson: the XLA path shards automatically, a custom call
    needs explicit specs), so the serving dispatch wraps the kernel in
    ``shard_map`` with these.

    The divide-or-replicate decision IS
    ``guest.tp_serving.kv_heads_shardable`` (the ONE predicate every KV
    placement routes through — n_kv_heads must divide tp or the GQA group
    structure breaks; imported at call time so the layouts cannot
    drift): when it divides, q ``[B, 1, H, D]`` and the pool
    slice ``[1, NT, KV, D]`` both shard their head axis (position 2) over
    ``model`` — each shard runs the kernel on its own KV groups, no
    collectives. When it does not (the kv-replicated layout), every spec
    replicates: each device runs the full kernel on the full operands —
    correct, memory-heavier, exactly the dense arena's replication trade.
    int8 ``QTensor`` pools expand leaf-wise (payload and per-vector scale
    share the head axis), like :func:`_layout_spec` everywhere else.

    Under the BLOCKS layout (ISSUE 14) the pool slice ``[1, NT, KV, D]``
    shards its TOKEN axis (position 1) over ``model`` — every shard
    holds its own physical blocks, whatever the model's KV head count —
    while q and the output replicate: each shard runs the kernel over
    ONLY its local blocks (shard-local DMA, ownership-masked splits) and
    cross-shard lanes combine through the same online-softmax split-K
    merge the kernel already carries across splits (the merge is
    associative — see ``ops.attention.make_decode_attn_fn``)."""
    from ..guest.tp_serving import KV_LAYOUT_BLOCKS, kv_heads_shardable
    from ..ops.quant import QTensor

    if kv_layout == KV_LAYOUT_BLOCKS:
        rep = P(None, None, None, None)
        tok = P(None, AXIS_MODEL, None, None)
        kv = QTensor(q=tok, scale=tok) if quantized else tok
        return rep, kv, rep
    if kv_heads_shardable(cfg, tp):
        head = P(None, None, AXIS_MODEL, None)
    else:
        head = P(None, None, None, None)
    kv = QTensor(q=head, scale=head) if quantized else head
    return head, kv, head


def match_partition_rules(rules, params: Any) -> Any:
    """PartitionSpec pytree for ``params`` from ``(regex, spec)`` rules.

    The regex-pytree pattern: each leaf's dotted path (``layers.wqkv``) is
    matched with ``re.search`` against the rules in order, first match
    wins; scalar / single-element leaves replicate unconditionally; a
    path no rule covers raises (a silently replicated 7B weight matrix
    would defeat the point of the mesh). Inference wrappers (int8
    ``QTensor``, ``LoRAWeight``) expand through the same
    :func:`_layout_spec` as the training rules, so one rule per WEIGHT
    covers every serving layout of it."""

    def spec_for(path: str, value: Any) -> P:
        shape = getattr(value, "shape", None)
        if shape is not None and (len(shape) == 0 or int(np.prod(shape)) == 1):
            return P()
        for pattern, spec in rules:
            if re.search(pattern, path):
                return spec
        raise ValueError(f"no serving partition rule matches param {path!r}")

    def node(value: Any, path: str) -> Any:
        if isinstance(value, dict):
            return {
                k: node(v, f"{path}.{k}" if path else k)
                for k, v in value.items()
            }
        return _layout_spec(spec_for(path, value), value)

    return node(params, "")


def serving_param_specs(params: Any) -> Any:
    """:data:`SERVING_RULES` applied to ``params`` (any serving layout)."""
    return match_partition_rules(SERVING_RULES, params)


def shard_serving_params(params: Any, mesh: Mesh) -> Any:
    """Place a param tree onto the serving mesh by :data:`SERVING_RULES`
    (embeddings replicated, attention/MLP column/row over ``model``)."""
    shardings = tree_map(
        lambda spec: NamedSharding(mesh, spec), serving_param_specs(params)
    )
    return jax.device_put(params, shardings)


def host_param_copy(params: Any) -> Any:
    """A full HOST copy of a (possibly sharded) param tree — the donor
    copy elastic mesh-shrink recovery (ISSUE 10) re-shards from after a
    chip loss: the dead chip's parameter shards are unrecoverable, so the
    degraded mesh must be fed from state that never lived on the device.
    One deliberate device→host gather per leaf at construction time (off
    every hot path); costs host RAM equal to the param bytes."""
    return jax.tree.map(np.asarray, params)


def param_shardings(params: Any, mesh: Mesh) -> Any:
    return tree_map(
        lambda spec: NamedSharding(mesh, spec), param_specs(params)
    )


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place a (host or single-device) param tree onto the mesh."""
    return jax.device_put(params, param_shardings(params, mesh))


def init_sharded_params(
    key: jax.Array, cfg: tfm.DecoderConfig, mesh: Mesh, dtype=jnp.float32
) -> Any:
    """Initialize directly into the sharded layout (never materializes the
    full model on one device — required at Llama-3-8B scale)."""
    shardings = param_shardings(
        jax.eval_shape(lambda: tfm.init_params(key, cfg, dtype)), mesh
    )
    init = jax.jit(
        lambda k: tfm.init_params(k, cfg, dtype), out_shardings=shardings
    )
    return init(key)


# ----- training ------------------------------------------------------------


def make_optimizer(
    lr: float = 3e-4,
    weight_decay: float = 0.01,
    warmup_steps: int = 0,
    total_steps: int = 0,
    min_lr_ratio: float = 0.1,
    grad_clip: float = 0.0,
) -> optax.GradientTransformation:
    """AdamW with the standard LLM-training extras, all opt-in:

    - ``total_steps > 0``: linear warmup over ``warmup_steps`` then cosine
      decay to ``lr · min_lr_ratio`` at ``total_steps`` (the Llama/Gemma
      recipe); otherwise constant ``lr``.
    - ``grad_clip > 0``: global-norm gradient clipping BEFORE the Adam
      update (sharded grads: optax's global norm is a psum XLA inserts —
      no host round-trip).
    """
    if total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=warmup_steps,
            decay_steps=total_steps,
            end_value=lr * min_lr_ratio,
        )
    else:
        schedule = lr
    tx = optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay)
    if grad_clip > 0.0:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
    return tx


def make_train_step(
    cfg: tfm.DecoderConfig,
    mesh: Mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    attn_fn: Optional[Callable] = None,
    remat: bool = False,
    accum_steps: int = 1,
    aux_metrics: bool = False,
):
    """Returns (init_state, step). ``step(state, tokens) -> (state, loss)``,
    jitted over the mesh with donated state. ``aux_metrics=True`` changes
    the step contract to ``(state, loss, aux)`` with
    ``aux = {"grad_norm": global_grad_norm}`` — the shape
    :func:`.trainer.fit`'s telemetry consumes (ISSUE 2); the norm is one
    extra fused reduction inside the same executable, negligible next to
    the backward pass.

    ``attn_fn`` defaults by mesh: on a mesh with a ``seq`` axis, ring
    attention over that axis (shard_map composes with the surrounding GSPMD
    step: batch stays on the data axes, heads on the model axis when they
    divide, and only the ring's ppermute moves K/V between seq neighbors),
    so long-context training (BASELINE configs[4]) runs as ONE program with
    fsdp/tp. On non-seq meshes ON TPU, the differentiable pallas flash
    kernel wrapped in shard_map over the same batch/head axes
    (``.flash_spmd.make_sharded_attention``) — a pallas custom call has no
    SPMD partitioning rule, so the shard_map is what lets the kernel
    partition instead of replicating; per-local-block eligibility still
    falls back to the XLA reference for unsupported shapes. Elsewhere
    (CPU test meshes), the XLA reference.

    ``accum_steps > 1``: gradient accumulation — ``tokens
    [accum_steps·B, S]`` is split into ``accum_steps`` microbatches, a
    ``lax.scan`` accumulates their mean gradients (one live microbatch
    of activations at a time — activation memory drops ~accum_steps×),
    and ONE optimizer update applies the mean. For dense configs the
    result equals the full-batch step exactly (mean of equal-sized
    microbatch means); MoE capacity dispatch makes it approximate, like
    every other batch-size change. The caller keeps each microbatch
    divisible by the mesh's batch axes."""
    optimizer = optimizer or make_optimizer()
    tp = mesh.shape.get(AXIS_MODEL, 1)
    # Shard the head dims over model only when BOTH divide: splitting q
    # heads without their KV heads (or vice versa) would break the GQA
    # group structure inside each shard.
    heads_divide = (
        tp > 1 and cfg.n_heads % tp == 0 and cfg.n_kv_heads % tp == 0
    )
    if attn_fn is None and _seq_size(mesh) > 1:
        from .ring import make_ring_attention

        attn_fn = make_ring_attention(
            mesh,
            axis=AXIS_SEQ,
            batch_axes=(AXIS_DATA, AXIS_FSDP),
            head_axis=AXIS_MODEL if heads_divide else None,
            kv_head_axis=AXIS_MODEL if heads_divide else None,
        )
    elif attn_fn is None:
        from ..ops.attention import on_tpu

        if on_tpu():
            from .flash_spmd import make_sharded_attention

            attn_fn = make_sharded_attention(
                mesh,
                batch_axes=(AXIS_DATA, AXIS_FSDP),
                head_axis=AXIS_MODEL if heads_divide else None,
                kv_head_axis=AXIS_MODEL if heads_divide else None,
            )

    def init_state(key: jax.Array):
        params = init_sharded_params(key, cfg, mesh)
        opt_state = jax.jit(
            optimizer.init, out_shardings=_opt_shardings(optimizer, params, mesh)
        )(params)
        # The step counter is mesh-replicated like the scalar opt leaves so
        # the WHOLE state tree has committed mesh shardings — a restored
        # checkpoint then re-places every leaf identically instead of mixing
        # single-device and mesh-committed arrays (which jit rejects).
        step_counter = jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        )
        return {"params": params, "opt": opt_state, "step": step_counter}

    def loss_fn(params, tokens):
        return tfm.next_token_loss(
            params, tokens, cfg, attn_fn=attn_fn,
            moe_mesh=mesh if cfg.moe else None, remat=remat,
        )

    from functools import partial

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, tokens):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(state["params"], tokens)
        else:
            B = tokens.shape[0]
            if B % accum_steps:
                raise ValueError(
                    f"batch {B} not divisible by accum_steps={accum_steps}"
                )
            micros = tokens.reshape(accum_steps, B // accum_steps,
                                    tokens.shape[1])

            def micro(carry, mb):
                g_sum, l_sum = carry
                l, g = jax.value_and_grad(loss_fn)(state["params"], mb)
                return (tree_map(jnp.add, g_sum, g), l_sum + l), None

            zeros = tree_map(jnp.zeros_like, state["params"])
            (g_sum, l_sum), _ = jax.lax.scan(
                micro, (zeros, jnp.float32(0.0)), micros
            )
            grads = tree_map(lambda g: g / accum_steps, g_sum)
            loss = l_sum / accum_steps
        updates, new_opt = optimizer.update(grads, state["opt"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": new_params, "opt": new_opt, "step": state["step"] + 1
        }
        if aux_metrics:
            return new_state, loss, {"grad_norm": optax.global_norm(grads)}
        return new_state, loss

    return init_state, step


def _opt_shardings(optimizer, params, mesh):
    """Optimizer-state shardings mirror the params they track (fsdp shards
    the Adam moments too); non-param leaves (step counters) replicate.

    Adam's mu/nu trees repeat the param tree structure, so a leaf's param
    identity is the longest path suffix that matches a PARAM_RULES entry.
    """
    replicated = NamedSharding(mesh, P())

    def leaf_sharding(path, _leaf):
        names = [p.key for p in path if hasattr(p, "key")]
        for n in range(len(names), 0, -1):
            cand = ".".join(names[-n:])
            if cand in PARAM_RULES:
                return NamedSharding(mesh, PARAM_RULES[cand])
        return replicated

    return tree_map_with_path(
        leaf_sharding, jax.eval_shape(optimizer.init, params)
    )


def shard_batch(tokens: jax.Array, mesh: Mesh) -> jax.Array:
    return jax.device_put(tokens, NamedSharding(mesh, batch_spec(mesh)))
