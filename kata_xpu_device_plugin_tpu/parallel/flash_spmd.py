"""SPMD wrapper that lets the pallas flash kernel partition over a mesh.

A pallas custom call has no SPMD partitioning rule, so inside a plain-jit
GSPMD train step XLA may replicate its operands instead of running it on
each device's shard — which is why sharded train steps used to fall back
to the XLA reference attention and forfeit the kernel (VERDICT r4 weak #3).

Self-attention is embarrassingly parallel over batch and heads: no
cross-device math touches the [S, S] block. So the fix is the exact
pattern ring attention already proved (``.ring.make_ring_attention``):
``shard_map`` over the batch axes (data, fsdp) and — when the head counts
divide — the model axis for q/kv heads. Each device then launches the
kernel on its LOCAL [B/dp, S, H/tp, D] block; entering the shard_map
inserts no gather because the specs match the shardings the surrounding
GSPMD matmuls already produce, and there are no collectives inside.

``make_train_step`` engages this automatically on TPU for non-seq meshes
(seq meshes ring instead); the kernel's own trace-time eligibility gate
(shape support, S ≥ 128) still decides flash-vs-reference PER LOCAL block,
so ineligible shapes degrade to the reference inside the same shard_map.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax

from ..compat.jaxapi import Mesh, P, shard_map
from .mesh import AXIS_DATA, AXIS_FSDP


def make_sharded_attention(
    mesh: Mesh,
    batch_axes=(AXIS_DATA, AXIS_FSDP),
    head_axis: Optional[str] = None,
    kv_head_axis: Optional[str] = None,
    use_flash: Optional[bool] = None,
    flash_interpret: bool = False,
):
    """Returns ``attn(q, k, v, causal=True, q_offset=None, window=0,
    logits_softcap=0.0)`` on GLOBAL [B, S, H, D] arrays — a drop-in for the
    model's attention seam on dp/fsdp/tp meshes.

    ``use_flash=None`` auto-engages the pallas kernel per local block on
    TPU (``flash_interpret`` forces the interpret-mode kernel so CPU tests
    drive the same code path). Windows and the Gemma-2 softcap ride into
    the kernel exactly as on the single-device path.
    """

    @lru_cache(maxsize=None)  # one shard_map per (softcap, window, causal)
    def attn_for(softcap: float, window: int, causal: bool):
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P(batch_axes, None, head_axis, None),
                P(batch_axes, None, kv_head_axis, None),
                P(batch_axes, None, kv_head_axis, None),
            ),
            out_specs=P(batch_axes, None, head_axis, None),
            check_vma=False,  # no collectives: every output is shard-local
        )
        def attn(q, k, v):
            from ..ops.attention import flash_eligible, reference_attention

            B, S, H, D = q.shape
            if use_flash is None:
                engage = flash_eligible(S, k.shape[1], D)
            else:
                engage = use_flash
            if engage:
                from ..ops.flash import pallas_flash_attention

                return pallas_flash_attention(
                    q, k, v, causal=causal, window=window, softcap=softcap,
                    interpret=flash_interpret,
                )
            return reference_attention(
                q, k, v, causal=causal, window=window, logits_softcap=softcap
            )

        return attn

    def sharded_attn(q, k, v, causal: bool = True,
                     q_offset: Optional[jax.Array] = None, window: int = 0,
                     logits_softcap: float = 0.0):
        if q_offset is not None:
            raise ValueError(
                "sharded flash attention is for self-attention "
                "(training/prefill); decode-into-cache has its own path"
            )
        return attn_for(float(logits_softcap), int(window), bool(causal))(q, k, v)

    return sharded_attn
