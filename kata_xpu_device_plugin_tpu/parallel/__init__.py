"""Parallelism: mesh construction, dp/fsdp/tp sharding rules + train step,
sequence-parallel ring attention, GPipe pipeline parallelism, (via ops.moe)
expert parallelism, sharding-aware checkpoint/resume, and the
deterministic resumable data loader."""
from .checkpoint import TrainCheckpointer
from .loader import TokenBatchLoader, make_loader
from .trainer import fit
from .composed import (
    composed_mesh,
    init_pp_params,
    make_pp_loss,
    make_pp_train_step,
    shard_microbatches,
    to_pp_params,
)
from .mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_SEQ,
    build_mesh,
    default_mesh_shape,
    seq_mesh,
)
from .pipeline import (
    AXIS_PIPE,
    make_pipeline,
    make_transformer_pipeline,
    pipe_mesh,
    sequential_reference,
    stack_stage_params,
)
from .flash_spmd import make_sharded_attention
from .ring import make_ring_attention
from .ulysses import make_ulysses_attention
from .sharding import (
    BATCH_SPEC,
    PARAM_RULES,
    batch_spec,
    init_sharded_params,
    make_optimizer,
    make_train_step,
    param_shardings,
    shard_batch,
    shard_params,
)

__all__ = [
    "composed_mesh",
    "init_pp_params",
    "make_pp_loss",
    "make_pp_train_step",
    "shard_microbatches",
    "to_pp_params",
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_MODEL",
    "AXIS_SEQ",
    "build_mesh",
    "default_mesh_shape",
    "seq_mesh",
    "AXIS_PIPE",
    "make_pipeline",
    "make_transformer_pipeline",
    "pipe_mesh",
    "sequential_reference",
    "stack_stage_params",
    "make_ring_attention",
    "make_sharded_attention",
    "make_ulysses_attention",
    "BATCH_SPEC",
    "batch_spec",
    "PARAM_RULES",
    "init_sharded_params",
    "make_optimizer",
    "make_train_step",
    "param_shardings",
    "shard_batch",
    "shard_params",
    "TrainCheckpointer",
    "TokenBatchLoader",
    "make_loader",
    "fit",
]
