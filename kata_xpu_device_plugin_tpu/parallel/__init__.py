"""Parallelism: mesh construction, dp/fsdp/tp sharding rules + train step,
and sequence-parallel ring attention."""
from .mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_SEQ,
    build_mesh,
    default_mesh_shape,
    seq_mesh,
)
from .ring import make_ring_attention
from .sharding import (
    BATCH_SPEC,
    PARAM_RULES,
    init_sharded_params,
    make_optimizer,
    make_train_step,
    param_shardings,
    shard_batch,
    shard_params,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_MODEL",
    "AXIS_SEQ",
    "build_mesh",
    "default_mesh_shape",
    "seq_mesh",
    "make_ring_attention",
    "BATCH_SPEC",
    "PARAM_RULES",
    "init_sharded_params",
    "make_optimizer",
    "make_train_step",
    "param_shardings",
    "shard_batch",
    "shard_params",
]
