"""The training loop: loader → sharded step → checkpoint/resume, as one
callable.

The reference ships no training stack (SURVEY §2/§5); the framework has the
three legs — :func:`.sharding.make_train_step` (GSPMD dp×fsdp×tp),
:class:`.loader.TokenBatchLoader` (deterministic, resumable), and
:class:`.checkpoint.TrainCheckpointer` (orbax, sharding-aware) — and this
module is the glue users otherwise hand-write: a preemption-safe ``fit()``
whose resumed run replays EXACTLY the interrupted one (same batches, same
losses, bit-identical states — tested), because the loader cursor is saved
next to the train state and both restore together.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional

import jax
import numpy as np

from .. import obs
from ..utils import log
from .checkpoint import TrainCheckpointer
from .loader import TokenBatchLoader

LOG = log.get("trainer")

# Trainer metrics (ISSUE 2) — created through the idempotent factory, so
# two fit() calls (or a module reload) share one set of collectors.
_step_seconds = obs.histogram(
    "kata_tpu_train_step_seconds", "Optimizer-step wall time (fenced)"
)
_loss_gauge = obs.gauge("kata_tpu_train_loss", "Last training loss")
_tokens_per_s = obs.gauge(
    "kata_tpu_train_tokens_per_s", "Training throughput, last step"
)
_grad_norm_gauge = obs.gauge(
    "kata_tpu_train_grad_norm", "Global gradient norm, last step"
)


def _loader_state_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"loader_{step}.json")


def fit(
    init_state: Callable,
    step_fn: Callable,
    loader: TokenBatchLoader,
    steps: int,
    key: Optional[jax.Array] = None,
    ckpt_dir: Optional[str] = None,
    ckpt_every: int = 0,
    log_every: int = 0,
    on_step: Optional[Callable] = None,
    profiler: Optional[obs.ProfilerHook] = None,
) -> tuple[Any, list]:
    """Train for ``steps`` optimizer steps; returns ``(state, losses)``.

    ``init_state``/``step_fn`` are :func:`.sharding.make_train_step`'s pair
    (or any pair of the same shape — a ``step_fn`` may also return
    ``(state, loss, aux)`` with an aux metrics dict, e.g.
    ``make_train_step(..., aux_metrics=True)``'s grad-norm). With
    ``ckpt_dir``:

    - every ``ckpt_every`` steps the train state is checkpointed (orbax,
      atomic) and the loader cursor written next to it;
    - on startup, if a checkpoint exists, BOTH restore and training
      continues at the exact batch the interrupted run would have drawn
      next — the resumed loss sequence equals the uninterrupted one.

    ``on_step(step, loss)`` is a host callback (metrics, early stop via
    raising); ``log_every`` emits structured log lines.

    Telemetry (ISSUE 2): with the obs event stream enabled
    (``KATATPU_OBS=1``), every step runs inside an ``obs.span`` that
    FENCES on the loss — per-step wall time, loss, tokens/sec and (when
    the step reports it) grad-norm stream to the JSONL sink and the
    ``kata_tpu_train_*`` Prometheus metrics, and a compile-vs-execute
    split is derived from the first step (which pays compilation) vs the
    steady state. The instrumented path syncs on every step by design —
    honest step times cost the async pipeline; with obs disabled the loop
    is byte-for-byte the old async one. ``profiler`` (default: from
    ``KATATPU_OBS_PROFILE_DIR``) dumps a ``jax.profiler`` trace around
    the configured step window.
    """
    if ckpt_every and not ckpt_dir:
        raise ValueError("ckpt_every needs ckpt_dir")
    if profiler is None:
        profiler = obs.profiler_from_env()
    state = init_state(key if key is not None else jax.random.PRNGKey(0))

    ckpt: Optional[TrainCheckpointer] = None
    start_step = 0
    if ckpt_dir:
        ckpt = TrainCheckpointer(ckpt_dir, save_interval_steps=1)
        latest = ckpt.latest_step()
        if latest is not None:
            # Free the freshly-initialized buffers BEFORE restore (the init
            # tree only supplies shapes/dtypes/shardings): without this,
            # resume transiently holds init + restored trees and can OOM a
            # model a fresh run fits.
            spec = jax.tree.map(
                lambda x: (
                    jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
                    if isinstance(x, jax.Array) else x
                ),
                state,
            )
            jax.tree.map(
                lambda x: x.delete() if isinstance(x, jax.Array) else None,
                state,
            )
            state = ckpt.restore(spec, step=latest)
            with open(_loader_state_path(ckpt_dir, latest)) as f:
                loader.load_state_dict(json.load(f))
            start_step = latest
            LOG.info(
                "resumed", extra=log.kv(step=latest, dir=ckpt_dir)
            )

    instrument = obs.default_sink() is not None
    step_durs: list[float] = []
    losses: list = []
    try:
        if profiler is not None:
            # Prime with the step we resume from ("step start_step has
            # completed"): a start_step=1 window starts before the first
            # executed step, and a resume landing mid-window still opens it.
            profiler.on_step(start_step)
        for s in range(start_step, steps):
            batch = next(loader)
            if instrument:
                state, loss = _instrumented_step(
                    step_fn, state, batch, s + 1, s == start_step, step_durs
                )
            else:
                state, loss, _aux = _unpack_step(step_fn(state, batch))
            if log_every and (s + 1) % log_every == 0:
                LOG.info(
                    "step", extra=log.kv(step=s + 1, loss=float(loss))  # jaxguard: allow(JG101) log_every-gated: logging a loss forces its read by design
                )
            if on_step is not None:
                on_step(s + 1, loss)
            losses.append(loss)
            if profiler is not None:
                profiler.on_step(s + 1)
            if ckpt is not None and ckpt_every and (s + 1) % ckpt_every == 0:
                # Loader cursor FIRST (tiny json), then the state; a kill
                # between the two leaves the previous step as orbax-latest
                # and its cursor file intact — never a state/cursor mismatch.
                with open(_loader_state_path(ckpt_dir, s + 1), "w") as f:
                    json.dump(loader.state_dict(), f)
                ckpt.save(s + 1, state)
                _prune_cursors(ckpt_dir, ckpt.steps())
    finally:
        if profiler is not None:
            profiler.stop()
        # on_step may raise to stop early (documented): in-flight async
        # orbax writes must still be finalized or the 'saved' checkpoint
        # is discarded by atomicity and resume falls back further.
        if ckpt is not None:
            ckpt.wait()
            ckpt.close()
    if instrument and len(step_durs) >= 2:
        # Compile-vs-execute split: the run's first step pays tracing +
        # XLA compilation on top of one execution; the steady-state
        # minimum is the execute-only cost, so the difference estimates
        # the compile. Derived, not directly measured — labeled as such.
        steady = min(step_durs[1:])
        obs.emit(
            "derived", "train.compile_estimate",
            dur_s=round(max(0.0, step_durs[0] - steady), 6),
            first_step_s=round(step_durs[0], 6),
            steady_step_s=round(steady, 6),
        )
    # Device scalars → host floats once, at the end (per-step .item() would
    # serialize the async dispatch pipeline).
    return state, [float(np.asarray(l)) for l in losses]  # jaxguard: allow(JG101) end-of-run conversion, after the loop


def _unpack_step(out) -> tuple[Any, Any, dict]:
    """Both step contracts: ``(state, loss)`` and ``(state, loss, aux)``."""
    if len(out) == 3:
        state, loss, aux = out
        return state, loss, dict(aux)
    state, loss = out
    return state, loss, {}


def _instrumented_step(
    step_fn, state, batch, step_num: int, first: bool, step_durs: list
):
    """One step under an ``obs.span`` that fences on the loss (one output
    of the jitted step executable is ready only when the whole step is —
    the host transfer IS the fence). Feeds the span, the JSONL sink, and
    the ``kata_tpu_train_*`` Prometheus collectors."""
    shape = getattr(batch, "shape", None)
    tokens = int(np.prod(shape)) if shape else None
    attrs = {"step": step_num}
    if tokens:
        attrs["tokens"] = tokens
    if first:
        attrs["includes_compile"] = True
    with obs.span("train.step", **attrs) as sp:
        state, loss, aux = _unpack_step(step_fn(state, batch))
        loss_val = float(np.asarray(loss))  # host transfer == fence  # jaxguard: allow(JG101) instrumented step syncs by design (honest step times)
        sp.set(loss=round(loss_val, 6))
        grad_norm = aux.get("grad_norm")
        if grad_norm is not None:
            grad_norm = float(np.asarray(grad_norm))
            sp.set(grad_norm=round(grad_norm, 6))
    step_durs.append(sp.duration_s)
    _step_seconds.observe(sp.duration_s)
    _loss_gauge.set(loss_val)
    if tokens and sp.duration_s > 0:
        _tokens_per_s.set(tokens / sp.duration_s)
    if grad_norm is not None:
        _grad_norm_gauge.set(grad_norm)
    return state, loss


def _prune_cursors(directory: str, live_steps) -> None:
    """Drop loader_*.json cursors whose orbax step was pruned by
    max_to_keep — stale cursors would otherwise accumulate unboundedly and
    outlive their checkpoints."""
    live = {int(s) for s in live_steps}
    directory = os.path.abspath(directory)
    for name in os.listdir(directory):
        if name.startswith("loader_") and name.endswith(".json"):
            try:
                step = int(name[len("loader_") : -len(".json")])
            except ValueError:
                continue
            if step not in live:
                os.unlink(os.path.join(directory, name))
