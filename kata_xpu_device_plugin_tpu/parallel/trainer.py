"""The training loop: loader → sharded step → checkpoint/resume, as one
callable.

The reference ships no training stack (SURVEY §2/§5); the framework has the
three legs — :func:`.sharding.make_train_step` (GSPMD dp×fsdp×tp),
:class:`.loader.TokenBatchLoader` (deterministic, resumable), and
:class:`.checkpoint.TrainCheckpointer` (orbax, sharding-aware) — and this
module is the glue users otherwise hand-write: a preemption-safe ``fit()``
whose resumed run replays EXACTLY the interrupted one (same batches, same
losses, bit-identical states — tested), because the loader cursor is saved
next to the train state and both restore together.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional

import jax
import numpy as np

from ..utils import log
from .checkpoint import TrainCheckpointer
from .loader import TokenBatchLoader

LOG = log.get("trainer")


def _loader_state_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"loader_{step}.json")


def fit(
    init_state: Callable,
    step_fn: Callable,
    loader: TokenBatchLoader,
    steps: int,
    key: Optional[jax.Array] = None,
    ckpt_dir: Optional[str] = None,
    ckpt_every: int = 0,
    log_every: int = 0,
    on_step: Optional[Callable] = None,
) -> tuple[Any, list]:
    """Train for ``steps`` optimizer steps; returns ``(state, losses)``.

    ``init_state``/``step_fn`` are :func:`.sharding.make_train_step`'s pair
    (or any pair of the same shape). With ``ckpt_dir``:

    - every ``ckpt_every`` steps the train state is checkpointed (orbax,
      atomic) and the loader cursor written next to it;
    - on startup, if a checkpoint exists, BOTH restore and training
      continues at the exact batch the interrupted run would have drawn
      next — the resumed loss sequence equals the uninterrupted one.

    ``on_step(step, loss)`` is a host callback (metrics, early stop via
    raising); ``log_every`` emits structured log lines.
    """
    if ckpt_every and not ckpt_dir:
        raise ValueError("ckpt_every needs ckpt_dir")
    state = init_state(key if key is not None else jax.random.PRNGKey(0))

    ckpt: Optional[TrainCheckpointer] = None
    start_step = 0
    if ckpt_dir:
        ckpt = TrainCheckpointer(ckpt_dir, save_interval_steps=1)
        latest = ckpt.latest_step()
        if latest is not None:
            # Free the freshly-initialized buffers BEFORE restore (the init
            # tree only supplies shapes/dtypes/shardings): without this,
            # resume transiently holds init + restored trees and can OOM a
            # model a fresh run fits.
            spec = jax.tree.map(
                lambda x: (
                    jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
                    if isinstance(x, jax.Array) else x
                ),
                state,
            )
            jax.tree.map(
                lambda x: x.delete() if isinstance(x, jax.Array) else None,
                state,
            )
            state = ckpt.restore(spec, step=latest)
            with open(_loader_state_path(ckpt_dir, latest)) as f:
                loader.load_state_dict(json.load(f))
            start_step = latest
            LOG.info(
                "resumed", extra=log.kv(step=latest, dir=ckpt_dir)
            )

    losses: list = []
    try:
        for s in range(start_step, steps):
            state, loss = step_fn(state, next(loader))
            if log_every and (s + 1) % log_every == 0:
                LOG.info(
                    "step", extra=log.kv(step=s + 1, loss=float(loss))
                )
            if on_step is not None:
                on_step(s + 1, loss)
            losses.append(loss)
            if ckpt is not None and ckpt_every and (s + 1) % ckpt_every == 0:
                # Loader cursor FIRST (tiny json), then the state; a kill
                # between the two leaves the previous step as orbax-latest
                # and its cursor file intact — never a state/cursor mismatch.
                with open(_loader_state_path(ckpt_dir, s + 1), "w") as f:
                    json.dump(loader.state_dict(), f)
                ckpt.save(s + 1, state)
                _prune_cursors(ckpt_dir, ckpt.steps())
    finally:
        # on_step may raise to stop early (documented): in-flight async
        # orbax writes must still be finalized or the 'saved' checkpoint
        # is discarded by atomicity and resume falls back further.
        if ckpt is not None:
            ckpt.wait()
            ckpt.close()
    # Device scalars → host floats once, at the end (per-step .item() would
    # serialize the async dispatch pipeline).
    return state, [float(np.asarray(l)) for l in losses]


def _prune_cursors(directory: str, live_steps) -> None:
    """Drop loader_*.json cursors whose orbax step was pruned by
    max_to_keep — stale cursors would otherwise accumulate unboundedly and
    outlive their checkpoints."""
    live = {int(s) for s in live_steps}
    directory = os.path.abspath(directory)
    for name in os.listdir(directory):
        if name.startswith("loader_") and name.endswith(".json"):
            try:
                step = int(name[len("loader_") : -len(".json")])
            except ValueError:
                continue
            if step not in live:
                os.unlink(os.path.join(directory, name))
