"""Sharding-aware train-state checkpoint/resume.

The reference has no checkpointing at all (SURVEY §5 "Checkpoint / resume:
none") — this is a beyond-parity component required for the BASELINE
Llama-3-8B training config: a multi-hour run must survive pod preemption
(the Kata guest can be killed at any step) and resume bit-identically.

TPU-native shape: orbax (the JAX checkpointing library) with OCDBT +
zarr3 under the hood — each host writes only the shards it owns, and
restore places shards directly into the target ``NamedSharding``s without
ever materializing a full array on one device. The wrapper pins the small
API surface the framework needs (save/restore/latest) so call sites do not
track orbax API churn.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax

from ..utils import log

LOG = log.get("checkpoint")


def _abstract_like(state: Any) -> Any:
    """ShapeDtypeStruct tree carrying each leaf's sharding — the restore
    target spec (restored arrays land already sharded, no host round-trip)."""

    def leaf(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(leaf, state)


class TrainCheckpointer:
    """Step-indexed train-state checkpoints in one directory.

    ``state`` is any pytree of jax.Arrays — the framework convention is
    ``{"params": ..., "opt": ..., "step": ...}`` from
    :func:`.sharding.make_train_step`. Writes are atomic (orbax finalizes a
    step directory only after all shards land), so a kill mid-save leaves
    the previous step as ``latest``.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    # ----- write -----------------------------------------------------------

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save ``state`` at ``step``. Returns False when the manager's
        save-interval policy skips this step (force=True overrides)."""
        saved = self._mngr.save(
            int(step), args=self._ocp.args.StandardSave(state), force=force
        )
        if saved:
            LOG.info("checkpoint saved", extra=log.kv(step=int(step), dir=self._dir))
        return bool(saved)

    def wait(self) -> None:
        """Block until async writes are durable (call before process exit)."""
        self._mngr.wait_until_finished()

    # ----- read ------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def steps(self):
        """All retained checkpoint steps (after max_to_keep pruning)."""
        return self._mngr.all_steps()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the same shapes/dtypes/shardings as ``state_like``
        (a live or abstract state tree). ``step=None`` means latest."""
        step = self._mngr.latest_step() if step is None else int(step)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self._dir}")
        restored = self._mngr.restore(
            step, args=self._ocp.args.StandardRestore(_abstract_like(state_like))
        )
        LOG.info("checkpoint restored", extra=log.kv(step=step, dir=self._dir))
        return restored

    def close(self) -> None:
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()
        return False
