"""Device mesh construction.

The scaling model is jax.sharding over an ICI mesh: pick a mesh, annotate
shardings, let XLA insert collectives. Axes:

- ``data``  — pure data parallelism (gradient psum over DCN/ICI)
- ``fsdp``  — data parallelism with parameter/optimizer sharding
             (all-gather params, reduce-scatter grads; rides ICI)
- ``model`` — tensor parallelism (heads / mlp-hidden sharding)
- ``seq``   — sequence/context parallelism (ring attention over ICI)

On hardware the mesh should map so ``model``/``seq`` ride ICI neighbors;
``jax.experimental.mesh_utils.create_device_mesh`` handles the physical
assignment on real slices.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"

MESH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_MODEL)


def default_mesh_shape(n_devices: int) -> dict[str, int]:
    """A reasonable dp×fsdp×tp factorization: tensor parallelism over the
    closest ICI neighbors (≤4 ways), FSDP over the rest, pure DP only when
    the device count has leftover factors."""
    model = 1
    for cand in (4, 2):
        if n_devices % cand == 0 and n_devices >= cand * 2:
            model = cand
            break
    rest = n_devices // model
    fsdp = rest
    data = 1
    if rest % 2 == 0 and rest >= 4:
        data = 2
        fsdp = rest // 2
    return {AXIS_DATA: data, AXIS_FSDP: fsdp, AXIS_MODEL: model}


def build_mesh(
    shape: Optional[dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Optional[Sequence[str]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    shape = shape or default_mesh_shape(len(devices))
    if axis_names is None:
        # The seq axis joins the mesh when the shape asks for it, so
        # sequence parallelism composes with dp/fsdp/tp on ONE mesh
        # instead of living on a private 1-D mesh.
        axis_names = MESH_AXES + (
            (AXIS_SEQ,) if shape.get(AXIS_SEQ, 1) > 1 else ()
        )
    dims = [shape.get(a, 1) for a in axis_names]
    if int(np.prod(dims)) != len(devices):
        raise ValueError(f"mesh shape {shape} does not cover {len(devices)} devices")
    try:
        from jax.experimental import mesh_utils  # lint: allow(JX002) no stable home on any supported line

        dev_array = mesh_utils.create_device_mesh(dims, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(dims)
    return Mesh(dev_array, tuple(axis_names))


def mesh_1d(
    n: int, axis_name: str, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """1-D mesh over the first ``n`` devices — shared constructor for the
    sequence-, pipeline- and expert-parallel axes."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n:
        raise ValueError(f"need {n} devices for axis {axis_name!r}, have {len(devices)}")
    return Mesh(np.asarray(devices[:n]), (axis_name,))


def seq_mesh(n_seq: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1D mesh for sequence-parallel ring attention tests/benchmarks."""
    return mesh_1d(n_seq, AXIS_SEQ, devices)
