"""Ulysses sequence parallelism: all-to-all head-parallel attention.

The second long-context strategy next to :mod:`.ring` (SURVEY §5 names both:
"no ring attention, context parallel, blockwise, or Ulysses anywhere" — the
reference has none). Where ring attention keeps queries resident and rotates
K/V around the ICI ring (n-1 neighbor hops, compute overlapped), Ulysses
re-shards ONCE: an all-to-all turns the sequence-sharded [B, S/n, H, D]
q/k/v into head-sharded [B, S, H/n, D], each device runs FULL-sequence
attention for its head group (the pallas flash kernel applies directly —
it is plain self-attention), and a reverse all-to-all restores sequence
sharding. Two collectives total, O(S·H·D/n) bytes each.

Tradeoffs (why both exist):
- Ulysses needs ``H % n == 0`` (and ``KV % n == 0`` unless KV heads are
  replicated); ring has no head-count constraint — MQA models (Gemma: KV=1)
  at high sp degree want ring.
- Ulysses does one big reshard; ring pays n-1 smaller hops but overlaps them
  with compute. On ICI both are bandwidth-fine; Ulysses wins when local
  full-sequence attention can use the flash kernel at its best block sizes.

GQA handling: when ``KV < n`` the KV heads are replicated across the group
after the all-to-all (each device needs its head group's KV anyway — the
cache is small relative to activations at that point); when ``KV % n == 0``
K/V all-to-all exactly like q.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import lax

from ..compat.jaxapi import Mesh, P, shard_map
from .mesh import AXIS_SEQ


def _seq_to_heads(x: jax.Array, axis: str) -> jax.Array:
    """[B, S_loc, H, D] (seq-sharded view) → [B, S, H_loc, D]: all-to-all
    splitting the head axis across the group and concatenating sequence."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def _heads_to_seq(x: jax.Array, axis: str) -> jax.Array:
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def make_ulysses_attention(
    mesh: Mesh,
    axis: str = AXIS_SEQ,
    attn_fn: Optional[Callable] = None,
):
    """Returns ``ulysses_attn(q, k, v)`` on GLOBAL [B, S, H, D] arrays
    sharded over ``axis`` in S (drop-in for the attention seam, like
    :func:`.ring.make_ring_attention`). ``attn_fn`` runs the full-sequence
    attention per head group and defaults to the flash dispatcher (pallas on
    TPU, XLA reference elsewhere)."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    n = mesh.shape[axis]

    from functools import lru_cache

    @lru_cache(maxsize=None)  # one shard_map per (window, softcap)
    def mapped_for(window: int, softcap: float):
        def local(q, k, v):
            # q [B, S_loc, H, D]; k/v [B, S_loc, KV, D]
            B, S_loc, H, D = q.shape
            KV = k.shape[2]
            if H % n:
                raise ValueError(
                    f"Ulysses needs n_heads % sp == 0, got H={H}, sp={n}"
                )
            qh = _seq_to_heads(q, axis)  # [B, S, H/n, D]
            if KV % n == 0:
                kh = _seq_to_heads(k, axis)
                vh = _seq_to_heads(v, axis)
            elif n % KV == 0:
                # Few KV heads (GQA/MQA), several devices per kv head: gather
                # the full sequence of all KV heads and slice the ONE kv head
                # this device's q-head group maps to (h_loc divides group here,
                # so the group never straddles a kv boundary; the slice count is
                # static). KV cache is small next to q at this point.
                k_full = lax.all_gather(k, axis, axis=1, tiled=True)  # [B, S, KV, D]
                v_full = lax.all_gather(v, axis, axis=1, tiled=True)
                group = H // KV  # q heads per kv head (global)
                h_loc = H // n
                kv_start = (lax.axis_index(axis) * h_loc) // group
                kh = lax.dynamic_slice_in_dim(k_full, kv_start, 1, axis=2)
                vh = lax.dynamic_slice_in_dim(v_full, kv_start, 1, axis=2)
            else:
                raise ValueError(
                    f"Ulysses sp degree {n} must divide n_kv_heads={KV} or be a "
                    f"multiple of it (ring attention has no such constraint)"
                )
            # Each device sees the FULL sequence for its head group, so the
            # sliding-window band and the Gemma-2 softcap forward straight
            # into the inner attention (flash block-skips the band on TPU).
            kw = {}
            if window:
                kw["window"] = window
            if softcap:
                kw["logits_softcap"] = softcap
            out = attn_fn(qh, kh, vh, causal=True, q_offset=None, **kw)
            return _heads_to_seq(out, axis)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(None, axis, None, None),) * 3,
            out_specs=P(None, axis, None, None),
            check_vma=False,
        )

    def ulysses_attn(q, k, v, causal: bool = True, q_offset=None,
                     window: int = 0, logits_softcap: float = 0.0):
        if not causal or q_offset is not None:
            raise ValueError("ulysses attention supports causal self-attention only")
        return mapped_for(int(window), float(logits_softcap))(q, k, v)

    return ulysses_attn
