"""Pipeline parallelism: a GPipe microbatch schedule over a 1-D ``pipe``
mesh axis.

The reference runs no model code (SURVEY §2 "parallelism strategies —
ABSENT"); this is part of the guest-side capability stack that validates what
the plugin injects. TPU-first design: the schedule is a single
``lax.fori_loop`` of compute + ``lax.ppermute`` neighbor exchanges — the
collective-permute rides ICI between adjacent chips, there is no
data-dependent Python control flow, and every shape is static so XLA can
overlap the permute with the next tick's compute.

Layout: stage ``s`` holds slice ``s`` of the stacked stage parameters
(leading axis sharded over ``pipe``). Microbatches enter at stage 0, flow
through the ring one hop per tick, and exit at stage ``P-1``; a run of ``M``
microbatches takes ``M + P - 1`` ticks (the classic GPipe bubble).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..compat.jaxapi import Mesh, P, pvary, shard_map, tree_map

AXIS_PIPE = "pipe"


def _pvary(x: jax.Array, axis: str) -> jax.Array:
    """Mark ``x`` as device-varying over ``axis`` (no-op on JAX versions
    whose shard_map has no varying-axis type system)."""
    return pvary(x, (axis,))


def pipe_mesh(n_stages: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh for pipeline stages (one stage per device)."""
    from .mesh import mesh_1d

    return mesh_1d(n_stages, AXIS_PIPE, devices)


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Stack per-stage parameter pytrees along a new leading axis — the axis
    the pipeline shards over ``pipe``."""
    return tree_map(lambda *leaves: jnp.stack(leaves), *stage_params)


def make_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    num_stages: int,
    mesh: Mesh,
    axis: str = AXIS_PIPE,
):
    """Build ``pipelined(stacked_params, microbatches) -> outputs``.

    ``stage_fn(params, x) -> y`` must preserve ``x``'s shape/dtype (a
    transformer block does); ``microbatches`` is ``(M, mb, ...)`` and comes
    back transformed by all ``num_stages`` stages in order, replicated on
    every device.

    Memory tradeoff (deliberate): every stage holds all M microbatches and
    the psum broadcasts full outputs — activation footprint does NOT scale
    with 1/P here. This wrapper is the simple, self-contained unit-semantics
    pipeline; the production path is :mod:`.composed`, whose schedule shards
    microbatch ingestion/egress per stage (1/P activations) and composes
    with fsdp/tp on one mesh.
    """
    if mesh.shape[axis] != num_stages:
        raise ValueError(
            f"mesh axis {axis!r} has {mesh.shape[axis]} devices, want {num_stages}"
        )
    shifts = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def per_stage(params_blk: Any, mbs: jax.Array) -> jax.Array:
        stage_idx = lax.axis_index(axis)
        own_params = tree_map(lambda p: p[0], params_blk)
        num_mb = mbs.shape[0]

        def tick(t, carry):
            state, outputs = carry
            # Stage 0 ingests microbatch t (clamped: past the end it feeds
            # don't-care values that never reach a valid output slot).
            inject = lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, num_mb - 1), 0, keepdims=False
            )
            x = jnp.where(stage_idx == 0, inject, state)
            y = stage_fn(own_params, x)
            # Stage P-1 has just finished microbatch t-(P-1).
            out_t = t - (num_stages - 1)
            safe_t = jnp.clip(out_t, 0, num_mb - 1)
            write = jnp.logical_and(stage_idx == num_stages - 1, out_t >= 0)
            prev = lax.dynamic_index_in_dim(outputs, safe_t, 0, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, y, prev), safe_t, 0
            )
            # One ICI hop: every stage hands its activation to the next.
            state = lax.ppermute(y, axis, shifts)
            return state, outputs

        # The loop carry is device-varying (each stage holds different
        # activations); the zero init must be marked varying over the pipe
        # axis or the carry types disagree under shard_map's type system.
        init = tree_map(
            lambda z: _pvary(z, axis), (jnp.zeros_like(mbs[0]), jnp.zeros_like(mbs))
        )
        _, outputs = lax.fori_loop(0, num_mb + num_stages - 1, tick, init)
        # Only the last stage holds real outputs; psum broadcasts them (all
        # other stages contribute zeros) so the result is replicated.
        outputs = jnp.where(stage_idx == num_stages - 1, outputs, jnp.zeros_like(outputs))
        return lax.psum(outputs, axis)

    return shard_map(per_stage, mesh=mesh, in_specs=(P(axis), P()), out_specs=P())


def transformer_stage_fn(cfg, attn_fn: Optional[Callable] = None):
    """One pipeline stage of a decoder: scan a [L_stage, ...]-stacked layer
    chunk over [B, S, D] activations. Shared by the 1-D pipeline wrapper and
    the composed pp×fsdp×tp step so the stage body cannot drift."""
    from ..models import transformer as tfm

    if getattr(cfg, "moe", False):
        # The stage body discards each layer's aux loss; training an MoE
        # config here would silently drop the router-balancing term.
        raise ValueError(
            "pipeline stages do not thread the MoE aux loss yet; "
            "use the unpipelined make_train_step for MoE configs"
        )
    if attn_fn is None:
        from ..ops.attention import reference_attention

        attn_fn = reference_attention

    def stage_fn(stage_layers: Any, x: jax.Array) -> jax.Array:
        B, S, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

        def body(h, layer):
            h, _, _aux = tfm._layer(cfg, attn_fn, h, layer, positions)
            return h, None

        x, _ = lax.scan(body, x, stage_layers)
        return x

    return stage_fn


def make_transformer_pipeline(
    cfg,
    n_stages: int,
    mesh: Mesh,
    axis: str = AXIS_PIPE,
    attn_fn: Optional[Callable] = None,
):
    """Pipeline-parallel decoder forward: the ``cfg.n_layers`` transformer
    blocks are split into ``n_stages`` contiguous chunks, each chunk living
    on one device of the ``pipe`` axis; microbatches of activations flow
    stage-to-stage over ``ppermute`` (ICI neighbor hops). Embedding,
    final norm and unembedding are replicated outside the pipeline (they are
    tiny next to the layer stack).

    Returns ``pipelined_forward(params, tokens_mb) -> logits`` with
    ``tokens_mb`` shaped ``[M, mb, S]`` (M microbatches) and logits
    ``[M, mb, S, vocab]``, equal to the unpipelined
    :func:`..models.transformer.forward` per microbatch.
    """
    from ..models import transformer as tfm

    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by {n_stages} stages"
        )
    if cfg.attn_windows or cfg.rope_theta_cycle or cfg.rope_linear_cycle:
        raise ValueError(
            "pipeline stages apply one uniform attention window and rope; "
            "per-layer attn_windows / rope cycles (Gemma-2/3 style) are "
            "not supported here"
        )
    layers_per_stage = cfg.n_layers // n_stages

    pipe = make_pipeline(transformer_stage_fn(cfg, attn_fn), n_stages, mesh, axis)

    def pipelined_forward(params: Any, tokens_mb: jax.Array) -> jax.Array:
        x = tfm.embed(params, tokens_mb, cfg)  # [M, mb, S, D]
        # Stacked layers [L, ...] → [n_stages, L/n_stages, ...]: leading axis
        # shards over ``pipe``, the second is each stage's local scan.
        stage_layers = tree_map(
            lambda a: a.reshape((n_stages, layers_per_stage) + a.shape[1:]),
            params["layers"],
        )
        y = pipe(stage_layers, x)
        return tfm.unembed(params, y, cfg)

    return pipelined_forward


def sequential_reference(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Sequence[Any],
    mbs: jax.Array,
) -> jax.Array:
    """What the pipeline must equal: every microbatch through every stage."""
    out = mbs
    for params in stage_params:
        out = jax.vmap(lambda x, p=params: stage_fn(p, x))(out)
    return out
