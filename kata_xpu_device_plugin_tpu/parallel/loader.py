"""Deterministic, resumable token-batch loader for the training path.

The reference has no input pipeline (it is node infrastructure; SURVEY §2
lists zero ML code), but a complete training stack needs the third leg next
to the sharded train step (:mod:`.sharding`) and checkpoint/resume
(:mod:`.checkpoint`): batches that are

- **deterministic** — a (seed, epoch) pair fixes the sample order exactly;
- **resumable** — ``state_dict()``/``load_state_dict()`` capture the cursor
  so a restored run continues with the SAME remaining batches the
  interrupted run would have seen (tested bit-identical);
- **mesh-aware** — batches land pre-sharded over the data/fsdp axes via
  :func:`.sharding.shard_batch` so the train step never re-lays them out;
- **multihost-aware** — with ``host_count > 1`` each host draws the
  disjoint ``host_index``-th stride of every global batch (per-host batch
  = batch // host_count; pair with the plugin-injected worker identity
  from ``guest.distributed``). Under real multi-process JAX the global
  array assembles from each process's rows via
  ``jax.make_array_from_process_local_data``; simulated multihost in one
  process yields the host-local rows unplaced.

TPU-first shape discipline: every batch is the same static
``[batch, seq_len + 1]`` int32 array (inputs ``[:, :-1]``, targets
``[:, 1:]`` — the convention :func:`..models.transformer.next_token_loss`
expects), so one compiled train step serves the whole run; a trailing
partial batch is dropped rather than shipped ragged.
"""
from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np


class TokenBatchLoader:
    """Iterate ``[batch, seq_len+1]`` windows over a token stream.

    ``tokens`` is any 1-D integer array-like — typically an ``np.memmap``
    of a tokenized corpus (the loader never copies the stream, only the
    gathered windows). Windows are non-overlapping and shuffled per epoch
    with a counter-based PRNG, so the order is a pure function of
    ``(seed, epoch)`` — no RNG state to persist beyond the cursor.
    """

    def __init__(self, tokens: Any, batch: int, seq_len: int,
                 seed: int = 0, shuffle: bool = True,
                 host_count: int = 1, host_index: int = 0,
                 mesh: Any = None):
        # np.asarray on a memmap is a no-copy view — the stream itself is
        # never copied, only gathered windows.
        self.tokens = np.asarray(tokens)
        if self.tokens.ndim != 1:
            raise ValueError(f"tokens must be 1-D, got {self.tokens.ndim}-D")
        if batch % host_count != 0:
            raise ValueError(f"batch {batch} not divisible by host_count {host_count}")
        if not 0 <= host_index < host_count:
            raise ValueError(f"host_index {host_index} not in [0, {host_count})")
        self.batch, self.seq_len = batch, seq_len
        self.window = seq_len + 1
        self.n_windows = len(self.tokens) // self.window
        if self.n_windows < batch:
            raise ValueError(
                f"stream has {self.n_windows} windows of {self.window} "
                f"tokens; need at least batch={batch}"
            )
        self.seed, self.shuffle = seed, shuffle
        self.host_count, self.host_index = host_count, host_index
        self.mesh = mesh
        self.epoch = 0
        self.step_in_epoch = 0  # next GLOBAL batch index within the epoch
        self._order_cache: Optional[tuple[int, np.ndarray]] = None

    @property
    def steps_per_epoch(self) -> int:
        return self.n_windows // self.batch  # trailing partial batch dropped

    # ----- deterministic order --------------------------------------------

    def _epoch_order(self, epoch: int) -> np.ndarray:
        # Cached per epoch: at corpus scale the permutation is O(n_windows)
        # to build and must not be recomputed per batch.
        if self._order_cache is not None and self._order_cache[0] == epoch:
            return self._order_cache[1]
        order = np.arange(self.n_windows, dtype=np.int64)
        if self.shuffle:
            # Generator seeded by (seed, epoch): the permutation is a pure
            # function of both, so resume never needs stored RNG state.
            np.random.default_rng((self.seed, epoch)).shuffle(order)
        self._order_cache = (epoch, order)
        return order

    # ----- iteration -------------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self.step_in_epoch >= self.steps_per_epoch:
            self.epoch += 1
            self.step_in_epoch = 0
        order = self._epoch_order(self.epoch)
        start = self.step_in_epoch * self.batch
        rows = order[start : start + self.batch]
        # Host shard: the host_index-th stride of the GLOBAL batch — every
        # host computes the same `order`, so shards are disjoint and cover.
        rows = rows[self.host_index :: self.host_count]
        batch = np.stack(
            [self.tokens[r * self.window : (r + 1) * self.window] for r in rows]
        ).astype(np.int32)
        self.step_in_epoch += 1
        if self.mesh is not None:
            import jax

            from .sharding import batch_spec, shard_batch

            if self.host_count == 1:
                return shard_batch(batch, self.mesh)
            if jax.process_count() > 1:
                # Real multihost: each process holds only its shard rows;
                # assemble the global array from process-local data (a
                # plain device_put of local rows would either fail on
                # non-addressable devices or ship a 1/host_count batch).
                # batch_spec is mesh-aware: a seq axis shards the sequence
                # dim too, matching what the train step expects.
                from jax.sharding import NamedSharding

                return jax.make_array_from_process_local_data(
                    NamedSharding(self.mesh, batch_spec(self.mesh)), batch,
                    global_shape=(self.batch, self.window),
                )
            # host_count > 1 simulated inside one process (tests): the
            # global mesh is fully addressable but this loader only built
            # its own shard — return it host-local, unplaced.
            return batch
        return batch

    # ----- checkpointable cursor ------------------------------------------

    def state_dict(self) -> dict:
        """The full cursor; small and JSON-able — save it next to the orbax
        train-state checkpoint (:mod:`.checkpoint`)."""
        return {
            "epoch": self.epoch,
            "step_in_epoch": self.step_in_epoch,
            "seed": self.seed,
            "shuffle": self.shuffle,
            "batch": self.batch,
            "seq_len": self.seq_len,
            # Corpus identity: a grown/swapped token stream changes the
            # permutation, silently repeating/skipping samples on resume.
            "n_windows": self.n_windows,
        }

    def load_state_dict(self, state: dict) -> None:
        for k in ("seed", "shuffle", "batch", "seq_len", "n_windows"):
            if state[k] != getattr(self, k):
                raise ValueError(
                    f"loader state mismatch on {k!r}: checkpoint has "
                    f"{state[k]!r}, loader has {getattr(self, k)!r} — "
                    "resuming with a different data order would silently "
                    "repeat or skip samples"
                )
        self.epoch = state["epoch"]
        self.step_in_epoch = state["step_in_epoch"]


def make_loader(tokens: Any, batch: int, seq_len: int,
                mesh: Any = None, seed: int = 0, shuffle: bool = True,
                host_count: Optional[int] = None,
                host_index: Optional[int] = None) -> TokenBatchLoader:
    """Build a :class:`TokenBatchLoader`. ``host_count``/``host_index``
    default to the jax process topology (1/0 single-controller), which in a
    Kata guest comes from the plugin-injected slice identity
    (``guest.distributed``)."""
    if host_count is None or host_index is None:
        import jax

        host_count = jax.process_count() if host_count is None else host_count
        host_index = jax.process_index() if host_index is None else host_index
    return TokenBatchLoader(
        tokens, batch, seq_len, seed=seed, shuffle=shuffle,
        host_count=host_count, host_index=host_index, mesh=mesh,
    )
