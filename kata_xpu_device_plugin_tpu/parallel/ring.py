"""Ring attention: sequence/context parallelism over an ICI axis.

Long-context is first-class (SURVEY §5): sequences longer than one chip's
HBM shard across the ``seq`` mesh axis; each device holds a [B, S/n] slice
of Q/K/V. K/V blocks rotate around the ring with ``lax.ppermute`` while each
device accumulates blockwise online-softmax attention of its local Q against
every block — compute overlaps the neighbor-to-neighbor ICI transfer, and no
device ever materializes the full sequence.

Causal masking works on *global* positions: the block arriving at step ``t``
on device ``i`` originated on device ``(i - t) mod n``, so its key offset is
known statically per step.

On TPU each arriving block is consumed by the pallas flash kernel
(:func:`..ops.flash.flash_block_attention`) — its blockwise online softmax
returns exactly the (out, logsumexp) pair the ring's running merge needs, so
sequence parallelism and the kernel compose; the XLA einsum path remains the
CPU/test fallback and the numerics oracle.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..compat.jaxapi import Mesh, P, axis_size, shard_map
from ..ops.attention import _expand_kv
from .mesh import AXIS_SEQ

NEG_INF = -1e30


def _local_ring_attention(
    q: jax.Array,  # [B, S_loc, H, D] — this device's query shard
    k: jax.Array,  # [B, S_loc, KV, D]
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    use_flash: bool = False,
    flash_interpret: bool = False,
    softcap: float = 0.0,
    window: int = 0,
) -> jax.Array:
    """Runs INSIDE shard_map over ``axis_name``.

    With ``use_flash`` each arriving K/V block is consumed by the pallas
    flash kernel (blockwise partial attention + logsumexp, global-position
    causal masking) and the ring carries the running (m, l, acc) merge —
    the sp path and the kernel compose instead of being two features that
    can't be used together (VERDICT r2 weak 6). Blocks entirely above the
    causal frontier are skipped without launching the kernel.

    ``window > 0`` (Mistral sliding window / Gemma-2 window cycles) masks
    keys to the global band ``(q_pos − window, q_pos]`` — and makes the
    ring CHEAPER, not unsupported: a non-wrapped block at hop ``t`` covers
    keys down to ``(idx−t)·S``, which falls out of every local query's band
    once ``t·S > S + window − 2``, so the rotation loop runs only
    ``min(n−1, (S + window − 2)//S)`` hops — both the kernel launches and
    the ppermute ICI traffic beyond the band are never emitted.
    """
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    if not use_flash:
        k = _expand_kv(k, H)
        v = _expand_kv(v, H)
    scale = 1.0 / jnp.sqrt(D).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    q_pos = idx * S + jnp.arange(S)  # global positions of local queries

    m = jnp.full((B, H, S, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, S, 1), jnp.float32)
    acc = jnp.zeros((B, S, H, D), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def accumulate_flash(t, k_blk, v_blk, m, l, acc):
        from ..ops.flash import flash_block_attention

        src = (idx - t) % n

        def masked(_):
            return m, l, acc

        def compute(_):
            out_blk, lse = flash_block_attention(
                q, k_blk, v_blk, q_offset=idx * S, k_offset=src * S,
                causal=causal, interpret=flash_interpret, softcap=softcap,
                window=window,
            )
            lse = lse.transpose(0, 2, 1)[..., None]  # [B, H, S, 1]
            m_new = jnp.maximum(m, lse)
            alpha = jnp.exp(m - m_new)  # rescale of the running sum
            beta = jnp.exp(lse - m_new)  # weight of this block's partial
            l_new = l * alpha + beta
            acc_new = acc * alpha.transpose(0, 2, 1, 3) + (
                out_blk.astype(jnp.float32) * beta.transpose(0, 2, 1, 3)
            )
            return m_new, l_new, acc_new

        if causal:
            # Entire block above the frontier: no kernel launch at all.
            return lax.cond(src > idx, masked, compute, operand=None)
        return compute(None)

    def accumulate(t, k_blk, v_blk, m, l, acc):
        if use_flash:
            return accumulate_flash(t, k_blk, v_blk, m, l, acc)
        src = (idx - t) % n  # ring owner of the block now resident here
        k_pos = src * S + jnp.arange(S)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32)) * scale
        if softcap > 0.0:
            # Gemma-2 logit cap, pre-mask like the reference: elementwise,
            # so the ring's cross-block (m, l, acc) merge is unaffected.
            logits = jnp.tanh(logits / softcap) * softcap
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]  # [S, S] global causal
            if window > 0:  # sliding band: keys in (q_pos − window, q_pos]
                mask &= k_pos[None, :] > q_pos[:, None] - window
            logits = jnp.where(mask[None, None], logits, NEG_INF)
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr.transpose(0, 2, 1, 3) + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
        )
        return m_new, l, acc

    def step(t, carry):
        k_blk, v_blk, m, l, acc = carry
        m, l, acc = accumulate(t, k_blk, v_blk, m, l, acc)
        # Rotate K/V to the next ring neighbor (ICI hop) for the next step.
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    # The block arriving for the last hop is consumed OUTSIDE the loop so the
    # final (dead) ppermute rotation is never emitted — fori_loop bodies are
    # traced once, so a trailing in-loop rotate would cost a full K+V ICI hop
    # every call. With a window, hops stop once non-wrapped blocks leave the
    # band (wrapped blocks, src > idx, are causal-dead on every device), so
    # the windowed ring does ceil-bounded work instead of n−1 rotations.
    t_last = n - 1
    if causal and window > 0:
        t_last = min(n - 1, (S + window - 2) // S)
    k_blk, v_blk, m, l, acc = lax.fori_loop(0, t_last, step, (k, v, m, l, acc))
    m, l, acc = accumulate(t_last, k_blk, v_blk, m, l, acc)
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1, 3)  # [B, S, H, 1]
    return (acc / denom).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    axis: str = AXIS_SEQ,
    use_flash: Optional[bool] = None,
    flash_interpret: bool = False,
    batch_axes=None,
    head_axis: Optional[str] = None,
    kv_head_axis: Optional[str] = None,
):
    """Returns ``ring_attn(q, k, v)`` operating on GLOBAL [B, S, H, D] arrays
    sharded over ``axis`` in S. Drop-in for the attention seam when the model
    runs sequence-parallel.

    ``use_flash=None`` auto-engages the pallas block kernel per ring step on
    TPU when the local shard shapes support it (``flash_interpret`` forces
    the interpret-mode kernel for CPU tests).

    Composition with the training mesh (seq × dp/fsdp × tp on ONE mesh):
    ``batch_axes`` shards the batch dim of q/k/v across the data axes and
    ``head_axis``/``kv_head_axis`` keep the q/kv head dims on the tensor
    axis — matching the shardings the surrounding GSPMD matmuls already
    produce, so entering the shard_map inserts no gather. Only the ring
    itself communicates (ppermute over ``axis``); the other axes just
    partition the local block."""

    @lru_cache(maxsize=None)  # one shard_map per distinct (softcap, window)
    def ring_for(softcap: float, window: int):
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P(batch_axes, axis, head_axis, None),
                P(batch_axes, axis, kv_head_axis, None),
                P(batch_axes, axis, kv_head_axis, None),
            ),
            out_specs=P(batch_axes, axis, head_axis, None),
            check_vma=False,  # online-softmax carries start axis-invariant
        )
        def ring(q, k, v):
            B, S_loc, H, D = q.shape
            if use_flash is None:
                from ..ops.attention import on_tpu
                from ..ops.flash import supports

                engage = on_tpu() and supports(S_loc, S_loc, D)
            else:
                engage = use_flash
            return _local_ring_attention(
                q, k, v, axis_name=axis, causal=True, use_flash=engage,
                flash_interpret=flash_interpret, softcap=softcap,
                window=window,
            )

        return ring

    def ring_attn(q, k, v, causal: bool = True,
                  q_offset: Optional[jax.Array] = None, window: int = 0,
                  logits_softcap: float = 0.0):
        if not causal or q_offset is not None:
            raise ValueError("ring attention supports causal self-attention only")
        # logits_softcap (Gemma-2) is modeled inside the ring accumulate —
        # einsum AND flash-block paths — so softcap configs train
        # sequence-parallel; _layer's softcap gate sees the kwarg here.
        # window (Mistral sliding window / Gemma-2 cycles) bounds both the
        # band mask and the number of ring hops — see _local_ring_attention.
        return ring_for(float(logits_softcap), int(window))(q, k, v)

    return ring_attn
