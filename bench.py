#!/usr/bin/env python3
"""Headline benchmark: Gemma-2B-architecture greedy decode throughput on the
attached TPU (BASELINE.json metric: "tokens/sec/chip").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

``vs_baseline`` is the fraction of the chip's memory-bandwidth roofline
achieved: greedy decode is HBM-bound — every generated token must stream all
model weights (plus the KV prefix) from HBM once — so

    roofline tok/s = batch * HBM_GB_per_s / bytes_read_per_step.

The reference publishes no numbers (SURVEY §6: "published": {}), so the
roofline is the honest fixed yardstick: 1.0 is perfect, and improvements
across rounds move the ratio up. Runs single-chip (the only hardware here);
multi-chip scaling is validated by __graft_entry__.dryrun_multichip.

Hardening (round-1 lesson: one transient backend failure must not cost the
round's perf evidence; round-3 lesson: the supervisor itself must fit the
driver's budget). A hung remote-TPU tunnel blocks *inside a native call*,
where no in-process watchdog (SIGALRM included) can fire — so the
measurement runs in a KILLABLE WORKER SUBPROCESS under a supervisor:

- a GLOBAL wall-clock budget (default 23 min, ``KATA_TPU_BENCH_TOTAL_BUDGET_S``)
  bounds everything the supervisor does; each stage's timeout is clipped to
  the time remaining minus a reserve for the CPU fallback, so the worst
  case — probe hang + attempt hang + fallback — still lands one JSON line
  inside the budget (r3 regression: 3×1500 s of TPU retries outlived the
  driver and the round recorded nothing);
- a short subprocess TUNNEL PROBE (one tiny dispatch, default 90 s) runs
  before attempt 1: a hung probe means the tunnel is wedged — sticky state,
  not a transient crash — so TPU attempts are skipped entirely;
- the supervisor SIGKILLs a hung worker, and classifies the hang as sticky:
  no further TPU retries (re-dispatching into a wedged tunnel at full
  timeout is how r3 died), straight to the labeled CPU fallback;
- fast *crashes* (nonzero rc) still retry in a fresh interpreter (a failed
  PJRT init is sticky in-process, not across processes);
- the CPU fallback pins ``JAX_PLATFORMS=cpu`` with smoke shapes so the
  round records *something*, clearly labeled with platform + config;
- if even the fallback fails the supervisor prints a machine-readable
  diagnostic JSON line and exits nonzero — never a bare stack trace.

Besides the headline bf16 number, the worker also measures int8 weight-only
decode (ops/quant.py) — reported as ``int8_tok_per_s`` against its own
actual-bytes roofline (``int8_vs_baseline``), so the quantized win shows up
in absolute tok/s without muddying the bf16 round-over-round series —
continuous-batching serving throughput (guest/serving.py, 16 mixed-length
requests through an 8-slot arena, ``serving_tok_per_s`` — plus a
draft-model speculative variant reporting ``serving_spec_tok_per_s`` and
the draft acceptance rate; ``KATA_TPU_BENCH_SPEC=0`` skips it), and
Gemma-2-style softcap prefill on the pallas flash path vs the XLA
reference (``softcap_prefill_flash_speedup``), a shared-prefix serving
A/B (``serving_prefix_*`` vs ``serving_prefix_cold_*`` — the same
system-prefix burst through a prefix-KV-store server and cold, reporting
the TTFT speedup and the fraction of prompt tokens whose prefill was
reused; ISSUE 5), a latency-under-load QPS sweep (ISSUE 8:
``serving_load_*`` — open-loop Poisson arrivals at 0.5×/1.5×/3× measured
capacity, TTFT + inter-token p50/p99 per rate, fifo_batch vs slo_chunked
admission with the oversubscribed-rate ITL-p99 and tok/s ratios;
``KATA_TPU_BENCH_LOAD=0`` skips it, ``make bench-load`` runs it alone),
a fused-dispatch A/B (ISSUE 13: ``serving_fused_*`` — slo_chunked
unfused K=1 baseline vs fused K∈{1,4} closed-loop tok/s plus ITL p99 at
3× capacity over identical arrivals; ``serving_fused_tok_per_s`` joins
the bench-trend headline set, ``KATA_TPU_BENCH_FUSED=0`` skips it),
a persistent-decode A/B (ISSUE 20: ``serving_persistent_*`` — greedy K=1
baseline vs multi-step K=8 vs the ``lax.while_loop`` persistent
executable, closed-loop tok/s + delivered steps per dispatch + devledger
dispatch-gap + ITL p99 ratio; ``serving_persistent_tok_per_s`` joins the
bench-trend headline set, ``KATA_TPU_BENCH_PERSISTENT=0`` skips it),
a KV layout + host-tier capacity A/B (ISSUE 14: ``serving_kv_*`` —
heads-vs-blocks pool placement at forced tp on a GQA/MQA config where
heads replicates, per-shard pool bytes + peak concurrent sessions +
preemptions at the SAME per-chip budget, and host-RAM tier on/off under
an idle-session zipfian resume workload; ``serving_kv_sessions`` joins
the bench-trend headline set, ``KATA_TPU_BENCH_KV=0`` skips it),
and a train-step MFU
section — one Llama-3-style ~256M model, one optimizer step on a 1-device
mesh, pallas-flash vs reference attention, reported against the chip's
public peak bf16 FLOP/s (``train_mfu``, ``train_flash_speedup``) so the
training path (flash fwd+bwd kernels, remat, GSPMD step) has chip
evidence, not just the decode path. All are crash-guarded side
sections emitted AFTER the banked headline line, each with its own
``KATA_TPU_BENCH_{INT8,SERVING,PREFIX,SOFTCAP,LOAD,FUSED,TRAIN}=0`` kill switch (the
supervisor flips all of them off on retries and in the CPU fallback); the
optional ``KATA_TPU_BENCH_W8A8=1`` adds the int8×int8-dot decode variant
inside the int8 section.

Flags: --profile-dir DIR dumps a jax.profiler (xplane) trace of the measured
decode runs. --smoke runs tiny shapes (harness validation, not the metric).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Optional

# Per-chip HBM bandwidth (GB/s) by TPU generation — public spec-sheet numbers.
HBM_GBPS = {"v5e": 819.0, "v5p": 2765.0, "v4": 1228.0, "v6e": 1640.0, "cpu": 50.0}
# Per-chip peak bf16 matmul throughput (TFLOP/s) by generation — public spec
# sheets; the denominator of the train section's MFU.
MXU_TFLOPS = {"v5e": 197.0, "v5p": 459.0, "v4": 275.0, "v6e": 918.0, "cpu": 0.1}

BATCH = 8
PROMPT_LEN = 128
DECODE_STEPS = 128
PREFILL_LEN = 2048  # separate prefill metric: long enough for flash to matter

METRIC = "gemma2b_decode_tok_per_s_per_chip"

MAX_ATTEMPTS = int(os.environ.get("KATA_TPU_BENCH_ATTEMPTS", "3"))
# 1080s: a fully COLD attempt (no tunnel executable cache) runs the
# headline (~3 min incl. compiles) plus four side sections, of which the
# r5 train section alone adds two fwd+bwd compiles (~6-8 min cold). The
# supervisor clips every stage to the global budget minus the fallback
# reserve regardless, so a large value here cannot break the budget
# invariant — it only stops a cold train section from being killed when
# time actually remains. The headline banks before any side section, and
# train runs LAST, so a mid-train kill still lands everything else.
ATTEMPT_TIMEOUT_S = int(os.environ.get("KATA_TPU_BENCH_ATTEMPT_TIMEOUT_S", "1080"))
SMOKE_TIMEOUT_S = int(os.environ.get("KATA_TPU_BENCH_SMOKE_TIMEOUT_S", "300"))
# Probe timeout: KATATPU_BENCH_PROBE_TIMEOUT is the documented knob (the
# obs-env spelling); the legacy KATA_TPU_BENCH_PROBE_TIMEOUT_S name keeps
# working. The last 10 BENCH_TPU runs all died on "probe: hung" at the
# default — operators need to shorten it (fail fast to the CPU fallback)
# without editing the bench.
PROBE_TIMEOUT_S = int(
    os.environ.get("KATATPU_BENCH_PROBE_TIMEOUT")
    or os.environ.get("KATA_TPU_BENCH_PROBE_TIMEOUT_S", "90")
)
# Hard ceiling on EVERYTHING the supervisor does (probe + attempts +
# fallback). 23 min keeps the worst case inside the driver's budget with
# margin. Cost model (r5, measured): headline ~3 min cold; +int8/serving/
# softcap ~3-4 min; +train ~6-8 min cold (two fwd+bwd compiles) but the
# tunnel caches executables across processes, so a warm full run is
# ~3-4 min total. The headline banks first and train runs last, so a
# budget kill costs only the tail sections.
TOTAL_BUDGET_S = int(os.environ.get("KATA_TPU_BENCH_TOTAL_BUDGET_S", "1380"))
# Time held back from TPU attempts so the CPU fallback can always run.
FALLBACK_RESERVE_S = SMOKE_TIMEOUT_S + 30


# --------------------------------------------------------------------------
# Supervisor: retries a killable worker; the ONLY stdout it emits is the one
# JSON result line (worker stdout is captured, stderr passes through).
# --------------------------------------------------------------------------


def probe_tunnel(deadline: float,
                 timeout_s: Optional[float] = None) -> tuple[bool, bool, str]:
    """One tiny dispatch in a killable subprocess: (ok, hung, message).
    ``timeout_s`` overrides the PROBE_TIMEOUT_S cap (the watchdog passes
    its --probe-timeout through; without the override, values above the
    env default would be silently clamped).

    ``jax.devices()`` can succeed while the transport is dead, so the probe
    round-trips an actual computation. A probe that must be SIGKILLed means
    the tunnel is in sticky wedged state (observed: hours-long), not a
    transient failure — the caller should skip TPU attempts entirely.

    The probe also reports the backend platform: a JAX that comes up on CPU
    (plugin missing, env leak) completes the dispatch fine but means there is
    no tunnel to measure through — that is "down", not "healthy".
    """
    cap = PROBE_TIMEOUT_S if timeout_s is None else timeout_s
    timeout = max(10.0, min(cap, deadline - time.monotonic()))
    code = (
        "import jax, jax.numpy as jnp, numpy as np\n"
        "np.asarray(jnp.ones((8,)) + 1)\n"
        "print('probe-ok', jax.devices()[0].platform)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        # The probe's stderr is merged into stdout — whatever the killed
        # interpreter managed to print (a PJRT handshake line, a tunnel
        # error) is the only post-mortem evidence of WHERE it wedged, so
        # the tail rides into the result line's error field instead of
        # being dropped on the floor.
        out, _ = proc.communicate()
        tail = _tail(out)
        return False, True, (
            f"probe: hung (killed after {timeout:.0f}s)"
            + (f", tail={tail}" if tail else "")
        )
    if proc.returncode == 0 and "probe-ok tpu" in (out or ""):
        return True, False, ""
    if proc.returncode == 0 and "probe-ok" in (out or ""):
        plat = (out or "").rsplit("probe-ok", 1)[-1].strip()
        return False, False, f"probe: completed but platform={plat!r}, not tpu"
    return False, False, f"probe: rc={proc.returncode}, tail={_tail(out)}"


def supervise(args: argparse.Namespace) -> int:  # lint: allow(JX004) wall-clock subprocess watchdog, no jax compute timed here
    deadline = time.monotonic() + TOTAL_BUDGET_S
    worker_cmd = [sys.executable, os.path.abspath(__file__), "--worker"]
    if args.profile_dir:
        worker_cmd += ["--profile-dir", args.profile_dir]
    if args.smoke:
        worker_cmd += ["--smoke"]
    if args.no_overlap:
        worker_cmd += ["--no-overlap"]

    errors: list[str] = []

    def run_once(cmd, env, timeout, label, configured=None):
        """Run one killable worker; returns (metric_line | None, hung).

        ``configured`` is the stage's un-clipped timeout — used only to label
        a kill honestly when ``timeout`` was budget-clipped below it.
        """
        configured = configured if configured is not None else timeout
        timeout = max(10.0, min(timeout, deadline - time.monotonic()))
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=sys.stderr, text=True
        )
        hung = False
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            hung = True
            # A kill at a budget-clipped timeout is NOT evidence of a wedge —
            # label it distinctly so the post-mortem can't misread it. The
            # slack is ABSOLUTE (180 s), not fractional: the configured
            # stage timeout (1080) sits above the budget's maximum
            # grantable window (1380 − reserve 330 − probe ≤ 90 ≈
            # 960–1050 s), so any first attempt killed with ≥ 900 s of
            # window had a fair run — that is a hang (the r3 post-mortem
            # distinction). A fractional threshold (0.9×configured = 972)
            # would sit ABOVE the slow-probe window of 960 s and mislabel
            # a genuine first-attempt wedge; the clip label is for
            # late-round attempts whose window was truly cut short.
            kind = (
                "hung" if timeout >= configured - 180
                else "budget clip, not a hang"
            )
            errors.append(f"{label}: killed after {timeout:.0f}s ({kind})")
            out = out or ""
        line = _last_json_line(out)
        if line is None and not hung:
            errors.append(f"{label}: rc={proc.returncode}, tail={_tail(out)}")
        if line is not None and proc.returncode != 0:
            # A printed metric line is by construction a COMPLETED headline
            # measurement — the worker banks the bf16-only line before the
            # extras — so accept it even from a worker that then hung or
            # crashed (annotated, so the partial run is visible).
            line["note"] = (
                f"worker rc={proc.returncode} after the headline "
                "measurement (extras section hung or crashed)"
            )
        return line, hung

    attempts = 0
    tunnel_dead = False
    cpu_pinned = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    # The smoke-shaped fallback applies to any FULL bench run (even one the
    # caller pinned to CPU — full Gemma-2B shapes can time out there too);
    # --smoke runs are themselves harness validation and get no fallback.
    has_fallback = not args.smoke
    # Full attempts are pointless below this window (the cold HEADLINE
    # alone needs ~3 min incl. compiles, and a banked headline is the
    # attempt's point — side sections are expendable); dispatching a
    # doomed budget-clipped attempt both wastes the reserve and gets
    # misread as a hang when killed.
    min_attempt_s = 60 if args.smoke else 360
    if not cpu_pinned:
        ok, hung, msg = probe_tunnel(deadline)
        if not ok:
            errors.append(msg)
        if hung:
            # Sticky wedge: re-dispatching at full timeout is how r3 lost
            # its round. Go straight to the labeled CPU fallback.
            tunnel_dead = True
            print(f"bench: {msg}; skipping TPU attempts", file=sys.stderr, flush=True)

    while not tunnel_dead and attempts < MAX_ATTEMPTS:
        remaining = deadline - time.monotonic() - (
            FALLBACK_RESERVE_S if has_fallback else 0
        )
        if remaining < min_attempt_s:
            errors.append(f"budget: {remaining:.0f}s left before fallback reserve")
            break
        env = dict(os.environ)
        if attempts >= 1:
            # Belt and braces: the pallas decode kernel is already opt-in
            # (it measured slower than XLA — see ops.attention.decode_eligible),
            # but if attempt 1 crashed, force it hard-off so an opted-in
            # kernel/runtime incompatibility can't cost the round; likewise
            # drop the side-measurements so the retry still delivers the
            # bf16 headline number.
            env["KATA_TPU_DECODE_KERNEL"] = "0"
            env["KATA_TPU_BENCH_INT8"] = "0"
            env["KATA_TPU_BENCH_SERVING"] = "0"
            env["KATA_TPU_BENCH_SOFTCAP"] = "0"
            env["KATA_TPU_BENCH_TRAIN"] = "0"
            env["KATA_TPU_BENCH_PREFIX"] = "0"
            env["KATA_TPU_BENCH_PAGED"] = "0"
            env["KATA_TPU_BENCH_KV"] = "0"
            env["KATA_TPU_BENCH_DECODE_ATTN"] = "0"
            env["KATA_TPU_BENCH_FAULTS"] = "0"
            env["KATA_TPU_BENCH_LOAD"] = "0"
            env["KATA_TPU_BENCH_FUSED"] = "0"
            env["KATA_TPU_BENCH_PERSISTENT"] = "0"
            env["KATA_TPU_BENCH_TP"] = "0"
            env["KATA_TPU_BENCH_DEGRADED"] = "0"
            env["KATA_TPU_BENCH_OBS"] = "0"
        attempts += 1
        stage_timeout = SMOKE_TIMEOUT_S if args.smoke else ATTEMPT_TIMEOUT_S
        line, hung = run_once(
            list(worker_cmd),
            env,
            min(stage_timeout, remaining),
            f"attempt {attempts}",
            configured=stage_timeout,
        )
        if line is not None:
            line["attempts"] = attempts
            print(json.dumps(line), flush=True)
            return 0
        if hung:
            # Never re-dispatch after a kill: on the tunnel a hang is sticky
            # wedged state (r3's fatal retry loop); on CPU it means the
            # shapes are too slow for the budget and a retry changes nothing.
            break
        if attempts < MAX_ATTEMPTS:
            delay = min(5.0 * (2 ** (attempts - 1)), 30.0)
            print(
                f"bench: {errors[-1]}; retrying in {delay:.0f}s "
                f"({attempts + 1}/{MAX_ATTEMPTS})",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(delay)

    if has_fallback:
        # Last resort: a labeled CPU smoke figure beats an empty round.
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["KATA_TPU_DECODE_KERNEL"] = "0"
        env["KATA_TPU_BENCH_INT8"] = "0"
        env["KATA_TPU_BENCH_SERVING"] = "0"
        env["KATA_TPU_BENCH_SOFTCAP"] = "0"
        env["KATA_TPU_BENCH_TRAIN"] = "0"
        env["KATA_TPU_BENCH_PREFIX"] = "0"
        env["KATA_TPU_BENCH_PAGED"] = "0"
        env["KATA_TPU_BENCH_KV"] = "0"
        env["KATA_TPU_BENCH_DECODE_ATTN"] = "0"
        env["KATA_TPU_BENCH_FAULTS"] = "0"
        env["KATA_TPU_BENCH_LOAD"] = "0"
        env["KATA_TPU_BENCH_FUSED"] = "0"
        env["KATA_TPU_BENCH_PERSISTENT"] = "0"
        env["KATA_TPU_BENCH_TP"] = "0"
        env["KATA_TPU_BENCH_DEGRADED"] = "0"
        env["KATA_TPU_BENCH_OBS"] = "0"
        cmd = list(worker_cmd) + ["--smoke", "--fallback"]
        line, _hung = run_once(cmd, env, SMOKE_TIMEOUT_S, "cpu-fallback")
        if line is not None:
            line["attempts"] = attempts
            if attempts == 0:
                # Honest labeling (BENCH_r05 lesson): with attempts == 0 no
                # TPU attempt was ever dispatched — "after TPU attempts
                # failed" misdescribes the round whether the probe hung,
                # the probe failed, or the budget ran out first. The error
                # field keeps the actual post-mortem as-is.
                line["note"] = (
                    ("probe hung; " if tunnel_dead else "")
                    + "no TPU attempt made — cpu fallback, not a TPU number"
                )
            else:
                # The worker's note deliberately carries no attempt
                # history (it can't know it); the supervisor does.
                line["note"] = (
                    f"cpu fallback after {attempts} failed TPU "
                    "attempt(s) — not a TPU number"
                )
            line["error"] = "; ".join(errors)[-600:]
            print(json.dumps(line), flush=True)
            return 0

    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": None,
                "unit": "tok/s",
                "vs_baseline": None,
                "error": "; ".join(errors)[-1000:],
                "attempts": attempts,
                "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
            }
        ),
        flush=True,
    )
    return 1


def _tail(out) -> str:
    out = (out or "").strip()
    return out.splitlines()[-1][:200] if out else ""


def _last_json_line(out: str):
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if obj.get("metric") == METRIC:
                return obj
    return None


# --------------------------------------------------------------------------
# Worker: one measurement attempt. Raises/exits nonzero on failure; the
# supervisor owns retries and the kill switch.
# --------------------------------------------------------------------------


def _detect_chip_spec(dev, table: dict) -> float:
    """Look up a per-generation spec (HBM GB/s, peak TFLOP/s) by device
    kind substring; unrecognized kinds (the axon relay reports 'TPU v5
    lite', matching no key) fall back to v5e on TPU, cpu otherwise."""
    kind = str(getattr(dev, "device_kind", "")).lower()
    for key, val in table.items():
        if key in kind:
            return val
    from kata_xpu_device_plugin_tpu.ops.attention import on_tpu

    return table["v5e" if on_tpu() else "cpu"]


def detect_hbm_gbps(dev) -> float:
    return _detect_chip_spec(dev, HBM_GBPS)


def detect_mxu_tflops(dev) -> float:
    return _detect_chip_spec(dev, MXU_TFLOPS)


def worker(args: argparse.Namespace) -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Some platform plugins ignore the env var; pin through jax.config
        # too (must happen before any backend initializes).
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    # Persistent compilation cache (ISSUE 3): the per-executable compile
    # cost the phase breakdown keeps showing is paid once per MACHINE —
    # the second worker process (a retry, the next round's run) loads the
    # compiled binaries instead of rebuilding them. Best-effort: an
    # unwritable cache dir degrades to the old always-compile behavior.
    from kata_xpu_device_plugin_tpu.compat.jaxapi import (
        enable_compilation_cache,
    )

    compile_cache_dir = enable_compilation_cache()

    devs = jax.devices()
    if not devs:
        raise RuntimeError("no devices visible")

    import jax.numpy as jnp
    import numpy as np

    from kata_xpu_device_plugin_tpu.models import gemma_2b_bench, tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import (
        decode,
        forward,
        fuse_decoder_params,
        init_params,
        prefill,
    )
    from kata_xpu_device_plugin_tpu.ops.attention import (
        decode_eligible,
        flash_attention,
        flash_eligible,
        reference_attention,
    )

    # A real tiny dispatch: devices() can succeed while the transport is
    # dead; one add must round-trip before we trust the backend.
    np.asarray(jnp.ones((8,)) + 1)

    global BATCH, PROMPT_LEN, DECODE_STEPS, PREFILL_LEN
    if args.smoke:
        cfg = tiny_test_config()
        BATCH, PROMPT_LEN, DECODE_STEPS, PREFILL_LEN = 2, 16, 8, 64
    else:
        cfg = gemma_2b_bench()
    max_len = PROMPT_LEN + DECODE_STEPS

    # ISSUE 2: the worker streams its measurement spans (compile / prefill
    # / decode) into the obs JSONL sink and parses them back into the
    # per-phase breakdown the result line reports — one pipeline for bench
    # evidence and production telemetry. KATATPU_OBS_FILE pins the path;
    # default is a fresh temp file per attempt.
    import tempfile

    from kata_xpu_device_plugin_tpu import obs

    events_path = os.environ.get("KATATPU_OBS_FILE") or os.path.join(
        tempfile.mkdtemp(prefix="bench_obs_"), "events.jsonl"
    )
    # A pinned path may already hold earlier runs' events (the sink
    # appends); remember where this run starts so the phase aggregation
    # below cannot mix runs.
    events_offset = (
        os.path.getsize(events_path) if os.path.exists(events_path) else 0
    )
    obs.set_default_sink(obs.EventSink(events_path))

    key = jax.random.PRNGKey(0)
    # Fused inference layout: wqkv / w_gateup stream each weight group in one
    # matmul on the bandwidth-bound decode step.
    params = jax.jit(
        lambda k: fuse_decoder_params(init_params(k, cfg, dtype=jnp.bfloat16))
    )(key)
    jax.block_until_ready(params)

    def run(p, seed: int, tag: str = "bench"):  # jaxguard: hot
        # Fresh prompt every iteration and a full device→host transfer of
        # the result: the remote-device tunnel can serve repeated identical
        # executions from cache and does not reliably block on
        # block_until_ready, so only transferred, input-varying runs measure
        # real decode time. Prefill and decode are timed SEPARATELY — the
        # tiny `last`-token transfer fences prefill completion so the decode
        # window contains only the decode scan (prefill is compute-bound;
        # folding it in understated decode tok/s by a few percent in r02).
        # ``tag`` namespaces the emitted spans (int8/w8a8 reruns must not
        # pollute the bf16 ``bench.*`` phase aggregates); tag=None silences
        # them (warm-up runs measure compile, not prefill/decode).
        prompt = jax.random.randint(
            jax.random.PRNGKey(seed), (BATCH, PROMPT_LEN), 0,
            cfg.vocab_size, dtype=jnp.int32,
        )
        np.asarray(prompt)  # jaxguard: allow(JG101) pre-materialize the input OUTSIDE the timed window
        t0 = time.perf_counter()
        with obs.span(f"{tag}.prefill", tokens=BATCH * PROMPT_LEN) if tag \
                else _null_span():
            caches, last, _pos = prefill(p, prompt, cfg, max_len)
            np.asarray(last)  # jaxguard: allow(JG101) tiny last-token transfer fences prefill (JX004)
        t_pre = time.perf_counter() - t0
        t1 = time.perf_counter()
        # pos as the static python int: decode's bound check must not cost a
        # device->host fetch inside the timed window.
        with obs.span(f"{tag}.decode", tokens=BATCH * DECODE_STEPS) if tag \
                else _null_span():
            out = np.asarray(decode(p, caches, last, PROMPT_LEN, cfg, DECODE_STEPS))  # jaxguard: allow(JG101) the transfer IS the timing fence (JX004)
        return t_pre, time.perf_counter() - t1, out

    from contextlib import nullcontext as _null_span

    with obs.span("bench.compile"):
        run(params, 0, tag=None)  # warm-up: compiles prefill + decode scan

    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    times = [run(params, seed)[:2] for seed in range(1, 4)]
    if args.profile_dir:
        jax.profiler.stop_trace()
    dt = min(t for _, t in times)  # decode-only window
    prompt_prefill_s = min(t for t, _ in times)
    best_e2e_s = min(tp + td for tp, td in times)  # best single run, not mixed mins

    # ----- separate prefill metric: pallas flash vs XLA reference ----------
    prefill_flash = flash_eligible(PREFILL_LEN, PREFILL_LEN, cfg.head_dim)

    def time_prefill(fn) -> float:  # jaxguard: hot
        best = float("inf")
        for seed in range(4):
            toks = jax.random.randint(
                jax.random.PRNGKey(100 + seed), (1, PREFILL_LEN), 0,
                cfg.vocab_size, dtype=jnp.int32,
            )
            np.asarray(toks)  # jaxguard: allow(JG101) pre-materialize the input OUTSIDE the timed window
            t0 = time.perf_counter()
            np.asarray(fn(params, toks))  # jaxguard: allow(JG101, JG404) defensive: fn is an opaque jitted closure the dataflow cannot taint; the transfer IS the timing fence (JX004)
            elapsed = time.perf_counter() - t0
            if seed > 0:  # first run includes compile
                best = min(best, elapsed)
        return best

    # The jitted fns return only the LAST-TOKEN logits: that still forces the
    # full forward on varying inputs, but the host transfer is ~1 MB instead
    # of the [S, vocab] fp32 tensor — which at tunnel bandwidth would swamp
    # the flash-vs-reference delta being measured.
    prefill_s = {
        "reference": time_prefill(
            jax.jit(lambda p, t: forward(p, t, cfg, attn_fn=reference_attention)[:, -1])
        )
    }
    if prefill_flash:
        prefill_s["flash"] = time_prefill(
            jax.jit(lambda p, t: forward(p, t, cfg, attn_fn=flash_attention)[:, -1])
        )

    total_tokens = BATCH * DECODE_STEPS  # the decode scan runs exactly this many
    tok_per_s = total_tokens / dt

    # Roofline: each decode step streams the weights once (bf16) plus the
    # mean KV prefix for the whole batch.
    param_bytes = cfg.num_params() * 2
    mean_prefix = PROMPT_LEN + DECODE_STEPS / 2
    kv_bytes_per_step = 2 * cfg.n_layers * BATCH * mean_prefix * cfg.kv_dim * 2
    hbm_gbps = detect_hbm_gbps(devs[0])
    roofline_steps = hbm_gbps * 1e9 / (param_bytes + kv_bytes_per_step)
    roofline_tok_s = roofline_steps * BATCH

    def measure_int8() -> dict:
        # int8 weight-only decode (ops/quant.py): same harness, quantized
        # layer weights — ~half the streamed bytes — scored against its OWN
        # roofline (actual pytree bytes, not 2 B/param) so the fraction stays
        # honest while absolute tok/s shows the win. A SIDE measurement: it
        # must never cost the bf16 headline, so the worker prints the
        # bf16-only result line BEFORE calling this (a hang here loses only
        # the extras), crashes are reported as int8_error, and the
        # supervisor disables it on retries (KATA_TPU_BENCH_INT8=0).
        if os.environ.get("KATA_TPU_BENCH_INT8", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.ops.quant import (
                params_hbm_bytes,
                quantize_decoder_params,
            )

            qparams = jax.jit(quantize_decoder_params)(params)
            jax.block_until_ready(qparams)
            # warm-up: int8 layouts recompile prefill+decode
            run(qparams, 0, tag=None)
            q_dt = min(
                t for _, t in [
                    run(qparams, seed, tag="int8")[:2] for seed in range(4, 7)
                ]
            )
            int8_bytes = params_hbm_bytes(qparams) + kv_bytes_per_step
            int8_roofline_tok_s = hbm_gbps * 1e9 / int8_bytes * BATCH
            out = {
                "int8_tok_per_s": round(total_tokens / q_dt, 1),
                "int8_vs_baseline": round(
                    total_tokens / q_dt / int8_roofline_tok_s, 4
                ),
                "int8_decode_s": round(q_dt, 4),
                "int8_speedup": round(dt / q_dt, 3),
            }
            if os.environ.get("KATA_TPU_BENCH_W8A8", "") == "1":
                # Opt-in: int8×int8 MXU dots (ops.quant.w8a8_enabled) — the
                # candidate for closing the int8 convert-tax gap
                # (BASELINE.md ablation). The flag binds at TRACE time
                # (explicit set_w8a8, not env mutation — the env snapshot
                # is import-time), so jax.clear_caches() forces fresh
                # traces — it also wipes every other cached executable
                # (the serving section after this re-warms itself, so that
                # is only recompile time).
                from kata_xpu_device_plugin_tpu.ops.quant import set_w8a8

                set_w8a8(True)
                try:
                    jax.clear_caches()
                    run(qparams, 10, tag=None)  # warm-up under the W8A8 trace
                    w_dt = min(
                        t for _, t in [
                            run(qparams, s, tag="w8a8")[:2] for s in (11, 12, 13)
                        ]
                    )
                    out["w8a8_tok_per_s"] = round(total_tokens / w_dt, 1)
                    out["w8a8_vs_baseline"] = round(
                        total_tokens / w_dt / int8_roofline_tok_s, 4
                    )
                finally:
                    set_w8a8(False)
            return out
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"int8_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_softcap_prefill() -> dict:
        # Gemma-2's attn-logit softcap on the pallas flash path (r4): same
        # bench architecture with the cap enabled, flash vs XLA reference —
        # the number that shows the softcap no longer forfeits the kernel.
        # SIDE measurement: runs after the banked headline, crash-guarded,
        # KATA_TPU_BENCH_SOFTCAP=0 disables.
        if (
            args.smoke
            or not prefill_flash
            or os.environ.get("KATA_TPU_BENCH_SOFTCAP", "1") == "0"
        ):
            return {}
        try:
            from dataclasses import replace as _replace

            cfg_sc = _replace(cfg, attn_logits_softcap=50.0)
            ref_s = time_prefill(
                jax.jit(
                    lambda p, t: forward(
                        p, t, cfg_sc, attn_fn=reference_attention
                    )[:, -1]
                )
            )
            fl_s = time_prefill(
                jax.jit(
                    lambda p, t: forward(p, t, cfg_sc, attn_fn=flash_attention)[
                        :, -1
                    ]
                )
            )
            return {
                "softcap_prefill_flash_s": round(fl_s, 4),
                "softcap_prefill_reference_s": round(ref_s, 4),
                "softcap_prefill_flash_speedup": round(ref_s / fl_s, 3),
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"softcap_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_serving() -> dict:  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
        # Continuous-batching throughput (guest/serving.py): 16 mixed-length
        # requests through an 8-slot arena, measured OVERLAPPED (the
        # pipelined default) and LOCK-STEP (--no-overlap's config) so the
        # decode tok/s and TTFT delta of the pipeline lands in the result
        # line (ISSUE 3 acceptance). Runs in smoke mode too — tiny shapes
        # are exactly where the host-side scheduling gap the overlap hides
        # is widest. A SIDE measurement with the same protections as int8:
        # after the banked headline line, crashes report as serving_error,
        # KATA_TPU_BENCH_SERVING=0 disables.
        if os.environ.get("KATA_TPU_BENCH_SERVING", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

            # Smoke keeps the full 64-token budgets but halves the chunk:
            # a pipeline only has rounds to overlap when each request
            # spans several chunks (budget == chunk degenerates to
            # lock-step by the dispatch gate's design).
            srv_chunk = 8 if args.smoke else 16

            def make_server(overlap):
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=PROMPT_LEN + 72,
                    chunk=srv_chunk, prefill_buckets=(PROMPT_LEN,),
                    overlap=overlap,
                    # Explicit 0: a daemon-injected KATA_TPU_PREFIX_CACHE_
                    # TOKENS env must not attach a prefix store to the
                    # overlap A/B (measure_prefix owns that comparison).
                    prefix_cache_tokens=0,
                )

            rng = jax.random.PRNGKey(42)
            new_per_req = 64
            len_step = max(1, PROMPT_LEN // 8)  # smoke-safe mixed lengths

            def reqs(srv, count, salt=0):
                out = []
                for i in range(count):
                    n = PROMPT_LEN - (i % 4) * len_step  # mixed, one bucket
                    p = jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (n,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    )
                    out.append(srv.submit(np.asarray(p), new_per_req))
                return out

            # Warm-up server: same shapes → the timed runs reuse the
            # compiled prefill/decode/_write_slot executables (every other
            # measurement here excludes compiles; this one must too). The
            # warm-up PROMPT differs (salt) so the remote tunnel's
            # identical-execution cache cannot serve the timed request.
            # Full queue-pressure warm-up (2×BATCH requests through the
            # overlapped server): one pass compiles the whole executable
            # family — the [N, bucket] batched-admission prefill, the
            # single-row refill prefill, _write_slot(s), the decode chunk,
            # and the overlap path's row merge — so neither A/B side pays
            # a compile inside its timed window.
            warm = make_server(overlap=True)
            reqs(warm, 2 * BATCH, salt=1000)
            warm.run()

            def timed_run(overlap, salt):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                # Best-of-3 like the headline: one serving run is ~tens of
                # ms at smoke shapes, well inside scheduler-noise range,
                # and the A/B delta is the whole point of the section.
                best = None
                for trial in range(3):
                    srv = make_server(overlap)
                    rids = reqs(srv, 2 * BATCH, salt=salt + trial)
                    t0 = time.perf_counter()
                    results = srv.run()
                    dt_s = time.perf_counter() - t0
                    total = sum(len(results[r]) for r in rids)
                    st = srv.stats()
                    if best is None or dt_s < best[1]:
                        best = (total, dt_s, st, len(rids))
                return best

            overlap_on = not args.no_overlap
            total, dt_s, st, n_req = timed_run(overlap_on, salt=0)
            ttft_sum = st["ttft_s"] or {}
            itl_sum = st["decode_token_s"] or {}
            out = {
                "serving_tok_per_s": round(total / dt_s, 1),
                "serving_requests": n_req,
                "serving_s": round(dt_s, 3),
                "serving_ttft_mean_s": round(ttft_sum.get("mean", 0.0), 4),
                # Latency percentiles (ISSUE 6 satellite → ROADMAP item 4's
                # latency-under-load bench): TTFT and inter-token latency
                # p50/p99 from the server's Rolling summaries — the
                # figures users of a loaded deployment actually feel.
                "serving_ttft_p50_s": round(ttft_sum.get("p50", 0.0), 4),
                "serving_ttft_p99_s": round(ttft_sum.get("p99", 0.0), 4),
                "serving_itl_p50_s": round(itl_sum.get("p50", 0.0), 5),
                "serving_itl_p99_s": round(itl_sum.get("p99", 0.0), 5),
                "serving_overlap": overlap_on,
            }
            if overlap_on:
                # A/B inside one worker: the same traffic through the
                # lock-step loop — the tok/s and TTFT deltas the pipeline
                # is worth on this platform. (--no-overlap instead makes
                # lock-step the PRIMARY config, for two-run A/Bs.)
                nv_total, nv_dt, nv_st, _ = timed_run(False, salt=5000)
                nv_ttft = (nv_st["ttft_s"] or {}).get("mean", 0.0)
                out.update({
                    "serving_noverlap_tok_per_s": round(nv_total / nv_dt, 1),
                    "serving_noverlap_s": round(nv_dt, 3),
                    "serving_noverlap_ttft_mean_s": round(nv_ttft, 4),
                    "serving_overlap_speedup": round(
                        (total / dt_s) / (nv_total / nv_dt), 3
                    ),
                })
            # Speculative sub-section: skipped in smoke (the A/B above is
            # the smoke payload; spec warms a second executable family).
            if not args.smoke and os.environ.get("KATA_TPU_BENCH_SPEC", "1") == "1":
                # Draft-model speculative serving: a depth-truncated
                # self-draft (zero extra weights to load) through the same
                # arena; reports throughput AND the acceptance rate — the
                # number k should be tuned by (VERDICT r4 next #5).
                from kata_xpu_device_plugin_tpu.models import self_draft

                cyc = max(1, len(cfg.window_cycle))
                depth = max(cyc, (cfg.n_layers // 4) // cyc * cyc)
                draft = self_draft(params, cfg, depth)

                def make_spec_server():
                    return GenerationServer(
                        params, cfg, max_batch=BATCH,
                        max_len=PROMPT_LEN + 72 + 4, chunk=16,
                        prefill_buckets=(PROMPT_LEN,), speculative_k=4,
                        # Explicit opt-in (ISSUE 8 satellite): spec is
                        # demoted behind KATA_TPU_SPEC — the A/B measures
                        # the path deliberately.
                        spec_opt_in=True,
                        draft=draft,
                    )

                warm_s = make_spec_server()
                reqs(warm_s, 1, salt=2000)
                warm_s.run()
                spec = make_spec_server()
                s_rids = reqs(spec, 2 * BATCH, salt=3000)
                t1 = time.perf_counter()
                s_results = spec.run()
                s_dt = time.perf_counter() - t1
                s_total = sum(len(s_results[r]) for r in s_rids)
                st = spec.stats()
                out.update({
                    "serving_spec_tok_per_s": round(s_total / s_dt, 1),
                    "serving_spec_draft_depth": depth,
                    "serving_spec_draft_acceptance": st.get(
                        "draft_acceptance", 0.0),
                })
            return out
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"serving_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_prefix() -> dict:  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
        # Shared-prefix KV cache A/B (ISSUE 5): the same burst of prompts
        # that all share a long system prefix, served once through a
        # prefix-store server (suffix-only prefill) and once cold — the
        # TTFT and prefill-FLOP reduction the radix store is worth on this
        # platform. Runs in smoke too (the acceptance gate: ≥50% of prompt
        # tokens reused at 100% hit rate on the timed phase). SIDE
        # measurement with the usual protections: after the banked
        # headline, crash-guarded, KATA_TPU_BENCH_PREFIX=0 disables.
        if os.environ.get("KATA_TPU_BENCH_PREFIX", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.prefix_cache import (
                PrefixStore,
            )
            from kata_xpu_device_plugin_tpu.guest.serving import (
                GenerationServer,
            )

            shared_len = PROMPT_LEN          # the common system prefix
            tail_len = max(2, PROMPT_LEN // 8)  # per-request unique suffix
            n_prompt = shared_len + tail_len
            # Ladder: one bucket for the suffix, one at the shared-prefix
            # boundary (the match), one fitting the whole prompt (cold).
            buckets = (tail_len, shared_len, n_prompt)
            new_per_req = 16
            rng = jax.random.PRNGKey(7)
            shared = np.asarray(jax.random.randint(
                rng, (shared_len,), 0, cfg.vocab_size, dtype=jnp.int32
            ))

            def make_prompts(count, salt):
                out = []
                for i in range(count):
                    tail = np.asarray(jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (tail_len,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    ))
                    out.append(np.concatenate([shared, tail]))
                return out

            def make_server(store):
                return GenerationServer(
                    params, cfg, max_batch=BATCH,
                    max_len=n_prompt + new_per_req, chunk=8,
                    prefill_buckets=buckets,
                    prefix_store=store,
                    # Explicit 0: the COLD side (store=None) must stay
                    # prefix-free even when the daemon injected a
                    # KATA_TPU_PREFIX_CACHE_TOKENS default into this env —
                    # otherwise the baseline would grow its own store and
                    # the A/B would compare prefix against prefix.
                    prefix_cache_tokens=0,
                    # Explicit int8 (the ISSUE 12 server default), pinned
                    # on BOTH sides so the injected store below always
                    # matches the arena dtype regardless of any
                    # KATA_TPU_KV_QUANT env this process inherited.
                    kv_quant=True,
                )

            def timed(store, salt):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                # Best-of-3 like the other serving sections: one run is
                # tens of ms at smoke shapes, inside scheduler noise, and
                # the A/B delta is the whole point. Fresh server per trial
                # (clean TTFT stats), shared store (prefix stays warm),
                # varied salts (the tunnel caches identical executions).
                best, best_ttft = None, float("inf")
                for trial in range(3):
                    srv = make_server(store)
                    prompts = make_prompts(2 * BATCH, salt + 50 * trial)
                    rids = [srv.submit(p, new_per_req) for p in prompts]
                    t0 = time.perf_counter()
                    results = srv.run()
                    dt_s = time.perf_counter() - t0
                    total = sum(len(results[r]) for r in rids)
                    st = srv.stats()
                    best_ttft = min(
                        best_ttft, (st["ttft_s"] or {}).get("mean", 0.0)
                    )
                    if best is None or dt_s < best[1]:
                        best = (total, dt_s, st)
                return best[0], best[1], best[2], best_ttft

            # The store is shared between the warm and timed servers, so
            # the timed phase starts with the prefix resident (100% hit
            # rate — the steady state of a long-running deployment) and
            # with every executable family compiled: suffix prefill,
            # store gather/insert, and the cold batched/bucketed prefills.
            # Two warm passes: the first request runs the COLD path and
            # populates the store; the second pass (store now warm) runs
            # the HIT path — lookups happen before inserts within one
            # admission pass, so a single pass would warm only cold.
            store = PrefixStore(cfg, capacity_tokens=4 * shared_len,
                                buckets=buckets, label="bench",
                                kv_quant=True)
            warm = make_server(store)
            warm.submit(make_prompts(1, salt=900)[0], new_per_req)
            warm.run()
            # Full-width hit pass: compiles the batched [BATCH, pad]
            # suffix executable the timed burst admits with.
            for p in make_prompts(2 * BATCH, salt=910):
                warm.submit(p, new_per_req)
            warm.run()
            cold_warm = make_server(None)
            for p in make_prompts(2 * BATCH, salt=800):
                cold_warm.submit(p, new_per_req)
            cold_warm.run()

            total, dt_s, st, ttft = timed(store, salt=0)
            c_total, c_dt, _c_st, c_ttft = timed(None, salt=200)
            submitted_tokens = 2 * BATCH * n_prompt
            out = {
                "serving_prefix_tok_per_s": round(total / dt_s, 1),
                "serving_prefix_s": round(dt_s, 3),
                "serving_prefix_ttft_mean_s": round(ttft, 4),
                "serving_prefix_hit_ratio": st["prefix_hit_ratio"],
                "serving_prefix_tokens_reused_frac": round(
                    st["prefix_tokens_reused"] / submitted_tokens, 4),
                # Prefill FLOPs scale with tokens actually run through a
                # forward, PADDED: cold admits [n_prompt]-bucket rows, the
                # hit path [tail_len]-bucket suffix rows — the ratio of
                # padded forward work is the honest FLOP reduction (it
                # differs from the reused-token fraction when suffix
                # padding adds work back; equal here by bucket choice).
                "serving_prefix_prefill_flop_reduction": round(
                    1.0 - tail_len / n_prompt, 4),
                "serving_prefix_cold_tok_per_s": round(c_total / c_dt, 1),
                "serving_prefix_cold_s": round(c_dt, 3),
                "serving_prefix_cold_ttft_mean_s": round(c_ttft, 4),
            }
            cold_ttft = out["serving_prefix_cold_ttft_mean_s"]
            hit_ttft = out["serving_prefix_ttft_mean_s"]
            if hit_ttft > 0:
                out["serving_prefix_ttft_speedup"] = round(
                    cold_ttft / hit_ttft, 3)
            return out
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"prefix_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_paged() -> dict:  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
        # Paged KV arena A/B (ISSUE 6): an OVERSUBSCRIBED burst — more
        # queued requests than the legacy slot count — served once through
        # the paged pool (token-budget continuous batching over
        # guest/kv_arena.py, twice the decode lanes over a pool smaller
        # than the lanes' dense footprint) and once through the fixed
        # [BATCH, max_len] slot grid, which can only serve the same burst
        # by queueing. Runs in smoke too. SIDE measurement with the usual
        # protections: after the banked headline, crash-guarded,
        # KATA_TPU_BENCH_PAGED=0 disables.
        if os.environ.get("KATA_TPU_BENCH_PAGED", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

            srv_max_len = PROMPT_LEN + 72
            new_per_req = 64
            n_req = 3 * BATCH          # > BATCH legacy slots: oversubscribed
            lanes = 2 * BATCH
            # Pool holds ~1.5 lanes' worth of FULL-length requests: more
            # concurrency than the slot grid in less memory, with real
            # allocation pressure (block tables grow per chunk; the tail
            # of the burst rides admission backpressure, not a crash).
            pool_tokens = (3 * BATCH // 2) * srv_max_len + 64
            rng = jax.random.PRNGKey(43)
            len_step = max(1, PROMPT_LEN // 8)

            def make_server(paged):
                return GenerationServer(
                    params, cfg, max_batch=lanes if paged else BATCH,
                    max_len=srv_max_len, chunk=8 if args.smoke else 16,
                    prefill_buckets=(PROMPT_LEN,),
                    # Explicit args on BOTH sides: a daemon-injected
                    # KATA_TPU_KV_POOL_TOKENS / ..PREFIX_CACHE_TOKENS env
                    # must not flip the baseline's config.
                    kv_pool_tokens=pool_tokens if paged else 0,
                    prefix_cache_tokens=0,
                )

            def reqs(srv, count, salt=0):
                out = []
                for i in range(count):
                    n = PROMPT_LEN - (i % 4) * len_step  # mixed, one bucket
                    p = jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (n,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    )
                    out.append(srv.submit(np.asarray(p), new_per_req))
                return out

            # Warm BOTH executable families (paged decode gathers through
            # block tables — a different executable from the dense arena's)
            # so neither timed side pays a compile.
            for paged in (True, False):
                warm = make_server(paged)
                reqs(warm, n_req, salt=7000)
                warm.run()

            def timed(paged, salt):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                best = None
                for trial in range(3):
                    srv = make_server(paged)
                    rids = reqs(srv, n_req, salt=salt + 100 * trial)
                    t0 = time.perf_counter()
                    results = srv.run()
                    dt_s = time.perf_counter() - t0
                    total = sum(len(results[r]) for r in rids)
                    if best is None or dt_s < best[1]:
                        best = (total, dt_s, srv.stats())
                return best

            p_total, p_dt, p_st = timed(True, salt=0)
            s_total, s_dt, s_st = timed(False, salt=500)
            p_ttft, p_itl = p_st["ttft_s"] or {}, p_st["decode_token_s"] or {}
            s_ttft = s_st["ttft_s"] or {}
            return {
                "serving_paged_tok_per_s": round(p_total / p_dt, 1),
                "serving_paged_s": round(p_dt, 3),
                "serving_paged_requests": n_req,
                "serving_paged_lanes": lanes,
                "serving_paged_pool_tokens": pool_tokens,
                "serving_paged_ttft_p50_s": round(p_ttft.get("p50", 0.0), 4),
                "serving_paged_ttft_p99_s": round(p_ttft.get("p99", 0.0), 4),
                "serving_paged_itl_p50_s": round(p_itl.get("p50", 0.0), 5),
                "serving_paged_itl_p99_s": round(p_itl.get("p99", 0.0), 5),
                "serving_paged_preemptions": p_st["preemptions"],
                "serving_paged_cow_copies": p_st["cow_copies"],
                "serving_paged_slotted_tok_per_s": round(s_total / s_dt, 1),
                "serving_paged_slotted_s": round(s_dt, 3),
                "serving_paged_slotted_slots": BATCH,
                "serving_paged_slotted_ttft_p99_s": round(
                    s_ttft.get("p99", 0.0), 4),
                "serving_paged_speedup": round(
                    (p_total / p_dt) / (s_total / s_dt), 3),
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"paged_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_kv_capacity() -> dict:  # lint: allow(JX004) srv.run()/step() return host numpy tokens each round — inherently fenced
        # KV layout + host-tier capacity A/B (ISSUE 14). Two comparisons:
        # (a) heads-vs-blocks pool layout at forced tp on a config whose
        # KV head count does NOT divide the mesh (smoke-tiny has 2 KV
        # heads, Gemma-2B is MQA — the heads layout REPLICATES the pool
        # onto every chip, the kv_replicated cliff) at the SAME per-chip
        # pool budget: the blocks pool is tp× the logical tokens for the
        # same per-chip bytes, so it sustains ~tp× the concurrent
        # sessions with fewer preemptions; (b) host-RAM tier on/off at
        # fixed device pool bytes under an idle-session zipfian resume
        # workload — with the tier, a resumed session's KV survives pool
        # pressure in host RAM (demotion instead of eviction) and
        # prefetches back on the hit. SIDE measurement with the usual
        # protections: after the banked headline, crash-guarded,
        # KATA_TPU_BENCH_KV=0 disables (the supervisor's retry kill
        # switch).
        if os.environ.get("KATA_TPU_BENCH_KV", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

            # The A/B's premise is that the HEADS layout replicates:
            # pick the largest degree this host offers whose mesh the
            # config's KV head count does NOT divide (smoke-tiny has 2
            # KV heads → tp=8; Gemma-2B is MQA → any tp>1). A host
            # where every feasible degree divides cannot show the cliff
            # — skip honestly rather than bank an inverted comparison.
            tp = min(8, jax.device_count())
            while tp >= 2 and cfg.n_kv_heads % tp == 0:
                tp -= 1
            if tp < 2:
                return {"kv_capacity_note":
                        "no kv-replicating tp on this host — skipped"}
            sess_prompt = min(16, PROMPT_LEN)
            sess_new = 8
            sess_len = sess_prompt + sess_new
            rng = jax.random.PRNGKey(67)

            def prompt(i, salt=0):
                return np.asarray(jax.random.randint(
                    jax.random.fold_in(rng, salt + i), (sess_prompt,), 0,
                    cfg.vocab_size, dtype=jnp.int32,
                ))

            def drive(srv, rids):  # jaxguard: hot  # lint: allow(JX004) srv.step()/run() return host numpy tokens each round — inherently fenced
                peak = 0
                t0 = time.perf_counter()
                while srv.step():
                    peak = max(peak, srv.stats()["slots_busy"])
                dt_s = time.perf_counter() - t0
                results = srv.run()
                total = sum(len(results[r]) for r in rids)
                return total, dt_s, peak, srv.stats()

            # -- (a) layout A/B at the same PER-CHIP pool budget --------
            # heads replicates: per-chip bytes == the LOGICAL pool, so a
            # per-chip budget of T tokens caps the heads pool at T while
            # the blocks pool (per-chip ~logical/tp) holds T*tp.
            budget_tokens = 3 * sess_len + 4 * 16
            n_req = 2 * tp
            lanes = min(n_req, 8)

            def layout_server(layout, pool_tokens):
                return GenerationServer(
                    params, cfg, max_batch=lanes,
                    max_len=sess_len + 16, chunk=4 if args.smoke else 8,
                    prefill_buckets=(sess_prompt,),
                    # Explicit args on BOTH sides: node-injected layout/
                    # pool/host envs must not flip either config.
                    kv_pool_tokens=pool_tokens, kv_block_size=8,
                    kv_layout=layout, kv_host_tokens=0,
                    prefix_cache_tokens=0, tp=tp,
                )

            def timed_layout(layout, pool_tokens, salt):  # jaxguard: hot
                warm = layout_server(layout, pool_tokens)
                for i in range(min(4, n_req)):
                    warm.submit(prompt(i, salt=9000 + salt), sess_new)
                warm.run()
                srv = layout_server(layout, pool_tokens)
                # Placement bytes read BEFORE traffic: decode donates the
                # pool every round and XLA's output-sharding inference
                # can drift a replicated pool off its placed spec — the
                # configured placement is the honest per-chip figure.
                placed = srv.stats()["arena_bytes"]
                rids = [
                    srv.submit(prompt(i, salt=salt), sess_new)
                    for i in range(n_req)
                ]
                return drive(srv, rids) + (placed,)

            h_tot, h_dt, h_peak, h_st, h_bytes = timed_layout(
                "heads", budget_tokens, salt=0)
            b_tot, b_dt, b_peak, b_st, b_bytes = timed_layout(
                "blocks", budget_tokens * tp, salt=300)
            # arena_bytes sums ADDRESSABLE shards: a replicated heads
            # pool reports tp × logical, a block-sharded pool its
            # logical bytes — per-chip is /tp either way.
            h_shard = h_bytes // tp
            b_shard = b_bytes // tp
            out_kv = {
                "serving_kv_layout": "blocks",
                "serving_kv_tp": tp,
                "serving_kv_heads_per_shard_bytes": h_shard,
                "serving_kv_blocks_per_shard_bytes": b_shard,
                # The replication overhead each layout pays per chip
                # beyond logical/tp at ITS OWN pool size. Heads
                # replicates (arena_bytes = tp × logical ⇒ per-chip =
                # logical, extra = (tp-1)/tp of it). Blocks holds tp×
                # the tokens at the same logical/tp-per-chip target —
                # which IS the heads pool's per-chip figure (same bytes
                # per token, tp× the tokens, /tp placement) — so its
                # extra is MEASURED against that independent number: ~0
                # when the layout truly shards, ~(tp−1)·h_shard if a
                # regression ever made it replicate.
                "serving_kv_heads_extra_bytes": (
                    h_shard - h_shard // tp
                ),
                "serving_kv_blocks_extra_bytes": (
                    b_shard - h_shard
                ),
                "serving_kv_heads_tok_per_s": round(h_tot / h_dt, 1),
                "serving_kv_blocks_tok_per_s": round(b_tot / b_dt, 1),
                "serving_kv_sessions": b_peak,
                "serving_kv_sessions_heads": h_peak,
                "serving_kv_heads_preemptions": h_st["preemptions"],
                "serving_kv_blocks_preemptions": b_st["preemptions"],
            }

            # -- (b) host tier on/off at fixed device pool bytes --------
            # Idle-session resume workload: every session runs turn 1,
            # then a zipfian-ordered resume stream replays extended
            # prompts — a resume whose turn-1 KV is still reachable
            # (device OR host tier) hits the prefix store; without the
            # tier, pool pressure EVICTED it and the session re-prefills
            # cold. "Sessions sustained" = sessions whose resume hit.
            n_sess = 6
            fixed_pool = 8 * (2 * (sess_prompt // 8 + 2) + 6)
            zipf = [0, 1, 0, 2, 0, 1, 3, 0, 4, 1, 5, 2]

            def session_server(host_tokens, pool_tokens=None):
                return GenerationServer(
                    params, cfg, max_batch=2,
                    max_len=2 * sess_len + 16, chunk=4,
                    prefill_buckets=(sess_prompt, 2 * sess_prompt),
                    kv_pool_tokens=pool_tokens or fixed_pool,
                    kv_block_size=8, kv_layout="heads",
                    kv_host_tokens=host_tokens,
                    prefix_cache_tokens=1, tp=1,
                )

            def _timed_sessions_once(host_tokens, salt, pool_tokens=None):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                srv = session_server(host_tokens, pool_tokens)
                firsts = {}
                for i in range(n_sess):
                    r = srv.submit(prompt(i, salt=salt), sess_new)
                    firsts[i] = np.concatenate([
                        prompt(i, salt=salt), srv.run()[r]
                    ]).astype(np.int32)
                hits0 = srv.stats()["prefix_hits"]
                sustained = set()
                t0 = time.perf_counter()
                total = 0
                for j in zipf:
                    before = srv.stats()["prefix_hits"]
                    r = srv.submit(firsts[j], sess_new)
                    total += len(srv.run()[r])
                    if srv.stats()["prefix_hits"] > before:
                        sustained.add(j)
                dt_s = time.perf_counter() - t0
                st = srv.stats()
                return (len(sustained), total / dt_s, st,
                        st["prefix_hits"] - hits0)

            def timed_sessions(host_tokens, salt, pool_tokens=None):
                # Best of 2: the first run of each (pool size, tier)
                # variant pays that shape family's compiles (pool ops
                # key on NT; the tier adds demote/prefetch executables)
                # — the second is warm by construction, so ordering
                # between variants cannot bias the A/B.
                a = _timed_sessions_once(host_tokens, salt, pool_tokens)
                b = _timed_sessions_once(host_tokens, salt, pool_tokens)
                return b if b[1] > a[1] else a

            h_sess, h_tok, host_st, h_hits = timed_sessions(
                64 * sess_len, salt=600)
            n_sessions, n_tok, nohost_st, n_hits = timed_sessions(
                0, salt=600)
            # No-pressure control: a pool that holds everything — the
            # tier must cost nothing when it never engages.
            _, idle_on, _, _ = timed_sessions(
                64 * sess_len, salt=900, pool_tokens=64 * sess_len)
            _, idle_off, _, _ = timed_sessions(
                0, salt=900, pool_tokens=64 * sess_len)
            out_kv.update({
                "serving_kv_host_sessions": h_sess,
                "serving_kv_nohost_sessions": n_sessions,
                "serving_kv_host_resume_hits": h_hits,
                "serving_kv_nohost_resume_hits": n_hits,
                "serving_kv_host_tok_per_s": round(h_tok, 1),
                "serving_kv_nohost_tok_per_s": round(n_tok, 1),
                "serving_kv_host_demotions": host_st["kv_demotions"],
                "serving_kv_host_prefetches": host_st["kv_prefetches"],
                "serving_kv_host_preemptions": host_st["preemptions"],
                "serving_kv_nohost_preemptions": nohost_st["preemptions"],
                "serving_kv_host_idle_ratio": round(
                    idle_on / idle_off, 3) if idle_off else 0.0,
            })
            return out_kv
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"kv_capacity_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_decode_attn() -> dict:  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
        # Paged-native decode-attention kernel A/B (ISSUE 12): the same
        # paged burst served through the split-K pallas kernel
        # (decode_attn="pallas_paged" — block tables walked in place,
        # int8 dequant fused in-kernel) and through the legacy
        # gather-back-to-dense XLA path, bf16 AND int8 KV. The pool holds
        # every lane fully resident so the A/B times attention, not
        # allocation pressure. On TPU this is the ROADMAP item-1 number
        # (the >2× decode target's direct evidence); on CPU smoke the
        # kernel runs interpret mode — harness validation, the speedup
        # there is meaningless and expected < 1. SIDE measurement with
        # the usual protections: after the banked headline, crash-
        # guarded, KATA_TPU_BENCH_DECODE_ATTN=0 disables (the
        # supervisor's retry kill switch).
        if os.environ.get("KATA_TPU_BENCH_DECODE_ATTN", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

            srv_max_len = PROMPT_LEN + 72
            new_per_req = 64
            n_req = 2 * BATCH
            pool_tokens = 2 * BATCH * srv_max_len + 64
            kv_block = 16  # the kernel's KV tile — banked with the result
            rng = jax.random.PRNGKey(53)
            len_step = max(1, PROMPT_LEN // 8)

            def make_server(backend, kv_quant):
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=srv_max_len,
                    chunk=8 if args.smoke else 16,
                    prefill_buckets=(PROMPT_LEN,),
                    # Explicit args on BOTH sides: node-injected pool/
                    # prefix/kv-quant envs must not flip either config.
                    kv_pool_tokens=pool_tokens, prefix_cache_tokens=0,
                    kv_block_size=kv_block,
                    kv_quant=kv_quant, decode_attn=backend,
                )

            def reqs(srv, count, salt=0):
                out = []
                for i in range(count):
                    n = PROMPT_LEN - (i % 4) * len_step  # mixed, one bucket
                    p = jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (n,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    )
                    out.append(srv.submit(np.asarray(p), new_per_req))
                return out

            def timed(backend, kvq, salt):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                # ONE server per variant, reused across the warm pass and
                # all trials: the kernel callable is a per-server closure
                # and a STATIC jit argument (its identity is the cache
                # key), so a fresh server per trial — the other sections'
                # pattern — would recompile _serve_decode inside every
                # pallas-side timed window while the reference side
                # (decode_kernel_fn=None, identity-stable) stayed warm,
                # biasing the speedup low by a full compile per trial.
                srv = make_server(backend, kvq)
                reqs(srv, n_req, salt=salt + 9000)
                srv.run()  # warm: compiles THIS server's executables
                best = None
                for trial in range(3):
                    rids = reqs(srv, n_req, salt=salt + 100 * trial)
                    t0 = time.perf_counter()
                    results = srv.run()
                    dt_s = time.perf_counter() - t0
                    total = sum(len(results[r]) for r in rids)
                    if best is None or dt_s < best[1]:
                        best = (total, dt_s, srv.stats())
                return best

            k_total, k_dt, k_st = timed("pallas_paged", False, salt=0)
            r_total, r_dt, _ = timed("xla_reference", False, salt=500)
            kq_total, kq_dt, _ = timed("pallas_paged", True, salt=1000)
            rq_total, rq_dt, _ = timed("xla_reference", True, salt=1500)
            return {
                # bf16 KV: kernel vs gather path.
                "serving_decode_attn_tok_per_s": round(k_total / k_dt, 1),
                "serving_decode_attn_reference_tok_per_s": round(
                    r_total / r_dt, 1),
                "serving_decode_attn_speedup": round(
                    (k_total / k_dt) / (r_total / r_dt), 3),
                # int8 KV: fused in-kernel dequant vs gather + XLA dequant.
                "serving_decode_attn_int8_tok_per_s": round(
                    kq_total / kq_dt, 1),
                "serving_decode_attn_int8_reference_tok_per_s": round(
                    rq_total / rq_dt, 1),
                "serving_decode_attn_int8_speedup": round(
                    (kq_total / kq_dt) / (rq_total / rq_dt), 3),
                # The backend the kernel side actually engaged (interpret-
                # mode smoke still reports pallas_paged) + the KV tile.
                "serving_decode_attn_backend": k_st["decode_backend"],
                "serving_decode_attn_block_size": kv_block,
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"decode_attn_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_faults() -> dict:  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
        # Fault-recovery smoke A/B (ISSUE 7): the same burst served once
        # clean and once under a SEEDED fault schedule (one transient
        # decode raise + one fence hang — the recovery supervisor's two
        # headline classes), reporting goodput (completed tok/s), the
        # recovery count, and TTFT p99 on both sides. What this pins in
        # the round-over-round series: recovery COMPLETES the whole burst
        # (goodput is a real number, not a crash) and its cost stays a
        # bounded fraction of clean throughput. Runs in smoke too. SIDE
        # measurement with the usual protections: after the banked
        # headline, crash-guarded, KATA_TPU_BENCH_FAULTS=0 disables.
        if os.environ.get("KATA_TPU_BENCH_FAULTS", "1") == "0":
            return {}
        # KATA_TPU_RECOVERY is env-only (no constructor override): pin it
        # on for the measurement — a shell with the kill switch exported
        # would otherwise collapse the faulted side to an error line.
        prev_rec = os.environ.get("KATA_TPU_RECOVERY")
        os.environ["KATA_TPU_RECOVERY"] = "1"
        try:
            from kata_xpu_device_plugin_tpu.guest.resilience import (
                FaultInjector,
                FaultSpec,
            )
            from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

            srv_max_len = PROMPT_LEN + 72
            new_per_req = 64
            n_req = 2 * BATCH
            rng = jax.random.PRNGKey(47)
            len_step = max(1, PROMPT_LEN // 8)
            schedule = [
                FaultSpec("decode_dispatch", 2),
                FaultSpec("fence", 4, "hang"),
            ]

            def make_server(injector):
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=srv_max_len,
                    chunk=8 if args.smoke else 16,
                    prefill_buckets=(PROMPT_LEN,),
                    # Explicit args on BOTH sides: a daemon-injected
                    # KATA_TPU_FAULTS / ..CHECKPOINT_ROUNDS /
                    # ..FENCE_TIMEOUT_S / ..QUARANTINE_K env must not
                    # contaminate the A/B (KATA_TPU_RECOVERY, env-only, is
                    # pinned below).
                    fault_injector=injector,
                    checkpoint_rounds=4,
                    fence_timeout_s=0.0, quarantine_after=3,
                    prefix_cache_tokens=0, kv_pool_tokens=0,
                    recovery_backoff_s=0.0,  # measure recovery, not sleep
                )

            def reqs(srv, salt=0):
                out = []
                for i in range(n_req):
                    n = PROMPT_LEN - (i % 4) * len_step
                    p = jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (n,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    )
                    out.append(srv.submit(np.asarray(p), new_per_req))
                return out

            warm = make_server(FaultInjector())
            reqs(warm, salt=9000)
            warm.run()

            def timed(injector, salt):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                srv = make_server(injector)
                rids = reqs(srv, salt=salt)
                t0 = time.perf_counter()
                results = srv.run()
                dt_s = time.perf_counter() - t0
                total = sum(len(results[r]) for r in rids if r in results)
                return total, dt_s, srv.stats(), srv.failures()

            c_total, c_dt, c_st, _ = timed(FaultInjector(), salt=0)
            f_total, f_dt, f_st, f_fail = timed(
                FaultInjector(schedule, seed=13), salt=0
            )
            c_ttft = c_st["ttft_s"] or {}
            f_ttft = f_st["ttft_s"] or {}
            return {
                "serving_faults_tok_per_s": round(f_total / f_dt, 1),
                "serving_faults_s": round(f_dt, 3),
                "serving_faults_recoveries": f_st["recoveries"],
                "serving_faults_stalls": f_st["device_stalls"],
                "serving_faults_checkpoints": f_st["checkpoints"],
                "serving_faults_quarantined": f_st["quarantined"],
                "serving_faults_failed_requests": len(f_fail),
                "serving_faults_ttft_p99_s": round(
                    f_ttft.get("p99", 0.0), 4),
                "serving_faults_clean_tok_per_s": round(c_total / c_dt, 1),
                "serving_faults_clean_s": round(c_dt, 3),
                "serving_faults_clean_ttft_p99_s": round(
                    c_ttft.get("p99", 0.0), 4),
                "serving_faults_goodput_ratio": round(
                    (f_total / f_dt) / (c_total / c_dt), 3)
                if c_total else 0.0,
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"faults_error": f"{type(exc).__name__}: {exc}"[:200]}
        finally:
            if prev_rec is None:
                os.environ.pop("KATA_TPU_RECOVERY", None)
            else:
                os.environ["KATA_TPU_RECOVERY"] = prev_rec

    def measure_load() -> dict:  # lint: allow(JX004) srv.step() returns host numpy tokens each round — inherently fenced
        # Latency-under-load (ISSUE 8, ROADMAP item 4): an OPEN-LOOP
        # Poisson arrival generator sweeps offered QPS and reports what a
        # loaded deployment's users actually feel — TTFT and inter-token
        # p50/p99 per rate, not batch tok/s. Long prompts (the admission
        # theft being measured) arrive at 0.5× / 1.5× / 3× the measured
        # closed-loop capacity, served through BOTH admission policies:
        # fifo_batch (whole-prefill admission, the identity baseline) and
        # slo_chunked (chunked prefill under a deadline, guest/scheduler
        # .py). The A/B acceptance at the oversubscribed rate: chunked ITL
        # p99 at or under the baseline's with aggregate tok/s within 10%.
        # Runs in smoke too. SIDE measurement with the usual protections:
        # after the banked headline, crash-guarded, KATA_TPU_BENCH_LOAD=0
        # disables.
        if os.environ.get("KATA_TPU_BENCH_LOAD", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import (
                GenerationServer,
            )

            load_prompt = 6 * PROMPT_LEN  # long: prefill >> one decode round
            # STAGGERED budgets: equal ones would synchronize lane
            # finishes, so every admission would run against an idle
            # arena (live=0) and no in-flight request would ever feel the
            # prefill theft the sweep exists to measure.
            new_per_req = 48
            budgets = [new_per_req + 8 * (i % 4) for i in range(64)]
            srv_max_len = load_prompt + max(budgets)
            srv_chunk = 4 if args.smoke else 16
            n_req = 4 * BATCH
            pchunk = max(8, load_prompt // 4)  # ~4 slices per admission
            key = jax.random.PRNGKey(53)

            def make_prompts(salt):
                return [
                    np.asarray(jax.random.randint(
                        jax.random.fold_in(key, salt + i), (load_prompt,),
                        0, cfg.vocab_size, dtype=jnp.int32,
                    ))
                    for i in range(n_req)
                ]

            def make_server(policy, slo_ms):
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=srv_max_len,
                    chunk=srv_chunk, prefill_buckets=(load_prompt,),
                    # Explicit args on BOTH sides: daemon-injected
                    # KATA_TPU_SCHED_* / pool / prefix envs must not
                    # contaminate the A/B.
                    sched_policy=policy, prefill_chunk=pchunk,
                    itl_slo_ms=slo_ms,
                    prefix_cache_tokens=0, kv_pool_tokens=0,
                )

            def drive(srv, prompts, arrivals):  # jaxguard: hot  # lint: allow(JX004) srv.step() returns host numpy tokens each round — inherently fenced
                # Open loop: requests arrive on the wall clock regardless
                # of server progress (closed loops hide queueing delay —
                # the whole point of the sweep).
                rids = []
                t0 = time.perf_counter()
                i = 0
                while i < len(prompts):
                    now = time.perf_counter() - t0
                    if arrivals[i] <= now:
                        rids.append(srv.submit(prompts[i], budgets[i]))
                        i += 1
                        continue
                    if not srv.step():
                        time.sleep(min(0.002, arrivals[i] - now))
                while srv.step():
                    pass
                dt_s = time.perf_counter() - t0
                results = srv.run()
                total = sum(len(results[r]) for r in rids if r in results)
                return total, dt_s, srv.stats()

            # Warm both executable families (the chunked side adds the
            # fixed-width suffix-chunk executable) and calibrate: the
            # closed-loop run measures capacity (offered-rate anchor) and
            # the unloaded inter-token cadence (the SLO anchor).
            warm = make_server("fifo_batch", 0.0)
            t0 = time.perf_counter()
            for i, p in enumerate(make_prompts(9000)):
                warm.submit(p, budgets[i])
            warm.run()
            warm_dt = time.perf_counter() - t0
            cap_rps = n_req / warm_dt
            itl_clean = (warm.stats()["decode_token_s"] or {}).get(
                "p50", 0.0)
            # The deadline: 1.5× the unloaded chunk cadence — tight enough
            # that a whole long-prompt prefill projects over it, honest
            # enough that plain decode rounds meet it.
            slo_ms = max(0.001, itl_clean * 1000.0 * 1.5)
            warm_c = make_server("slo_chunked", slo_ms)
            for i, p in enumerate(make_prompts(9100)):
                warm_c.submit(p, budgets[i])
            warm_c.run()

            rng = np.random.default_rng(17)
            out = {
                "serving_load_requests": n_req,
                "serving_load_prompt_len": load_prompt,
                "serving_load_prefill_chunk": pchunk,
                "serving_load_slo_ms": round(slo_ms, 3),
                "serving_load_capacity_rps": round(cap_rps, 2),
            }
            top = {}
            for j, mult in enumerate((0.5, 1.5, 3.0)):
                rate = cap_rps * mult
                # One arrival draw per rate, shared by both policies — the
                # A/B must compare identical traffic.
                arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
                out[f"serving_load_r{j}_offered_qps"] = round(rate, 2)
                for tag, policy in (("fifo", "fifo_batch"),
                                    ("slo", "slo_chunked")):
                    srv = make_server(policy, slo_ms)
                    total, dt_s, st = drive(
                        srv, make_prompts(100 * j), arrivals
                    )
                    ttft = st["ttft_s"] or {}
                    itl = st["decode_token_s"] or {}
                    pre = f"serving_load_r{j}_{tag}"
                    out.update({
                        f"{pre}_tok_per_s": round(total / dt_s, 1),
                        f"{pre}_ttft_p50_s": round(ttft.get("p50", 0.0), 4),
                        f"{pre}_ttft_p99_s": round(ttft.get("p99", 0.0), 4),
                        f"{pre}_itl_p50_s": round(itl.get("p50", 0.0), 5),
                        f"{pre}_itl_p99_s": round(itl.get("p99", 0.0), 5),
                    })
                    if tag == "slo":
                        out.update({
                            f"{pre}_chunks": st["sched_chunks"],
                            f"{pre}_defers": st["sched_defers"],
                            f"{pre}_slo_violations": st["slo_violations"],
                        })
                    if j == 2:
                        top[tag] = (total / dt_s, itl.get("p99", 0.0))
            # The oversubscribed-rate acceptance ratios (ISSUE 8): ITL p99
            # ratio <= 1 means chunking protected inter-token latency;
            # tok/s ratio >= 0.9 means it cost < 10% aggregate throughput.
            if top.get("fifo") and top["fifo"][1] > 0 and top["fifo"][0] > 0:
                out["serving_load_itl_p99_ratio"] = round(
                    top["slo"][1] / top["fifo"][1], 3)
                out["serving_load_tok_per_s_ratio"] = round(
                    top["slo"][0] / top["fifo"][0], 3)
            return out
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"load_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_fused() -> dict:  # lint: allow(JX004) srv.step()/run() return host numpy tokens each round — inherently fenced
        # Fused prefill+decode + multi-step dispatch A/B (ISSUE 13): the
        # serving-vs-raw-decode gap is per-round host dispatch overhead
        # plus slo_chunked slices stealing decode rounds. Two knobs, two
        # comparisons against the slo_chunked-unfused-K=1 baseline:
        # (a) THROUGHPUT — one closed-loop burst served at fused K=1 and
        # fused K=4 (decode_steps multiplies the per-dispatch scan, so
        # K=4 pays ~4× fewer host round-trips); acceptance: K=4 tok/s
        # strictly above the baseline. (b) ITL UNDER LOAD — open-loop
        # Poisson arrivals at 3× measured capacity, fused vs unfused;
        # acceptance: fused ITL p99 no worse (the chunk rides the decode
        # dispatch instead of stalling a round of its own). SIDE
        # measurement with the usual protections: after the banked
        # headline, crash-guarded, KATA_TPU_BENCH_FUSED=0 disables.
        if os.environ.get("KATA_TPU_BENCH_FUSED", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import (
                GenerationServer,
            )

            f_prompt = 4 * PROMPT_LEN
            f_chunk = 2 if args.smoke else 8
            new_per_req = 32
            budgets = [new_per_req + 4 * (i % 4) for i in range(64)]
            f_max_len = f_prompt + max(budgets)
            n_req = 6 * BATCH
            pchunk = max(8, f_prompt // 4)  # ~4 slices per admission
            key = jax.random.PRNGKey(71)

            def make_prompts(salt):
                return [
                    np.asarray(jax.random.randint(
                        jax.random.fold_in(key, salt + i), (f_prompt,),
                        0, cfg.vocab_size, dtype=jnp.int32,
                    ))
                    for i in range(n_req)
                ]

            def make_server(k_steps, fused, slo_ms):
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=f_max_len,
                    chunk=f_chunk, prefill_buckets=(f_prompt,),
                    # Explicit args on EVERY side: daemon-injected
                    # KATA_TPU_DECODE_STEPS / FUSED / SCHED_* envs must
                    # not contaminate the A/B.
                    sched_policy="slo_chunked", prefill_chunk=pchunk,
                    itl_slo_ms=slo_ms, decode_steps=k_steps, fused=fused,
                    prefix_cache_tokens=0, kv_pool_tokens=0,
                )

            def burst(srv, prompts):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                rids = [srv.submit(p, budgets[i])
                        for i, p in enumerate(prompts)]
                t0 = time.perf_counter()
                results = srv.run()
                dt = time.perf_counter() - t0
                total = sum(len(results[r]) for r in rids if r in results)
                return total, dt

            def drive(srv, prompts, arrivals):  # jaxguard: hot  # lint: allow(JX004) srv.step() returns host numpy tokens — inherently fenced
                rids = []
                t0 = time.perf_counter()
                i = 0
                while i < len(prompts):
                    now = time.perf_counter() - t0
                    if arrivals[i] <= now:
                        rids.append(srv.submit(prompts[i], budgets[i]))
                        i += 1
                        continue
                    if not srv.step():
                        time.sleep(min(0.002, arrivals[i] - now))
                while srv.step():
                    pass
                srv.run()
                return srv.stats()

            # Warm every executable family + calibrate capacity and the
            # SLO anchor on the unfused baseline.
            warm = make_server(1, False, 0.0)
            t0 = time.perf_counter()
            for i, p in enumerate(make_prompts(9000)):
                warm.submit(p, budgets[i])
            warm.run()
            cap_rps = n_req / (time.perf_counter() - t0)
            itl_clean = (warm.stats()["decode_token_s"] or {}).get(
                "p50", 0.0)
            slo_ms = max(0.001, itl_clean * 1000.0 * 1.5)
            for k_steps, fused in ((1, True), (4, True), (4, False)):
                w = make_server(k_steps, fused, slo_ms)
                for i, p in enumerate(make_prompts(9100)):
                    w.submit(p, budgets[i])
                w.run()

            out = {
                "serving_fused_requests": n_req,
                "serving_fused_prompt_len": f_prompt,
                "serving_fused_prefill_chunk": pchunk,
                "serving_fused_chunk": f_chunk,
                "serving_fused_slo_ms": round(slo_ms, 3),
            }
            # (a) closed-loop throughput, best-of-2 per side, same burst.
            rates = {}
            for tag, (k_steps, fused) in (
                ("base", (1, False)), ("k1", (1, True)), ("k4", (4, True)),
            ):
                best, best_st = 0.0, {}
                for trial in range(2):
                    srv = make_server(k_steps, fused, slo_ms)
                    total, dt = burst(srv, make_prompts(300 + trial))
                    if total / dt > best:
                        # Stats must describe the SAME run the reported
                        # tok/s came from, not whichever trial ran last.
                        best, best_st = total / dt, srv.stats()
                rates[tag] = best
                pre = f"serving_fused_{tag}" if tag != "k4" else \
                    "serving_fused"
                out[f"{pre}_tok_per_s"] = round(best, 1)
                out[f"{pre}_fused_admissions"] = best_st.get(
                    "fused_admissions", 0)
            out["serving_fused_speedup"] = round(
                rates["k4"] / rates["base"], 3) if rates["base"] else 0.0
            out["serving_fused_k1_speedup"] = round(
                rates["k1"] / rates["base"], 3) if rates["base"] else 0.0
            # (b) ITL p99 at 3× capacity: fused K=1 vs unfused baseline
            # over IDENTICAL arrival draws.
            rng = np.random.default_rng(23)
            arrivals = np.cumsum(
                rng.exponential(1.0 / (3.0 * cap_rps), n_req))
            itl = {}
            for tag, fused in (("base", False), ("fused", True)):
                st = drive(make_server(1, fused, slo_ms),
                           make_prompts(500), arrivals)
                d = st["decode_token_s"] or {}
                t = st["ttft_s"] or {}
                pre = f"serving_fused_load_{tag}"
                out.update({
                    f"{pre}_itl_p50_s": round(d.get("p50", 0.0), 5),
                    f"{pre}_itl_p99_s": round(d.get("p99", 0.0), 5),
                    f"{pre}_ttft_p50_s": round(t.get("p50", 0.0), 4),
                    f"{pre}_ttft_p99_s": round(t.get("p99", 0.0), 4),
                    f"{pre}_defers": st["sched_defers"],
                })
                itl[tag] = d.get("p99", 0.0)
            if itl.get("base"):
                # <= 1 means the fused plan protected ITL at least as
                # well as the unfused chunked scheduler (the acceptance
                # bar: "no worse at 3× load").
                out["serving_fused_itl_p99_ratio"] = round(
                    itl["fused"] / itl["base"], 3)
            return out
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"fused_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_persistent() -> dict:  # lint: allow(JX004) srv.step()/run() return host numpy tokens each round — inherently fenced
        # Persistent on-device decode rounds A/B (ISSUE 20): the
        # while_loop executable decodes until the heartbeat-cadence cap
        # or a lane freeze, so the host round-trips the K=1 baseline
        # pays per token — and the multi-step K=8 plan still pays per
        # K tokens — collapse to one per DELIVERED round. Three sides,
        # closed-loop, same burst, greedy everywhere (the loop is
        # greedy-only): (a) THROUGHPUT — K1 baseline vs multi-step K8 vs
        # persistent; acceptance: persistent strictly above K1.
        # (b) delivered steps per dispatch + the PR 18 devledger
        # dispatch-gap per side. (c) ITL p99 ratio at capacity:
        # persistent vs K1 over identical closed-loop bursts — <= 1
        # means no client-visible latency regression. SIDE measurement
        # with the usual protections: after the banked headline,
        # crash-guarded, KATA_TPU_BENCH_PERSISTENT=0 disables.
        if os.environ.get("KATA_TPU_BENCH_PERSISTENT", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import (
                GenerationServer,
            )

            p_prompt = 2 * PROMPT_LEN
            p_chunk = 2 if args.smoke else 8
            new_per_req = 24 if args.smoke else 48
            budgets = [new_per_req + 4 * (i % 4) for i in range(64)]
            p_max_len = p_prompt + max(budgets)
            n_req = 4 * BATCH
            key = jax.random.PRNGKey(73)

            def make_prompts(salt):
                return [
                    np.asarray(jax.random.randint(
                        jax.random.fold_in(key, salt + i), (p_prompt,),
                        0, cfg.vocab_size, dtype=jnp.int32,
                    ))
                    for i in range(n_req)
                ]

            def make_server(k_steps, persistent):
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=p_max_len,
                    chunk=p_chunk, prefill_buckets=(p_prompt,),
                    # Explicit args on EVERY side: daemon-injected
                    # KATA_TPU_PERSISTENT / DECODE_STEPS envs must not
                    # contaminate the A/B. Greedy (temperature=0) on
                    # every side — the persistent loop is greedy-only,
                    # so the baselines must be too for a fair ITL bar.
                    temperature=0.0, decode_steps=k_steps,
                    persistent=persistent, overlap=False,
                    heartbeat_rounds=8,
                    prefix_cache_tokens=0, kv_pool_tokens=0,
                )

            def burst(srv, prompts):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                rids = [srv.submit(p, budgets[i])
                        for i, p in enumerate(prompts)]
                t0 = time.perf_counter()
                results = srv.run()
                dt = time.perf_counter() - t0
                total = sum(len(results[r]) for r in rids if r in results)
                return total, dt

            # Warm every executable family once per side.
            for k_steps, persistent in ((1, False), (8, False), (1, True)):
                w = make_server(k_steps, persistent)
                for i, p in enumerate(make_prompts(9200)):
                    w.submit(p, budgets[i])
                w.run()

            out = {
                "serving_persistent_requests": n_req,
                "serving_persistent_prompt_len": p_prompt,
                "serving_persistent_chunk": p_chunk,
            }
            rates, itl = {}, {}
            for tag, (k_steps, persistent) in (
                ("k1", (1, False)), ("k8", (8, False)),
                ("persistent", (1, True)),
            ):
                best, best_st = 0.0, {}
                for trial in range(2):
                    srv = make_server(k_steps, persistent)
                    total, dt = burst(srv, make_prompts(320 + trial))
                    if total / dt > best:
                        # Stats must describe the SAME run the reported
                        # tok/s came from.
                        best, best_st = total / dt, srv.stats()
                rates[tag] = best
                pre = ("serving_persistent" if tag == "persistent"
                       else f"serving_persistent_{tag}")
                out[f"{pre}_tok_per_s"] = round(best, 1)
                d = best_st.get("decode_token_s") or {}
                itl[tag] = d.get("p99", 0.0)
                out[f"{pre}_itl_p99_s"] = round(itl[tag], 5)
                out[f"{pre}_dispatch_gap_ms"] = best_st.get(
                    "dispatch_gap_ms", 0.0)
                if tag == "persistent":
                    # Delivered steps per dispatch: the host-round-trip
                    # amortization the while_loop actually bought.
                    rounds = best_st.get("persistent_rounds", 0)
                    out["serving_persistent_delivered_per_dispatch"] = (
                        round(best_st.get("delivered_steps_total", 0)
                              / rounds, 2) if rounds else 0.0
                    )
                    out["serving_persistent_exits"] = best_st.get(
                        "persistent_exits", {})
            out["serving_persistent_speedup"] = round(
                rates["persistent"] / rates["k1"], 3) if rates["k1"] else 0.0
            out["serving_persistent_k8_speedup"] = round(
                rates["persistent"] / rates["k8"], 3) if rates["k8"] else 0.0
            if itl.get("k1"):
                # <= 1 means the persistent plan held ITL p99 at least
                # as well as the K=1 baseline (the acceptance bar:
                # strictly-better tok/s at no-worse ITL p99).
                out["serving_persistent_itl_p99_ratio"] = round(
                    itl["persistent"] / itl["k1"], 3)
            return out
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"persistent_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_tp() -> dict:  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
        # Tensor-parallel serving A/B (ISSUE 9): the same burst served at
        # tp=1 (single chip) and tp=2/4 over the 1×N serving mesh
        # (guest/tp_serving.py — params by SERVING_RULES, KV arena
        # head-sharded, collectives riding ICI on hardware). What the
        # round-over-round series pins: aggregate tok/s and TTFT/ITL
        # percentiles per degree — the ROADMAP item-3 multiplier this PR
        # exists for. On CPU (smoke, forced
        # --xla_force_host_platform_device_count) the numbers validate
        # the harness, not the hardware scaling. Each degree also
        # reports its greedy token-match fraction vs tp=1
        # (serving_tp{N}_token_match): the sharding MATH is exact (the
        # fp32 CI matrix in tests/test_tp_serving.py asserts
        # bit-identity), but this section runs the production bf16
        # params, and XLA CPU retiles a bf16 matmul's fp32 accumulation
        # for different output widths — last-bit rounding that can flip
        # greedy near-ties. On trained weights ties are rare and the
        # fraction sits near 1.0; the smoke model's RANDOM weights have
        # near-flat logits (ties everywhere), so its fraction runs much
        # lower — watch the round-over-round TREND, a drop to ~0 on the
        # same config flags a real sharding bug. SIDE measurement
        # with the usual protections: after the banked headline,
        # crash-guarded, KATA_TPU_BENCH_TP=0 disables.
        if os.environ.get("KATA_TPU_BENCH_TP", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import (
                GenerationServer,
            )

            degrees = [d for d in (2, 4) if d <= jax.device_count()]
            if args.smoke:
                degrees = degrees[:1]  # protect the smoke budget
            if not degrees:
                return {
                    "serving_tp_note": (
                        "1 device visible — tp A/B skipped (CPU smoke "
                        "forces a virtual 8-device host; single-chip TPU "
                        "rounds have nothing to shard over)"
                    )
                }
            srv_max_len = PROMPT_LEN + 72
            new_per_req = 64
            n_req = 2 * BATCH
            rng = jax.random.PRNGKey(59)
            len_step = max(1, PROMPT_LEN // 8)

            def make_server(tp):
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=srv_max_len,
                    chunk=8 if args.smoke else 16,
                    prefill_buckets=(PROMPT_LEN,),
                    # Explicit args on EVERY side: a daemon-injected
                    # KATA_TPU_TP / pool / prefix env must not flip the
                    # baseline's config (tp=1 pins single-chip serving).
                    tp=tp, prefix_cache_tokens=0, kv_pool_tokens=0,
                )

            def reqs(srv, salt=0):
                out = []
                for i in range(n_req):
                    n = PROMPT_LEN - (i % 4) * len_step
                    p = jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (n,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    )
                    out.append(srv.submit(np.asarray(p), new_per_req))
                return out

            # Warm every degree's executable family (sharded prefill/
            # decode compile separately per mesh) so no timed side pays a
            # compile.
            for tp in [1] + degrees:
                warm = make_server(tp)
                reqs(warm, salt=11000 + 100 * tp)
                warm.run()

            def timed(tp, salt):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                best, match_toks = None, None
                for trial in range(2 if args.smoke else 3):
                    srv = make_server(tp)
                    rids = reqs(srv, salt=salt + 10 * trial)
                    t0 = time.perf_counter()
                    results = srv.run()
                    dt_s = time.perf_counter() - t0
                    total = sum(len(results[r]) for r in rids)
                    if trial == 0:
                        # The cross-degree token match compares trial 0
                        # ONLY: per-trial salts exist for timing honesty
                        # (the tunnel caches identical executions), but
                        # the best-timed trial can differ per degree —
                        # matching best-vs-best would compare unrelated
                        # prompts and read ~0 on a healthy config.
                        match_toks = [results[r] for r in rids]
                    if best is None or dt_s < best[1]:
                        best = (total, dt_s, srv.stats())
                return best + (match_toks,)

            out = {}
            base = timed(1, salt=0)
            b_ttft, b_itl = base[2]["ttft_s"] or {}, base[2]["decode_token_s"] or {}
            out.update({
                "serving_tp1_tok_per_s": round(base[0] / base[1], 1),
                "serving_tp1_ttft_p50_s": round(b_ttft.get("p50", 0.0), 4),
                "serving_tp1_ttft_p99_s": round(b_ttft.get("p99", 0.0), 4),
                "serving_tp1_itl_p50_s": round(b_itl.get("p50", 0.0), 5),
                "serving_tp1_itl_p99_s": round(b_itl.get("p99", 0.0), 5),
            })
            for tp in degrees:
                got = timed(tp, salt=0)
                # Trial-0 of both degrees ran the SAME salt → same
                # requests: the mean greedy token-match fraction vs tp=1
                # is the coarse end-to-end sharding check (see the
                # section comment for why bf16 makes this a fraction,
                # not an assert).
                match = float(np.mean([
                    (a == b).mean() for a, b in zip(base[3], got[3])
                ]))
                ttft = got[2]["ttft_s"] or {}
                itl = got[2]["decode_token_s"] or {}
                pre = f"serving_tp{tp}"
                out.update({
                    f"{pre}_tok_per_s": round(got[0] / got[1], 1),
                    f"{pre}_ttft_p50_s": round(ttft.get("p50", 0.0), 4),
                    f"{pre}_ttft_p99_s": round(ttft.get("p99", 0.0), 4),
                    f"{pre}_itl_p50_s": round(itl.get("p50", 0.0), 5),
                    f"{pre}_itl_p99_s": round(itl.get("p99", 0.0), 5),
                    f"{pre}_token_match": round(match, 4),
                    f"{pre}_speedup": round(
                        (got[0] / got[1]) / (base[0] / base[1]), 3),
                })
            return out
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"tp_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_degraded() -> dict:  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
        # Chip-loss degraded-mode A/B (ISSUE 10): the same burst served
        # three ways — tp=4 clean, tp=4 with a seeded mid-run chip_loss
        # (the server shrinks to tp=2 and finishes degraded), and tp=2
        # clean (the shrunk steady state the degraded run converges to).
        # What the round-over-round series pins: a chip loss COMPLETES
        # the burst (tok/s is a real number, tp_final == 2, zero failed
        # requests) and the degraded run's cost stays a bounded fraction
        # of the clean tp-shrunk baseline (the ratio — the shrink +
        # re-shard + replay overhead amortized over the burst). TTFT/ITL
        # p99 before/after quantify the client-visible tail. On CPU
        # (smoke, forced 8-device host) the numbers validate the
        # harness, not hardware. SIDE measurement with the usual
        # protections: after the banked headline, crash-guarded,
        # KATA_TPU_BENCH_DEGRADED=0 disables.
        if os.environ.get("KATA_TPU_BENCH_DEGRADED", "1") == "0":
            return {}
        if jax.device_count() < 4:
            return {}
        # KATA_TPU_RECOVERY / KATA_TPU_DEGRADED are env-only: pin both on
        # for the measurement so an exported kill switch cannot collapse
        # the faulted side to an error line.
        prev_env = {k: os.environ.get(k)
                    for k in ("KATA_TPU_RECOVERY", "KATA_TPU_DEGRADED")}
        os.environ["KATA_TPU_RECOVERY"] = "1"
        os.environ["KATA_TPU_DEGRADED"] = "1"
        try:
            from kata_xpu_device_plugin_tpu.guest.resilience import (
                FaultInjector,
                FaultSpec,
            )
            from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

            srv_max_len = PROMPT_LEN + 72
            new_per_req = 64
            n_req = 2 * BATCH
            rng = jax.random.PRNGKey(61)
            len_step = max(1, PROMPT_LEN // 8)
            # The chip dies a few decode rounds in: prefills are done,
            # lanes are mid-stream — the worst realistic moment.
            schedule = [FaultSpec("decode_dispatch", 3, "chip_loss", 1)]

            def make_server(tp, injector):
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=srv_max_len,
                    chunk=8 if args.smoke else 16,
                    prefill_buckets=(PROMPT_LEN,),
                    # Explicit args on EVERY side: a daemon-injected
                    # KATA_TPU_TP / TP_MIN / FAULTS / pool / prefix env
                    # must not contaminate the A/B.
                    tp=tp, tp_min=1, fault_injector=injector,
                    checkpoint_rounds=4, prefix_cache_tokens=0,
                    kv_pool_tokens=0, recovery_backoff_s=0.0,
                )

            def reqs(srv, salt=0):
                out = []
                for i in range(n_req):
                    n = PROMPT_LEN - (i % 4) * len_step
                    p = jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (n,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    )
                    out.append(srv.submit(np.asarray(p), new_per_req))
                return out

            # Warm both degrees' executable families (sharded prefill/
            # decode compile per mesh) so no timed side pays a compile —
            # including the tp=2 family the degraded run shrinks INTO.
            for tp in (4, 2):
                warm = make_server(tp, FaultInjector())
                reqs(warm, salt=12000 + 100 * tp)
                warm.run()

            def timed(tp, injector, salt):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                srv = make_server(tp, injector)
                rids = reqs(srv, salt=salt)
                t0 = time.perf_counter()
                results = srv.run()
                dt_s = time.perf_counter() - t0
                total = sum(len(results[r]) for r in rids if r in results)
                return total, dt_s, srv.stats(), srv.failures()

            c_total, c_dt, c_st, _ = timed(4, FaultInjector(), salt=0)
            s_total, s_dt, s_st, _ = timed(2, FaultInjector(), salt=0)
            d_total, d_dt, d_st, d_fail = timed(
                4, FaultInjector(schedule, seed=17), salt=0
            )
            c_ttft = c_st["ttft_s"] or {}
            d_ttft = d_st["ttft_s"] or {}
            c_itl = c_st["decode_token_s"] or {}
            d_itl = d_st["decode_token_s"] or {}
            shrunk_rate = s_total / s_dt if s_dt else 0.0
            return {
                "serving_degraded_tok_per_s": round(d_total / d_dt, 1),
                "serving_degraded_s": round(d_dt, 3),
                "serving_degraded_tp_final": d_st["tp_degree"],
                "serving_degraded_shrinks": d_st["tp_shrinks"],
                "serving_degraded_recoveries": d_st["recoveries"],
                "serving_degraded_failed_requests": len(d_fail),
                "serving_degraded_ttft_p99_s": round(
                    d_ttft.get("p99", 0.0), 4),
                "serving_degraded_itl_p99_s": round(
                    d_itl.get("p99", 0.0), 5),
                "serving_degraded_clean_tok_per_s": round(
                    c_total / c_dt, 1),
                "serving_degraded_clean_ttft_p99_s": round(
                    c_ttft.get("p99", 0.0), 4),
                "serving_degraded_clean_itl_p99_s": round(
                    c_itl.get("p99", 0.0), 5),
                "serving_degraded_shrunk_tok_per_s": round(shrunk_rate, 1),
                # Degraded throughput over the clean tp-shrunk baseline:
                # ~1.0 means the shrink itself (re-shard + replay) cost
                # nothing beyond serving at the smaller degree.
                "serving_degraded_vs_shrunk_ratio": round(
                    (d_total / d_dt) / shrunk_rate, 3) if shrunk_rate
                else 0.0,
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"degraded_error": f"{type(exc).__name__}: {exc}"[:200]}
        finally:
            for k, v in prev_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def measure_obs() -> dict:  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
        # Telemetry-overhead A/B (ISSUE 11 + 15): the same burst served
        # three ways — (a) the PRODUCTION DEFAULT: request lifecycle
        # ledger + always-armed flight-recorder ring + serving HEARTBEAT
        # (every 4 rounds here — denser than the production 32, so the
        # measured cost upper-bounds it) + SLO-burn watchdog, JSONL sink
        # off (serving_obs_*); (b) everything disarmed, recorder and
        # heartbeat included (serving_obs_off_*); (c) the full opt-in
        # KATATPU_OBS JSONL sink (serving_obs_sink_*). What this pins:
        # the always-on tier's cost is noise (serving_obs_overhead_ratio
        # ~1.0, acceptance <= 1% tok/s — ISSUE 15's bar now INCLUDES
        # heartbeat+watchdog), greedy outputs are BIT-IDENTICAL tracing
        # on/off (serving_obs_token_match == 1.0 — telemetry must never
        # touch numerics), phase attribution is complete
        # (serving_obs_trace_coverage ~1.0), and heartbeats actually
        # flowed (serving_obs_heartbeats > 0, watchdog fed, zero alerts
        # on a healthy run). The sink side's ratio is context: per-line
        # flushed file I/O is the documented opt-in cost, visible at
        # smoke-tiny round times. SIDE measurement with the usual
        # protections: after the banked headline, crash-guarded,
        # KATA_TPU_BENCH_OBS=0 disables (off on retries/fallback).
        if os.environ.get("KATA_TPU_BENCH_OBS", "1") == "0":
            return {}
        try:
            import tempfile

            from kata_xpu_device_plugin_tpu.guest.serving import (
                GenerationServer,
            )
            from kata_xpu_device_plugin_tpu.obs import flight as obs_flight

            srv_chunk = 8 if args.smoke else 16
            new_per_req = 64
            rng = jax.random.PRNGKey(53)
            len_step = max(1, PROMPT_LEN // 8)

            def make_server(instrumented: bool = True):
                return GenerationServer(
                    params, cfg, max_batch=BATCH,
                    max_len=PROMPT_LEN + 72, chunk=srv_chunk,
                    prefill_buckets=(PROMPT_LEN,),
                    # Explicit offs: daemon-injected pool/prefix envs
                    # must not contaminate the A/B.
                    prefix_cache_tokens=0, kv_pool_tokens=0,
                    # Heartbeat + watchdog ride the instrumented sides
                    # (ISSUE 15): 4-round cadence beats the production
                    # default 8×, so the ratio upper-bounds the real
                    # cost; the off side runs the uninstrumented loop.
                    heartbeat_rounds=4 if instrumented else 0,
                )

            def reqs(srv, salt=0):
                out_r = []
                for i in range(2 * BATCH):
                    n = PROMPT_LEN - (i % 4) * len_step
                    p = jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (n,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    )
                    out_r.append(srv.submit(np.asarray(p), new_per_req))
                return out_r

            warm = make_server()
            reqs(warm, salt=7000)
            warm.run()

            tmpdir = tempfile.mkdtemp(prefix="bench_obs_")

            def one_trial(mode: str, trial: int):  # jaxguard: hot  # lint: allow(JX004) srv.run() returns host numpy tokens each round — inherently fenced
                # Same salt on every side and across trials: the A/B's
                # whole claim is identical work, identical outputs.
                # mode: "ring" (recorder armed, sink off — the
                # production default), "off" (everything disarmed),
                # "sink" (full JSONL stream).
                rec = (
                    obs_flight.FlightRecorder(capacity=4096)
                    if mode != "off" else None
                )
                sink = (
                    obs.EventSink(os.path.join(
                        tmpdir, f"events_{trial}.jsonl"
                    )) if mode == "sink" else None
                )
                prev_rec = obs_flight.set_default_recorder(rec)
                prev_sink = obs.set_default_sink(sink)
                try:
                    srv = make_server(instrumented=mode != "off")
                    rids = reqs(srv, salt=0)
                    t0 = time.perf_counter()
                    results = srv.run()
                    dt_s = time.perf_counter() - t0
                    # Device-ledger snapshot (ISSUE 17): the ring side
                    # runs the armed ledger, so the overhead ratio below
                    # automatically covers its per-dispatch cost.
                    st = srv.stats()
                finally:
                    obs.set_default_sink(prev_sink)
                    obs_flight.set_default_recorder(prev_rec)
                    if sink is not None:
                        sink.close()
                total = sum(len(results[r]) for r in rids)
                return (total, dt_s, results, rec, st)

            # INTERLEAVED trials (ring/off/sink per round, best-of-4 per
            # side): host drift — thermal, page cache, a background
            # compile — then lands on every side equally instead of
            # biasing whichever side ran last.
            best: dict = {}
            for trial in range(4):
                for mode in ("ring", "off", "sink"):
                    r = one_trial(mode, trial)
                    if mode not in best or r[1] < best[mode][1]:
                        best[mode] = r
            ring_total, ring_s, ring_results, ring_rec, ring_st = best["ring"]
            off_total, off_s, off_results, _r, _st = best["off"]
            sink_total, sink_s, sink_results, _r2, _st2 = best["sink"]

            def outputs_equal(a, b):
                return float(
                    set(a) == set(b)
                    and all(np.array_equal(a[r], b[r]) for r in a)
                )

            match = min(
                outputs_equal(ring_results, off_results),
                outputs_equal(sink_results, off_results),
            )
            ring_events = ring_rec.snapshot() if ring_rec else []
            traces = [
                e for e in ring_events if e.get("name") == "request_trace"
            ]
            heartbeats = [
                e for e in ring_events
                if e.get("name") == "serving_heartbeat"
            ]
            wd_alerts = [
                e for e in ring_events if e.get("name") == "watchdog_alert"
            ]
            coverage = (
                sum(
                    e["attributed_s"] / e["wall_s"]
                    for e in traces if e.get("wall_s")
                ) / len(traces)
            ) if traces else 0.0
            ring_rate = ring_total / ring_s
            off_rate = off_total / off_s
            sink_rate = sink_total / sink_s
            # Steady-state tripwire probe (ISSUE 19): the trial servers
            # above are fresh per trial, so each run() is its own warmup
            # and their tripwires never arm — a DEDICATED two-drain
            # server banks the census contract instead: drain once
            # (warmup compiles the bucketed surface), resubmit the same
            # shape of work, drain again, and read the steady-state
            # counters. ZERO is the only passing value —
            # tools/bench_trend.py lists serving_steady_state_compiles
            # in ZERO_REQUIRED_METRICS (nonzero is a regression by
            # definition, never "flat").
            tw_srv = make_server()
            reqs(tw_srv, salt=0)
            tw_srv.run()
            reqs(tw_srv, salt=0)
            tw_srv.run()
            tw_st = tw_srv.stats()
            return {
                "serving_steady_state_compiles": int(
                    tw_st["steady_state_compiles"]
                ),
                "serving_steady_state_reshards": int(
                    tw_st["steady_state_reshards"]
                ),
                "serving_obs_tok_per_s": round(ring_rate, 1),
                "serving_obs_off_tok_per_s": round(off_rate, 1),
                # >= 0.99 is the acceptance bar (<= 1% tok/s overhead
                # for the always-armed tier); interleaved best-of-4 on
                # every side keeps scheduler noise out.
                "serving_obs_overhead_ratio": round(
                    ring_rate / off_rate, 3) if off_rate else 0.0,
                # Context: the opt-in JSONL stream's cost (per-line
                # flushed writes — expected to be visible at smoke-tiny
                # round times, amortized on hardware).
                "serving_obs_sink_tok_per_s": round(sink_rate, 1),
                "serving_obs_sink_ratio": round(
                    sink_rate / off_rate, 3) if off_rate else 0.0,
                "serving_obs_token_match": match,
                "serving_obs_traces": len(traces),
                "serving_obs_trace_coverage": round(coverage, 4),
                # Heartbeat + watchdog rode the instrumented sides
                # (ISSUE 15): heartbeats flowed at the 4-round cadence,
                # and a healthy burst must fire zero watchdog alerts.
                "serving_obs_heartbeats": len(heartbeats),
                "serving_obs_watchdog_alerts": len(wd_alerts),
                # Device ledger (ISSUE 17), from the armed ring side:
                # last-interval MFU / busy fraction / mean dispatch gap
                # — utilization trend lines (gap is lower-is-better:
                # bench_trend renders it as an info row, direction-
                # aware, never a regression gate).
                "serving_mfu": float(ring_st.get("mfu", 0.0)),
                "serving_device_busy_frac": float(
                    ring_st.get("device_busy_frac", 0.0)
                ),
                "serving_dispatch_gap_ms": float(
                    ring_st.get("dispatch_gap_ms", 0.0)
                ),
                "serving_devledger_armed": int(
                    ring_st.get("devledger", {}).get("armed", 0)
                ),
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"obs_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_train() -> dict:
        # Train-step MFU (r5): the flash bwd kernels, remat, and the GSPMD
        # train step were inference-unmeasured claims until this section —
        # the bench series only ever timed decode/prefill. One Llama-3-
        # style ~256M model, one train step on a 1-device mesh (multi-chip
        # scaling is the dryrun's job; this measures the per-chip compute
        # path), pallas-flash attention vs the XLA reference, reported as
        # model-FLOPs MFU against the chip's public peak. SIDE measurement
        # with the usual protections: after the banked headline, crash-
        # guarded, KATA_TPU_BENCH_TRAIN=0 disables.
        if args.smoke or os.environ.get("KATA_TPU_BENCH_TRAIN", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu import parallel
            from kata_xpu_device_plugin_tpu.models import llama3_train_bench

            tcfg = llama3_train_bench()
            # Shape swept on v5e (r5): B=16/S=1024 gives the best MFU
            # (0.300 vs 0.266 at B=8, 0.285 at B=8/S=2048); remat=False
            # measured slightly SLOWER than remat=True at B=8 (228 vs
            # 220 ms) and OOMs at B=16, so remat stays on for both
            # variants — it is also the long-context recipe.
            TB, TS = 16, 1024
            mesh = parallel.build_mesh(devices=devs[:1])

            # Model FLOPs per step (PaLM-appendix MFU convention): fwd+bwd
            # matmuls = 6 × matmul-params × tokens (embedding gather
            # excluded, unembedding projection included), plus causal
            # attention 12·L·B·S²·H·Dh halved for the causal triangle.
            matmul_params_per_layer = (
                tcfg.d_model * tcfg.q_dim          # wq
                + 2 * tcfg.d_model * tcfg.kv_dim   # wk, wv
                + tcfg.q_dim * tcfg.d_model        # wo
                + 3 * tcfg.d_model * tcfg.d_ff     # swiglu gate/up/down
            )
            matmul_params = (
                tcfg.n_layers * matmul_params_per_layer
                + tcfg.d_model * tcfg.vocab_size   # untied unembed
            )
            tokens_per_step = TB * TS
            attn_flops = (
                6 * tcfg.n_layers * TB * TS * TS * tcfg.n_heads * tcfg.head_dim
            )
            flops_per_step = 6 * matmul_params * tokens_per_step + attn_flops

            def run_variant(attn_fn):  # jaxguard: hot
                # remat for both variants: the reference attention's [S,S]
                # logits only fit by recomputation, and remat is the
                # long-context recipe the train step ships with anyway.
                init_state, step = parallel.make_train_step(
                    tcfg, mesh, attn_fn=attn_fn, remat=True
                )
                state = init_state(jax.random.PRNGKey(7))

                def batch(i):
                    d = jax.random.randint(
                        jax.random.fold_in(jax.random.PRNGKey(11), i),
                        (TB, TS), 0, tcfg.vocab_size, dtype=jnp.int32,
                    )
                    np.asarray(d)  # materialize outside the timed region
                    return d

                state, loss = step(state, batch(0))  # compile + warm
                np.asarray(loss)
                best = float("inf")
                for i in range(1, 4):  # varied data: tunnel caches replays
                    d = batch(i)
                    t0 = time.perf_counter()
                    state, loss = step(state, d)
                    lv = float(np.asarray(loss))
                    best = min(best, time.perf_counter() - t0)
                del state
                return best, lv

            flash_s, flash_loss = run_variant(None)  # None → flash on TPU
            from kata_xpu_device_plugin_tpu.ops.attention import (
                reference_attention as _ref,
            )

            ref_s, _ = run_variant(_ref)
            peak = detect_mxu_tflops(devs[0]) * 1e12
            return {
                "train_config": "llama3_train_bench",
                "train_tokens_per_step": tokens_per_step,
                "train_step_s": round(flash_s, 4),
                "train_tok_per_s": round(tokens_per_step / flash_s, 1),
                "train_mfu": round(flops_per_step / (flash_s * peak), 4),
                "train_ref_step_s": round(ref_s, 4),
                "train_flash_speedup": round(ref_s / flash_s, 3),
                "train_loss_finite": bool(np.isfinite(flash_loss)),
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"train_error": f"{type(exc).__name__}: {exc}"[:200]}

    # Per-phase breakdown, parsed back from the JSONL event stream the
    # spans above emitted (ISSUE 2 acceptance: BENCH output carries
    # compile/prefill/decode instead of one opaque number). Crash-guarded:
    # a telemetry parse failure must never cost the headline.
    try:
        phases = obs.summarize_phases(
            obs.read_events(events_path, offset=events_offset),
            prefix="bench.",
        )
    except Exception as exc:  # noqa: BLE001 — headline must survive
        phases = {"error": f"{type(exc).__name__}: {exc}"[:200]}

    out = {
        "metric": METRIC,
        "value": round(tok_per_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(tok_per_s / roofline_tok_s, 4),
        "phases": phases,
        "obs_events_file": events_path,
        "compile_cache_dir": compile_cache_dir,
        "platform": devs[0].platform,
        "device_kind": str(getattr(devs[0], "device_kind", "")),
        "config": "smoke-tiny" if args.smoke else "gemma2b",
        "decode_attn": (
            "pallas_fused"
            if decode_eligible(1, max_len, cfg.head_dim, True, 0)
            else "xla_reference"
        ),
        "decode_s": round(dt, 4),
        "prompt_prefill_s": round(prompt_prefill_s, 4),
        "e2e_tok_per_s": round(total_tokens / best_e2e_s, 1),
        "prefill_attn": "pallas_flash" if prefill_flash else "xla_reference",
        "prefill_tok_per_s": round(PREFILL_LEN / min(prefill_s.values()), 1),
    }
    if args.fallback:
        # The worker cannot know the supervisor's attempt history — claim
        # only what is true from here (the supervisor annotates the line
        # with attempts/error and rewrites the note when NO attempt ran).
        out["note"] = "cpu fallback — smoke shapes, not a TPU number"
    if prefill_flash:
        out["prefill_flash_s"] = round(prefill_s["flash"], 4)
        out["prefill_reference_s"] = round(prefill_s["reference"], 4)
        out["prefill_flash_speedup"] = round(
            prefill_s["reference"] / prefill_s["flash"], 3
        )
    # The bf16 headline is complete here — bank it before the int8 extras
    # (the supervisor accepts the LAST metric line, even from a worker it
    # had to kill, so a hang in the int8 section can't void this result).
    print(json.dumps(out), flush=True)
    int8_out = measure_int8()
    if int8_out:
        out.update(int8_out)
        print(json.dumps(out), flush=True)
    serving_out = measure_serving()
    if serving_out:
        out.update(serving_out)
        print(json.dumps(out), flush=True)
    prefix_out = measure_prefix()
    if prefix_out:
        out.update(prefix_out)
        print(json.dumps(out), flush=True)
    paged_out = measure_paged()
    if paged_out:
        out.update(paged_out)
        print(json.dumps(out), flush=True)
    kv_out = measure_kv_capacity()
    if kv_out:
        out.update(kv_out)
        print(json.dumps(out), flush=True)
    decode_attn_out = measure_decode_attn()
    if decode_attn_out:
        out.update(decode_attn_out)
        print(json.dumps(out), flush=True)
    faults_out = measure_faults()
    if faults_out:
        out.update(faults_out)
        print(json.dumps(out), flush=True)
    load_out = measure_load()
    if load_out:
        out.update(load_out)
        print(json.dumps(out), flush=True)
    fused_out = measure_fused()
    if fused_out:
        out.update(fused_out)
        print(json.dumps(out), flush=True)
    persistent_out = measure_persistent()
    if persistent_out:
        out.update(persistent_out)
        print(json.dumps(out), flush=True)
    tp_out = measure_tp()
    if tp_out:
        out.update(tp_out)
        print(json.dumps(out), flush=True)
    degraded_out = measure_degraded()
    if degraded_out:
        out.update(degraded_out)
        print(json.dumps(out), flush=True)
    obs_out = measure_obs()
    if obs_out:
        out.update(obs_out)
        print(json.dumps(out), flush=True)
    softcap_out = measure_softcap_prefill()
    if softcap_out:
        out.update(softcap_out)
        print(json.dumps(out), flush=True)
    # Train MFU runs LAST: an overrun in the newest, most expensive
    # section (two fwd+bwd compiles) must cost only itself, never the
    # established int8/serving/softcap round-over-round series.
    train_out = measure_train()
    if train_out:
        out.update(train_out)
        print(json.dumps(out), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile-dir", default="", help="dump a jax.profiler trace here")
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="tiny config/shapes: validates the harness end-to-end in seconds "
        "(the number it prints is NOT the headline metric)",
    )
    ap.add_argument(
        "--no-overlap",
        action="store_true",
        help="serving section A/B baseline: run the GenerationServer "
        "lock-step (overlap=False) as the primary serving config instead "
        "of the pipelined default (a default run already reports both "
        "sides as serving_* vs serving_noverlap_*)",
    )
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--fallback", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.worker:
        worker(args)
        return 0
    return supervise(args)


if __name__ == "__main__":
    sys.exit(main())
