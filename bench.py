#!/usr/bin/env python3
"""Headline benchmark: Gemma-2B-architecture greedy decode throughput on the
attached TPU (BASELINE.json metric: "tokens/sec/chip").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` is the fraction of the chip's memory-bandwidth roofline
achieved: greedy decode is HBM-bound — every generated token must stream all
model weights (plus the KV prefix) from HBM once — so

    roofline tok/s = batch * HBM_GB_per_s / bytes_read_per_step.

The reference publishes no numbers (SURVEY §6: "published": {}), so the
roofline is the honest fixed yardstick: 1.0 is perfect, and improvements
across rounds move the ratio up. Runs single-chip (the only hardware here);
multi-chip scaling is validated by __graft_entry__.dryrun_multichip.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from kata_xpu_device_plugin_tpu.models import gemma_2b_bench
from kata_xpu_device_plugin_tpu.models.transformer import generate, init_params

# Per-chip HBM bandwidth (GB/s) by TPU generation — public spec-sheet numbers.
HBM_GBPS = {"v5e": 819.0, "v5p": 2765.0, "v4": 1228.0, "v6e": 1640.0, "cpu": 50.0}

BATCH = 8
PROMPT_LEN = 128
DECODE_STEPS = 128


def detect_hbm_gbps() -> float:
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for key, bw in HBM_GBPS.items():
        if key in kind:
            return bw
    return HBM_GBPS["v5e" if dev.platform == "tpu" else "cpu"]


def main() -> None:
    cfg = gemma_2b_bench()
    key = jax.random.PRNGKey(0)
    params = jax.jit(lambda k: init_params(k, cfg, dtype=jnp.bfloat16))(key)
    jax.block_until_ready(params)

    import numpy as np

    max_len = PROMPT_LEN + DECODE_STEPS

    def run(seed: int):
        # Fresh prompt every iteration and a full device→host transfer of the
        # result: the remote-device (axon) path can serve repeated identical
        # executions from cache and does not reliably block on
        # block_until_ready, so only transferred, input-varying runs measure
        # real decode time.
        prompt = jax.random.randint(
            jax.random.PRNGKey(seed), (BATCH, PROMPT_LEN), 0, cfg.vocab_size,
            dtype=jnp.int32,
        )
        np.asarray(prompt)
        t0 = time.perf_counter()
        out = np.asarray(generate(params, prompt, cfg, steps=DECODE_STEPS, max_len=max_len))
        return time.perf_counter() - t0, out

    run(0)  # warm-up: compiles prefill + decode scan
    times = [run(seed)[0] for seed in range(1, 4)]
    dt = min(times)

    total_tokens = BATCH * DECODE_STEPS  # decode tokens (prefill amortized in)
    tok_per_s = total_tokens / dt

    # Roofline: each decode step streams the weights once (bf16) plus the
    # mean KV prefix for the whole batch.
    param_bytes = cfg.num_params() * 2
    mean_prefix = PROMPT_LEN + DECODE_STEPS / 2
    kv_bytes_per_step = (
        2 * cfg.n_layers * BATCH * mean_prefix * cfg.kv_dim * 2
    )
    roofline_steps = detect_hbm_gbps() * 1e9 / (param_bytes + kv_bytes_per_step)
    roofline_tok_s = roofline_steps * BATCH

    print(
        json.dumps(
            {
                "metric": "gemma2b_decode_tok_per_s_per_chip",
                "value": round(tok_per_s, 1),
                "unit": "tok/s",
                "vs_baseline": round(tok_per_s / roofline_tok_s, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
