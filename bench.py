#!/usr/bin/env python3
"""Headline benchmark: Gemma-2B-architecture greedy decode throughput on the
attached TPU (BASELINE.json metric: "tokens/sec/chip").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

``vs_baseline`` is the fraction of the chip's memory-bandwidth roofline
achieved: greedy decode is HBM-bound — every generated token must stream all
model weights (plus the KV prefix) from HBM once — so

    roofline tok/s = batch * HBM_GB_per_s / bytes_read_per_step.

The reference publishes no numbers (SURVEY §6: "published": {}), so the
roofline is the honest fixed yardstick: 1.0 is perfect, and improvements
across rounds move the ratio up. Runs single-chip (the only hardware here);
multi-chip scaling is validated by __graft_entry__.dryrun_multichip.

Hardening (round-1 lesson: one transient backend failure must not cost the
round's perf evidence). A hung remote-TPU tunnel blocks *inside a native
call*, where no in-process watchdog (SIGALRM included) can fire — so the
measurement runs in a KILLABLE WORKER SUBPROCESS under a supervisor:

- the supervisor enforces a hard wall-clock budget per attempt and SIGKILLs
  a hung worker;
- failures retry with backoff in a fresh interpreter (a failed PJRT init is
  sticky in-process);
- the final attempt pins ``JAX_PLATFORMS=cpu`` with smoke shapes so the
  round records *something*, clearly labeled with platform + config;
- after all retries the supervisor still prints a machine-readable
  diagnostic JSON line and exits nonzero — never a bare stack trace.

Besides the headline bf16 number, the worker also measures int8 weight-only
decode (ops/quant.py) — reported as ``int8_tok_per_s`` against its own
actual-bytes roofline (``int8_vs_baseline``), so the quantized win shows up
in absolute tok/s without muddying the bf16 round-over-round series — and
continuous-batching serving throughput (guest/serving.py, 16 mixed-length
requests through an 8-slot arena, ``serving_tok_per_s``). Both are
crash-guarded side sections emitted AFTER the banked headline line.

Flags: --profile-dir DIR dumps a jax.profiler (xplane) trace of the measured
decode runs. --smoke runs tiny shapes (harness validation, not the metric).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# Per-chip HBM bandwidth (GB/s) by TPU generation — public spec-sheet numbers.
HBM_GBPS = {"v5e": 819.0, "v5p": 2765.0, "v4": 1228.0, "v6e": 1640.0, "cpu": 50.0}

BATCH = 8
PROMPT_LEN = 128
DECODE_STEPS = 128
PREFILL_LEN = 2048  # separate prefill metric: long enough for flash to matter

METRIC = "gemma2b_decode_tok_per_s_per_chip"

MAX_ATTEMPTS = int(os.environ.get("KATA_TPU_BENCH_ATTEMPTS", "3"))
ATTEMPT_TIMEOUT_S = int(os.environ.get("KATA_TPU_BENCH_ATTEMPT_TIMEOUT_S", "1500"))
SMOKE_TIMEOUT_S = int(os.environ.get("KATA_TPU_BENCH_SMOKE_TIMEOUT_S", "600"))


# --------------------------------------------------------------------------
# Supervisor: retries a killable worker; the ONLY stdout it emits is the one
# JSON result line (worker stdout is captured, stderr passes through).
# --------------------------------------------------------------------------


def supervise(args: argparse.Namespace) -> int:
    worker_cmd = [sys.executable, os.path.abspath(__file__), "--worker"]
    if args.profile_dir:
        worker_cmd += ["--profile-dir", args.profile_dir]
    if args.smoke:
        worker_cmd += ["--smoke"]

    errors: list[str] = []
    for attempt in range(MAX_ATTEMPTS):
        env = dict(os.environ)
        cmd = list(worker_cmd)
        timeout = SMOKE_TIMEOUT_S if args.smoke else ATTEMPT_TIMEOUT_S
        if attempt >= 1:
            # Belt and braces: the pallas decode kernel is already opt-in
            # (it measured slower than XLA — see ops.attention.decode_eligible),
            # but if attempt 1 hung or crashed, force it hard-off so an
            # opted-in kernel/runtime incompatibility can't cost the round.
            env["KATA_TPU_DECODE_KERNEL"] = "0"
            # Likewise drop the side-measurements on retries: if one hung
            # attempt 1 (a hang can't be caught in-process), the retry must
            # still deliver the bf16 headline number.
            env["KATA_TPU_BENCH_INT8"] = "0"
            env["KATA_TPU_BENCH_SERVING"] = "0"
        if attempt == MAX_ATTEMPTS - 1 and attempt > 0 and not args.smoke:
            # Last resort: a labeled CPU smoke figure beats an empty round.
            env["JAX_PLATFORMS"] = "cpu"
            cmd += ["--smoke", "--fallback"]
            timeout = SMOKE_TIMEOUT_S
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=sys.stderr, text=True
        )
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            errors.append(f"attempt {attempt + 1}: killed after {timeout}s (hung)")
            out = out or ""
        line = _last_json_line(out)
        if line is not None:
            # A printed metric line is by construction a COMPLETED headline
            # measurement — the worker banks the bf16-only line before the
            # int8 extras — so accept it even from a worker that then hung
            # or crashed (annotated, so the partial run is visible).
            line["attempts"] = attempt + 1
            if proc.returncode != 0:
                line["note"] = (
                    f"worker rc={proc.returncode} after the headline "
                    "measurement (extras section hung or crashed)"
                )
            print(json.dumps(line), flush=True)
            return 0
        if not errors or not errors[-1].startswith(f"attempt {attempt + 1}"):
            errors.append(
                f"attempt {attempt + 1}: rc={proc.returncode}, "
                f"tail={out.strip().splitlines()[-1][:200] if out.strip() else ''}"
            )
        if attempt + 1 < MAX_ATTEMPTS:
            delay = 5.0 * (2**attempt)
            print(
                f"bench: {errors[-1]}; retrying in {delay:.0f}s "
                f"({attempt + 2}/{MAX_ATTEMPTS})",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(delay)

    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": None,
                "unit": "tok/s",
                "vs_baseline": None,
                "error": "; ".join(errors)[-1000:],
                "attempts": MAX_ATTEMPTS,
                "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
            }
        ),
        flush=True,
    )
    return 1


def _last_json_line(out: str):
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if obj.get("metric") == METRIC:
                return obj
    return None


# --------------------------------------------------------------------------
# Worker: one measurement attempt. Raises/exits nonzero on failure; the
# supervisor owns retries and the kill switch.
# --------------------------------------------------------------------------


def detect_hbm_gbps(dev) -> float:
    kind = str(getattr(dev, "device_kind", "")).lower()
    for key, bw in HBM_GBPS.items():
        if key in kind:
            return bw
    from kata_xpu_device_plugin_tpu.ops.attention import on_tpu

    return HBM_GBPS["v5e" if on_tpu() else "cpu"]


def worker(args: argparse.Namespace) -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Some platform plugins ignore the env var; pin through jax.config
        # too (must happen before any backend initializes).
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    devs = jax.devices()
    if not devs:
        raise RuntimeError("no devices visible")

    import jax.numpy as jnp
    import numpy as np

    from kata_xpu_device_plugin_tpu.models import gemma_2b_bench, tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import (
        decode,
        forward,
        fuse_decoder_params,
        init_params,
        prefill,
    )
    from kata_xpu_device_plugin_tpu.ops.attention import (
        decode_eligible,
        flash_attention,
        flash_eligible,
        reference_attention,
    )

    # A real tiny dispatch: devices() can succeed while the transport is
    # dead; one add must round-trip before we trust the backend.
    np.asarray(jnp.ones((8,)) + 1)

    global BATCH, PROMPT_LEN, DECODE_STEPS, PREFILL_LEN
    if args.smoke:
        cfg = tiny_test_config()
        BATCH, PROMPT_LEN, DECODE_STEPS, PREFILL_LEN = 2, 16, 8, 64
    else:
        cfg = gemma_2b_bench()
    max_len = PROMPT_LEN + DECODE_STEPS

    key = jax.random.PRNGKey(0)
    # Fused inference layout: wqkv / w_gateup stream each weight group in one
    # matmul on the bandwidth-bound decode step.
    params = jax.jit(
        lambda k: fuse_decoder_params(init_params(k, cfg, dtype=jnp.bfloat16))
    )(key)
    jax.block_until_ready(params)

    def run(p, seed: int):
        # Fresh prompt every iteration and a full device→host transfer of
        # the result: the remote-device tunnel can serve repeated identical
        # executions from cache and does not reliably block on
        # block_until_ready, so only transferred, input-varying runs measure
        # real decode time. Prefill and decode are timed SEPARATELY — the
        # tiny `last`-token transfer fences prefill completion so the decode
        # window contains only the decode scan (prefill is compute-bound;
        # folding it in understated decode tok/s by a few percent in r02).
        prompt = jax.random.randint(
            jax.random.PRNGKey(seed), (BATCH, PROMPT_LEN), 0,
            cfg.vocab_size, dtype=jnp.int32,
        )
        np.asarray(prompt)
        t0 = time.perf_counter()
        caches, last, _pos = prefill(p, prompt, cfg, max_len)
        np.asarray(last)
        t_pre = time.perf_counter() - t0
        t1 = time.perf_counter()
        # pos as the static python int: decode's bound check must not cost a
        # device->host fetch inside the timed window.
        out = np.asarray(decode(p, caches, last, PROMPT_LEN, cfg, DECODE_STEPS))
        return t_pre, time.perf_counter() - t1, out

    run(params, 0)  # warm-up: compiles prefill + decode scan

    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    times = [run(params, seed)[:2] for seed in range(1, 4)]
    if args.profile_dir:
        jax.profiler.stop_trace()
    dt = min(t for _, t in times)  # decode-only window
    prompt_prefill_s = min(t for t, _ in times)
    best_e2e_s = min(tp + td for tp, td in times)  # best single run, not mixed mins

    # ----- separate prefill metric: pallas flash vs XLA reference ----------
    prefill_flash = flash_eligible(PREFILL_LEN, PREFILL_LEN, cfg.head_dim)

    def time_prefill(fn) -> float:
        best = float("inf")
        for seed in range(4):
            toks = jax.random.randint(
                jax.random.PRNGKey(100 + seed), (1, PREFILL_LEN), 0,
                cfg.vocab_size, dtype=jnp.int32,
            )
            np.asarray(toks)
            t0 = time.perf_counter()
            np.asarray(fn(params, toks))
            elapsed = time.perf_counter() - t0
            if seed > 0:  # first run includes compile
                best = min(best, elapsed)
        return best

    # The jitted fns return only the LAST-TOKEN logits: that still forces the
    # full forward on varying inputs, but the host transfer is ~1 MB instead
    # of the [S, vocab] fp32 tensor — which at tunnel bandwidth would swamp
    # the flash-vs-reference delta being measured.
    prefill_s = {
        "reference": time_prefill(
            jax.jit(lambda p, t: forward(p, t, cfg, attn_fn=reference_attention)[:, -1])
        )
    }
    if prefill_flash:
        prefill_s["flash"] = time_prefill(
            jax.jit(lambda p, t: forward(p, t, cfg, attn_fn=flash_attention)[:, -1])
        )

    total_tokens = BATCH * DECODE_STEPS  # the decode scan runs exactly this many
    tok_per_s = total_tokens / dt

    # Roofline: each decode step streams the weights once (bf16) plus the
    # mean KV prefix for the whole batch.
    param_bytes = cfg.num_params() * 2
    mean_prefix = PROMPT_LEN + DECODE_STEPS / 2
    kv_bytes_per_step = 2 * cfg.n_layers * BATCH * mean_prefix * cfg.kv_dim * 2
    hbm_gbps = detect_hbm_gbps(devs[0])
    roofline_steps = hbm_gbps * 1e9 / (param_bytes + kv_bytes_per_step)
    roofline_tok_s = roofline_steps * BATCH

    def measure_int8() -> dict:
        # int8 weight-only decode (ops/quant.py): same harness, quantized
        # layer weights — ~half the streamed bytes — scored against its OWN
        # roofline (actual pytree bytes, not 2 B/param) so the fraction stays
        # honest while absolute tok/s shows the win. A SIDE measurement: it
        # must never cost the bf16 headline, so the worker prints the
        # bf16-only result line BEFORE calling this (a hang here loses only
        # the extras), crashes are reported as int8_error, and the
        # supervisor disables it on retries (KATA_TPU_BENCH_INT8=0).
        if os.environ.get("KATA_TPU_BENCH_INT8", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.ops.quant import (
                params_hbm_bytes,
                quantize_decoder_params,
            )

            qparams = jax.jit(quantize_decoder_params)(params)
            jax.block_until_ready(qparams)
            run(qparams, 0)  # warm-up: int8 layouts recompile prefill+decode
            q_dt = min(
                t for _, t in [run(qparams, seed)[:2] for seed in range(4, 7)]
            )
            int8_bytes = params_hbm_bytes(qparams) + kv_bytes_per_step
            int8_roofline_tok_s = hbm_gbps * 1e9 / int8_bytes * BATCH
            return {
                "int8_tok_per_s": round(total_tokens / q_dt, 1),
                "int8_vs_baseline": round(
                    total_tokens / q_dt / int8_roofline_tok_s, 4
                ),
                "int8_decode_s": round(q_dt, 4),
                "int8_speedup": round(dt / q_dt, 3),
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"int8_error": f"{type(exc).__name__}: {exc}"[:200]}

    def measure_serving() -> dict:
        # Continuous-batching throughput (guest/serving.py): 16 mixed-length
        # requests through an 8-slot arena. A SIDE measurement with the same
        # protections as int8: runs after the banked headline line, crashes
        # report as serving_error, KATA_TPU_BENCH_SERVING=0 disables.
        if args.smoke or os.environ.get("KATA_TPU_BENCH_SERVING", "1") == "0":
            return {}
        try:
            from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

            def make_server():
                return GenerationServer(
                    params, cfg, max_batch=BATCH, max_len=PROMPT_LEN + 72,
                    chunk=16, prefill_buckets=(PROMPT_LEN,),
                )

            rng = jax.random.PRNGKey(42)
            new_per_req = 64

            def reqs(srv, count, salt=0):
                out = []
                for i in range(count):
                    n = PROMPT_LEN - (i % 4) * 16  # mixed lengths, one bucket
                    p = jax.random.randint(
                        jax.random.fold_in(rng, salt + i), (n,), 0,
                        cfg.vocab_size, dtype=jnp.int32,
                    )
                    out.append(srv.submit(np.asarray(p), new_per_req))
                return out

            # Warm-up server: same shapes → the timed run reuses the
            # compiled prefill/decode/_write_slot executables (every other
            # measurement here excludes compiles; this one must too). The
            # warm-up PROMPT differs (salt) so the remote tunnel's
            # identical-execution cache cannot serve the timed request.
            warm = make_server()
            reqs(warm, 1, salt=1000)
            warm.run()

            srv = make_server()
            rids = reqs(srv, 2 * BATCH)
            t0 = time.perf_counter()
            results = srv.run()
            dt_s = time.perf_counter() - t0
            total = sum(len(results[r]) for r in rids)
            return {
                "serving_tok_per_s": round(total / dt_s, 1),
                "serving_requests": len(rids),
                "serving_s": round(dt_s, 3),
            }
        except Exception as exc:  # noqa: BLE001 — headline must survive
            return {"serving_error": f"{type(exc).__name__}: {exc}"[:200]}

    out = {
        "metric": METRIC,
        "value": round(tok_per_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(tok_per_s / roofline_tok_s, 4),
        "platform": devs[0].platform,
        "device_kind": str(getattr(devs[0], "device_kind", "")),
        "config": "smoke-tiny" if args.smoke else "gemma2b",
        "decode_attn": (
            "pallas_fused"
            if decode_eligible(1, max_len, cfg.head_dim, True, 0)
            else "xla_reference"
        ),
        "decode_s": round(dt, 4),
        "prompt_prefill_s": round(prompt_prefill_s, 4),
        "e2e_tok_per_s": round(total_tokens / best_e2e_s, 1),
        "prefill_attn": "pallas_flash" if prefill_flash else "xla_reference",
        "prefill_tok_per_s": round(PREFILL_LEN / min(prefill_s.values()), 1),
    }
    if args.fallback:
        out["note"] = "cpu fallback after TPU attempts failed; not a TPU number"
    if prefill_flash:
        out["prefill_flash_s"] = round(prefill_s["flash"], 4)
        out["prefill_reference_s"] = round(prefill_s["reference"], 4)
        out["prefill_flash_speedup"] = round(
            prefill_s["reference"] / prefill_s["flash"], 3
        )
    # The bf16 headline is complete here — bank it before the int8 extras
    # (the supervisor accepts the LAST metric line, even from a worker it
    # had to kill, so a hang in the int8 section can't void this result).
    print(json.dumps(out), flush=True)
    int8_out = measure_int8()
    if int8_out:
        out.update(int8_out)
        print(json.dumps(out), flush=True)
    serving_out = measure_serving()
    if serving_out:
        out.update(serving_out)
        print(json.dumps(out), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile-dir", default="", help="dump a jax.profiler trace here")
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="tiny config/shapes: validates the harness end-to-end in seconds "
        "(the number it prints is NOT the headline metric)",
    )
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--fallback", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.worker:
        worker(args)
        return 0
    return supervise(args)


if __name__ == "__main__":
    sys.exit(main())
