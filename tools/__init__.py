"""Repo tooling (not shipped in the wheel): static analysis, CI helpers."""
