"""jaxguard's data model: findings, the rule catalogue, and the knobs
that root the dataflow analysis in this repo's conventions.

The analyzer (see :mod:`.graph` and :mod:`.dataflow`) is interprocedural
but name-based — it resolves calls through import maps and ``self.``
method dispatch, not through runtime types. The configuration here is
what anchors that approximation to reality:

- :data:`DEVICE_FN_NAMES` — callables whose results are device arrays
  even when the analyzer cannot see their bodies (the ISSUE's roots:
  ``prefill``/``decode_chunk``/``make_train_step`` results and friends,
  plus the ``step_fn`` convention for train-step callables passed as
  parameters).
- :data:`DEVICE_PREFIXES` — dotted prefixes that produce device values
  (``jnp.``, ``jax.random.``, …).
- :data:`HOT_ROOT_SUFFIXES` — the serving/training step bodies every
  function reachable from which is "hot": a host sync there stalls the
  pipelined round loop. ``# jaxguard: hot`` on a def line adds a root
  anywhere (bench/scripts mark their timed windows this way).
"""
from __future__ import annotations

from dataclasses import dataclass

ALL_RULES = {
    "JG101": "implicit host sync in a hot path "
             "(float/int/bool/.item/np.asarray/if on a device value)",
    "JG102": "use-after-donation (a buffer donated to a jitted call is "
             "read afterwards)",
    "JG103": "tracer leak (traced value stored to self/global/closure "
             "state that outlives the traced call)",
    "JG104": "recompile hazard (unhashable or loop-varying static args; "
             "shape-dependent Python branching in a jitted body)",
    # --- JG2xx: lock discipline (tools.analyze.concurrency) -------------
    "JG201": "lock-guarded attribute accessed without the lock on a "
             "thread-reachable path (data race)",
    "JG202": "lock acquired while holding another lock against the "
             "global lock order (deadlock hazard)",
    "JG203": "blocking call (sleep/file-IO/gRPC) made while holding a "
             "lock in a hot daemon path",
    # --- JG3xx: knob contract (tools.analyze.contracts) -----------------
    "JG301": "ENV_* knob has no matching validated Config field",
    "JG302": "ENV_* knob is never injected by an allocator/plugin site",
    "JG303": "ENV_* knob parse site converts (int/float) outside a "
             "degrade-with-event guard — malformed env would raise",
    "JG304": "ENV_* knob has no row in docs/observability.md",
    # --- JG4xx: dispatch-surface contract (tools.analyze.dispatch) ------
    "JG401": "dispatch census violation (a static arg of a serving-"
             "reachable jitted callable draws from a traced, loop-"
             "varying, or unbounded source — the executable set is not "
             "closed)",
    "JG402": "donation incompleteness (a persistent buffer donated to a "
             "jitted call is never rebound at the call site — the "
             "attribute dangles on a deleted buffer)",
    "JG403": "sharding-spec coverage gap (shard_map without explicit "
             "in/out specs, a kv-layout branch outside the lattice or "
             "falling through to None, or device_put on the serving "
             "path outside allow_transfer)",
    "JG404": "stale pragma (an allow(RULE) whose rule no longer fires "
             "on that line — dead sanction debt)",
}

# Callables whose RESULTS are device values regardless of whether the
# analyzer resolved their bodies. Matched against the call's leaf name, so
# the convention covers both direct imports (`prefill(...)`) and callables
# passed as parameters (`step_fn(state, batch)` — make_train_step's
# contract).
DEVICE_FN_NAMES = frozenset({
    "prefill",
    "prefill_batch",
    "decode",
    "decode_chunk",
    "generate",
    "forward",
    "step_fn",
    "make_train_step",
    "init_params",
    "init_sharded_params",
    "init_kv_caches",
    "init_cycle_kv_caches",
    "device_put",
    "block_until_ready",  # returns its (device) argument
})

# Dotted-call prefixes that produce device arrays. jax.device_get is the
# explicit escape hatch (host result, sanctioned) — carved out in the
# dataflow engine, not here.
DEVICE_PREFIXES = (
    "jnp.",
    "jax.numpy.",
    "jax.random.",
    "jax.lax.",
    "lax.",
    "jax.nn.",
    "jax.tree.",
    "jax.tree_util.",
)

# Attribute reads that return host metadata, not a device view.
NONDEVICE_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "sharding",
    "addressable_shards", "device", "devices", "aval", "weak_type",
})

# Host-sync sinks: builtins coercing a device value, numpy materializers,
# and array methods that force a transfer.
SYNC_BUILTINS = frozenset({"float", "int", "bool", "complex"})
SYNC_NUMPY = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
})
SYNC_METHODS = frozenset({"item", "tolist"})

# Hot roots: matched as suffixes of the analyzer's function qualnames
# ("pkg.guest.serving:GenerationServer.step"). The serving round loop and
# the trainer step body are hot by definition; everything they reach
# inherits it. (The ISSUE names run_round/Trainer.fit; this repo's
# spellings are GenerationServer.step/run and parallel.trainer.fit.)
HOT_ROOT_SUFFIXES = (
    "GenerationServer.run_round",
    "GenerationServer.step",
    "GenerationServer.run",
    ".trainer.fit",
    "Trainer.fit",
)

# Inline marker that makes any function a hot root (same comment channel
# as the allow() pragmas; see tools.pragmas for the suppression side).
HOT_MARK = "# jaxguard: hot"

# ---------------------------------------------------------------------------
# JG4xx — dispatch-surface contract (tools.analyze.dispatch)
# ---------------------------------------------------------------------------

# The SERVING roots of the dispatch census: unlike HOT_ROOT_SUFFIXES this
# deliberately excludes the trainer — the census/reshard contract is a
# serving-loop property (training legitimately device_puts batches and
# compiles per shape bucket on its own schedule).
DISPATCH_ROOT_SUFFIXES = (
    "GenerationServer.step",
    "GenerationServer.run",
)

# Modules whose spec helpers must cover the whole kv-layout lattice
# (JG403): every layout comparison resolves to a lattice member and no
# layout falls off the end of a spec function.
SPEC_MODULE_PATHS = (
    "kata_xpu_device_plugin_tpu/guest/tp_serving.py",
    "kata_xpu_device_plugin_tpu/parallel/sharding.py",
    "kata_xpu_device_plugin_tpu/ops/decode_attn.py",
)

# Parameter names that carry a kv-layout selector into a spec helper.
LAYOUT_PARAM_NAMES = frozenset({"layout", "kv_layout"})

# ---------------------------------------------------------------------------
# JG2xx — lock discipline (tools.analyze.concurrency)
# ---------------------------------------------------------------------------

# Methods of a ``*Servicer`` subclass that the gRPC runtime invokes on its
# own thread pool — the kubelet device-plugin v1beta1 surface. Any method
# of a class whose base name ends in "Servicer" AND is named here is a
# thread entry point.
GRPC_ENTRY_METHODS = frozenset({
    "GetDevicePluginOptions",
    "ListAndWatch",
    "GetPreferredAllocation",
    "Allocate",
    "PreStartContainer",
})

# Thread entry points the AST cannot see structurally (no ``Thread(target=
# ...)`` spelling in reach): hooks invoked on OTHER components' threads.
# Matched as "Class.method" (or bare "function") suffixes of qualnames.
THREAD_ENTRY_REGISTRY = (
    # obs.events.emit runs on EVERY emitting thread (serving loop, gRPC
    # handlers, watcher) and fans into the sink + flight ring + watchdog.
    "EventSink.emit",
    "FlightRecorder.record",
    "SLOBurnWatchdog.observe",
    # SIGUSR1 debug-dump thread reads these while the daemon runs.
    "SLOBurnWatchdog.stats",
    "PluginManager.debug_report",
    "HeartbeatAggregator.snapshot",
    # Allocate handlers call the journal through the on_allocate hook
    # (a lambda the resolver cannot chase).
    "AllocationJournal.record",
)

# Dotted call spellings that block (scheduler-visible sleeps, file IO,
# gRPC dials) — JG203 flags these while a lock is held on a hot daemon
# path. Matched against the call's dotted name exactly, or by prefix for
# the entries ending in ".".
BLOCKING_CALLS = frozenset({
    "time.sleep",
    "open",
    "os.makedirs",
    "os.replace",
    "os.rename",
    "os.remove",
    "os.unlink",
    "os.truncate",
    "os.listdir",
    "os.stat",
    "json.dump",
    "json.load",
    "shutil.rmtree",
    "subprocess.run",
})
BLOCKING_PREFIXES = ("grpc.",)

# ---------------------------------------------------------------------------
# JG3xx — knob contract (tools.analyze.contracts)
# ---------------------------------------------------------------------------

# Module (relative path) holding the ENV_* catalogue the contract pass
# cross-references, and the Config module that must back each knob.
KNOB_CONSTANTS_PATH = "kata_xpu_device_plugin_tpu/cdi/constants.py"
KNOB_CONFIG_PATH = "kata_xpu_device_plugin_tpu/config.py"
KNOB_DOC_PATH = "docs/observability.md"

# Injection surface: modules (path prefixes) where a reference to the
# constant counts as "the daemon injects/consumes this env".
KNOB_INJECTION_PREFIXES = (
    "kata_xpu_device_plugin_tpu/plugin/",
    "kata_xpu_device_plugin_tpu/topology",
    "kata_xpu_device_plugin_tpu/runtime_env",
)

# Identity/topology envs the daemon injects but which are not operator
# knobs: no Config field, no guest parse contract, documented in
# docs/architecture.md rather than the observability knob table. Fully
# exempt from JG301–JG304.
KNOB_EXEMPT = frozenset({
    "ENV_CDI_VENDOR_CLASS",
    "ENV_TPU_ACCELERATOR_TYPE",
    "ENV_TPU_CHIPS_PER_HOST_BOUNDS",
    "ENV_TPU_HOST_BOUNDS",
    "ENV_TPU_WORKER_ID",
    "ENV_TPU_WORKER_HOSTNAMES",
    "ENV_TPU_VISIBLE_CHIPS",
    "ENV_TPU_SKIP_MDS_QUERY",
})

# Constants whose Config field does not follow the value-derived
# convention (strip "KATA_TPU_", lowercase).
KNOB_FIELD_OVERRIDES = {
    "ENV_SERVING_TP": "serving_tp",          # value is KATA_TPU_TP
    "ENV_SERVING_TP_MIN": "serving_tp_min",  # value is KATA_TPU_TP_MIN
    "ENV_TRACE_CTX": "trace_context",        # value is KATA_TPU_TRACE_CTX
    "ENV_FAULT_SCHEDULE": "faults",          # value is KATA_TPU_FAULTS
    # The obs pair is switched by config.guest_events_dir, not a
    # same-named field (KATATPU_OBS=1 + file path are what the allocator
    # derives FROM guest_events_dir).
    "ENV_OBS": "guest_events_dir",
    "ENV_OBS_FILE": "guest_events_dir",
    "ENV_HEARTBEAT_ROUNDS": "heartbeat_rounds",
}


@dataclass(frozen=True)
class Finding:
    """One analyzer finding. Shape-compatible with ``tools.lint.rules
    .Finding`` (path/line/rule/message) so the shared suppression logic
    and CI formatting apply to both; ``function`` names the enclosing
    callable for the JSON report."""

    path: str
    line: int
    rule: str
    message: str
    function: str = ""

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "function": self.function,
            "message": self.message,
        }
