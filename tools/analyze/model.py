"""jaxguard's data model: findings, the rule catalogue, and the knobs
that root the dataflow analysis in this repo's conventions.

The analyzer (see :mod:`.graph` and :mod:`.dataflow`) is interprocedural
but name-based — it resolves calls through import maps and ``self.``
method dispatch, not through runtime types. The configuration here is
what anchors that approximation to reality:

- :data:`DEVICE_FN_NAMES` — callables whose results are device arrays
  even when the analyzer cannot see their bodies (the ISSUE's roots:
  ``prefill``/``decode_chunk``/``make_train_step`` results and friends,
  plus the ``step_fn`` convention for train-step callables passed as
  parameters).
- :data:`DEVICE_PREFIXES` — dotted prefixes that produce device values
  (``jnp.``, ``jax.random.``, …).
- :data:`HOT_ROOT_SUFFIXES` — the serving/training step bodies every
  function reachable from which is "hot": a host sync there stalls the
  pipelined round loop. ``# jaxguard: hot`` on a def line adds a root
  anywhere (bench/scripts mark their timed windows this way).
"""
from __future__ import annotations

from dataclasses import dataclass

ALL_RULES = {
    "JG101": "implicit host sync in a hot path "
             "(float/int/bool/.item/np.asarray/if on a device value)",
    "JG102": "use-after-donation (a buffer donated to a jitted call is "
             "read afterwards)",
    "JG103": "tracer leak (traced value stored to self/global/closure "
             "state that outlives the traced call)",
    "JG104": "recompile hazard (unhashable or loop-varying static args; "
             "shape-dependent Python branching in a jitted body)",
}

# Callables whose RESULTS are device values regardless of whether the
# analyzer resolved their bodies. Matched against the call's leaf name, so
# the convention covers both direct imports (`prefill(...)`) and callables
# passed as parameters (`step_fn(state, batch)` — make_train_step's
# contract).
DEVICE_FN_NAMES = frozenset({
    "prefill",
    "prefill_batch",
    "decode",
    "decode_chunk",
    "generate",
    "forward",
    "step_fn",
    "make_train_step",
    "init_params",
    "init_sharded_params",
    "init_kv_caches",
    "init_cycle_kv_caches",
    "device_put",
    "block_until_ready",  # returns its (device) argument
})

# Dotted-call prefixes that produce device arrays. jax.device_get is the
# explicit escape hatch (host result, sanctioned) — carved out in the
# dataflow engine, not here.
DEVICE_PREFIXES = (
    "jnp.",
    "jax.numpy.",
    "jax.random.",
    "jax.lax.",
    "lax.",
    "jax.nn.",
    "jax.tree.",
    "jax.tree_util.",
)

# Attribute reads that return host metadata, not a device view.
NONDEVICE_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "sharding",
    "addressable_shards", "device", "devices", "aval", "weak_type",
})

# Host-sync sinks: builtins coercing a device value, numpy materializers,
# and array methods that force a transfer.
SYNC_BUILTINS = frozenset({"float", "int", "bool", "complex"})
SYNC_NUMPY = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
})
SYNC_METHODS = frozenset({"item", "tolist"})

# Hot roots: matched as suffixes of the analyzer's function qualnames
# ("pkg.guest.serving:GenerationServer.step"). The serving round loop and
# the trainer step body are hot by definition; everything they reach
# inherits it. (The ISSUE names run_round/Trainer.fit; this repo's
# spellings are GenerationServer.step/run and parallel.trainer.fit.)
HOT_ROOT_SUFFIXES = (
    "GenerationServer.run_round",
    "GenerationServer.step",
    "GenerationServer.run",
    ".trainer.fit",
    "Trainer.fit",
)

# Inline marker that makes any function a hot root (same comment channel
# as the allow() pragmas; see tools.pragmas for the suppression side).
HOT_MARK = "# jaxguard: hot"


@dataclass(frozen=True)
class Finding:
    """One analyzer finding. Shape-compatible with ``tools.lint.rules
    .Finding`` (path/line/rule/message) so the shared suppression logic
    and CI formatting apply to both; ``function`` names the enclosing
    callable for the JSON report."""

    path: str
    line: int
    rule: str
    message: str
    function: str = ""

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "function": self.function,
            "message": self.message,
        }
