"""jaxguard pass: the standard-knob contract (JG3xx).

Every operator knob in this repo follows one path: an ``ENV_*`` constant
in ``cdi/constants.py`` → a validated ``Config`` field → an allocator
injection site (the daemon stamps the env into the container) → a guest
parse site that DEGRADES on malformed input (emits a ``*_invalid`` /
``*_disabled`` event and falls back, never raises on a node-wide env) →
a documented row in ``docs/observability.md``. That contract has been
re-implemented by hand in every PR since the knob path appeared; this
pass makes it checkable:

JG301 — no matching ``Config`` field (the daemon cannot set the knob).
JG302 — no injection-surface reference (the env is never delivered).
JG303 — a parse site converts the env with ``int()``/``float()``
    outside a try/degrade guard (malformed env would crash the guest).
JG304 — no row in ``docs/observability.md`` (operators cannot find it).

Field matching is by convention — strip ``KATA_TPU_`` from the env
VALUE and lowercase — with the explicit exceptions in
:data:`model.KNOB_FIELD_OVERRIDES`. Identity/topology envs the daemon
injects but which are not operator knobs are listed in
:data:`model.KNOB_EXEMPT` and skipped entirely. Findings anchor at the
constant's definition line (JG301/302/304) or the unsafe conversion
(JG303).
"""
from __future__ import annotations

import ast
from typing import Optional

from .graph import Module, Program, dotted
from .model import (
    Finding,
    KNOB_CONFIG_PATH,
    KNOB_CONSTANTS_PATH,
    KNOB_DOC_PATH,
    KNOB_EXEMPT,
    KNOB_FIELD_OVERRIDES,
    KNOB_INJECTION_PREFIXES,
)

_ENV_GET = frozenset({
    "os.environ.get", "environ.get", "os.getenv", "getenv",
})
_CONVERTERS = frozenset({"int", "float"})
_FIELD_PREFIX = "KATA_TPU_"


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _module_constants(mod: Module) -> dict:
    """Module-level ``NAME = "literal"`` string constants."""
    out: dict = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = (node.value.value, node.lineno)
    return out


def _in_try_map(root: ast.AST) -> set:
    """ids of nodes lexically inside a ``try:`` body — the degrade
    guard JG303 looks for."""
    inside: set = set()

    def visit(node: ast.AST, guarded: bool) -> None:
        if guarded:
            inside.add(id(node))
        if isinstance(node, ast.Try):
            for child in node.body:
                visit(child, True)
            for part in (node.handlers, node.orelse, node.finalbody):
                for child in part:
                    visit(child, guarded)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, guarded)

    visit(root, False)
    return inside


def _env_arg_value(
    node: ast.AST, local_consts: dict, env_values: dict
) -> Optional[str]:
    """The env-var NAME a ``environ.get(...)`` first argument denotes:
    a string literal, a module-local constant, or an ``ENV_*`` spelling
    (``C.ENV_X`` / imported name) matched by its leaf against the
    constants catalogue."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = dotted(node)
    if name is None:
        return None
    leaf = name.split(".")[-1]
    if leaf in local_consts:
        return local_consts[leaf][0]
    if leaf in env_values:
        return env_values[leaf]
    return None


class _ParseSite:
    def __init__(self, mod: Module, fn_node: ast.AST, call: ast.AST,
                 env_value: str) -> None:
        self.mod = mod
        self.fn_node = fn_node
        self.call = call
        self.env_value = env_value


def _function_nodes(mod: Module):
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _unsafe_conversions(fn_node: ast.AST, get_calls: list) -> list:
    """``int()``/``float()`` calls applied to an env-get result (the
    call itself, or a name bound from one) OUTSIDE any try body — the
    raising conversions JG303 exists to catch. Returns the offending
    conversion nodes."""
    in_try = _in_try_map(fn_node)
    get_ids = {id(c) for c in get_calls}
    bound: set = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and id(node.value) in get_ids:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    bound.add(tgt.id)
    out = []
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.Call) and isinstance(
            node.func, ast.Name
        ) and node.func.id in _CONVERTERS):
            continue
        feeds = False
        for sub in ast.walk(node):
            if id(sub) in get_ids or (
                isinstance(sub, ast.Name) and sub.id in bound
            ):
                feeds = True
                break
        if feeds and id(node) not in in_try:
            out.append(node)
    return out


def analyze_contracts(
    program: Program, doc_text: Optional[str] = None
) -> list:
    """Run the JG3xx knob-contract pass. ``doc_text`` is the content of
    ``docs/observability.md`` (None → the JG304 leg is skipped, for
    source subsets that do not carry docs)."""
    findings: list = []
    const_mod = None
    config_mod = None
    for mod in program.modules.values():
        if _norm(mod.path) == KNOB_CONSTANTS_PATH:
            const_mod = mod
        elif _norm(mod.path) == KNOB_CONFIG_PATH:
            config_mod = mod
    if const_mod is None:
        return findings
    env_consts = {
        name: (value, lineno)
        for name, (value, lineno) in _module_constants(const_mod).items()
        if name.startswith("ENV_")
    }
    env_values = {n: v for n, (v, _ln) in env_consts.items()}

    # Leg (a): Config fields (AnnAssign names of the dataclass body).
    config_fields: set = set()
    if config_mod is not None:
        for node in ast.walk(config_mod.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Config":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name
                    ):
                        config_fields.add(stmt.target.id)

    # Leg (b): references on the injection surface.
    injected: set = set()
    for mod in program.modules.values():
        path = _norm(mod.path)
        if path == KNOB_CONSTANTS_PATH or not path.startswith(
            KNOB_INJECTION_PREFIXES
        ):
            continue
        for node in ast.walk(mod.tree):
            leaf = None
            if isinstance(node, ast.Attribute):
                leaf = node.attr
            elif isinstance(node, ast.Name):
                leaf = node.id
            if leaf in env_consts:
                injected.add(leaf)

    # Leg (c): parse sites and their conversion discipline, program-wide.
    # Helpers that take the env NAME as a parameter (the watchdog's
    # ``_f``/``_i`` pattern) count as parse sites at their call sites,
    # with the helper body's discipline.
    unsafe_values: dict = {}   # env value → first unsafe (mod, node)
    helper_safety: dict = {}   # (modname, fn name) → is_unsafe
    helper_param_pos: dict = {}
    for mod in program.modules.values():
        local_consts = _module_constants(mod)
        for fn_node in _function_nodes(mod):
            params = [a.arg for a in fn_node.args.args]
            direct_gets: list = []
            param_gets: list = []
            for node in ast.walk(fn_node):
                if not (isinstance(node, ast.Call) and dotted(
                    node.func
                ) in _ENV_GET and node.args):
                    continue
                arg = node.args[0]
                value = _env_arg_value(arg, local_consts, env_values)
                if value is not None:
                    direct_gets.append((node, value))
                elif isinstance(arg, ast.Name) and arg.id in params:
                    param_gets.append((node, arg.id))
            for conv in _unsafe_conversions(
                fn_node, [c for c, _v in direct_gets]
            ):
                # Attribute the conversion to every env this function
                # parses — the common case is exactly one.
                for _call, value in direct_gets:
                    unsafe_values.setdefault(value, (mod, conv))
            if param_gets:
                unsafe = bool(_unsafe_conversions(
                    fn_node, [c for c, _p in param_gets]
                ))
                key = (mod.modname, fn_node.name)
                helper_safety[key] = unsafe
                helper_param_pos[key] = params.index(param_gets[0][1])
    # Helper call sites: helper(ENV_X, ...) with a resolvable env name.
    for mod in program.modules.values():
        local_consts = _module_constants(mod)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            callee = dotted(node.func)
            if callee is None:
                continue
            leaf = callee.split(".")[-1]
            for (modname, fname), unsafe in helper_safety.items():
                if fname != leaf or not unsafe:
                    continue
                pos = helper_param_pos[(modname, fname)]
                if pos < len(node.args):
                    value = _env_arg_value(
                        node.args[pos], local_consts, env_values
                    )
                    if value is not None:
                        unsafe_values.setdefault(value, (mod, node))

    for name, (value, lineno) in sorted(
        env_consts.items(), key=lambda kv: kv[1][1]
    ):
        if name in KNOB_EXEMPT:
            continue
        field = KNOB_FIELD_OVERRIDES.get(name)
        if field is None:
            stripped = value[len(_FIELD_PREFIX):] if value.startswith(
                _FIELD_PREFIX
            ) else value
            field = stripped.lower()
        if config_mod is not None and field not in config_fields:
            findings.append(Finding(
                path=const_mod.path, line=lineno, rule="JG301",
                message=f"{name}={value} has no Config field "
                        f"{field!r} backing it",
                function=name,
            ))
        if name not in injected:
            findings.append(Finding(
                path=const_mod.path, line=lineno, rule="JG302",
                message=f"{name}={value} is never referenced on the "
                        f"allocator/plugin injection surface",
                function=name,
            ))
        if value in unsafe_values:
            mod, node = unsafe_values[value]
            findings.append(Finding(
                path=mod.path, line=getattr(node, "lineno", 0),
                rule="JG303",
                message=f"{value} parsed with int()/float() outside a "
                        f"degrade guard — malformed env raises instead "
                        f"of emitting *_invalid/*_disabled",
                function=name,
            ))
        if doc_text is not None and value not in doc_text:
            findings.append(Finding(
                path=const_mod.path, line=lineno, rule="JG304",
                message=f"{name}={value} has no row in {KNOB_DOC_PATH}",
                function=name,
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
