"""jaxguard: interprocedural AST + dataflow analysis for JAX hazards.

The per-function linter (``tools.lint``) pattern-matches single
functions; it cannot see that a value produced inside ``jax.jit`` flows
into ``float()`` three calls later. This package builds a per-module
symbol table and call graph over the repo (:mod:`.graph`), runs a
device-value taint fixpoint across it (:mod:`.dataflow`), and reports:

- **JG101** — implicit host sync in a hot path,
- **JG102** — use-after-donation,
- **JG103** — tracer leak,
- **JG104** — recompile hazard.

Every static rule is paired with a runtime strict-mode switch
(``kata_xpu_device_plugin_tpu.compat.jaxapi.strict_mode`` /
``KATA_TPU_STRICT=1``), so CI enforces the same contract both ways:
jaxguard catches what never runs, the transfer guard catches what the
analyzer cannot resolve. Suppression pragmas share the lint grammar:
``# jaxguard: allow(JG101) <reason>`` (see ``tools.pragmas``).
"""
from .cli import analyze_source, analyze_sources, main, run, write_report
from .dataflow import Analyzer, analyze_program
from .graph import Program, load_program
from .model import ALL_RULES, Finding

__all__ = [
    "ALL_RULES",
    "Analyzer",
    "Finding",
    "Program",
    "analyze_program",
    "analyze_source",
    "analyze_sources",
    "load_program",
    "main",
    "run",
    "write_report",
]
