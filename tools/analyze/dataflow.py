"""jaxguard pass 2: interprocedural device-value dataflow + the JG rules.

The engine runs three fixpoints over the :class:`~.graph.Program`'s call
graph, then a collection pass per function:

1. **returns-device** — a function whose return expression is tainted
   (or that is jitted) marks its CALLERS' call results tainted, so a
   value produced inside ``jax.jit`` is still device-tainted three calls
   later (the case the per-function linter provably cannot see).
2. **parameter taint** — a call site passing a tainted value marks the
   callee's parameter tainted (context-insensitive: any caller taints
   all contexts — errs toward finding the sync).
3. **class-attribute taint** — ``self.X = <tainted>`` in any method
   taints ``self.X`` reads in every method of that class (the serving
   arena pattern).

Taint sources: calls to jitted callables, calls resolved to
returns-device functions, the :data:`~.model.DEVICE_FN_NAMES` /
:data:`~.model.DEVICE_PREFIXES` conventions, and — inside jitted
bodies — the non-static parameters themselves (they are tracers there).

Rules (catalogue in :data:`~.model.ALL_RULES`): JG101 fires only in
functions HOT (reachable from the serving/trainer step roots or marked
``# jaxguard: hot``) and not themselves traced; JG102/JG104a/b fire at
call sites of jitted callables anywhere; JG103/JG104c fire inside traced
bodies. Suppression: ``# jaxguard: allow(JGxxx) reason`` on the finding
line (shared grammar — ``tools.pragmas``).
"""
from __future__ import annotations

import ast
from collections import defaultdict
from typing import Optional

from .graph import FunctionInfo, Program, dotted
from .model import (
    ALL_RULES,
    DEVICE_FN_NAMES,
    DEVICE_PREFIXES,
    Finding,
    HOT_ROOT_SUFFIXES,
    NONDEVICE_ATTRS,
    SYNC_BUILTINS,
    SYNC_METHODS,
    SYNC_NUMPY,
)

_UNHASHABLE = (
    ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp, ast.DictComp,
    ast.GeneratorExp,
)
# Mutating methods that leak a traced value into the receiver. Only calls
# whose RESULT is discarded (bare expression statements) count: optax's
# `updates, state = optimizer.update(...)` is pure-functional despite the
# name, and binding the result is the tell.
_MUTATORS = frozenset({"append", "extend", "add", "insert", "update"})

# How many times Analyzer.run() executed its interprocedural fixpoint.
# The CLI builds ONE engine and threads it through every pass family;
# tests/test_jaxguard.py pins this at 1 per CLI run so a refactor that
# quietly rebuilds the graph per pass shows up as a perf regression.
FIXPOINT_RUNS = 0


def _any(t) -> bool:
    """Collapse a (possibly tuple-structured) taint to a plain bool."""
    return any(t) if isinstance(t, tuple) else bool(t)


def _merge_taint(a, b):
    """Join two taints: True dominates; same-length tuples join
    element-wise (mixed-return functions like ``(do_sample, key)`` keep
    per-element precision); everything else collapses."""
    if a is True or b is True:
        return True
    if isinstance(a, tuple) and isinstance(b, tuple):
        if len(a) == len(b):
            return tuple(x or y for x, y in zip(a, b))
        return _any(a) or _any(b)
    if a is False:
        return b
    if b is False:
        return a
    return _any(a) or _any(b)


class Analyzer:
    def __init__(self, program: Program):
        self.prog = program
        self.returns_device: dict[str, bool] = {}
        self.tainted_params: dict[str, set] = defaultdict(set)
        self.class_attrs: dict[tuple, set] = defaultdict(set)
        self.call_edges: dict[str, set] = defaultdict(set)

    # ----- driver -----------------------------------------------------------

    def run(self) -> list[Finding]:
        global FIXPOINT_RUNS
        FIXPOINT_RUNS += 1
        fns = self.prog.functions
        for q, fn in fns.items():
            if fn.jit is not None:
                self.returns_device[q] = True
        changed, passes = True, 0
        while changed and passes < 12:
            changed, passes = False, passes + 1
            for q, fn in fns.items():
                ev = _FnEval(self, fn)
                ev.walk()
                self.call_edges[q] = ev.edges
                merged = _merge_taint(
                    self.returns_device.get(q, False), ev.returns_struct
                )
                if merged != self.returns_device.get(q, False):
                    self.returns_device[q] = merged
                    changed = True
                for callee_q, pname in ev.param_taints:
                    if pname not in self.tainted_params[callee_q]:
                        self.tainted_params[callee_q].add(pname)
                        changed = True
                for key, attr in ev.attr_taints:
                    if attr not in self.class_attrs[key]:
                        self.class_attrs[key].add(attr)
                        changed = True
        hot = self._hot_set()
        findings: list[Finding] = []
        seen = set()
        for q, fn in fns.items():
            ev = _FnEval(
                self, fn,
                collect=True,
                hot=(q in hot) and not self.traced(fn),
            )
            ev.walk()
            for f in ev.findings:
                key = (f.path, f.line, f.rule, f.message)
                if key not in seen:
                    seen.add(key)
                    findings.append(f)
        return sorted(findings, key=lambda f: (f.path, f.line, f.rule))

    def traced(self, fn: FunctionInfo) -> bool:
        """Is ``fn``'s body traced when it runs — jitted itself, or nested
        inside a jitted def (scan bodies, shard_map closures)?"""
        if fn.jit is not None:
            return True
        qual = fn.qualname
        while "." in qual.split(":", 1)[1]:
            qual = qual.rsplit(".", 1)[0]
            anc = self.prog.functions.get(qual)
            if anc is not None and anc.jit is not None:
                return True
        return False

    def _hot_set(self) -> set:
        hot = set()
        for q, fn in self.prog.functions.items():
            flat = q.replace(":", ".")
            if fn.hot_marked or any(
                flat.endswith(s) for s in HOT_ROOT_SUFFIXES
            ):
                hot.add(q)
        frontier = list(hot)
        while frontier:
            q = frontier.pop()
            for callee in self.call_edges.get(q, ()):
                fn = self.prog.functions.get(callee)
                if fn is None or callee in hot:
                    continue
                if fn.jit is not None:
                    continue  # device code: no host syncs inside
                hot.add(callee)
                frontier.append(callee)
        return hot


class _FnEval:
    """One pass over one function body: taint propagation in statement
    order with rule checks as side effects. ``collect=False`` runs the
    same walk for the fixpoint facts only."""

    def __init__(
        self,
        an: Analyzer,
        fn: FunctionInfo,
        collect: bool = False,
        hot: bool = False,
    ):
        self.an = an
        self.fn = fn
        self.collect = collect
        self.hot = hot
        self.traced = an.traced(fn)
        self.mod = an.prog.modules[fn.modname]
        self.env: dict[str, bool] = {}
        statics = fn.static_param_names()
        for p in fn.params:
            if self.traced:
                self.env[p] = p not in statics and p not in ("self", "cls")
            else:
                self.env[p] = p in an.tainted_params.get(fn.qualname, ())
        self.watches: dict[str, tuple] = {}  # dotted → (line, callee name)
        # Staged-dispatch bindings: `fargs = (…)` / `fkw = dict(…)` later
        # splatted into `fn(*fargs, **fkw)` (the _dispatch_decode idiom).
        # The donation/static checks expand through them so the single
        # dispatch site is as visible as a direct call.
        self.tuple_stages: dict[str, list] = {}
        self.dict_stages: dict[str, dict] = {}
        self.loop_vars: list[set] = []
        self.globals_decl: set = set()
        self.edges: set = set()
        self.param_taints: set = set()
        self.attr_taints: set = set()
        self.returns_struct = False  # bool | tuple[bool, ...]
        self.findings: list[Finding] = []
        self._pure = 0
        self._expr_value: Optional[ast.AST] = None

    # ----- helpers ----------------------------------------------------------

    def _add(self, node: ast.AST, rule: str, message: str) -> None:
        if not self.collect or self._pure:
            return
        self.findings.append(Finding(
            self.fn.path, getattr(node, "lineno", 1), rule, message,
            function=self.fn.qualname,
        ))

    def _sync(self, node: ast.AST, what: str) -> None:
        if self.hot:
            self._add(
                node, "JG101",
                f"{what} forces an implicit device→host sync in a hot "
                "path — move it to a sanctioned sync point or annotate "
                "'# jaxguard: allow(JG101) <reason>'",
            )

    def _check_watch(self, node: ast.AST, name: str) -> None:
        """A load of ``name`` while a donation watch covers it (exact or
        prefix) is a use-after-donation."""
        if self._pure or not self.watches:
            return
        for watched, (line, callee) in self.watches.items():
            if name == watched or name.startswith(watched + ".") or (
                watched.startswith(name + ".")
            ):
                self._add(
                    node, "JG102",
                    f"'{name}' was donated to '{callee}' at line {line} "
                    "and is read afterwards — donated buffers are deleted "
                    "by XLA; rebind the call's result instead",
                )

    def _store(self, name: str) -> None:
        if self._pure:
            return
        for watched in list(self.watches):
            if watched == name or watched.startswith(name + "."):
                del self.watches[watched]

    def _in_loop_vars(self, expr: ast.AST) -> Optional[str]:
        names = {n for scope in self.loop_vars for n in scope}
        if not names:
            return None
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in names:
                return sub.id
        return None

    # ----- expression taint -------------------------------------------------

    def taint(self, node: Optional[ast.AST]):
        """Evaluate ``node``'s taint: bool, or a tuple of bools for tuple
        literals / structured returns (per-element precision survives
        unpacking)."""
        if node is None:
            return False
        m = getattr(
            self, f"_t_{type(node).__name__}", None
        )
        if m is not None:
            return m(node)
        # Default: visit children, propagate any taint.
        out = False
        for child in ast.iter_child_nodes(node):
            out = _any(self.taint(child)) or out
        return out

    def _t_Constant(self, node) -> bool:
        return False

    def _t_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self._check_watch(node, node.id)
        return self.env.get(node.id, False)

    def _t_Attribute(self, node) -> bool:
        d = dotted(node)
        if d is not None and isinstance(node.ctx, ast.Load):
            self._check_watch(node, d)
        if (
            d is not None
            and d.startswith("self.")
            and d.count(".") == 1
            and self.fn.cls is not None
        ):
            return node.attr in self.an.class_attrs.get(
                (self.fn.modname, self.fn.cls), ()
            )
        base = _any(self.taint(node.value))
        if node.attr in NONDEVICE_ATTRS:
            return False
        return base

    def _t_Subscript(self, node):
        base = self.taint(node.value)
        self.taint(node.slice)
        if isinstance(base, tuple):
            if isinstance(node.slice, ast.Constant) and isinstance(
                node.slice.value, int
            ) and -len(base) <= node.slice.value < len(base):
                return base[node.slice.value]
            return _any(base)
        return base

    def _t_Tuple(self, node):
        return tuple(_any(self.taint(e)) for e in node.elts)

    def _t_List(self, node) -> bool:
        return any([_any(self.taint(e)) for e in node.elts])

    _t_Set = _t_List

    def _t_Dict(self, node) -> bool:
        out = False
        for k, v in zip(node.keys, node.values):
            self.taint(k)
            out = self.taint(v) or out
        return out

    def _t_BinOp(self, node) -> bool:
        left = _any(self.taint(node.left))
        return _any(self.taint(node.right)) or left

    def _t_UnaryOp(self, node) -> bool:
        return _any(self.taint(node.operand))

    def _t_BoolOp(self, node) -> bool:
        out = False
        for v in node.values:
            t = _any(self.taint(v))
            if t:
                self._sync(v, "truth-testing a device value (and/or)")
            out = t or out
        return out

    def _t_Compare(self, node) -> bool:
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            self.taint(node.left)
            for c in node.comparators:
                self.taint(c)
            return False
        out = _any(self.taint(node.left))
        for c in node.comparators:
            out = _any(self.taint(c)) or out
        return out

    def _t_IfExp(self, node):
        if _any(self.taint(node.test)):
            self._sync(node.test, "branching on a device value (ternary)")
        body = self.taint(node.body)
        return _merge_taint(body, self.taint(node.orelse))

    def _t_Lambda(self, node) -> bool:
        return False  # opaque; its body runs in the callee's context

    def _t_JoinedStr(self, node) -> bool:
        for v in node.values:
            self.taint(v)
        return False

    def _t_Await(self, node) -> bool:
        return self.taint(node.value)

    def _t_Starred(self, node) -> bool:
        return self.taint(node.value)

    def _comp(self, node) -> bool:
        for gen in node.generators:
            it = _any(self.taint(gen.iter))
            self._assign_target(gen.target, it, None)
            for cond in gen.ifs:
                self.taint(cond)
        if isinstance(node, ast.DictComp):
            self.taint(node.key)
            return _any(self.taint(node.value))
        return _any(self.taint(node.elt))

    _t_ListComp = _comp
    _t_SetComp = _comp
    _t_GeneratorExp = _comp
    _t_DictComp = _comp

    # ----- calls ------------------------------------------------------------

    def _t_Call(self, node: ast.Call):
        d = dotted(node.func) or ""
        leaf = d.rsplit(".", 1)[-1]

        # Evaluate the receiver ONCE, non-pure: a method call on a donated
        # buffer (`arena.sum()`) is a read of it, and the base's taint
        # feeds the method-sync and mutator checks below.
        base_taint = False
        if isinstance(node.func, ast.Attribute):
            base_taint = _any(self.taint(node.func.value))

        arg_taints = [_any(self.taint(a)) for a in node.args]
        kw_taints = {
            k.arg: _any(self.taint(k.value)) for k in node.keywords
        }

        # Host-sync sinks (result is host; the CALL is the event).
        if d in SYNC_BUILTINS and arg_taints[:1] == [True]:
            self._sync(node, f"{d}() of a device value")
            return False
        if d in SYNC_NUMPY and (
            any(arg_taints) or any(kw_taints.values())
        ):
            self._sync(node, f"{d}() of a device value")
            return False
        if isinstance(node.func, ast.Attribute) and (
            node.func.attr in SYNC_METHODS
        ):
            if base_taint:
                self._sync(node, f".{node.func.attr}() of a device value")
                return False

        # A host materializer passed INTO a tree mapper is the same sync
        # one level up: jax.tree.map(np.asarray, <device tree>) transfers
        # every leaf (the spill/demotion spelling).
        if leaf in ("map", "tree_map") and d.startswith(
            ("jax.tree", "tree.")
        ) and node.args:
            f0 = dotted(node.args[0])
            if f0 in SYNC_NUMPY and (
                any(arg_taints[1:]) or any(kw_taints.values())
            ):
                # Anchor on the materializer reference itself — that is
                # the line the sanctioning pragma rides.
                self._sync(node.args[0], f"{d}({f0}, ...) over a device tree")
                return False

        # Explicit, sanctioned host reads / fences.
        if leaf == "device_get":
            return False
        if leaf == "block_until_ready":
            return any(arg_taints)
        if d == "len":
            return False

        callee = self.prog_resolve(d)
        if callee is not None:
            self.edges.add(callee.qualname)
            self._record_param_taints(node, callee, arg_taints, kw_taints)
            self._check_donation(node, callee, d)
            self._check_statics(node, callee)
            if callee.jit is not None:
                return True
            return self.an.returns_device.get(callee.qualname, False)

        # Unresolved: fall back to the naming conventions.
        if d.startswith(("np.", "numpy.")):
            return False
        if d.startswith(DEVICE_PREFIXES) or d in ("jnp", "jax"):
            return True
        if leaf in DEVICE_FN_NAMES:
            return True
        if isinstance(node.func, ast.Attribute):
            # Mutator leak: list.append(tracer) on non-local state, and
            # taint-through-mutation for locals (losses.append(loss)).
            # Only discarded-result calls count as mutations — binding the
            # result (optax's `updates, st = optimizer.update(...)`) is
            # the pure-functional tell.
            base_d = dotted(node.func.value)
            if (
                node.func.attr in _MUTATORS
                and any(arg_taints)
                and node is self._expr_value
            ):
                if base_d is not None and base_d in self.env:
                    self.env[base_d] = True
                elif self.traced:
                    self._add(
                        node, "JG103",
                        f"'{base_d or '?'}.{node.func.attr}(...)' stores a "
                        "traced value into state that outlives the traced "
                        "call (tracer leak)",
                    )
            if base_taint:
                return True  # x.astype / x.reshape / x.argmax … stay device
        return False

    def prog_resolve(self, d: str):
        if not d:
            return None
        return self.an.prog.resolve_call(self.mod, self.fn.cls, d)

    def _call_offset(self, callee: FunctionInfo, d: str) -> int:
        return 1 if (
            callee.cls is not None
            and callee.params[:1] in (("self",), ("cls",))
            and "." in d
        ) else 0

    def _expanded_call(self, node: ast.Call) -> tuple:
        """(positional exprs, (name, expr) keyword pairs) with staged
        ``*fargs`` / ``**fkw`` spliced back in from their local
        bindings."""
        args: list = []
        for a in node.args:
            if isinstance(a, ast.Starred) and isinstance(a.value, ast.Name):
                staged = self.tuple_stages.get(a.value.id)
                if staged is not None:
                    args.extend(staged)
                    continue
            args.append(a)
        kws: list = []
        for k in node.keywords:
            if k.arg is None and isinstance(k.value, ast.Name):
                staged_kw = self.dict_stages.get(k.value.id)
                if staged_kw is not None:
                    kws.extend(staged_kw.items())
                    continue
            if k.arg is not None:
                kws.append((k.arg, k.value))
        return args, kws

    def _record_param_taints(self, node, callee, arg_taints, kw_taints):
        if callee.jit is not None:
            return
        off = self._call_offset(callee, dotted(node.func) or "")
        for i, t in enumerate(arg_taints):
            if t and i + off < len(callee.params):
                self.param_taints.add(
                    (callee.qualname, callee.params[i + off])
                )
        for name, t in kw_taints.items():
            if t and name in callee.params:
                self.param_taints.add((callee.qualname, name))

    def _check_donation(self, node, callee, d):
        if self._pure or callee.jit is None or not callee.jit.donates:
            return
        off = self._call_offset(callee, d)
        donated = set(callee.donated_positions())
        names = set(callee.jit.donate_argnames)
        args, kws = self._expanded_call(node)
        exprs = []
        for i, arg in enumerate(args):
            if i + off in donated:
                exprs.append(arg)
        for kname, kval in kws:
            if kname in names or (
                kname in callee.params
                and callee.params.index(kname) in donated
            ):
                exprs.append(kval)
        for expr in exprs:
            name = dotted(expr)
            if name is not None:
                self.watches[name] = (node.lineno, callee.name)

    def _check_statics(self, node, callee):
        if self._pure or callee.jit is None:
            return
        statics = callee.static_param_names()
        if not statics:
            return
        off = self._call_offset(callee, dotted(node.func) or "")
        args, kws = self._expanded_call(node)
        pairs = []
        for i, arg in enumerate(args):
            if i + off < len(callee.params) and (
                callee.params[i + off] in statics
            ):
                pairs.append((callee.params[i + off], arg))
        for kname, kval in kws:
            if kname in statics:
                pairs.append((kname, kval))
        for pname, arg in pairs:
            if isinstance(arg, _UNHASHABLE):
                self._add(
                    arg, "JG104",
                    f"unhashable {type(arg).__name__} passed as static arg "
                    f"'{pname}' of jitted '{callee.name}' — jit statics "
                    "must be hashable (use a tuple)",
                )
                continue
            var = self._in_loop_vars(arg)
            if var is not None:
                self._add(
                    arg, "JG104",
                    f"static arg '{pname}' of jitted '{callee.name}' varies "
                    f"with loop variable '{var}' — one fresh executable "
                    "compiles per iteration",
                )

    # ----- statements -------------------------------------------------------

    def walk(self) -> None:
        self._stmts(self.fn.node.body)

    def _stmts(self, body) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, node) -> None:
        kind = type(node).__name__
        m = getattr(self, f"_s_{kind}", None)
        if m is not None:
            m(node)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # indexed and checked as their own functions
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.taint(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    def _s_Expr(self, node) -> None:
        self._expr_value = node.value
        self.taint(node.value)
        self._expr_value = None

    def _assign_target(self, target, t, value_node) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = t
            self._store(target.id)
            if self.traced and _any(t) and target.id in self.globals_decl:
                self._add(
                    target, "JG103",
                    f"traced value stored to global '{target.id}' — it "
                    "outlives the traced call (tracer leak)",
                )
        elif isinstance(target, ast.Attribute):
            d = dotted(target)
            if d is not None:
                self._store(d)
            if self.traced and _any(t):
                self._add(
                    target, "JG103",
                    f"traced value stored to '{d or '?'}' — attribute "
                    "state outlives the traced call (tracer leak)",
                )
            elif (
                _any(t)
                and d is not None
                and d.startswith("self.")
                and d.count(".") == 1
                and self.fn.cls is not None
            ):
                self.attr_taints.add(
                    ((self.fn.modname, self.fn.cls), target.attr)
                )
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            parts = (
                t if isinstance(t, tuple) and len(t) == len(elts)
                else [_any(t)] * len(elts)
            )
            for tgt, part in zip(elts, parts):
                if isinstance(tgt, ast.Starred):
                    tgt = tgt.value
                self._assign_target(tgt, part, None)
        elif isinstance(target, ast.Subscript):
            # Writing INTO a watched (donated) buffer is a read of it.
            self.taint(target.value)
            self.taint(target.slice)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, t, None)

    def _s_Assign(self, node) -> None:
        t = self.taint(node.value)
        for target in node.targets:
            self._assign_target(target, t, node.value)
            if isinstance(target, ast.Name):
                self._record_staging(target.id, node.value)

    def _record_staging(self, name: str, value: ast.AST) -> None:
        self.tuple_stages.pop(name, None)
        self.dict_stages.pop(name, None)
        if isinstance(value, ast.Tuple):
            self.tuple_stages[name] = list(value.elts)
        elif isinstance(value, ast.Call) and dotted(
            value.func
        ) == "dict" and not value.args:
            self.dict_stages[name] = {
                k.arg: k.value for k in value.keywords if k.arg is not None
            }
        elif isinstance(value, ast.Dict):
            self.dict_stages[name] = {
                k.value: v for k, v in zip(value.keys, value.values)
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }

    def _s_AnnAssign(self, node) -> None:
        if node.value is not None:
            self._assign_target(node.target, self.taint(node.value), node.value)

    def _s_AugAssign(self, node) -> None:
        prior = _any(self.taint(node.target))  # load side (watch check incl.)
        t = _any(self.taint(node.value)) or prior
        self._assign_target(node.target, t, None)

    def _s_Return(self, node) -> None:
        if node.value is None:
            return
        t = self.taint(node.value)
        if isinstance(t, tuple):
            t = tuple(bool(x) for x in t)
        self.returns_struct = _merge_taint(self.returns_struct, t)

    def _branch_test(self, test, kind: str) -> None:
        if _any(self.taint(test)):
            self._sync(test, f"branching on a device value ({kind})")
        if self.traced:
            self._shape_branch(test, kind)

    def _shape_branch(self, test, kind: str) -> None:
        for sub in ast.walk(test):
            hit = None
            if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim"
            ):
                hit = sub.value
            elif isinstance(sub, ast.Call) and dotted(sub.func) == "len" and (
                sub.args
            ):
                hit = sub.args[0]
            if hit is None:
                continue
            self._pure += 1
            tainted = _any(self.taint(hit))
            self._pure -= 1
            if tainted:
                self._add(
                    sub, "JG104",
                    f"shape-dependent Python {kind} inside a jitted body — "
                    "one executable compiles per distinct shape (bucket "
                    "inputs, or annotate '# jaxguard: allow(JG104) <why>')",
                )

    def _s_If(self, node) -> None:
        self._branch_test(node.test, "if")
        self._stmts(node.body)
        self._stmts(node.orelse)

    def _s_While(self, node) -> None:
        self._branch_test(node.test, "while")
        self._stmts(node.body)
        self._stmts(node.body)  # loop-carried taint/donations
        self._stmts(node.orelse)

    def _s_For(self, node) -> None:
        it = _any(self.taint(node.iter))
        self._assign_target(node.target, it, None)
        scope = {
            n.id for n in ast.walk(node.target) if isinstance(n, ast.Name)
        }
        self.loop_vars.append(scope)
        self._stmts(node.body)
        self._stmts(node.body)  # loop-carried taint/donations
        self.loop_vars.pop()
        self._stmts(node.orelse)

    _s_AsyncFor = _s_For

    def _s_With(self, node) -> None:
        for item in node.items:
            t = self.taint(item.context_expr)
            if item.optional_vars is not None:
                self._assign_target(item.optional_vars, t, None)
        self._stmts(node.body)

    _s_AsyncWith = _s_With

    def _s_Try(self, node) -> None:
        self._stmts(node.body)
        for handler in node.handlers:
            if handler.name:
                self.env[handler.name] = False
            self._stmts(handler.body)
        self._stmts(node.orelse)
        self._stmts(node.finalbody)

    def _s_Assert(self, node) -> None:
        self._branch_test(node.test, "assert")
        if node.msg is not None:
            self.taint(node.msg)

    def _s_Global(self, node) -> None:
        self.globals_decl.update(node.names)

    _s_Nonlocal = _s_Global

    def _s_Delete(self, node) -> None:
        for tgt in node.targets:
            name = dotted(tgt)
            if name is not None:
                self._store(name)
                self.env.pop(name, None)


def analyze_program(program: Program) -> list[Finding]:
    return Analyzer(program).run()


__all__ = ["Analyzer", "analyze_program", "ALL_RULES", "Finding"]
