"""jaxguard pass: lock discipline for the daemon's thread surface (JG2xx).

The daemon half of this repo is concurrent by construction — gRPC
Allocate handlers share the :class:`AllocationJournal`, the health
poller flips device state under ``ListAndWatch`` streams, the
heartbeat aggregator tails guest event files on its own thread, and the
flight ring inside ``obs.events.emit`` runs on EVERY emitting thread.
This pass checks the lock discipline those components rely on:

JG201 — a lock-guarded instance attribute is read or written without
    the lock on a path reachable from a thread entry point. Two
    triggers: (i) the attribute is written under ``with self._lock:``
    somewhere (so the lock IS its guard) but accessed bare elsewhere;
    (ii) the attribute is written bare in thread-reachable code of a
    class that owns a lock at all — state of a lock-owning class is
    either guarded or explicitly ``# jaxguard: allow(JG201)``-sanctioned
    as thread-confined.
JG202 — a lock is acquired while another lock is already held, in an
    order that is inverted elsewhere in the program (classic AB/BA
    deadlock), or re-acquired while already held (self-deadlock for a
    non-reentrant ``threading.Lock``).
JG203 — a blocking call (``time.sleep``, file IO, gRPC) happens while a
    lock is held on a thread-reachable path: every other thread that
    touches that lock stalls behind the IO. Sanctioned cases (the
    journal's crash-consistent tmp+rename, the flight ring's postmortem
    snapshot) carry reason pragmas.

Thread entry points (the model is documented in docs/compat_and_lint.md):

- any function passed as ``target=`` to ``threading.Thread(...)``;
- ``run`` of a ``threading.Thread`` subclass;
- the kubelet device-plugin gRPC methods on a ``*Servicer`` subclass
  (:data:`model.GRPC_ENTRY_METHODS`);
- the curated :data:`model.THREAD_ENTRY_REGISTRY` — hooks invoked on
  other components' threads that no AST spelling reveals.

Reachability follows the same name-based call resolution as the JG1xx
dataflow pass, extended with the attribute-type map ``graph.py`` builds
from ``self.x = Ctor(...)`` assignments (so ``self._aggregator
.poll_once()`` resolves). Lock context is lexical (``with self._lock:``
regions) plus one interprocedural refinement: a private method whose
every call site holds a lock analyzes as lock-held (the
``_save_locked`` convention).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from .graph import (
    FunctionInfo,
    Module,
    Program,
    dotted,
    held_lock_map,
    self_attr,
)
from .model import (
    BLOCKING_CALLS,
    BLOCKING_PREFIXES,
    Finding,
    GRPC_ENTRY_METHODS,
    THREAD_ENTRY_REGISTRY,
)

# Method names that mutate their receiver in place: a bare
# ``self.attr.append(x)`` is a WRITE of ``self.attr`` for guard purposes.
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort",
})

_THREAD_CTORS = frozenset({"threading.Thread", "Thread"})
_INIT_METHODS = frozenset({"__init__", "__new__", "__post_init__"})


def _lock_id(modname: str, cls: str, attr: str) -> str:
    return f"{modname}:{cls}.{attr}"


@dataclass
class _Access:
    fn: FunctionInfo
    node: ast.AST
    attr: str
    write: bool
    held: frozenset


@dataclass
class _FnFacts:
    fn: FunctionInfo
    held: dict                      # id(node) → tuple of candidate lock attrs
    calls: list = field(default_factory=list)   # (node, dotted callee)
    inherited: frozenset = frozenset()          # locks held at every call site


class _Pass:
    def __init__(self, program: Program) -> None:
        self.program = program
        self.facts: dict[str, _FnFacts] = {}
        self.entry: set[str] = set()
        self.reachable: set[str] = set()
        self._seen: set[tuple] = set()
        self.findings: list[Finding] = []

    # ----- fact collection --------------------------------------------------

    def build(self) -> None:
        for fn in self.program.functions.values():
            held = held_lock_map(fn.node)
            facts = _FnFacts(fn, held)
            for node in ast.walk(fn.node):
                if id(node) not in held:
                    continue  # body of a nested def — its own FunctionInfo
                if isinstance(node, ast.Call):
                    name = dotted(node.func)
                    if name:
                        facts.calls.append((node, name))
            self.facts[fn.qualname] = facts
        self._find_entries()
        self._propagate_inherited()
        self._compute_reachable()

    def _resolve(self, fn: FunctionInfo, callee: str) -> Optional[FunctionInfo]:
        mod = self.program.modules[fn.modname]
        info = self.program.resolve_call(mod, fn.cls, callee)
        if info is not None:
            return info
        if callee.startswith("self.") and fn.cls is not None:
            parts = callee[len("self."):].split(".")
            if len(parts) == 2:
                owner = self.program.attr_class(mod, fn.cls, parts[0])
                if owner is not None:
                    owner_mod = self.program.modules.get(owner.modname)
                    if owner_mod is not None:
                        return owner_mod.functions.get(
                            f"{owner.name}.{parts[1]}"
                        )
        return None

    def _find_entries(self) -> None:
        for qual, facts in self.facts.items():
            fn = facts.fn
            mod = self.program.modules[fn.modname]
            # (a) threading.Thread(target=...) spellings anywhere.
            for node, name in facts.calls:
                if name not in _THREAD_CTORS:
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    ref = dotted(kw.value)
                    if ref is None:
                        continue
                    target = self._resolve(fn, ref)
                    if target is not None:
                        self.entry.add(target.qualname)
            if fn.cls is None:
                continue
            cls_info = mod.classes.get(fn.cls)
            bases = cls_info.bases if cls_info else ()
            # (b) run() of a Thread subclass.
            if fn.name == "run" and any(
                b in _THREAD_CTORS for b in bases
            ):
                self.entry.add(qual)
            # (c) gRPC servicer methods.
            if fn.name in GRPC_ENTRY_METHODS and any(
                b.split(".")[-1].endswith("Servicer") for b in bases
            ):
                self.entry.add(qual)
            # (d) the curated registry.
            if f"{fn.cls}.{fn.name}" in THREAD_ENTRY_REGISTRY:
                self.entry.add(qual)

    def _propagate_inherited(self) -> None:
        """Private methods whose EVERY resolved intra-class call site
        holds a lock analyze with that lock held (``_save_locked``); a
        fixpoint so locked wrappers chain. Public methods never inherit
        — anyone may call them bare."""
        # callers[callee] = list of (caller facts, locks at call node)
        callers: dict[str, list] = {}
        for facts in self.facts.values():
            fn = facts.fn
            if fn.cls is None:
                continue
            cls_locks = self._class_locks(fn)
            for node, name in facts.calls:
                if not name.startswith("self."):
                    continue
                target = self._resolve(fn, name)
                if target is None or target.cls != fn.cls:
                    continue
                site = frozenset(
                    a for a in facts.held.get(id(node), ())
                    if a in cls_locks
                )
                callers.setdefault(target.qualname, []).append((facts, site))
        for _ in range(4):  # fixpoint: intersections only shrink
            changed = False
            for qual, sites in callers.items():
                facts = self.facts.get(qual)
                if facts is None or not facts.fn.name.startswith("_"):
                    continue
                if facts.fn.qualname in self.entry:
                    continue  # entered bare by another thread
                inherited = None
                for caller, site in sites:
                    eff = site | caller.inherited
                    inherited = eff if inherited is None else (
                        inherited & eff
                    )
                inherited = frozenset(inherited or ())
                if inherited != facts.inherited:
                    facts.inherited = inherited
                    changed = True
            if not changed:
                break

    def _compute_reachable(self) -> None:
        todo = list(self.entry)
        self.reachable = set(todo)
        while todo:
            qual = todo.pop()
            facts = self.facts.get(qual)
            if facts is None:
                continue
            for _node, name in facts.calls:
                target = self._resolve(facts.fn, name)
                if target is not None and target.qualname not in self.reachable:
                    self.reachable.add(target.qualname)
                    todo.append(target.qualname)

    # ----- shared helpers ---------------------------------------------------

    def _class_locks(self, fn: FunctionInfo) -> frozenset:
        if fn.cls is None:
            return frozenset()
        cls = self.program.modules[fn.modname].classes.get(fn.cls)
        return cls.lock_attrs if cls else frozenset()

    def _held_at(self, facts: _FnFacts, node: ast.AST) -> frozenset:
        cls_locks = self._class_locks(facts.fn)
        local = frozenset(
            a for a in facts.held.get(id(node), ()) if a in cls_locks
        )
        return local | facts.inherited

    def _emit(self, fn: FunctionInfo, node: ast.AST, rule: str,
              message: str, key: tuple = ()) -> None:
        dedupe = (fn.path, getattr(node, "lineno", 0), rule) + key
        if dedupe in self._seen:
            return
        self._seen.add(dedupe)
        self.findings.append(Finding(
            path=fn.path,
            line=getattr(node, "lineno", 0),
            rule=rule,
            message=message,
            function=fn.qualname,
        ))

    # ----- JG201 ------------------------------------------------------------

    def _accesses(self, facts: _FnFacts) -> list:
        """Every ``self.X`` load/store in the function's own body, with
        the effective lock set. Stores cover plain/aug/ann assignment,
        subscript stores (``self.x[k] = v``), ``del self.x[k]``, and
        in-place mutator calls (``self.x.append(v)``)."""
        out: list[_Access] = []
        fn = facts.fn

        def add(node: ast.AST, attr: str, write: bool) -> None:
            out.append(_Access(
                fn=fn, node=node, attr=attr, write=write,
                held=self._held_at(facts, node),
            ))

        for node in ast.walk(fn.node):
            if id(node) not in facts.held:
                continue
            if isinstance(node, ast.Attribute):
                attr = self_attr(node)
                if attr is None:
                    continue
                add(node, attr, isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ))
            elif isinstance(node, ast.Subscript):
                attr = self_attr(node.value)
                if attr is not None and isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ):
                    add(node, attr, True)
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS
                ):
                    attr = self_attr(node.func.value)
                    if attr is not None:
                        add(node, attr, True)
        return out

    def jg201(self) -> None:
        # First pass: learn each class's guarded attributes — attr →
        # lock(s) it was ever written under, outside construction.
        guards: dict[tuple, dict] = {}   # (modname, cls) → {attr: set(locks)}
        per_fn: dict[str, list] = {}
        for qual, facts in self.facts.items():
            fn = facts.fn
            if fn.cls is None or not self._class_locks(fn):
                continue
            accesses = self._accesses(facts)
            per_fn[qual] = accesses
            if fn.name in _INIT_METHODS:
                continue
            cls_guards = guards.setdefault((fn.modname, fn.cls), {})
            for acc in accesses:
                if acc.write and acc.held:
                    cls_guards.setdefault(acc.attr, set()).update(acc.held)
        # Second pass: flag bare accesses on thread-reachable paths.
        for qual, accesses in per_fn.items():
            fn = self.facts[qual].fn
            if qual not in self.reachable or fn.name in _INIT_METHODS:
                continue
            cls_locks = self._class_locks(fn)
            cls_guards = guards.get((fn.modname, fn.cls), {})
            for acc in accesses:
                if acc.held or acc.attr in cls_locks:
                    continue
                guard = cls_guards.get(acc.attr)
                if guard:
                    verb = "written" if acc.write else "read"
                    lock = "/".join(sorted(guard))
                    self._emit(
                        fn, acc.node, "JG201",
                        f"self.{acc.attr} {verb} without self.{lock} "
                        f"(its guard elsewhere) on a thread-reachable "
                        f"path",
                        key=(acc.attr,),
                    )
                elif acc.write:
                    lock = "/".join(sorted(cls_locks))
                    self._emit(
                        fn, acc.node, "JG201",
                        f"self.{acc.attr} written without any lock on a "
                        f"thread-reachable path (class {fn.cls} guards "
                        f"its state with self.{lock})",
                        key=(acc.attr,),
                    )

    # ----- JG202 ------------------------------------------------------------

    def jg202(self) -> None:
        edges: dict[tuple, list] = {}   # (outer id, inner id) → sites
        for facts in self.facts.values():
            fn = facts.fn
            cls_locks = self._class_locks(fn)
            if not cls_locks:
                continue
            for node in ast.walk(fn.node):
                if id(node) not in facts.held or not isinstance(
                    node, ast.With
                ):
                    continue
                stack = tuple(
                    a for a in facts.held[id(node)] if a in cls_locks
                ) + tuple(sorted(facts.inherited - set(
                    facts.held[id(node)]
                )))
                for item in node.items:
                    attr = self_attr(item.context_expr)
                    if attr is None or attr not in cls_locks:
                        continue
                    if attr in stack:
                        self._emit(
                            fn, node, "JG202",
                            f"self.{attr} re-acquired while already "
                            f"held — deadlock for a non-reentrant "
                            f"threading.Lock",
                            key=(attr,),
                        )
                        continue
                    inner = _lock_id(fn.modname, fn.cls, attr)
                    for outer_attr in stack:
                        outer = _lock_id(fn.modname, fn.cls, outer_attr)
                        edges.setdefault((outer, inner), []).append(
                            (fn, node, outer_attr, attr)
                        )
        adj: dict[str, set] = {}
        for (outer, inner) in edges:
            adj.setdefault(outer, set()).add(inner)

        def reaches(src: str, dst: str) -> bool:
            todo, seen = [src], set()
            while todo:
                cur = todo.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                todo.extend(adj.get(cur, ()))
            return False

        for (outer, inner), sites in edges.items():
            if not reaches(inner, outer):
                continue
            for fn, node, outer_attr, attr in sites:
                self._emit(
                    fn, node, "JG202",
                    f"self.{attr} acquired while holding "
                    f"self.{outer_attr}, but the opposite order exists "
                    f"elsewhere — inconsistent global lock order",
                    key=(outer_attr, attr),
                )

    # ----- JG203 ------------------------------------------------------------

    def jg203(self) -> None:
        for qual, facts in self.facts.items():
            if qual not in self.reachable:
                continue
            fn = facts.fn
            if fn.name in _INIT_METHODS:
                continue
            for node, name in facts.calls:
                if not (name in BLOCKING_CALLS or name.startswith(
                    BLOCKING_PREFIXES
                )):
                    continue
                held = self._held_at(facts, node)
                if not held:
                    continue
                lock = "/".join(sorted(held))
                self._emit(
                    fn, node, "JG203",
                    f"blocking call {name}() while holding self.{lock} "
                    f"on a thread-reachable path",
                    key=(name,),
                )


def analyze_concurrency(program: Program) -> list:
    """Run the JG2xx lock-discipline pass over an analyzed Program."""
    p = _Pass(program)
    p.build()
    p.jg201()
    p.jg202()
    p.jg203()
    p.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return p.findings
