"""``python -m tools.analyze`` — run jaxguard over the repo surface.

Exit status mirrors ``tools.lint``: 0 clean, 1 findings, 2 usage error.
Findings print as ``path:line: RULE message``; ``--json FILE`` writes the
machine-readable report (always, clean or not — CI uploads it as the
per-PR artifact).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterable, Optional

import re

from ..pragmas import allowed_lines, suppress
from .concurrency import analyze_concurrency
from .contracts import analyze_contracts
from .dataflow import Analyzer
from .dispatch import analyze_dispatch, stale_pragmas
from .graph import load_program
from .model import ALL_RULES, KNOB_DOC_PATH, Finding

# `--rule JG1xx` selects a whole pass family (every catalogue id sharing
# the JG<digit> prefix) — the spelling the docs use for the families.
_FAMILY_RE = re.compile(r"^JG(\d)[xX]{2}$")

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}
_SKIP_SUFFIXES = ("_pb2.py", "_pb2_grpc.py")

# Default analysis surface: the package plus the bench/experiment scripts
# whose timed windows carry `# jaxguard: hot` marks. Tests and tools are
# out of scope — they neither serve traffic nor donate buffers in loops,
# and fixture code intentionally writes rule-triggering patterns.
DEFAULT_TARGETS = (
    "kata_xpu_device_plugin_tpu",
    "bench.py",
    "scripts",
)


def _iter_py_files(target: str) -> Iterable[str]:
    if os.path.isfile(target):
        if target.endswith(".py"):
            yield target
        return
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for name in sorted(filenames):
            if name.endswith(".py") and not name.endswith(_SKIP_SUFFIXES):
                yield os.path.join(dirpath, name)


def run(
    targets: Optional[Iterable[str]] = None,
    root: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
    sources: Optional[dict] = None,
) -> list[Finding]:
    """Analyze ``targets`` (or an in-memory ``{rel_path: src}`` map).

    Interprocedural: the WHOLE selected file set is loaded into one
    program before any rule runs — narrowing targets narrows what the
    call graph can see, so CI runs the default surface.
    """
    root = root or os.getcwd()
    doc_text: Optional[str] = None
    if sources is None:
        doc_path = os.path.join(root, KNOB_DOC_PATH)
        if os.path.exists(doc_path):
            with open(doc_path, encoding="utf-8") as fh:
                doc_text = fh.read()
    else:
        # Tests deliver the doc leg through a pseudo-path in the sources
        # mapping; it is text, not Python — pop it before the program load.
        sources = dict(sources)
        doc_text = sources.pop(KNOB_DOC_PATH, None)
    if sources is None:
        chosen = list(targets) if targets else [
            t for t in DEFAULT_TARGETS
            if os.path.exists(os.path.join(root, t))
        ]
        paths: list[str] = []
        for target in chosen:
            abs_target = (
                target if os.path.isabs(target)
                else os.path.join(root, target)
            )
            if not os.path.exists(abs_target):
                raise FileNotFoundError(
                    f"analyze target {target!r} does not exist"
                )
            paths.extend(_iter_py_files(abs_target))
        if not paths:
            # A gate that analyzed nothing must not report clean: an empty
            # default surface means the cwd/root is wrong, not that the
            # code is hazard-free.
            raise FileNotFoundError(
                f"no analyzable files under {root!r} — none of "
                f"{', '.join(DEFAULT_TARGETS)} exist (wrong --root/cwd?)"
            )
        program, errors = load_program(paths, root)
    else:
        program, errors = load_program([], root, sources=sources)
    findings = [
        Finding(msg.split(":", 1)[0], int(msg.split(":", 2)[1]), "E999",
                msg.split(":", 2)[2].strip())
        for msg in errors
    ]
    # ONE engine for every pass family: the dataflow fixpoint builds the
    # interprocedural call graph, and the dispatch pass reuses it (the
    # FIXPOINT_RUNS perf pin in tests/test_jaxguard.py).
    engine = Analyzer(program)
    findings.extend(engine.run())
    findings.extend(analyze_concurrency(program))
    findings.extend(analyze_contracts(program, doc_text))
    findings.extend(analyze_dispatch(program, engine))
    # JG404 adjudicates pragmas against the RAW (pre-suppression) finding
    # set of every pass above — then rides through suppression like any
    # other rule (allow(JG404) is the keep-this-pragma escape hatch).
    findings.extend(stale_pragmas(
        program, [f for f in findings if f.rule != "E999"]
    ))
    out: list[Finding] = []
    by_path: dict[str, list] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    for path, fs in by_path.items():
        mod = next(
            (m for m in program.modules.values() if m.path == path), None
        )
        allowed = allowed_lines(mod.src) if mod is not None else {}
        out.extend(suppress(fs, allowed, rules))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def analyze_sources(
    sources: dict, rules: Optional[Iterable[str]] = None
) -> list[Finding]:
    """Test-facing API: analyze an in-memory ``{rel_path: src}`` file set
    as one program (interprocedural across the mapping)."""
    return run(rules=rules, sources=sources)


def analyze_source(
    src: str, path: str = "mod_under_test.py",
    rules: Optional[Iterable[str]] = None,
) -> list[Finding]:
    """Single-file convenience for fixture tests."""
    return analyze_sources({path: src}, rules=rules)


def write_report(findings: list, path: str, root: str) -> None:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    report = {
        "tool": "jaxguard",
        "root": os.path.abspath(root),
        "rules": ALL_RULES,
        "summary": {"total": len(findings), "by_rule": counts},
        "findings": [f.to_dict() for f in findings],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=False)
        fh.write("\n")


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description=(
            "jaxguard: interprocedural dataflow analysis for JAX "
            "tracer/transfer/donation hazards (JG101-JG104), daemon "
            "lock discipline (JG201-JG203), the ENV_* knob contract "
            "(JG301-JG304), and the dispatch-surface contract — "
            "executable census, donation completeness, sharding-spec "
            "coverage, stale pragmas (JG401-JG404)."
        ),
    )
    parser.add_argument(
        "targets", nargs="*",
        help="files/directories to analyze (default: package + bench + scripts)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="ID",
        help="restrict to one or more rule ids (repeatable); a family "
             "spelling like JG4xx selects every rule in that family",
    )
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="diff mode: fail only on findings NEW versus this committed "
             "jaxguard report (by path+rule+function occurrence count)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    parser.add_argument(
        "--json", default=None, metavar="FILE",
        help="write the machine-readable report here (CI artifact)",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root paths are reported relative to (default: cwd)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, summary in sorted(ALL_RULES.items()):
            print(f"{rule}  {summary}")
        return 0

    if args.rules:
        expanded: list = []
        for rule in args.rules:
            m = _FAMILY_RE.match(rule)
            if m:
                family = [
                    r for r in sorted(ALL_RULES)
                    if r.startswith(f"JG{m.group(1)}")
                ]
                if family:
                    expanded.extend(family)
                    continue
            expanded.append(rule)
        args.rules = expanded
        unknown = set(args.rules) - set(ALL_RULES)
        if unknown:
            print(f"unknown rule ids: {sorted(unknown)}", file=sys.stderr)
            return 2

    baseline_counts: Optional[dict] = None
    if args.baseline:
        try:
            with open(args.baseline, encoding="utf-8") as fh:
                report = json.load(fh)
            baseline_counts = {}
            for f in report["findings"]:
                key = (f["path"], f["rule"], f.get("function", ""))
                baseline_counts[key] = baseline_counts.get(key, 0) + 1
        except (OSError, ValueError, KeyError, TypeError) as err:
            print(
                f"unreadable baseline {args.baseline!r}: {err}",
                file=sys.stderr,
            )
            return 2

    try:
        findings = run(args.targets or None, args.root, args.rules)
    except FileNotFoundError as err:
        print(str(err), file=sys.stderr)
        return 2

    if args.json:
        write_report(findings, args.json, args.root or os.getcwd())

    if baseline_counts is not None:
        # Diff mode: a finding is NEW when its occurrence index within
        # its (path, rule, function) key exceeds the baseline's count —
        # line numbers shift on every edit, so they don't key.
        seen: dict = {}
        new: list = []
        for finding in findings:
            key = (finding.path, finding.rule, finding.function)
            seen[key] = seen.get(key, 0) + 1
            if seen[key] > baseline_counts.get(key, 0):
                new.append(finding)
        for finding in new:
            print(f"{finding}  [new vs baseline]")
        print(
            f"\n{len(findings)} finding(s), {len(new)} new vs baseline "
            f"{os.path.basename(args.baseline)}.",
            file=sys.stderr,
        )
        return 1 if new else 0

    for finding in findings:
        print(finding)
    if findings:
        print(
            f"\n{len(findings)} finding(s). Rule docs: "
            "docs/compat_and_lint.md#jaxguard",
            file=sys.stderr,
        )
        return 1
    return 0
