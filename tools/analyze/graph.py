"""jaxguard pass 1: per-module symbol tables and the callable index.

Turns a set of Python sources into a :class:`Program`:

- every module gets an import map (local alias → fully-dotted target,
  relative imports resolved against the module's package), so a call
  spelled ``prefill(...)`` in ``guest/serving.py`` resolves to the
  function OBJECT defined in ``models/transformer.py``;
- every function/method — including nested defs, which is where this
  repo jits its train steps — is indexed with its jit wrapping parsed
  off the decorators (``@jax.jit``, ``@partial(jax.jit, static_argnames=…,
  donate_argnums=…)``) or off a module-level ``name = jax.jit(fn, …)``
  assignment;
- ``# jaxguard: hot`` markers on (or directly above) a ``def`` line are
  recorded, so bench/script timing windows can opt into the hot-path
  rules without being reachable from the serving/trainer roots.

Resolution is name-based and best-effort by design: an unresolved call
contributes no taint and no reachability — the analyzer errs quiet, and
the runtime strict mode (``compat.jaxapi.strict_mode``) is the backstop
for what static analysis cannot see.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional

from .model import HOT_MARK

_JIT_NAMES = frozenset({"jit", "jax.jit"})
_PARTIAL_NAMES = frozenset({"partial", "functools.partial"})
_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "Lock", "RLock",
})


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute/name chain → ``"a.b.c"`` (None otherwise)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass(frozen=True)
class JitInfo:
    static_argnums: tuple = ()
    static_argnames: tuple = ()
    donate_argnums: tuple = ()
    donate_argnames: tuple = ()

    @property
    def donates(self) -> bool:
        return bool(self.donate_argnums or self.donate_argnames)


@dataclass
class FunctionInfo:
    qualname: str          # "pkg.mod:Class.meth" / "pkg.mod:fn" / "pkg.mod:outer.inner"
    modname: str
    path: str
    name: str              # leaf name
    cls: Optional[str]
    node: ast.AST          # FunctionDef / AsyncFunctionDef
    params: tuple          # positional+kwonly parameter names, in order
    jit: Optional[JitInfo]
    hot_marked: bool
    nested: bool = False   # defined inside another function

    def static_param_names(self) -> frozenset:
        if self.jit is None:
            return frozenset()
        names = set(self.jit.static_argnames)
        for i in self.jit.static_argnums:
            if isinstance(i, int) and 0 <= i < len(self.params):
                names.add(self.params[i])
        return frozenset(names)

    def donated_positions(self) -> tuple:
        """Donated parameter indices (argnames mapped through the
        signature), for matching positional args at call sites."""
        if self.jit is None:
            return ()
        idx = set(
            i for i in self.jit.donate_argnums if isinstance(i, int)
        )
        for name in self.jit.donate_argnames:
            if name in self.params:
                idx.add(self.params.index(name))
        return tuple(sorted(idx))


@dataclass
class ClassInfo:
    """One class definition, with the concurrency-relevant facts the
    JG2xx pass needs: which attributes are ``threading.Lock``/``RLock``
    instances, and which attributes are constructed from classes the
    analyzer can see (``self._aggregator = HeartbeatAggregator(...)`` —
    the attr-type map that lets ``self._aggregator.poll_once()``
    resolve)."""

    name: str
    modname: str
    node: ast.AST
    bases: tuple = ()          # dotted base-class spellings
    lock_attrs: frozenset = frozenset()
    attr_ctors: dict = field(default_factory=dict)  # attr → dotted ctor name


@dataclass
class Module:
    modname: str
    path: str
    src: str
    tree: ast.AST
    imports: dict = field(default_factory=dict)   # alias → dotted target
    functions: dict = field(default_factory=dict)  # local name → FunctionInfo
    classes: dict = field(default_factory=dict)    # class name → ClassInfo


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` → ``"X"`` (None for anything else, including deeper
    chains — ``self.a.b`` is not a direct attribute of the instance)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _ctor_calls(value: ast.AST):
    """Calls on the right-hand side of an attribute assignment, unwrapped
    through conditional expressions (``X(...) if flag else None`` is how
    the manager builds its optional aggregator/journal)."""
    if isinstance(value, ast.Call):
        yield value
    elif isinstance(value, ast.IfExp):
        yield from _ctor_calls(value.body)
        yield from _ctor_calls(value.orelse)


def held_lock_map(fn_node: ast.AST) -> dict:
    """Map ``id(ast node)`` → tuple of ``self.<lock>`` attr names held at
    that node, from lexical ``with self._lock:`` regions. The map records
    every candidate ``with self.X:`` acquisition; the concurrency pass
    intersects against the class's known lock attributes. Acquisition
    order is preserved (JG202 needs the nesting order). Nested function
    bodies are excluded — they run when called, not where defined."""
    held: dict = {}

    def visit(node: ast.AST, stack: tuple) -> None:
        held[id(node)] = stack
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                attr = self_attr(item.context_expr)
                if attr is not None:
                    acquired.append(attr)
                visit(item, stack)  # the acquisition expr runs unheld
            inner = stack + tuple(a for a in acquired if a not in stack)
            for child in node.body:
                visit(child, inner)
            return
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda))
            and node is not fn_node
        ):
            return
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    visit(fn_node, ())
    return held


def _const_tuple(node: ast.AST) -> tuple:
    """Literal int/str (or tuple/list of them) → python tuple; anything
    dynamic → empty (the analyzer only trusts what it can read)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, str)):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, (int, str)
            ):
                out.append(elt.value)
        return tuple(out)
    return ()


def _jit_kwargs(keywords) -> JitInfo:
    kw = {}
    for k in keywords:
        if k.arg in (
            "static_argnums", "static_argnames",
            "donate_argnums", "donate_argnames",
        ):
            vals = _const_tuple(k.value)
            kw[k.arg] = tuple(v for v in vals if isinstance(v, str)) if (
                k.arg.endswith("argnames")
            ) else tuple(v for v in vals if isinstance(v, int))
    return JitInfo(**kw)


def parse_jit_decorator(dec: ast.AST) -> Optional[JitInfo]:
    """Recognize the jit spellings this repo uses: ``@jax.jit``/``@jit``
    and ``@partial(jax.jit, ...)`` (functools-qualified too)."""
    d = dotted(dec)
    if d in _JIT_NAMES:
        return JitInfo()
    if isinstance(dec, ast.Call):
        fn = dotted(dec.func)
        if fn in _JIT_NAMES:
            return _jit_kwargs(dec.keywords)
        if fn in _PARTIAL_NAMES and dec.args and dotted(
            dec.args[0]
        ) in _JIT_NAMES:
            return _jit_kwargs(dec.keywords)
    return None


def _param_names(node: ast.AST) -> tuple:
    a = node.args
    names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
    names += [p.arg for p in a.kwonlyargs]
    return tuple(names)


def _hot_marked(src_lines: list, node: ast.AST) -> bool:
    for lineno in (node.lineno, node.lineno - 1):
        if 1 <= lineno <= len(src_lines) and HOT_MARK in src_lines[lineno - 1]:
            return True
    return False


def path_to_modname(rel_path: str) -> str:
    p = rel_path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.strip("/").replace("/", ".")


class Program:
    """The whole analyzed source set: modules, the function index, and
    name resolution across them."""

    def __init__(self) -> None:
        self.modules: dict[str, Module] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self._by_dotted: dict[str, str] = {}  # dotted name → qualname
        self._classes_by_dotted: dict[str, ClassInfo] = {}

    # ----- construction -----------------------------------------------------

    def add_source(self, src: str, rel_path: str) -> Optional[str]:
        """Parse and index one module; returns a syntax-error message
        instead of raising (the CLI reports it as a finding)."""
        modname = path_to_modname(rel_path)
        try:
            tree = ast.parse(src, filename=rel_path)
        except SyntaxError as err:
            return f"{rel_path}:{err.lineno or 1}: syntax error: {err.msg}"
        mod = Module(modname, rel_path, src, tree)
        self.modules[modname] = mod
        self._index_imports(mod)
        self._index_functions(mod)
        self._index_classes(mod)
        return None

    def _index_classes(self, mod: Module) -> None:
        """Record every class with its bases, its ``threading.Lock``/
        ``RLock`` attributes, and its constructed-attribute types (any
        ``self.X = Ctor(...)`` in any method, conditional ctors
        unwrapped)."""
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            locks: set = set()
            ctors: dict = {}
            for sub in ast.walk(node):
                targets: list = []
                if isinstance(sub, ast.Assign):
                    targets, value = sub.targets, sub.value
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    targets, value = [sub.target], sub.value
                else:
                    continue
                for tgt in targets:
                    attr = self_attr(tgt)
                    if attr is None:
                        continue
                    for call in _ctor_calls(value):
                        ctor = dotted(call.func)
                        if ctor in _LOCK_CTORS:
                            locks.add(attr)
                        elif ctor is not None:
                            ctors.setdefault(attr, ctor)
            info = ClassInfo(
                name=node.name,
                modname=mod.modname,
                node=node,
                bases=tuple(
                    d for d in (dotted(b) for b in node.bases) if d
                ),
                lock_attrs=frozenset(locks),
                attr_ctors=ctors,
            )
            mod.classes[node.name] = info
            self._classes_by_dotted[f"{mod.modname}.{node.name}"] = info

    def _index_imports(self, mod: Module) -> None:
        is_pkg = mod.path.replace("\\", "/").endswith("__init__.py")
        parts = mod.modname.split(".") if mod.modname else []
        pkg_parts = parts if is_pkg else parts[:-1]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else alias.name.split(".")[0]
                    mod.imports[local] = target
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                else:
                    base = []
                src_mod = ".".join(
                    base + (node.module.split(".") if node.module else [])
                )
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    mod.imports[local] = (
                        f"{src_mod}.{alias.name}" if src_mod else alias.name
                    )

    def _index_functions(self, mod: Module) -> None:
        src_lines = mod.src.splitlines()

        def visit(node, cls: Optional[str], fn_path: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, fn_path)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    jit = None
                    for dec in child.decorator_list:
                        jit = parse_jit_decorator(dec) or jit
                    local = (
                        f"{fn_path}.{child.name}" if fn_path else (
                            f"{cls}.{child.name}" if cls else child.name
                        )
                    )
                    info = FunctionInfo(
                        qualname=f"{mod.modname}:{local}",
                        modname=mod.modname,
                        path=mod.path,
                        name=child.name,
                        cls=cls,
                        node=child,
                        params=_param_names(child),
                        jit=jit,
                        hot_marked=_hot_marked(src_lines, child),
                        nested=bool(fn_path),
                    )
                    self.functions[info.qualname] = info
                    mod.functions[local] = info
                    if not fn_path:
                        self._by_dotted[f"{mod.modname}.{local}"] = info.qualname
                    visit(child, None, local)
                else:
                    visit(child, cls, fn_path)

        visit(mod.tree, None, "")
        self._index_jit_assignments(mod)

    def _index_jit_assignments(self, mod: Module) -> None:
        """``decode_fast = jax.jit(decode_step, donate_argnums=(1,))`` at
        module level: the wrapped local function gets the JitInfo and the
        new name becomes an alias for it."""
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            )):
                continue
            if dotted(node.value.func) not in _JIT_NAMES:
                continue
            if not node.value.args:
                continue
            target_fn = dotted(node.value.args[0])
            info = mod.functions.get(target_fn or "")
            if info is None:
                continue
            info.jit = _jit_kwargs(node.value.keywords)
            for tgt in node.targets:
                name = dotted(tgt)
                if name and "." not in name:
                    mod.functions[name] = info
                    self._by_dotted[f"{mod.modname}.{name}"] = info.qualname

    # ----- resolution -------------------------------------------------------

    def chase(self, dotted_name: str, depth: int = 0) -> Optional[FunctionInfo]:
        """Fully-dotted name → FunctionInfo, following one re-export hop
        per level (``pkg.obs.emit`` → ``pkg.obs.events.emit``)."""
        if depth > 4:
            return None
        qual = self._by_dotted.get(dotted_name)
        if qual is not None:
            return self.functions[qual]
        parts = dotted_name.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.modules.get(".".join(parts[:i]))
            if mod is None:
                continue
            rest = parts[i:]
            target = mod.imports.get(rest[0])
            if target is None:
                return None
            return self.chase(".".join([target] + rest[1:]), depth + 1)
        return None

    def resolve_call(
        self, mod: Module, cls: Optional[str], callee: str
    ) -> Optional[FunctionInfo]:
        """Resolve a call's dotted spelling from inside ``mod`` (method
        context ``cls``). Returns None for anything dynamic."""
        if callee.startswith("self.") and cls is not None:
            rest = callee[len("self."):]
            if "." in rest:  # self.obj.method — attribute types unknown
                return None
            return self.modules[mod.modname].functions.get(f"{cls}.{rest}")
        head, _, rest = callee.partition(".")
        if not rest:
            info = mod.functions.get(callee)
            if info is not None:
                return info
            target = mod.imports.get(callee)
            return self.chase(target) if target else None
        target = mod.imports.get(head)
        if target is not None:
            return self.chase(f"{target}.{rest}")
        return None

    def chase_class(self, dotted_name: str, depth: int = 0) -> Optional[ClassInfo]:
        """Fully-dotted name → ClassInfo, following one re-export hop per
        level (mirror of :meth:`chase` for classes)."""
        if depth > 4:
            return None
        info = self._classes_by_dotted.get(dotted_name)
        if info is not None:
            return info
        parts = dotted_name.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.modules.get(".".join(parts[:i]))
            if mod is None:
                continue
            rest = parts[i:]
            target = mod.imports.get(rest[0])
            if target is None:
                return None
            return self.chase_class(".".join([target] + rest[1:]), depth + 1)
        return None

    def resolve_class(self, mod: Module, name: str) -> Optional[ClassInfo]:
        """Resolve a class spelling (``HeartbeatAggregator`` or
        ``manager.HeartbeatAggregator``) from inside ``mod``."""
        head, _, rest = name.partition(".")
        if not rest:
            info = mod.classes.get(name)
            if info is not None:
                return info
            target = mod.imports.get(name)
            return self.chase_class(target) if target else None
        target = mod.imports.get(head)
        if target is not None:
            return self.chase_class(f"{target}.{rest}")
        return None

    def attr_class(
        self, mod: Module, cls: Optional[str], attr: str
    ) -> Optional[ClassInfo]:
        """The class an instance attribute was constructed from, if the
        owning class assigned ``self.<attr> = Ctor(...)`` somewhere and
        ``Ctor`` resolves to an analyzed class."""
        if cls is None:
            return None
        owner = mod.classes.get(cls)
        if owner is None:
            return None
        ctor = owner.attr_ctors.get(attr)
        if ctor is None:
            return None
        return self.resolve_class(mod, ctor)


def load_program(
    paths: list, root: str, sources: Optional[dict] = None
) -> tuple[Program, list]:
    """Build a Program from files on disk (``paths`` relative to or under
    ``root``) or from an in-memory ``{rel_path: src}`` mapping (tests).
    Returns ``(program, parse_error_messages)``."""
    prog = Program()
    errors = []
    if sources is not None:
        for rel, src in sources.items():
            err = prog.add_source(src, rel)
            if err:
                errors.append(err)
        return prog, errors
    for path in paths:
        abs_path = path if os.path.isabs(path) else os.path.join(root, path)
        rel = os.path.relpath(abs_path, root)
        with open(abs_path, encoding="utf-8") as fh:
            err = prog.add_source(fh.read(), rel)
        if err:
            errors.append(err)
    return prog, errors
