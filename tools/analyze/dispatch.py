"""jaxguard pass: the dispatch-surface contract (JG4xx).

The serving hot path funnels every decode round through ONE dispatch
site (``GenerationServer._dispatch_decode``) fanning into plain/fused ×
slotted/paged × tp shard_map executable forms, and its performance story
rests on three invariants nothing checked statically until this pass:

JG401 — **dispatch census**: every jit-wrapped callable reachable from
    the serving roots (``GenerationServer.step``/``run``) must draw each
    STATIC argument from a bounded source — a literal, a config/self
    attribute, a module constant, or a knob-lattice value — so the
    executable count is ``buckets × K × forms``, a closed set. A static
    fed by a traced/device value, a loop variable, or an unresolvable
    host computation makes the census unbounded: each distinct value
    compiles a fresh executable (the multi-second spikes the bucket
    ladder exists to prevent).
JG402 — **donation completeness** (the dual of JG102's use-after-
    donation): a PERSISTENT buffer (an attribute chain — ``self.arena``,
    ``self.kv_pool.arena``, ``p.caches``) donated to a jitted callable
    must be REBOUND after the call at every call site, on every branch.
    JG102 fires when the stale buffer is read in the same function;
    JG402 fires when it is simply left dangling — the next reader (often
    another method, beyond JG102's intra-procedural watch) gets a
    deleted buffer.
JG403 — **sharding-spec coverage**: every ``shard_map`` carries explicit
    ``in_specs``/``out_specs``; every layout-switched spec helper in the
    spec modules (``guest/tp_serving.py``, ``parallel/sharding.py``,
    ``ops/decode_attn.py``) covers the WHOLE kv-layout lattice
    (heads/blocks both) with no silent ``None`` fall-through; and no
    ``device_put`` runs on the serving-reachable path outside a
    sanctioned ``allow_transfer`` region (the implicit-reshard class the
    runtime tripwire counts as near-misses).
JG404 — **stale-pragma audit**: a ``# jaxguard: allow(RULE)`` whose rule
    no longer fires anywhere on that line is itself a finding, so
    sanctioned-sync debt cannot rot in place.

The pass REUSES the dataflow engine's program graph (``Analyzer.run``'s
``call_edges``) instead of rebuilding it — the CLI constructs one
:class:`~.dataflow.Analyzer` and threads it through every pass (the
multi-pass graph is built once; ``tests/test_jaxguard.py`` pins it).
"""
from __future__ import annotations

import ast
from typing import Optional

from .graph import FunctionInfo, Module, Program, dotted
from .model import (
    DEVICE_FN_NAMES,
    DEVICE_PREFIXES,
    DISPATCH_ROOT_SUFFIXES,
    Finding,
    LAYOUT_PARAM_NAMES,
    SPEC_MODULE_PATHS,
)

# Source classes for a static argument's value lattice (JG401).
_BOUNDED = "bounded"
_DEVICE = "device"
_UNBOUNDED = "unbounded"

# Host builtins whose result is as bounded as their arguments.
_PURE_HOST = frozenset({"min", "max", "abs", "round", "tuple", "str", "repr"})

_ALLOW_LEAVES = frozenset({"allow_transfer"})


def _norm(path: str) -> str:
    return path.replace("\\", "/")


# ---------------------------------------------------------------------------
# The knob lattice (JG401's value universe for knob-derived statics)
# ---------------------------------------------------------------------------


def knob_lattice(program: Program) -> dict:
    """Map env-var NAME → its statically known value lattice, derived
    from the knob constants the contract pass (JG3xx) already anchors
    on: a module defining ``ENV_FOO = "KATA_TPU_FOO"`` next to a
    same-stem choice tuple (``FOO + "S"`` — e.g. ``KV_LAYOUTS`` for
    ``ENV_KV_LAYOUT``) yields that tuple as the closed lattice; an env
    constant with no choice tuple (``KATA_TPU_DECODE_STEPS``) yields the
    ``"per-process"`` marker — the knob is read once at server init, so
    it contributes ONE value per process to the census, not an unbounded
    family."""
    out: dict = {}
    for mod in program.modules.values():
        env_names: dict = {}     # const name (sans ENV_) → env value
        tuples: dict = {}        # const name → tuple of string choices
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str
                ) and tgt.id.startswith("ENV_"):
                    env_names[tgt.id[len("ENV_"):]] = node.value.value
                elif isinstance(node.value, ast.Tuple):
                    elts = [
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
                    # Resolve Name elements through the module's own
                    # string constants (KV_LAYOUTS = (KV_LAYOUT_HEADS,
                    # KV_LAYOUT_BLOCKS) — the repo's actual spelling).
                    consts = {
                        t.id: n.value.value
                        for n in mod.tree.body
                        if isinstance(n, ast.Assign)
                        and isinstance(n.value, ast.Constant)
                        and isinstance(n.value.value, str)
                        for t in n.targets if isinstance(t, ast.Name)
                    }
                    for e in node.value.elts:
                        if isinstance(e, ast.Name) and e.id in consts:
                            elts.append(consts[e.id])
                    if elts and len(elts) == len(node.value.elts):
                        tuples[tgt.id] = tuple(elts)
        for stem, env_value in env_names.items():
            choices = tuples.get(stem + "S")
            out[env_value] = choices if choices else "per-process"
    return out


# ---------------------------------------------------------------------------
# Serving reachability over the shared call graph
# ---------------------------------------------------------------------------


def serving_reachable(program: Program, call_edges: dict) -> set:
    """Qualnames reachable from the SERVING roots (``GenerationServer.
    step``/``run``) over the dataflow engine's resolved call graph —
    crossing INTO jitted callees (the census wants the executables
    themselves), unlike the JG101 hot set which stops at the jit
    boundary."""
    roots = set()
    for q, fn in program.functions.items():
        flat = q.replace(":", ".")
        if any(flat.endswith(s) for s in DISPATCH_ROOT_SUFFIXES):
            roots.add(q)
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        q = frontier.pop()
        for callee in call_edges.get(q, ()):
            if callee not in seen and callee in program.functions:
                seen.add(callee)
                frontier.append(callee)
    return seen


# ---------------------------------------------------------------------------
# The per-function walk: census sources, donation watches, transfer sites
# ---------------------------------------------------------------------------


class _SiteWalk(ast.NodeVisitor):
    """One lexical pass over one HOST (non-traced) function reachable
    from the serving roots: classifies every static argument fed to a
    jitted callee (JG401), watches every donated persistent buffer for
    its rebind (JG402), and records ``device_put`` sites with their
    ``allow_transfer`` sanction state (JG403). Sees through the staged
    dispatch idiom — ``fargs = (...)`` / ``fkw = dict(...)`` then
    ``fn(*fargs, **fkw)`` — the same expansion the dataflow engine's
    donation/static checks use."""

    def __init__(self, prog: Program, fn: FunctionInfo) -> None:
        self.prog = prog
        self.fn = fn
        self.mod = prog.modules[fn.modname]
        self.locals: dict[str, str] = {}      # name → source class
        self.tuple_stages: dict[str, list] = {}
        self.dict_stages: dict[str, dict] = {}
        self.loop_vars: list[set] = []
        self.allow_depth = 0
        self.in_return = 0
        # dotted → (line, callee leaf name): donated persistent buffers
        # awaiting their rebind.
        self.donation_watches: dict[str, tuple] = {}
        self.findings: list[Finding] = []
        # (call node, sanctioned: bool) for every device_put reached.
        self.transfer_sites: list[tuple] = []
        # (caller-relative) call records: callee qualname → list of
        # ``inside allow region`` bools, for the sanction fixpoint.
        self.call_sanction: list[tuple] = []

    # ----- helpers ----------------------------------------------------------

    def _add(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(Finding(
            self.fn.path, getattr(node, "lineno", 1), rule, message,
            function=self.fn.qualname,
        ))

    def _resolve(self, d: str) -> Optional[FunctionInfo]:
        if not d:
            return None
        return self.prog.resolve_call(self.mod, self.fn.cls, d)

    def _call_offset(self, callee: FunctionInfo, d: str) -> int:
        return 1 if (
            callee.cls is not None
            and callee.params[:1] in (("self",), ("cls",))
            and "." in d
        ) else 0

    def _expand_call(self, node: ast.Call) -> tuple:
        """(positional exprs, keyword (name, expr) pairs) with staged
        ``*fargs`` / ``**fkw`` spliced back in."""
        args: list = []
        for a in node.args:
            if isinstance(a, ast.Starred) and isinstance(a.value, ast.Name):
                staged = self.tuple_stages.get(a.value.id)
                if staged is not None:
                    args.extend(staged)
                    continue
            args.append(a)
        kws: list = []
        for k in node.keywords:
            if k.arg is None and isinstance(k.value, ast.Name):
                staged_kw = self.dict_stages.get(k.value.id)
                if staged_kw is not None:
                    kws.extend(staged_kw.items())
                    continue
            if k.arg is not None:
                kws.append((k.arg, k.value))
        return args, kws

    def _in_loop_vars(self, expr: ast.AST) -> Optional[str]:
        names = {n for scope in self.loop_vars for n in scope}
        if not names:
            return None
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in names:
                return sub.id
        return None

    # ----- JG401: static-source classification ------------------------------

    def classify(self, expr: ast.AST, depth: int = 0) -> str:
        """The source class of a static argument's value: ``bounded``
        (literal / self attribute / module constant / param — one value
        per process or per server instance), ``device`` (a traced value
        — can never be static), or ``unbounded`` (an unresolvable host
        computation — the census cannot close over it)."""
        if depth > 8:
            return _UNBOUNDED
        if isinstance(expr, ast.Constant):
            return _BOUNDED
        if isinstance(expr, ast.Name):
            if expr.id in self.locals:
                return self.locals[expr.id]
            if expr.id in self.fn.params:
                return _BOUNDED
            if expr.id in self.mod.imports or expr.id in getattr(
                self.mod, "functions", {}
            ):
                return _BOUNDED  # imported constant / module callable
            # A module-level constant of this module.
            for node in self.mod.tree.body:
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) and tgt.id == expr.id:
                            return _BOUNDED
            return _UNBOUNDED
        if isinstance(expr, ast.Attribute):
            d = dotted(expr)
            if d is not None:
                head = d.split(".", 1)[0]
                if head in ("self", "cls"):
                    return _BOUNDED  # instance config, fixed per server
                if head in self.mod.imports:
                    return _BOUNDED  # module attr — a constant spelling
                if head in self.fn.params:
                    return _BOUNDED  # cfg.block_size and friends
                if head in self.locals:
                    return self.locals[head]
            return self.classify(expr.value, depth + 1)
        if isinstance(expr, ast.IfExp):
            return self._join(
                self.classify(expr.body, depth + 1),
                self.classify(expr.orelse, depth + 1),
            )
        if isinstance(expr, (ast.BinOp,)):
            return self._join(
                self.classify(expr.left, depth + 1),
                self.classify(expr.right, depth + 1),
            )
        if isinstance(expr, ast.UnaryOp):
            return self.classify(expr.operand, depth + 1)
        if isinstance(expr, ast.BoolOp):
            out = _BOUNDED
            for v in expr.values:
                out = self._join(out, self.classify(v, depth + 1))
            return out
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = _BOUNDED
            for e in expr.elts:
                out = self._join(out, self.classify(e, depth + 1))
            return out
        if isinstance(expr, ast.Subscript):
            return self.classify(expr.value, depth + 1)
        if isinstance(expr, ast.Compare):
            return _BOUNDED  # a bool of host values
        if isinstance(expr, ast.Call):
            return self._classify_call(expr, depth)
        return _UNBOUNDED

    @staticmethod
    def _join(a: str, b: str) -> str:
        order = (_DEVICE, _UNBOUNDED, _BOUNDED)
        for cls in order:
            if a == cls or b == cls:
                return cls
        return _BOUNDED

    def _classify_call(self, expr: ast.Call, depth: int) -> str:
        d = dotted(expr.func) or ""
        leaf = d.rsplit(".", 1)[-1]
        callee = self._resolve(d)
        if callee is not None and callee.jit is not None:
            return _DEVICE
        if d.startswith(DEVICE_PREFIXES) or leaf in DEVICE_FN_NAMES:
            return _DEVICE
        if d in _PURE_HOST or leaf in _PURE_HOST:
            out = _BOUNDED
            for a in expr.args:
                out = self._join(out, self.classify(a, depth + 1))
            return out
        return _UNBOUNDED

    # ----- JG402: donation watches ------------------------------------------

    def _watch_donations(self, node: ast.Call, callee: FunctionInfo,
                         d: str, args: list, kws: list) -> None:
        if callee.jit is None or not callee.jit.donates:
            return
        if self.in_return:
            # The successor escapes to OUR caller — rebinding is its
            # responsibility, not statically trackable from here.
            return
        off = self._call_offset(callee, d)
        donated = set(callee.donated_positions())
        names = set(callee.jit.donate_argnames)
        exprs = []
        for i, arg in enumerate(args):
            if i + off in donated:
                exprs.append(arg)
        for kname, kval in kws:
            if kname in names or (
                kname in callee.params
                and callee.params.index(kname) in donated
            ):
                exprs.append(kval)
        for expr in exprs:
            name = dotted(expr)
            # Only PERSISTENT locations (attribute chains) are watched:
            # a donated local that is never touched again simply dies
            # with the frame — no dangling state survives the call.
            if name is not None and "." in name:
                self.donation_watches[name] = (node.lineno, callee.name)

    def _clear_watch(self, name: Optional[str]) -> None:
        if name is None:
            return
        for watched in list(self.donation_watches):
            if (
                watched == name
                or watched.startswith(name + ".")
                or name.startswith(watched + ".")
            ):
                del self.donation_watches[watched]

    # ----- JG401/JG402/JG403 at the call site --------------------------------

    def _check_call(self, node: ast.Call) -> None:
        d = dotted(node.func) or ""
        leaf = d.rsplit(".", 1)[-1]
        if leaf == "device_put":
            self.transfer_sites.append((node, self.allow_depth > 0))
        callee = self._resolve(d)
        if callee is None:
            return
        self.call_sanction.append((callee.qualname, self.allow_depth > 0))
        if callee.jit is None:
            return
        args, kws = self._expand_call(node)
        self._watch_donations(node, callee, d, args, kws)
        statics = callee.static_param_names()
        if not statics:
            return
        off = self._call_offset(callee, d)
        pairs = []
        for i, arg in enumerate(args):
            if i + off < len(callee.params) and (
                callee.params[i + off] in statics
            ):
                pairs.append((callee.params[i + off], arg))
        for kname, kval in kws:
            if kname in statics:
                pairs.append((kname, kval))
        for pname, arg in pairs:
            cls = self.classify(arg)
            if cls == _DEVICE:
                self._add(
                    node, "JG401",
                    f"traced/device value feeds static arg '{pname}' of "
                    f"jitted '{callee.name}' — a traced arg can never be "
                    "static; pass it as a traced operand or hoist the "
                    "decision to server config",
                )
                continue
            var = self._in_loop_vars(arg)
            if var is not None:
                self._add(
                    node, "JG401",
                    f"static arg '{pname}' of jitted '{callee.name}' "
                    f"varies with loop variable '{var}' — the executable "
                    "census is unbounded (one compile per iteration)",
                )
                continue
            if cls == _UNBOUNDED:
                src = ast.dump(arg)[:60] if dotted(arg) is None else (
                    dotted(arg)
                )
                self._add(
                    node, "JG401",
                    f"static arg '{pname}' of jitted '{callee.name}' "
                    f"draws from an unbounded source '{src}' — bind it "
                    "to a config attribute or knob constant so the "
                    "dispatch census stays closed",
                )

    # ----- statement/expression traversal ------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        self._check_call(node)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        is_allow = any(
            isinstance(item.context_expr, ast.Call)
            and (dotted(item.context_expr.func) or "").rsplit(".", 1)[-1]
            in _ALLOW_LEAVES
            for item in node.items
        )
        for item in node.items:
            self.visit(item.context_expr)
        if is_allow:
            self.allow_depth += 1
        for child in node.body:
            self.visit(child)
        if is_allow:
            self.allow_depth -= 1

    visit_AsyncWith = visit_With

    def visit_Return(self, node: ast.Return) -> None:
        self.in_return += 1
        self.generic_visit(node)
        self.in_return -= 1

    def visit_If(self, node: ast.If) -> None:
        # Branch-SENSITIVE donation watches: each arm starts from the
        # pre-branch watch set and the arms' leftovers UNION afterwards —
        # a donation rebound on one branch but dangling on its sibling
        # (the per-branch asymmetry JG402 exists for) stays visible.
        self.visit(node.test)
        before = dict(self.donation_watches)
        for child in node.body:
            self.visit(child)
        after_body = self.donation_watches
        self.donation_watches = dict(before)
        for child in node.orelse:
            self.visit(child)
        merged = dict(after_body)
        merged.update(self.donation_watches)
        self.donation_watches = merged

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        for tgt in node.targets:
            self._assign(tgt, node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
            self._assign(node.target, node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        self._clear_watch(dotted(node.target))

    def _assign(self, tgt: ast.AST, value: ast.AST) -> None:
        if isinstance(tgt, ast.Name):
            self._clear_watch(tgt.id)
            if isinstance(value, ast.Tuple):
                self.tuple_stages[tgt.id] = list(value.elts)
            elif isinstance(value, ast.Call) and dotted(
                value.func
            ) == "dict" and not value.args:
                self.dict_stages[tgt.id] = {
                    k.arg: k.value for k in value.keywords
                    if k.arg is not None
                }
            elif isinstance(value, ast.Dict):
                self.dict_stages[tgt.id] = {
                    k.value: v for k, v in zip(value.keys, value.values)
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                }
            self.locals[tgt.id] = self.classify(value)
        elif isinstance(tgt, ast.Attribute):
            self._clear_watch(dotted(tgt))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                if isinstance(e, ast.Starred):
                    e = e.value
                self._assign(e, value)
        elif isinstance(tgt, ast.Starred):
            self._assign(tgt.value, value)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        self._assign(node.target, node.iter)
        scope = {
            n.id for n in ast.walk(node.target) if isinstance(n, ast.Name)
        }
        self.loop_vars.append(scope)
        for child in node.body:
            self.visit(child)
        self.loop_vars.pop()
        for child in node.orelse:
            self.visit(child)

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While) -> None:
        # ISSUE 20: a host `while` is a loop scope like `for` — any name
        # REASSIGNED inside the body varies per iteration, so feeding it
        # to a jit static from inside the loop is the same
        # unbounded-signature hazard JG401 flags for `for` targets. (The
        # persistent decode executable itself is the converse case: its
        # `lax.while_loop` is a TRACED callee — `analyze_dispatch`
        # skips traced bodies — and counts as ONE dispatch signature.)
        scope = set()
        for child in node.body:
            for n in ast.walk(child):
                if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    tgts = (
                        n.targets if isinstance(n, ast.Assign)
                        else [n.target]
                    )
                    for t in tgts:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                scope.add(leaf.id)
        self.visit(node.test)
        self.loop_vars.append(scope)
        for child in node.body:
            self.visit(child)
        self.loop_vars.pop()
        for child in node.orelse:
            self.visit(child)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._clear_watch(dotted(tgt))

    def visit_FunctionDef(self, node) -> None:
        if node is not self.fn.node:
            return  # nested defs are walked as their own functions
        for child in node.body:
            self.visit(child)

    visit_AsyncFunctionDef = visit_FunctionDef

    def run(self) -> None:
        self.visit_FunctionDef(self.fn.node)
        for name, (line, callee) in sorted(self.donation_watches.items()):
            self.findings.append(Finding(
                self.fn.path, line, "JG402",
                f"'{name}' is donated to jitted '{callee}' but never "
                "rebound in this function — XLA deleted the buffer, the "
                "attribute now dangles; store the call's result back "
                f"('{name} = ...')",
                function=self.fn.qualname,
            ))


# ---------------------------------------------------------------------------
# JG403 — shard_map spec completeness + layout-lattice coverage
# ---------------------------------------------------------------------------


def _shard_map_findings(program: Program) -> list:
    findings: list = []
    for mod in program.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            if d.rsplit(".", 1)[-1] != "shard_map":
                continue
            kw = {k.arg for k in node.keywords if k.arg is not None}
            # Positional spelling: shard_map(fn, mesh, in_specs, out_specs).
            have = len(node.args)
            for i, name in enumerate(("in_specs", "out_specs"), start=2):
                explicit = name in kw or have > i
                none_valued = any(
                    k.arg == name and isinstance(k.value, ast.Constant)
                    and k.value.value is None
                    for k in node.keywords
                )
                if not explicit or none_valued:
                    findings.append(Finding(
                        mod.path, node.lineno, "JG403",
                        f"shard_map call without an explicit '{name}' — "
                        "every array crossing the manual-mesh boundary "
                        "needs a declared PartitionSpec (implicit specs "
                        "reshard silently)",
                    ))
    return findings


def _layout_coverage_findings(program: Program, lattice: dict) -> list:
    """In the SPEC modules, a function switching on a kv-layout param
    must (a) only compare it against lattice members and (b) not let a
    layout fall off the end of the function (an implicit ``None`` spec
    is an implicit reshard at the dispatch)."""
    layouts: tuple = ()
    for value, choices in lattice.items():
        if isinstance(choices, tuple) and "LAYOUT" in value.upper():
            layouts = choices
    findings: list = []
    spec_paths = {p for p in SPEC_MODULE_PATHS}
    # Leaf-named string constants across the WHOLE program, so a spec
    # module comparing against an IMPORTED layout constant
    # (`tp_serving.KV_LAYOUT_BLOCKS`) still resolves to its value.
    global_consts: dict = {}
    for m in program.modules.values():
        for n in m.tree.body:
            if isinstance(n, ast.Assign) and isinstance(
                n.value, ast.Constant
            ) and isinstance(n.value.value, str):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        global_consts.setdefault(t.id, n.value.value)
    for mod in program.modules.values():
        if _norm(mod.path) not in spec_paths:
            continue
        consts = dict(global_consts)
        consts.update({
            t.id: n.value.value
            for n in mod.tree.body
            if isinstance(n, ast.Assign)
            and isinstance(n.value, ast.Constant)
            and isinstance(n.value.value, str)
            for t in n.targets if isinstance(t, ast.Name)
        })
        for fn in mod.functions.values():
            if fn.nested:
                continue
            node = fn.node
            lay_params = [
                p for p in fn.params if p in LAYOUT_PARAM_NAMES
            ]
            if not lay_params:
                continue
            compared: set = set()
            bad: list = []
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Compare):
                    continue
                sides = [sub.left] + list(sub.comparators)
                if not any(
                    isinstance(s, ast.Name) and s.id in lay_params
                    for s in sides
                ):
                    continue
                for s in sides:
                    value = None
                    if isinstance(s, ast.Constant) and isinstance(
                        s.value, str
                    ):
                        value = s.value
                    else:
                        ds = dotted(s)
                        if ds is not None:
                            leaf = ds.split(".")[-1]
                            value = consts.get(leaf)
                            if value is None and leaf in mod.imports:
                                tail = mod.imports[leaf].rsplit(".", 1)[-1]
                                value = consts.get(tail)
                    if value is None:
                        continue
                    compared.add(value)
                    if layouts and value not in layouts:
                        bad.append((sub, value))
            for sub, value in bad:
                findings.append(Finding(
                    mod.path, sub.lineno, "JG403",
                    f"'{fn.name}' compares its layout param against "
                    f"{value!r}, which is not in the kv-layout lattice "
                    f"{layouts} — a stale/typo'd layout name can never "
                    "match",
                    function=fn.qualname,
                ))
            if not compared:
                continue
            terminal = any(
                isinstance(stmt, ast.Return) for stmt in node.body
            )
            missing = [v for v in layouts if v not in compared]
            if missing and not terminal:
                findings.append(Finding(
                    mod.path, node.lineno, "JG403",
                    f"'{fn.name}' switches on a kv-layout param but "
                    f"layout(s) {missing} fall off the end of the "
                    "function — an implicit None spec reshards at "
                    "dispatch; add the branch or a terminal default "
                    "return",
                    function=fn.qualname,
                ))
    return findings


# ---------------------------------------------------------------------------
# JG404 — stale-pragma audit
# ---------------------------------------------------------------------------


def stale_pragmas(program: Program, raw_findings: list) -> list:
    """A ``# jaxguard: allow(JGxxx)`` pragma whose rule did not fire on
    its own line in THIS analysis run is dead sanction debt: either the
    hazard was fixed (delete the pragma) or the analyzer stopped seeing
    it (the pragma hides nothing — audit why). ``raw_findings`` must be
    the PRE-suppression finding set of every other pass; JG404 findings
    are themselves suppressible (``allow(JG404) <why this pragma is
    intentionally defensive>``)."""
    from ..pragmas import allowed_lines

    fired: dict = {}
    for f in raw_findings:
        fired.setdefault((f.path, f.line), set()).add(f.rule)
    findings: list = []
    for mod in program.modules.values():
        for line, rules in sorted(allowed_lines(mod.src).items()):
            for rule in sorted(rules):
                if not rule.startswith("JG") or rule == "JG404":
                    continue
                if rule not in fired.get((mod.path, line), ()):
                    findings.append(Finding(
                        mod.path, line, "JG404",
                        f"stale pragma: allow({rule}) but {rule} no "
                        "longer fires on this line — delete the pragma, "
                        "or annotate allow(JG404) with why it must stay",
                    ))
    return findings


# ---------------------------------------------------------------------------
# Pass driver
# ---------------------------------------------------------------------------


def analyze_dispatch(program: Program, analyzer=None) -> list:
    """Run JG401–JG403 over ``program``. ``analyzer`` is the already-run
    :class:`~.dataflow.Analyzer` whose ``call_edges`` this pass reuses —
    pass it from the CLI so the interprocedural graph is built once;
    ``None`` builds one standalone (test convenience)."""
    if analyzer is None:
        from .dataflow import Analyzer

        analyzer = Analyzer(program)
        analyzer.run()
    reach = serving_reachable(program, analyzer.call_edges)
    lattice = knob_lattice(program)
    findings: list = []
    transfer_fns: dict = {}  # qualname → list of (node, lexically sanctioned)
    sites: list = []         # (caller qualname, callee qualname, in allow)
    for q in sorted(reach):
        fn = program.functions[q]
        if analyzer.traced(fn):
            continue  # traced bodies dispatch nothing themselves
        walk = _SiteWalk(program, fn)
        walk.run()
        findings.extend(walk.findings)
        if walk.transfer_sites:
            transfer_fns[q] = walk.transfer_sites
        for callee_q, in_allow in walk.call_sanction:
            sites.append((q, callee_q, in_allow))
    # JG403(c): a device_put on the serving-reachable path is sanctioned
    # when it sits lexically inside allow_transfer, or when EVERY serving
    # call site of its enclosing function does — directly or through a
    # sanctioned caller (the _restore_lane → _kv_host_upload inheritance
    # pattern). Inheritance is DEPTH-LIMITED to 2 call levels below the
    # lexical `with`: an allow region wrapping a broad phase (the
    # admission wrap) must not silently sanction a serialized upload
    # three helpers down — that is exactly the prefetch-miss slow path
    # this rule exists to surface; a deep slow path earns its own
    # reasoned allow_transfer at the transfer itself.
    _SANCTION_DEPTH = 2
    by_callee: dict = {}
    for caller_q, callee_q, in_allow in sites:
        by_callee.setdefault(callee_q, []).append((caller_q, in_allow))
    depth: dict = {}  # qualname → levels below the nearest lexical with
    for _ in range(_SANCTION_DEPTH + 1):
        changed = False
        for q, callers in by_callee.items():
            if q in depth:
                continue
            contrib: list = []
            for caller_q, in_allow in callers:
                if in_allow:
                    contrib.append(0)
                elif caller_q in depth:
                    contrib.append(depth[caller_q])
                else:
                    contrib = None
                    break
            if contrib is None:
                continue
            d = 1 + max(contrib, default=0)
            if d <= _SANCTION_DEPTH:
                depth[q] = d
                changed = True
        if not changed:
            break
    sanctioned_fns = set(depth)
    for q, put_sites in sorted(transfer_fns.items()):
        fn = program.functions[q]
        if q in sanctioned_fns:
            continue
        for node, lexical in put_sites:
            if lexical:
                continue
            findings.append(Finding(
                fn.path, node.lineno, "JG403",
                "device_put on the serving-reachable path outside an "
                "allow_transfer region — an implicit reshard/upload "
                "serializes the decode round (wrap the sanctioned slow "
                "path in jaxapi.allow_transfer(<reason>))",
                function=fn.qualname,
            ))
    findings.extend(_shard_map_findings(program))
    findings.extend(_layout_coverage_findings(program, lattice))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


__all__ = [
    "analyze_dispatch",
    "knob_lattice",
    "serving_reachable",
    "stale_pragmas",
]
