"""int8-KV promotion gate (ISSUE 12).

``guest.serving.GenerationServer`` defaults to the int8 KV arena (the
measured-1.7×-faster decode path). This module is the QUALITY GATE behind
that default: a fixed-prompt-set comparison of int8-KV decoding against
the bf16 oracle — greedy token agreement plus the max-abs logit drift of
the first decode step (prefill attends the FRESH k/v, so the first
decode step is the first read that crosses the quantized cache). The
release rule: :func:`gate` must pass (``make eval-kv``, and
``tests/test_kv_quant.py::test_int8_default_quality_gate`` in tier-1)
for the int8 default to stand; models that fail ship with the
``KATA_TPU_KV_QUANT=bf16`` opt-out (``config.kv_quant`` daemon-side).

Complementary to ``scripts/eval_quality.py`` (the full bf16/int8/W8A8
WEIGHT-quantization ladder with delta-CE on real checkpoints): this is
the small, dependency-free, CI-runnable check for the KV-cache axis
alone — importable (the tier-1 test calls :func:`evaluate_kv_quant`
directly) and scriptable (``python -m tools.eval_quality``).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

# Gate defaults: int8 KV carries ~0.4% relative error per cache read
# (ops/quant.py), so greedy streams agree but can diverge late (one
# flipped near-tie token derails the rest of a stream — agreement is
# step-wise, not prefix-wise). The floors sit below the measured tiny-
# model band (tests/test_kv_quant.py: >= 0.7-0.75 agreement) and well
# below real-checkpoint behavior; the logit ceiling bounds the first
# decode step's drift before any token has diverged.
DEFAULT_MIN_GREEDY_MATCH = 0.7
DEFAULT_MAX_LOGIT_ERR = 0.5


def evaluate_kv_quant(params, cfg, prompts, steps: int = 12,
                      max_len: int = 0) -> dict:
    """Compare int8-KV decoding against the bf16-cache oracle on a fixed
    prompt set. ``prompts``: list of 1-D int32 token arrays. Returns the
    gate's evidence: per-prompt greedy agreement and first-decode-step
    logit drift, plus the aggregates :func:`gate` thresholds."""
    import jax.numpy as jnp
    import numpy as np

    from kata_xpu_device_plugin_tpu.models.transformer import (
        decode,
        forward,
        greedy_token,
        prefill,
    )

    per_prompt = []
    for prompt in prompts:
        prompt = np.asarray(prompt, np.int32).reshape(1, -1)
        S = prompt.shape[1]
        m_len = max_len or S + steps
        # Prefill both arenas. Prefill attention runs over the FRESH k/v
        # (transformer._layer's prefill branch), so the returned logits
        # are identical by construction — the caches differ only in
        # storage dtype.
        caches_bf, logits_bf, pos = prefill(
            params, jnp.asarray(prompt), cfg, m_len, return_logits=True,
        )
        caches_q, _logits_q, _ = prefill(
            params, jnp.asarray(prompt), cfg, m_len, return_logits=True,
            kv_quantized=True,
        )
        tok = greedy_token(logits_bf)
        positions = jnp.full((1, 1), pos, jnp.int32)
        step_bf, _ = forward(
            params, tok[:, None], cfg, positions=positions,
            kv_caches=caches_bf, cache_offset=pos,
        )
        step_q, _ = forward(
            params, tok[:, None], cfg, positions=positions,
            kv_caches=caches_q, cache_offset=pos,
        )
        logit_err = float(jnp.max(jnp.abs(step_q - step_bf)))
        out_bf = np.asarray(decode(params, caches_bf, tok, int(pos), cfg,
                                   steps))
        out_q = np.asarray(decode(params, caches_q, tok, int(pos), cfg,
                                  steps))
        agree = int((out_bf == out_q).sum())
        per_prompt.append({
            "prompt_len": S,
            "greedy_match": round(agree / out_bf.size, 4),
            "tokens_agree": agree,
            "tokens": int(out_bf.size),
            "logit_max_abs_err": round(logit_err, 6),
        })
    total = sum(p["tokens"] for p in per_prompt)
    return {
        "prompts": len(per_prompt),
        "steps": steps,
        # POOLED token agreement over the whole prompt set (the
        # tests/test_kv_quant.py convention): step-wise, so one flipped
        # near-tie token that derails the rest of ONE stream (greedy
        # divergence cascades by design) is weighted by its tokens, not
        # by vetoing the set. worst_prompt_match stays as evidence.
        "greedy_match": round(
            sum(p["tokens_agree"] for p in per_prompt) / total, 4
        ),
        "worst_prompt_match": round(
            min(p["greedy_match"] for p in per_prompt), 4
        ),
        "logit_max_abs_err": round(
            max(p["logit_max_abs_err"] for p in per_prompt), 6
        ),
        "per_prompt": per_prompt,
    }


def gate(result: dict,
         min_greedy_match: float = DEFAULT_MIN_GREEDY_MATCH,
         max_logit_err: float = DEFAULT_MAX_LOGIT_ERR) -> bool:
    """The promotion decision: POOLED token agreement over the whole
    prompt set at or above the floor AND worst-prompt first-decode-step
    logit drift at or below the ceiling. Pooled deliberately — one
    flipped near-tie token derails the rest of its stream by greedy
    cascade, so a worst-prompt floor would veto on a single rounding
    tie; ``result["worst_prompt_match"]`` stays available for callers
    that want the stricter check."""
    return (
        result["greedy_match"] >= min_greedy_match
        and result["logit_max_abs_err"] <= max_logit_err
    )


def _default_prompts(cfg, n: int, seed: int = 0):
    import jax
    import numpy as np

    key = jax.random.PRNGKey(seed)
    lengths = [5 + 3 * i for i in range(n)]
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (ln,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, ln in enumerate(lengths)
    ]


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="int8-KV promotion gate: greedy agreement + logit "
        "drift vs the bf16 KV oracle on a fixed prompt set"
    )
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX_PLATFORMS=cpu (CI / laptops)")
    ap.add_argument("--prompts", type=int, default=4)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-match", type=float,
                    default=DEFAULT_MIN_GREEDY_MATCH)
    ap.add_argument("--max-logit-err", type=float,
                    default=DEFAULT_MAX_LOGIT_ERR)
    args = ap.parse_args(argv)
    if args.cpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax.numpy as jnp

    from kata_xpu_device_plugin_tpu.models import tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import init_params

    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(args.seed), cfg,
                         dtype=jnp.float32)
    result = evaluate_kv_quant(
        params, cfg, _default_prompts(cfg, args.prompts, args.seed),
        steps=args.steps,
    )
    ok = gate(result, args.min_match, args.max_logit_err)
    result["gate"] = "pass" if ok else "fail"
    result["thresholds"] = {
        "min_greedy_match": args.min_match,
        "max_logit_err": args.max_logit_err,
    }
    print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
