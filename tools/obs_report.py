"""Offline telemetry reporter (ISSUE 15): events JSONL → human report.

Turn any recorded event stream — a bench smoke run, a chaos gate, a
production guest's heartbeat file, a flight-recorder postmortem — into a
readable report with four sections:

- **phase waterfall** — span events aggregated per phase name
  (``obs.summarize_phases``), rendered as scaled bars: where the wall
  clock went;
- **heartbeat timelines** — per-server tokens/s, occupancy, queue depth
  and ITL over the ``serving_heartbeat`` stream, with interval summaries
  and a downsampled timeline table;
- **utilization** — the device ledger's heartbeat fields (ISSUE 17):
  MFU / device-busy summaries, the dispatch-gap waterfall (which loop
  phase owns the retire→dispatch host gap), and HBM headroom where the
  stream carries memory fields;
- **top-N slowest requests** — ``request_trace`` events ranked by wall
  time, each with its PR 11 phase ledger (queue/prefill/decode/...)
  spelled out;
- **watchdog incidents** — ``watchdog_alert``/``watchdog_clear`` pairs
  (kind, reason, flight-dump path) plus the recovery/degraded/fatal
  event counts around them.

Outputs: markdown (stdout by default, ``--md PATH``) and machine JSON
(``--json PATH``). ``--check`` validates the report against the
required schema (:func:`check_schema`) and exits non-zero on drift —
the ``make obs-report`` CI smoke gate. ``--generate PATH`` produces a
fresh smoke events file by running a tiny instrumented serving burst on
CPU (the only mode that imports jax).

Reading and rendering are stdlib + ``obs.events`` only, so the reporter
runs on any machine the JSONL landed on — no jax, no prometheus.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Iterable, Optional

from kata_xpu_device_plugin_tpu.obs import events as obs_events

SCHEMA_VERSION = 2

# Required report shape: top-level keys and the per-section fields the
# --check gate pins. Adding a field is fine; REMOVING or renaming one of
# these is schema drift and fails CI (downstream dashboards parse the
# JSON form).
REQUIRED_TOP = (
    "schema", "source", "events", "phases", "heartbeats", "requests",
    "incidents",
)
REQUIRED_HEARTBEAT_FIELDS = (
    "count", "tokens_per_s", "itl_p99_ms", "batch_occupancy",
    "kv_pool_occupancy", "queued", "timeline", "utilization",
)
REQUIRED_REQUEST_FIELDS = ("rid", "outcome", "wall_s", "tokens", "phases")
REQUIRED_INCIDENT_FIELDS = ("alerts", "clears", "event_counts")

# Event names folded into the incident section's context counts.
_INCIDENT_EVENTS = (
    "recovery", "tp_degraded", "device_stall", "fault_injected",
    "chip_loss_fatal", "fatal_error", "request_failed", "kv_preempt",
    "drain",
)

_BAR_WIDTH = 40


def _bar(frac: float, width: int = _BAR_WIDTH) -> str:
    n = max(0, min(width, round(frac * width)))
    return "█" * n + "·" * (width - n)


def _downsample(rows: list, limit: int = 48) -> list:
    """Keep at most ``limit`` evenly spaced rows (first and last always
    survive) — a day-long heartbeat stream must not render as ten
    thousand table lines."""
    if len(rows) <= limit:
        return rows
    step = (len(rows) - 1) / (limit - 1)
    return [rows[round(i * step)] for i in range(limit)]


def _minmeanmax(vals: Iterable[float], digits: int = 3) -> dict:
    vals = [float(v) for v in vals]
    if not vals:
        return {"min": 0.0, "mean": 0.0, "max": 0.0}
    return {
        "min": round(min(vals), digits),
        "mean": round(sum(vals) / len(vals), digits),
        "max": round(max(vals), digits),
    }


# ----- report assembly ------------------------------------------------------


def build_report(events: list[dict], source: str = "",
                 top: int = 10) -> dict:
    """Assemble the machine-readable report from parsed events."""
    heartbeats: dict[str, list[dict]] = {}
    requests: list[dict] = []
    alerts: list[dict] = []
    clears: list[dict] = []
    event_counts: dict[str, int] = {}
    kinds: dict[str, int] = {}
    ts_min = ts_max = None
    for ev in events:
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            ts_min = ts if ts_min is None else min(ts_min, ts)
            ts_max = ts if ts_max is None else max(ts_max, ts)
        kinds[str(ev.get("kind"))] = kinds.get(str(ev.get("kind")), 0) + 1
        name = ev.get("name")
        if name == "serving_heartbeat":
            heartbeats.setdefault(
                str(ev.get("server", "unknown")), []
            ).append(ev)
        elif name == "request_trace":
            requests.append(ev)
        elif name == "watchdog_alert":
            alerts.append({
                "server": ev.get("server", ""),
                "alert": ev.get("alert", ""),
                "reason": ev.get("reason", ""),
                "dump": ev.get("dump", ""),
                "round": ev.get("round"),
                "ts": ev.get("ts"),
            })
        elif name == "watchdog_clear":
            clears.append({
                "server": ev.get("server", ""),
                "alert": ev.get("alert", ""),
                "round": ev.get("round"),
                "ts": ev.get("ts"),
            })
        if name in _INCIDENT_EVENTS:
            event_counts[str(name)] = event_counts.get(str(name), 0) + 1

    hb_sections = {}
    for server, hbs in sorted(heartbeats.items()):
        timeline = _downsample([
            {
                "ts": hb.get("ts"),
                "round": hb.get("round"),
                "tokens_per_s": hb.get("tokens_per_s", 0.0),
                "itl_p99_ms": hb.get("itl_p99_ms", 0.0),
                "batch_occupancy": hb.get("batch_occupancy", 0.0),
                "kv_pool_occupancy": hb.get("kv_pool_occupancy", 0.0),
                "kv_host_occupancy": hb.get("kv_host_occupancy", 0.0),
                "queued": hb.get("queued", 0),
                "mfu": hb.get("mfu", 0.0),
            }
            for hb in hbs
        ])
        phase_totals: dict[str, float] = {}
        for hb in hbs:
            for k, v in hb.items():
                if k.startswith("phase_") and k.endswith("_s"):
                    phase = k[len("phase_"):-len("_s")]
                    phase_totals[phase] = (
                        phase_totals.get(phase, 0.0) + float(v or 0.0)
                    )
        # Device ledger fields (ISSUE 17). Omission-honest: the summaries
        # cover only heartbeats that CARRY the fields (disarmed ledgers
        # and pre-PR streams fold to count 0, not fake zeros), and
        # hbm_headroom_bytes appears only when the stream did. The
        # per-phase gap waterfall weights each interval's per-gap means
        # by its dispatch count so busy intervals dominate.
        util_hbs = [hb for hb in hbs if "mfu" in hb]
        gap_phase: dict[str, float] = {}
        gap_w = 0.0
        for hb in util_hbs:
            w = float(hb.get("dispatches_delta") or 0.0)
            if w <= 0:
                continue
            gap_w += w
            for k, v in hb.items():
                if (k.startswith("dispatch_gap_") and k.endswith("_ms")
                        and k != "dispatch_gap_ms"):
                    p = k[len("dispatch_gap_"):-len("_ms")]
                    gap_phase[p] = gap_phase.get(p, 0.0) + float(v or 0.0) * w
        utilization = {
            "count": len(util_hbs),
            "mfu": _minmeanmax(
                (hb.get("mfu", 0.0) for hb in util_hbs), digits=6
            ),
            "device_busy_frac": _minmeanmax(
                hb.get("device_busy_frac", 0.0) for hb in util_hbs
            ),
            "dispatch_gap_ms": _minmeanmax(
                hb.get("dispatch_gap_ms", 0.0) for hb in util_hbs
            ),
            "gap_phase_ms": {
                p: round(v / gap_w, 4)
                for p, v in sorted(gap_phase.items())
            } if gap_w else {},
        }
        headroom = [
            hb["hbm_headroom_bytes"] for hb in hbs
            if "hbm_headroom_bytes" in hb
        ]
        if headroom:
            utilization["hbm_headroom_bytes"] = _minmeanmax(headroom)
        hb_sections[server] = {
            "count": len(hbs),
            "utilization": utilization,
            "tokens_per_s": _minmeanmax(
                hb.get("tokens_per_s", 0.0) for hb in hbs
            ),
            "itl_p99_ms": _minmeanmax(
                hb.get("itl_p99_ms", 0.0) for hb in hbs
            ),
            "batch_occupancy": _minmeanmax(
                hb.get("batch_occupancy", 0.0) for hb in hbs
            ),
            "kv_pool_occupancy": _minmeanmax(
                hb.get("kv_pool_occupancy", 0.0) for hb in hbs
            ),
            "queued": _minmeanmax(hb.get("queued", 0) for hb in hbs),
            "loop_phase_s": {
                k: round(v, 6) for k, v in sorted(phase_totals.items())
            },
            "timeline": timeline,
        }

    requests.sort(key=lambda r: -float(r.get("wall_s") or 0.0))
    slowest = [
        {
            "rid": r.get("rid"),
            "server": r.get("server", ""),
            "outcome": r.get("outcome", ""),
            "reason": r.get("reason", ""),
            "wall_s": round(float(r.get("wall_s") or 0.0), 6),
            "tokens": r.get("tokens", 0),
            "prompt_len": r.get("prompt_len", 0),
            "replays": r.get("replays", 0),
            # The PR 11 phase ledger: only phases with time in them.
            "phases": {
                k[:-len("_s")]: round(float(v), 6)
                for k, v in r.items()
                if k.endswith("_s") and k not in ("wall_s", "attributed_s")
                and float(v or 0.0) > 0
            },
        }
        for r in requests[:top]
    ]

    return {
        "schema": SCHEMA_VERSION,
        "source": source,
        "events": {
            "count": len(events),
            "span_s": (
                round(ts_max - ts_min, 3)
                if ts_min is not None and ts_max is not None else 0.0
            ),
            "kinds": dict(sorted(kinds.items())),
        },
        "phases": obs_events.summarize_phases(events),
        "heartbeats": {"servers": hb_sections},
        "requests": {"total_traces": len(requests), "slowest": slowest},
        "incidents": {
            "alerts": alerts,
            "clears": clears,
            "event_counts": dict(sorted(event_counts.items())),
        },
    }


# ----- schema gate ----------------------------------------------------------


def check_schema(report: dict, require_data: bool = False) -> list[str]:
    """Validate the report structure; returns a list of drift errors
    (empty = clean). ``require_data=True`` additionally demands a
    non-empty phase waterfall and at least one heartbeat server — the
    smoke gate's bar (a reporter that renders an empty report from a
    fresh smoke stream IS drift, just upstream of the schema)."""
    errors: list[str] = []
    for key in REQUIRED_TOP:
        if key not in report:
            errors.append(f"missing top-level key: {key}")
    if errors:
        return errors
    if report["schema"] != SCHEMA_VERSION:
        errors.append(
            f"schema version {report['schema']} != {SCHEMA_VERSION}"
        )
    for name, stats in report["phases"].items():
        for k in ("count", "total_s", "mean_s"):
            if k not in stats:
                errors.append(f"phase {name!r} missing field {k}")
    for server, sec in report["heartbeats"].get("servers", {}).items():
        for k in REQUIRED_HEARTBEAT_FIELDS:
            if k not in sec:
                errors.append(f"heartbeat section {server!r} missing {k}")
    for req in report["requests"].get("slowest", []):
        for k in REQUIRED_REQUEST_FIELDS:
            if k not in req:
                errors.append(f"request entry missing {k}: {req}")
    for k in REQUIRED_INCIDENT_FIELDS:
        if k not in report["incidents"]:
            errors.append(f"incidents section missing {k}")
    if require_data:
        if not report["phases"]:
            errors.append("empty phase waterfall (no span events parsed)")
        servers = report["heartbeats"].get("servers")
        if not servers:
            errors.append("no serving_heartbeat events parsed")
        elif not any(
            sec.get("utilization", {}).get("count")
            for sec in servers.values()
        ):
            errors.append(
                "no utilization fields in any heartbeat (device ledger "
                "disarmed or absent from the smoke stream)"
            )
    return errors


# ----- markdown rendering ---------------------------------------------------


def render_markdown(report: dict) -> str:
    out: list[str] = []
    ev = report["events"]
    out.append("# Telemetry report")
    out.append("")
    out.append(
        f"`{report['source']}` — {ev['count']} events over "
        f"{ev['span_s']:.1f}s (schema v{report['schema']})"
    )

    out.append("")
    out.append("## Phase waterfall")
    out.append("")
    phases = report["phases"]
    if phases:
        longest = max(s["total_s"] for s in phases.values()) or 1.0
        width = max(len(n) for n in phases)
        out.append("```")
        for name, s in sorted(
                phases.items(), key=lambda kv: -kv[1]["total_s"]):
            out.append(
                f"{name:<{width}}  {_bar(s['total_s'] / longest)} "
                f"{s['total_s']:9.3f}s  ×{s['count']:<5} "
                f"mean {s['mean_s'] * 1e3:8.2f}ms"
            )
        out.append("```")
    else:
        out.append("_no span events in the stream_")

    out.append("")
    out.append("## Serving heartbeats")
    servers = report["heartbeats"]["servers"]
    if not servers:
        out.append("")
        out.append("_no serving_heartbeat events in the stream_")
    for server, sec in servers.items():
        tps, itl = sec["tokens_per_s"], sec["itl_p99_ms"]
        out.append("")
        out.append(
            f"### {server} — {sec['count']} heartbeats, tokens/s "
            f"{tps['min']}/{tps['mean']}/{tps['max']} (min/mean/max), "
            f"ITL p99 {itl['mean']}ms mean"
        )
        lp = sec.get("loop_phase_s") or {}
        if lp:
            total = sum(lp.values()) or 1.0
            parts = ", ".join(
                f"{k} {100 * v / total:.0f}%" for k, v in sorted(
                    lp.items(), key=lambda kv: -kv[1]
                )
            )
            out.append(f"loop time: {parts}")
        out.append("")
        out.append(
            "| round | tok/s | ITL p99 ms | batch | pool | host | queued "
            "| mfu |"
        )
        out.append("|---:|---:|---:|---:|---:|---:|---:|---:|")
        for row in sec["timeline"]:
            out.append(
                f"| {row['round']} | {row['tokens_per_s']} "
                f"| {row['itl_p99_ms']} | {row['batch_occupancy']} "
                f"| {row['kv_pool_occupancy']} | {row['kv_host_occupancy']} "
                f"| {row['queued']} | {row.get('mfu', 0.0)} |"
            )

    out.append("")
    out.append("## Utilization")
    any_util = False
    for server, sec in servers.items():
        util = sec.get("utilization") or {}
        if not util.get("count"):
            continue
        any_util = True
        mfu = util["mfu"]
        busy = util["device_busy_frac"]
        gap = util["dispatch_gap_ms"]
        out.append("")
        out.append(
            f"### {server} — MFU {mfu['mean']} mean / {mfu['max']} peak, "
            f"device busy {busy['mean']} mean, dispatch gap "
            f"{gap['mean']}ms mean"
        )
        gp = util.get("gap_phase_ms") or {}
        shown = {p: v for p, v in gp.items() if v > 0}
        if shown:
            out.append("")
            out.append("dispatch-gap waterfall (ms per gap, by loop phase):")
            out.append("```")
            longest = max(shown.values()) or 1.0
            width = max(len(p) for p in shown)
            for p, v in sorted(shown.items(), key=lambda kv: -kv[1]):
                out.append(
                    f"{p:<{width}}  {_bar(v / longest, 24)} {v:9.4f}ms"
                )
            out.append("```")
        hr = util.get("hbm_headroom_bytes")
        if hr:
            out.append(
                f"HBM headroom bytes {hr['min']}/{hr['mean']}/{hr['max']} "
                f"(min/mean/max)"
            )
        else:
            out.append(
                "_no hbm_* fields in the stream (backend exposes no "
                "memory_stats)_"
            )
    if not any_util:
        out.append("")
        out.append("_no utilization fields in the heartbeat stream_")

    out.append("")
    out.append("## Slowest requests")
    out.append("")
    slowest = report["requests"]["slowest"]
    if slowest:
        out.append(
            f"{report['requests']['total_traces']} request traces; "
            f"top {len(slowest)} by wall time:"
        )
        out.append("```")
        longest = max(r["wall_s"] for r in slowest) or 1.0
        for r in slowest:
            ledger = " | ".join(
                f"{k} {v:.3f}s" for k, v in sorted(
                    r["phases"].items(), key=lambda kv: -kv[1]
                )
            )
            tag = r["outcome"] + (
                f"({r['reason']})" if r.get("reason") else ""
            )
            out.append(
                f"rid {r['rid']:>5} {_bar(r['wall_s'] / longest, 20)} "
                f"{r['wall_s']:8.3f}s {tag:<10} {r['tokens']:>5} tok  "
                f"{ledger}"
            )
        out.append("```")
    else:
        out.append("_no request_trace events in the stream_")

    out.append("")
    out.append("## Watchdog incidents")
    out.append("")
    inc = report["incidents"]
    if inc["alerts"]:
        for a in inc["alerts"]:
            out.append(
                f"- **{a['alert']}** on `{a['server']}` at round "
                f"{a['round']}: {a['reason']}"
                + (f" — flight dump `{a['dump']}`" if a["dump"] else "")
            )
        for c in inc["clears"]:
            out.append(
                f"- cleared **{c['alert']}** on `{c['server']}` at round "
                f"{c['round']}"
            )
    else:
        out.append("_no watchdog alerts_")
    if inc["event_counts"]:
        counts = ", ".join(
            f"{k}×{v}" for k, v in inc["event_counts"].items()
        )
        out.append("")
        out.append(f"incident-adjacent events: {counts}")
    out.append("")
    return "\n".join(out)


# ----- smoke-stream generation (the only jax-touching mode) -----------------


def generate_smoke(path: str) -> str:
    """Run a tiny instrumented serving burst on CPU and stream its
    events to ``path`` — the ``make obs-report`` gate's input. Kept
    inside the reporter so the smoke stream and the report it must parse
    can never drift apart."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kata_xpu_device_plugin_tpu import obs
    from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
    from kata_xpu_device_plugin_tpu.models import tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import init_params

    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    # Fresh means fresh: the sink appends, so a leftover stream from a
    # previous run would make the schema gate validate mixed data.
    if os.path.exists(path):
        os.unlink(path)
    sink = obs.EventSink(path)
    prev = obs.set_default_sink(sink)
    try:
        srv = GenerationServer(
            params, cfg, max_batch=2, max_len=64, chunk=2,
            kv_quant=False, heartbeat_rounds=2,
            kv_pool_tokens=2 * 64, prefix_cache_tokens=0,
        )
        key = jax.random.PRNGKey(7)
        for i in range(6):
            p = jax.random.randint(
                jax.random.fold_in(key, i), (8 + 2 * (i % 3),), 0,
                cfg.vocab_size,
            )
            srv.submit(np.asarray(p, np.int32), 10)
        srv.run()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    return path


# ----- CLI ------------------------------------------------------------------


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.obs_report",
        description="Render an events JSONL into a telemetry report "
                    "(phase waterfall, heartbeat timelines, slowest "
                    "requests, watchdog incidents).",
    )
    ap.add_argument("events", nargs="?", help="events JSONL to report on")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest requests to list (default 10)")
    ap.add_argument("--md", help="write the markdown report here")
    ap.add_argument("--json", dest="json_path",
                    help="write the JSON report here")
    ap.add_argument("--check", action="store_true",
                    help="validate the report schema (exit 2 on drift); "
                         "also requires a non-empty waterfall + heartbeats")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the stdout markdown")
    ap.add_argument("--generate", metavar="PATH",
                    help="generate a smoke events file by running a tiny "
                         "instrumented serving burst (CPU), then exit "
                         "(combine with a second invocation to report)")
    args = ap.parse_args(argv)

    if args.generate:
        path = generate_smoke(args.generate)
        print(f"smoke events written: {path}")
        return 0
    if not args.events:
        ap.error("events file required (or --generate PATH)")

    try:
        events = obs_events.read_events(args.events)
    except OSError as e:
        print(f"cannot read events file: {e}", file=sys.stderr)
        return 2
    report = build_report(events, source=args.events, top=args.top)
    md = render_markdown(report)
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1)
    if args.md:
        with open(args.md, "w", encoding="utf-8") as fh:
            fh.write(md)
    if not args.quiet:
        print(md)
    if args.check:
        errors = check_schema(report, require_data=True)
        if errors:
            for e in errors:
                print(f"SCHEMA DRIFT: {e}", file=sys.stderr)
            return 2
        print(
            f"schema ok: v{report['schema']}, {report['events']['count']} "
            f"events, {len(report['phases'])} phases, "
            f"{len(report['heartbeats']['servers'])} heartbeat server(s)",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
