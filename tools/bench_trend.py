"""Bench-bank trend comparison (ISSUE 11 satellite).

The TPU bench banks one dated ``BENCH_TPU_<utcstamp>.json`` per healthy
round (``scripts/bench_when_healthy.py``), but nothing ever LOOKED at
two of them side by side — decode tok/s/chip sat at 1303.8 across the
entire bank without anyone noticing, because each file is only ever
read alone. This tool makes the trajectory visible: it loads the two
newest banks, prints the per-metric delta for every numeric field they
share, marks headline metrics that moved more than the threshold, and
exits non-zero on a headline REGRESSION so CI can surface it (the CI
step is non-blocking — a bench regression is a flag to read, not a
merge gate; the numbers come from shared hardware).

Usage::

    python -m tools.bench_trend [--dir .] [--threshold 0.10] [--json]

Conventions:

- Banks sort by filename — the UTC stamp in ``BENCH_TPU_<stamp>.json``
  is lexicographically ordered.
- HEADLINE metrics are throughputs (higher is better); a drop beyond
  the threshold is a regression. All other shared numeric fields are
  reported as context, never flagged.
- A headline metric whose value is bit-identical across both banks is
  marked ``flat`` — the "nobody is moving this number" signal this tool
  exists to raise.
- Banks that flipped a ``*_layout`` config field between rounds (e.g. a
  ``serving_kv_layout`` heads → blocks A/B, ISSUE 14) mark that family's
  moved metrics ``layout`` instead of ``regression``/``improved`` — an
  intentional config flip is a fact to print, not a perf alarm, and it
  must not fail the trend gate.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional

# Throughput headlines (higher is better): a >threshold drop flags.
HEADLINE_METRICS = (
    "value",                 # the banked headline (decode tok/s/chip)
    "e2e_tok_per_s",
    "prefill_tok_per_s",
    "int8_tok_per_s",
    "serving_tok_per_s",
    # Decode tok/s through the paged-native attention kernel (ISSUE 12):
    # the serving-decode numbers the ROADMAP item-1 >2× claim rides —
    # bf16 and the int8-by-default configuration.
    "serving_decode_attn_tok_per_s",
    "serving_decode_attn_int8_tok_per_s",
    # Fused prefill+decode + multi-step dispatch serving tok/s (ISSUE
    # 13): the serving-vs-raw-decode-gap number the fused scheduler and
    # decode_steps=K exist to move.
    "serving_fused_tok_per_s",
    # Peak concurrent sessions the block-sharded pool sustains at the
    # fixed per-chip budget (ISSUE 14): the sessions-per-chip capacity
    # number the blocks layout + host tier exist to move.
    "serving_kv_sessions",
    # Fraction of the heartbeat interval covered by in-flight decode
    # rounds (ISSUE 17): the device-side "are the chips actually
    # working" headline the ledger exists to move.
    "serving_device_busy_frac",
    # Persistent while_loop decode serving tok/s (ISSUE 20): the
    # host-round-trip-amortization number the persistent executable
    # exists to move.
    "serving_persistent_tok_per_s",
)

# Lower-is-better INFO metrics (ISSUE 17): direction-aware statuses
# ("info-better" when the value DROPPED past the threshold,
# "info-worse" when it rose, "info" otherwise) — trend context, never a
# regression gate and never counted in the headline summary line
# (host-gap means at smoke-tiny round times are too noisy to block on).
INFO_LOWER_IS_BETTER = (
    "serving_dispatch_gap_ms",
)

# Zero-is-the-only-passing-value metrics (ISSUE 19): the steady-state
# compile/reshard tripwire. A nonzero NEW value is a regression by
# definition — the warm dispatch surface recompiled (a jit static arg
# varied per round) — regardless of threshold, and two equal nonzero
# banks are still a regression, never "flat": the breach does not age
# into a baseline.
ZERO_REQUIRED_METRICS = (
    "serving_steady_state_compiles",
    "serving_steady_state_reshards",
)

DEFAULT_THRESHOLD = 0.10  # 10%

# Non-measurement fields a bank carries that must not enter the table.
_SKIP = {"attempts", "ts"}


def find_banks(directory: str = ".") -> list[str]:
    """All bench banks in ``directory``, oldest → newest (the filename
    stamp is the order)."""
    return sorted(glob.glob(os.path.join(directory, "BENCH_TPU_*.json")))


def numeric_metrics(bank: dict) -> dict[str, float]:
    """The flat numeric fields of one bank line (nested dicts like
    ``phases``, strings, lists, and bookkeeping fields are skipped)."""
    out: dict[str, float] = {}
    for k, v in bank.items():
        if k.startswith("_") or k in _SKIP:
            continue
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[k] = float(v)
    return out


def layout_flips(old: dict, new: dict) -> dict[str, tuple]:
    """String-valued ``*_layout`` config fields both banks carry whose
    values DIFFER — an intentional A/B flip (the operator changed the
    bank's configuration between rounds), keyed by field name with the
    (old, new) pair. The metric family sharing the field's prefix (e.g.
    ``serving_kv_`` for ``serving_kv_layout``) is then printed as
    ``layout`` rather than flagged."""
    out: dict[str, tuple] = {}
    for k, v in old.items():
        if k.endswith("_layout") and isinstance(v, str):
            w = new.get(k)
            if isinstance(w, str) and w != v:
                out[k] = (v, w)
    return out


def compare(old: dict, new: dict,
            threshold: float = DEFAULT_THRESHOLD) -> list[dict]:
    """Per-metric rows for the fields both banks carry: old/new values,
    relative delta, and a status — ``regression`` (headline, dropped
    beyond threshold), ``improved`` (headline, rose beyond threshold),
    ``flat`` (headline, bit-identical), ``layout`` (the metric's family
    flipped a ``*_layout`` config field between the banks — an
    intentional A/B, never a regression), ``info-better`` /
    ``info-worse`` / ``info`` (lower-is-better info metrics — direction
    flipped, never gating), or ``""`` (context). Tripwire metrics
    (``ZERO_REQUIRED_METRICS``) gate on the NEW value alone: nonzero is
    ``regression`` even when both banks match — never ``flat``."""
    om, nm = numeric_metrics(old), numeric_metrics(new)
    flip_prefixes = tuple(
        k[: -len("layout")] for k in layout_flips(old, new)
    )
    rows: list[dict] = []
    for k in sorted(set(om) & set(nm)):
        a, b = om[k], nm[k]
        delta = (b - a) / a if a else (0.0 if b == a else float("inf"))
        status = ""
        if k in ZERO_REQUIRED_METRICS:
            status = "regression" if b != 0 else (
                "improved" if a != 0 else "flat"
            )
        elif k in HEADLINE_METRICS:
            if b == a:
                status = "flat"
            elif delta < -threshold:
                status = "regression"
            elif delta > threshold:
                status = "improved"
            if status in ("regression", "improved") and any(
                    k.startswith(p) for p in flip_prefixes):
                status = "layout"
        elif k in INFO_LOWER_IS_BETTER:
            if delta < -threshold:
                status = "info-better"
            elif delta > threshold:
                status = "info-worse"
            else:
                status = "info"
        rows.append({
            "metric": k,
            "old": a,
            "new": b,
            "delta_pct": round(delta * 100.0, 2),
            "status": status,
        })
    # Headlines first (bank order), then context alphabetically.
    order = {m: i for i, m in enumerate(HEADLINE_METRICS)}
    rows.sort(key=lambda r: (order.get(r["metric"], len(order)),
                             r["metric"]))
    return rows


def analyzer_findings(directory: str = ".") -> Optional[dict]:
    """The jaxguard summary riding next to the banks, when a
    ``jaxguard_report.json`` is present (``make analyze`` writes one).
    The trend footer carries it so a PR that buys its green analyzer
    run with a pile of new pragmas is visible in the same place the
    perf trajectory is. Unreadable/absent report → None (the footer
    line is simply omitted — the analyzer gate, not this tool, owns
    failing on findings)."""
    path = os.path.join(directory, "jaxguard_report.json")
    try:
        with open(path, encoding="utf-8") as fh:
            report = json.load(fh)
        summary = report["summary"]
        return {
            "total": int(summary["total"]),
            "by_rule": dict(summary.get("by_rule", {})),
        }
    except (OSError, ValueError, KeyError, TypeError):
        return None


def render(rows: list[dict], old_path: str, new_path: str,
           flips: Optional[dict] = None,
           analyzer: Optional[dict] = None) -> str:
    lines = [
        f"bench trend: {os.path.basename(old_path)} -> "
        f"{os.path.basename(new_path)}",
    ]
    for field, (a, b) in sorted((flips or {}).items()):
        lines.append(f"layout change: {field} {a} -> {b}")
    lines.append(
        f"{'metric':<38} {'old':>12} {'new':>12} {'delta':>9}  status"
    )
    for r in rows:
        lines.append(
            f"{r['metric']:<38} {r['old']:>12.4g} {r['new']:>12.4g} "
            f"{r['delta_pct']:>+8.2f}%  {r['status']}"
        )
    n_reg = sum(r["status"] == "regression" for r in rows)
    n_flat = sum(r["status"] == "flat" for r in rows)
    lines.append(
        f"headline: {n_reg} regression(s), {n_flat} flat "
        f"(of {sum(r['metric'] in HEADLINE_METRICS for r in rows)} present)"
    )
    if analyzer is not None:
        note = f"jaxguard: {analyzer['total']} finding(s)"
        if analyzer["by_rule"]:
            note += " (" + ", ".join(
                f"{rule}={n}" for rule, n in sorted(
                    analyzer["by_rule"].items()
                )
            ) + ")"
        lines.append(note)
    return "\n".join(lines)


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_TPU_*.json banks")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="headline regression threshold (fraction, "
                         "default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as one JSON object instead "
                         "of the table")
    args = ap.parse_args(argv)

    # Degrade-to-a-note contract (ISSUE 13 satellite): a workspace with
    # fewer than two READABLE banks — none at all, a single bank, or a
    # newest bank that is truncated/corrupt JSON or not a dict (a
    # half-written file from an interrupted bench round) — must print a
    # "no trend yet" note and exit 0, never unwind with a traceback; the
    # CI step should be non-blocking by CONTENT, not because
    # continue-on-error masks a crash.
    loaded: list[tuple[str, dict]] = []
    for path in reversed(find_banks(args.dir)):  # newest first
        try:
            with open(path, encoding="utf-8") as fh:
                bank = json.load(fh)
            if not isinstance(bank, dict):
                raise ValueError(f"bank is {type(bank).__name__}, not dict")
        except (OSError, ValueError) as exc:
            print(
                f"bench-trend: skipping unreadable bank "
                f"{os.path.basename(path)} ({exc})",
                file=sys.stderr,
            )
            continue
        loaded.append((path, bank))
        if len(loaded) == 2:
            break  # only the two newest readable banks compare
    if len(loaded) < 2:
        print(
            f"bench-trend: no trend yet — need two readable "
            f"BENCH_TPU_*.json banks in {args.dir!r}, found {len(loaded)}"
        )
        return 0  # an empty bank is not a failure
    (new_path, new), (old_path, old) = loaded[0], loaded[1]
    rows = compare(old, new, threshold=args.threshold)
    flips = layout_flips(old, new)
    analyzer = analyzer_findings(args.dir)
    if args.json:
        print(json.dumps({
            "old": os.path.basename(old_path),
            "new": os.path.basename(new_path),
            "threshold": args.threshold,
            "layout_changes": {k: list(v) for k, v in flips.items()},
            "analyzer": analyzer,
            "rows": rows,
        }, indent=2))
    else:
        print(render(rows, old_path, new_path, flips=flips,
                     analyzer=analyzer))
    return 1 if any(r["status"] == "regression" for r in rows) else 0


if __name__ == "__main__":
    sys.exit(main())
