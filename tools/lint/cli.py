"""``python -m tools.lint`` — run the repo's static-analysis rules.

Exit status: 0 clean, 1 findings, 2 usage error. Findings print as
``path:line: RULE message`` (editor/CI friendly).
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import Iterable, Optional

from .rules import ALL_RULES, Finding, check_file

# Directories never worth linting (generated protobufs change names on
# regeneration; caches are not source).
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}
_SKIP_SUFFIXES = ("_pb2.py", "_pb2_grpc.py")

# Default lint surface, repo-root relative.
DEFAULT_TARGETS = (
    "kata_xpu_device_plugin_tpu",
    "tools",
    "tests",
    "scripts",
    "bench.py",
    "__graft_entry__.py",
)


def _iter_py_files(target: str) -> Iterable[str]:
    if os.path.isfile(target):
        if target.endswith(".py"):
            yield target
        return
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for name in sorted(filenames):
            if name.endswith(".py") and not name.endswith(_SKIP_SUFFIXES):
                yield os.path.join(dirpath, name)


def run(
    targets: Optional[Iterable[str]] = None,
    root: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
) -> list[Finding]:
    """Lint ``targets`` (files or directories, resolved under ``root``)."""
    root = root or os.getcwd()
    chosen = list(targets) if targets else [
        t for t in DEFAULT_TARGETS if os.path.exists(os.path.join(root, t))
    ]
    findings: list[Finding] = []
    for target in chosen:
        abs_target = target if os.path.isabs(target) else os.path.join(root, target)
        if not os.path.exists(abs_target):
            raise FileNotFoundError(f"lint target {target!r} does not exist")
        for path in _iter_py_files(abs_target):
            rel = os.path.relpath(path, root)
            findings.extend(check_file(path, rel, rules))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="Repo static analysis: JAX drift + hermeticity rules.",
    )
    parser.add_argument(
        "targets", nargs="*",
        help="files/directories to lint (default: the repo surface)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="ID",
        help="restrict to one or more rule ids (repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root paths are reported relative to (default: cwd)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        # Both static-analysis surfaces in one catalogue: the per-function
        # lint rules here, and the interprocedural jaxguard rules (their
        # engine lives in tools.analyze; the pragma grammar is shared —
        # tools.pragmas).
        print("# tools.lint (per-function AST rules)")
        for rule, summary in sorted(ALL_RULES.items()):
            print(f"{rule}  {summary}")
        from ..analyze.model import ALL_RULES as JG_RULES

        print("# tools.analyze / jaxguard (interprocedural dataflow rules)")
        for rule, summary in sorted(JG_RULES.items()):
            print(f"{rule}  {summary}")
        return 0

    if args.rules:
        unknown = set(args.rules) - set(ALL_RULES)
        if unknown:
            print(f"unknown rule ids: {sorted(unknown)}", file=sys.stderr)
            return 2

    try:
        findings = run(args.targets or None, args.root, args.rules)
    except FileNotFoundError as err:
        print(str(err), file=sys.stderr)
        return 2

    for finding in findings:
        print(finding)
    if findings:
        print(
            f"\n{len(findings)} finding(s). Rule docs: docs/compat_and_lint.md",
            file=sys.stderr,
        )
        return 1
    return 0
