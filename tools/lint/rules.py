"""AST rules for the repo linter.

Each rule encodes a failure class this repo has actually hit (or is one
change away from hitting):

- **JX001** — direct import/use of a *drifted* JAX symbol outside
  ``compat/``. ``from jax import shard_map`` is exactly the seed bug that
  left 19 test files uncollectable on jax 0.4.x; the symbols in
  :data:`DRIFTED_JAX_SYMBOLS` must come from
  ``kata_xpu_device_plugin_tpu.compat.jaxapi``.
- **JX002** — ``jax.experimental.*`` import outside ``compat/``.
  Experimental APIs move between releases; each use needs either a shim in
  compat or an explicit ``# lint: allow(JX002)`` pragma naming why there is
  no stable home (pallas, mesh_utils).
- **JX003** — float64 literals/dtypes in TPU-path code
  (``ops/``/``models/``/``parallel/``). TPUs demote f64 to f32 silently;
  a double-precision constant is a numerics bug waiting for hardware.
- **JX004** — a timing loop (two+ ``perf_counter``/``time.time`` calls in
  one function) with no dispatch fence (``block_until_ready``,
  ``device_get``, or an ``np.asarray`` host transfer). Async dispatch means
  such a loop measures Python dispatch, not compute.
- **JX005** — a raw timing window (two+ timer calls in one function) in
  LIBRARY code (``kata_xpu_device_plugin_tpu/`` outside ``obs/``). Bench
  scripts may fence by hand (JX004 checks they do); library code must use
  ``obs.span``/``obs.timer``, which fence on exit AND emit the measurement
  into the telemetry pipeline — a fenced-but-unexported timer is a number
  nobody sees, and an unfenced one is wrong. A single timer call (e.g.
  stamping a request's submit time) is fine.
- **TS001** — non-hermetic test patterns in ``tests/``: probing hardcoded
  ``/dev/...`` device nodes (tests must target fake sysfs roots) or
  calling out to the network.

A finding on a line carrying ``# lint: allow(RULE[, RULE...])`` is
suppressed; the pragma should name its reason inline. The grammar (and
the suppression semantics) are shared with the jaxguard analyzer — see
``tools.pragmas``.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable, Optional

from ..pragmas import allowed_lines, suppress

# Symbols whose import location (or existence) differs across supported JAX
# versions — resolved once in compat/jaxapi.py, nowhere else.
DRIFTED_JAX_SYMBOLS = frozenset({
    "shard_map",
    "AxisType",
    "axis_size",
    "pvary",
    "pcast",
    "make_mesh",
})

# Dotted call targets a test may not reach for (network egress).
_NETWORK_CALLS = frozenset({
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.delete",
    "requests.request",
    "http.client.HTTPConnection",
    "http.client.HTTPSConnection",
    "socket.create_connection",
})

# Filesystem probes that must not target literal /dev paths in tests.
_FS_PROBE_CALLS = frozenset({
    "open",
    "os.path.exists",
    "os.path.isfile",
    "os.path.isdir",
    "os.listdir",
    "os.stat",
    "os.scandir",
    "os.open",
    "Path",
    "pathlib.Path",
})

# Calls that fence JAX's async dispatch before a timer is read.
_TIMING_FENCES = frozenset({"block_until_ready", "device_get", "asarray", "array"})
_TIMER_CALLS = frozenset({"perf_counter", "monotonic", "time"})

ALL_RULES = {
    "JX001": "direct import of a version-drifted JAX symbol outside compat/",
    "JX002": "jax.experimental import outside compat/ without a pragma",
    "JX003": "float64 literal/dtype in TPU-path code (silently demoted on TPU)",
    "JX004": "timing loop without a dispatch fence (measures dispatch, not compute)",
    "JX005": "raw perf_counter timing in library code (use obs.span/obs.timer)",
    "TS001": "non-hermetic test pattern (hardcoded /dev/* probe or network call)",
}


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute/name chain → ``"a.b.c"`` (None if not a chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _walk_own_body(fn: ast.AST):
    """Yield ``fn``'s nodes EXCLUDING nested function/lambda bodies —
    ``ast.walk`` cannot be pruned, and for the timing rule a fence inside a
    nested callback must not excuse the enclosing function's unfenced
    timers (nested defs are checked on their own visit)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _scopes(path: str) -> dict[str, bool]:
    p = path.replace("\\", "/")
    in_compat = "/compat/" in p or p.startswith("compat/")
    base = p.rsplit("/", 1)[-1]
    return {
        "jx001": not in_compat and not p.startswith("tools/"),
        "jx002": (
            "kata_xpu_device_plugin_tpu/" in p or p.startswith(
                "kata_xpu_device_plugin_tpu"
            )
        ) and not in_compat,
        "jx003": any(
            f"kata_xpu_device_plugin_tpu/{d}/" in p
            for d in ("ops", "models", "parallel")
        ),
        "jx004": base.startswith("bench") or (
            "scripts/" in p and "bench" in base
        ) or ("eval" in base and "scripts/" in p),
        "jx005": (
            "kata_xpu_device_plugin_tpu/" in p
            or p.startswith("kata_xpu_device_plugin_tpu")
        ) and "/obs/" not in p and not p.startswith("obs/"),
        "ts001": "tests/" in p or p.startswith("tests"),
    }


class _Checker(ast.NodeVisitor):
    def __init__(self, path: str, scopes: dict[str, bool]):
        self.path = path
        self.scopes = scopes
        self.findings: list[Finding] = []

    def _add(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(
            Finding(self.path, getattr(node, "lineno", 1), rule, message)
        )

    # -- imports (JX001 / JX002) --------------------------------------------

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        if self.scopes["jx001"] and (mod == "jax" or mod.startswith("jax.")):
            for alias in node.names:
                if alias.name in DRIFTED_JAX_SYMBOLS:
                    self._add(
                        node, "JX001",
                        f"'from {mod} import {alias.name}' drifts across JAX "
                        "releases; import it from "
                        "kata_xpu_device_plugin_tpu.compat.jaxapi",
                    )
        if self.scopes["jx002"] and (
            mod.startswith("jax.experimental")
            or (mod == "jax" and any(a.name == "experimental" for a in node.names))
        ):
            self._add(
                node, "JX002",
                f"'from {mod} import ...' reaches into jax.experimental; "
                "shim it in compat/jaxapi.py or annotate "
                "'# lint: allow(JX002) <reason>'",
            )
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        if self.scopes["jx002"]:
            for alias in node.names:
                if alias.name.startswith("jax.experimental"):
                    self._add(
                        node, "JX002",
                        f"'import {alias.name}' reaches into jax.experimental; "
                        "shim it in compat/jaxapi.py or annotate "
                        "'# lint: allow(JX002) <reason>'",
                    )
        self.generic_visit(node)

    # -- attribute use of drifted symbols (JX001) ---------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.scopes["jx001"] and node.attr in DRIFTED_JAX_SYMBOLS:
            dotted = _dotted(node)
            if dotted and (
                dotted.startswith("jax.") or dotted.startswith("lax.")
            ):
                self._add(
                    node, "JX001",
                    f"'{dotted}' drifts across JAX releases; use the "
                    "kata_xpu_device_plugin_tpu.compat.jaxapi export",
                )
        if self.scopes["jx003"] and node.attr == "float64":
            self._add(
                node, "JX003",
                f"'{_dotted(node) or node.attr}' in TPU-path code: TPUs "
                "demote f64 to f32 silently — use float32/bfloat16",
            )
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if self.scopes["jx003"] and node.value == "float64":
            self._add(
                node, "JX003",
                "dtype string 'float64' in TPU-path code: TPUs demote f64 "
                "to f32 silently — use 'float32'/'bfloat16'",
            )
        self.generic_visit(node)

    # -- bench timing fences (JX004) ----------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_timing(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_timing(node)
        self.generic_visit(node)

    def _check_timing(self, fn: ast.AST) -> None:
        if not (self.scopes["jx004"] or self.scopes["jx005"]):
            return
        timers = fences = 0
        for sub in _walk_own_body(fn):
            if isinstance(sub, ast.Call):
                dotted = _dotted(sub.func) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if leaf in _TIMER_CALLS and (
                    dotted.startswith("time.")
                    or leaf in ("perf_counter", "monotonic")
                ):
                    # qualified time.* calls, plus the unambiguous bare
                    # spellings (`from time import perf_counter`); a bare
                    # `time()` stays unflagged — too generic a name.
                    timers += 1
                elif leaf in _TIMING_FENCES:
                    fences += 1
        if self.scopes["jx005"] and timers >= 2:
            # Library scope: a hand-rolled timing window is flagged even
            # when fenced — the measurement belongs in the telemetry
            # pipeline (obs.span/obs.timer fence AND emit).
            self._add(
                fn, "JX005",
                f"function '{getattr(fn, 'name', '?')}' hand-rolls a "
                "timing window in library code — use obs.span/obs.timer "
                "(they fence device dispatch and emit the measurement)",
            )
        elif self.scopes["jx004"] and timers >= 2 and fences == 0:
            self._add(
                fn, "JX004",
                f"function '{getattr(fn, 'name', '?')}' times a region but "
                "never fences dispatch (jax.block_until_ready / "
                "jax.device_get / np.asarray of the result) — it measures "
                "dispatch, not compute",
            )

    # -- test hermeticity (TS001) -------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if self.scopes["ts001"]:
            dotted = _dotted(node.func) or ""
            if dotted in _NETWORK_CALLS:
                self._add(
                    node, "TS001",
                    f"'{dotted}' in a test: tests must not reach the "
                    "network (fake the endpoint or mark/skip explicitly)",
                )
            if dotted in _FS_PROBE_CALLS:
                for arg in node.args:
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ) and arg.value.startswith("/dev/"):
                        self._add(
                            node, "TS001",
                            f"'{dotted}({arg.value!r})' probes a real device "
                            "node: tests must target a fake root (tmp_path)",
                        )
        self.generic_visit(node)


def check_source(
    src: str, path: str = "<string>", rules: Optional[Iterable[str]] = None
) -> list[Finding]:
    """Lint ``src`` as repo-relative ``path``. ``rules`` restricts to a
    subset of rule ids (default: all)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as err:
        return [
            Finding(path, err.lineno or 1, "E999", f"syntax error: {err.msg}")
        ]
    checker = _Checker(path, _scopes(path))
    checker.visit(tree)
    out = suppress(checker.findings, allowed_lines(src), rules)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def check_file(
    path: str, rel: Optional[str] = None, rules: Optional[Iterable[str]] = None
) -> list[Finding]:
    with open(path, encoding="utf-8") as fh:
        return check_source(fh.read(), rel or path, rules)
