"""Repo-specific static analysis: the JAX-drift and test-hermeticity rules
that turn this repo's known failure classes into PR-time lint errors.

Run as ``python -m tools.lint [paths...]`` (default: the whole repo).
Rule catalogue and rationale: ``docs/compat_and_lint.md``.
"""
from .rules import (
    ALL_RULES,
    DRIFTED_JAX_SYMBOLS,
    Finding,
    check_file,
    check_source,
)
from .cli import main, run

__all__ = [
    "ALL_RULES",
    "DRIFTED_JAX_SYMBOLS",
    "Finding",
    "check_file",
    "check_source",
    "main",
    "run",
]
