"""Shared inline-suppression grammar for the repo's static analyzers.

Both rule engines — ``tools.lint`` (JX/TS rules) and ``tools.analyze``
(jaxguard JG rules) — honor the same pragma shape::

    # lint: allow(JX002) pallas has no stable home
    # jaxguard: allow(JG101) admission host read is the sanctioned sync
    # lint: allow(JX004, JX005) wall-clock watchdog

``allow(RULE[, RULE...])`` takes any number of rule ids; the text after the
closing paren should name the reason (convention, not enforced). The tool
prefix is documentation — rule ids are globally unique (JX*/TS* belong to
lint, JG* to jaxguard), so either prefix suppresses either family and a
line carrying both tools' pragmas works with one or two comments.

This module is the ONE place the grammar and the suppression semantics
live: both engines call :func:`allowed_lines` on the source and
:func:`suppress` on their raw findings, so the per-rule filtering logic
cannot drift apart (it had already started to: the lint engine grew its
own regex and filter loop, and a second copy in the analyzer would have
been the third).
"""
from __future__ import annotations

import re
from typing import Iterable, Optional, Protocol

# One grammar for every engine: "<tool>: allow(RULES)". New engines add
# their prefix here, not a new regex.
PRAGMA_RE = re.compile(
    r"#\s*(?:lint|jaxguard):\s*allow\(([A-Z0-9, ]+)\)"
)


class _FindingLike(Protocol):
    rule: str
    line: int


def allowed_lines(src: str) -> dict[int, frozenset[str]]:
    """line number → rule ids allowed by inline pragmas on that line.

    Multiple pragmas on one line union (a line may carry both a
    ``# lint:`` and a ``# jaxguard:`` comment).
    """
    out: dict[int, frozenset[str]] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        rules: set[str] = set()
        for m in PRAGMA_RE.finditer(text):
            rules.update(r.strip() for r in m.group(1).split(",") if r.strip())
        if rules:
            out[i] = frozenset(rules)
    return out


def suppress(
    findings: Iterable[_FindingLike],
    allowed: dict[int, frozenset[str]],
    selected: Optional[Iterable[str]] = None,
) -> list:
    """Drop findings suppressed by ``allowed`` (from :func:`allowed_lines`)
    and, when ``selected`` is given, findings outside that rule subset.
    A pragma suppresses findings anchored to ITS OWN line.

    Parse failures (rule ``E999``) bypass the ``selected`` filter: a file
    the engine could not read at all is never "out of scope" of a rule
    selection — dropping it would report broken code as clean."""
    chosen = set(selected) if selected is not None else None
    out = []
    for f in findings:
        if chosen is not None and f.rule != "E999" and f.rule not in chosen:
            continue
        if f.rule in allowed.get(f.line, frozenset()):
            continue
        out.append(f)
    return out
