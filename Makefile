# Build/deploy targets for kata-xpu-device-plugin-tpu.
# (Pattern of the reference Makefile:1-16, with the broken image/binary name
# mismatch — ref Makefile:6 vs Dockerfile:65 — fixed by using one variable.)
NAME    := kata-tpu-device-plugin
VERSION := 0.1.0
IMAGE   := $(NAME):v$(VERSION)
PY      := python3

.PHONY: all build proto lint analyze census race verify-static test test-fast bench bench-smoke bench-load bench-trend bench-watch chaos tp decode-attn fused persistent kv-layout devledger eval eval-kv demo dryrun image clean deploy obs-check obs-report

all: build

build: proto
	$(PY) -m compileall -q kata_xpu_device_plugin_tpu

# Regenerate protobuf message modules from the authored .proto files.
# Generated *_pb2.py files are checked in so runtime/protoc are decoupled.
PROTOS := $(wildcard kata_xpu_device_plugin_tpu/plugin/api/*.proto)
proto:
ifneq ($(PROTOS),)
	protoc -Ikata_xpu_device_plugin_tpu/plugin/api \
	  --python_out=kata_xpu_device_plugin_tpu/plugin/api $(PROTOS)
endif

# Static analysis: the repo's own AST rules (JAX drift, hermeticity —
# always available), then ruff + mypy when installed. The repo rules are
# the gate that catches the class of bug that shipped the seed broken
# (drifted JAX imports crashing pytest collection); ruff/mypy deepen it
# where the toolchain has them. Strict scope (compat/, tools/lint) is
# configured in pyproject.toml.
lint:
	$(PY) -m tools.lint
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check kata_xpu_device_plugin_tpu/compat tools/lint && \
	  ruff check --exit-zero kata_xpu_device_plugin_tpu tests scripts bench.py; \
	else echo "lint: ruff not installed — skipped (pip install ruff)"; fi
	@if command -v mypy >/dev/null 2>&1; then \
	  mypy; \
	else echo "lint: mypy not installed — skipped (pip install mypy)"; fi

# jaxguard (ISSUE 4, extended ISSUE 16/19): interprocedural dataflow
# analysis over the package + bench/scripts — implicit host syncs on hot
# paths (JG101), use-after-donation (JG102), tracer leaks (JG103),
# recompile hazards (JG104), daemon lock discipline (JG201-JG203), the
# five-leg ENV_* knob contract (JG301-JG304), and the dispatch-surface
# contract (JG401 census, JG402 donation completeness, JG403 sharding
# coverage, JG404 stale pragmas). The JSON report is the CI artifact
# (and the --baseline ratchet input); exit 1 on any unsuppressed
# finding. Pure-stdlib AST analysis: no jax import, runs anywhere.
analyze:
	$(PY) -m tools.analyze --json jaxguard_report.json

# Steady-state compile/reshard tripwire gate (ISSUE 19): the runtime
# twin of the JG4xx census — the tripwire suite on the forced-8-device
# host (compile_tripwire units, warmup-then-steady drains across
# slotted/strict-fused/paged-tp2 servers asserting ZERO new XLA
# compiles and ZERO unsanctioned reshards, the exact-mode negative
# control proving the counter counts, greedy bit-identity tripwire
# on/off), with and without KATA_TPU_STRICT=1; obs JSONL artifacts
# (serving heartbeats carry tripwire_warmed / steady_state_*) uploaded.
census:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/census_events.jsonl \
	  $(PY) -m pytest tests/test_census.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/census_events_strict.jsonl \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_census.py -q

# Runtime race harness (ISSUE 16): the dynamic twin of the JG2xx pass —
# barrier-driven N threads × M ops stress over the allocation journal,
# the heartbeat aggregator, the flight ring, and the metrics registry,
# asserting parse-back integrity and counter conservation across 200+
# seeded iterations, then again under KATA_TPU_STRICT=1. jax-free (the
# structures under stress are the host daemon's); event-stream artifacts
# of the last iteration land in artifacts/ for CI upload.
race:
	RACE_ARTIFACTS=artifacts $(PY) tests/race_harness.py
	KATA_TPU_STRICT=1 RACE_ITERS=50 RACE_ARTIFACTS= $(PY) tests/race_harness.py

# The whole static gate in one target: lint rules, telemetry rules + obs
# unit tests, and the jaxguard dataflow pass. CI runs the pieces
# separately (artifact uploads); this is the pre-push spelling.
verify-static: lint obs-check analyze

# Telemetry gate (ISSUE 2): the JX005 rule (raw perf_counter timing in
# library code must go through obs.span/obs.timer) plus the obs unit
# tests (spans, registry, sinks, profiler hook, lint fixtures). The obs
# test run itself streams into an event file — the tier-1 timing
# artifact CI uploads.
obs-check:
	$(PY) -m tools.lint --rule JX005
	JAX_PLATFORMS=cpu KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/obs_check_events.jsonl \
	  $(PY) -m pytest tests/test_obs.py tests/test_lint.py -q

# Offline-reporter smoke gate (ISSUE 15): generate a fresh instrumented
# serving burst (heartbeats, request traces, spans), render it through
# tools/obs_report.py (markdown + JSON), and FAIL on report-schema drift
# (--check also demands a non-empty phase waterfall and heartbeat
# section — an empty report from a fresh stream is drift upstream of
# the schema). Wired into CI next to the chaos/kv-layout jobs.
obs-report:
	JAX_PLATFORMS=cpu $(PY) -m tools.obs_report --generate \
	  artifacts/obs_report_smoke_events.jsonl
	$(PY) -m tools.obs_report artifacts/obs_report_smoke_events.jsonl \
	  --md artifacts/obs_report_smoke.md \
	  --json artifacts/obs_report_smoke.json --check --quiet

test:
	$(PY) -m pytest tests/ -x -q

test-fast:
	$(PY) -m pytest tests/ -x -q -m "not slow"

bench:
	$(PY) bench.py

# Harness validation in seconds (ISSUE 3): smoke-tiny shapes on CPU with
# the persistent compilation cache on and the obs JSONL stream captured —
# the serving section's overlap-vs-lockstep A/B, the shared-prefix
# serving A/B (ISSUE 5: serving_prefix_* vs serving_prefix_cold_* — TTFT
# speedup, hit ratio, reused-token fraction), the oversubscribed
# paged-vs-slotted A/B (ISSUE 6: serving_paged_* vs
# serving_paged_slotted_* — more queued requests than the legacy slot
# count, TTFT/inter-token p50/p99, preemptions), and the compile/prefill/
# decode phase breakdown all land in the emitted line; CI uploads
# bench_smoke_events.jsonl next to the tier-1 timing artifact. The number
# printed is NOT the headline metric.
bench-smoke:
	JAX_PLATFORMS=cpu KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/bench_smoke_events.jsonl \
	KATA_TPU_COMPILE_CACHE_DIR=$${KATA_TPU_COMPILE_CACHE_DIR:-.cache/xla-compile} \
	XLA_FLAGS="$${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
	  $(PY) bench.py --smoke

# Latency-under-load sweep alone (ISSUE 8): the serving_load_* section —
# open-loop Poisson arrivals at 0.5×/1.5×/3× measured capacity, TTFT +
# inter-token p50/p99 per rate, fifo_batch vs slo_chunked admission —
# with every other side section off, so the result line is the sweep.
# CI's bench-smoke job runs the same sweep as part of the full smoke and
# uploads the result lines + events JSONL as artifacts.
bench-load:
	JAX_PLATFORMS=cpu KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/bench_load_events.jsonl \
	KATA_TPU_COMPILE_CACHE_DIR=$${KATA_TPU_COMPILE_CACHE_DIR:-.cache/xla-compile} \
	KATA_TPU_BENCH_INT8=0 KATA_TPU_BENCH_SERVING=0 KATA_TPU_BENCH_SOFTCAP=0 \
	KATA_TPU_BENCH_TRAIN=0 KATA_TPU_BENCH_PREFIX=0 KATA_TPU_BENCH_PAGED=0 \
	KATA_TPU_BENCH_FAULTS=0 KATA_TPU_BENCH_SPEC=0 KATA_TPU_BENCH_TP=0 \
	KATA_TPU_BENCH_DEGRADED=0 KATA_TPU_BENCH_OBS=0 KATA_TPU_BENCH_FUSED=0 \
	KATA_TPU_BENCH_KV=0 \
	  $(PY) bench.py --smoke

# Bench-bank trend (ISSUE 11 satellite): compare the two newest
# BENCH_TPU_*.json banks, print per-metric deltas, flag >10% headline
# regressions (exit 1). decode tok/s/chip sat at 1303.8 across the whole
# bank unnoticed — this makes the trajectory visible. CI runs it
# non-blocking: a bench regression is a flag to read, not a merge gate.
bench-trend:
	$(PY) -m tools.bench_trend

# Chaos gate (ISSUE 7): the serving test subset under a FIXED seeded
# fault schedule injected through the same KATA_TPU_FAULTS env the
# daemon's chaos knob rides. Every test must still pass — scheduled
# entries that a given test's workload reaches fire (and the recovery
# supervisor must make them invisible); the rest stay pending. Runs
# twice, with and without KATA_TPU_STRICT=1, so recovery's rebuild path
# is also transfer-guard-clean; the obs JSONL stream is the CI artifact.
# Seam rounds are chosen past the small fixtures' natural counts for the
# tiny tests and inside them for the serving matrices — the point is one
# REPLAYABLE schedule, not maximal carnage. The sched_tick entry (ISSUE 8)
# fires at a chunked-prefill slice boundary in every scheduler-test server
# that crosses it, so recovery × chunked-prefill replay (mid-chunk fault →
# strict-FIFO requeue from the prompt) runs under BOTH strict modes.
chaos:
	rm -rf artifacts/chaos_flight_dumps
	JAX_PLATFORMS=cpu KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_events.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="decode_dispatch:5,fence:7:hang,prefill:3,sched_tick:2" \
	KATA_TPU_FAULTS_SEED=13 \
	  $(PY) -m pytest tests/test_recovery.py tests/test_serving.py \
	    tests/test_serving_pipeline.py tests/test_scheduler.py -q
	JAX_PLATFORMS=cpu KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_events_strict.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="decode_dispatch:5,fence:7:hang,prefill:3,sched_tick:2" \
	KATA_TPU_FAULTS_SEED=13 KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_recovery.py tests/test_serving.py \
	    tests/test_serving_pipeline.py tests/test_scheduler.py -q
	# Chip-loss schedule at tp=4 (ISSUE 10): the degraded-mode suite under
	# the PERMANENT fault kinds — the tp=4 server must shrink to tp=2
	# mid-run, finish the burst bit-identically, and the daemon half
	# (quarantine events, allocation-journal reconcile) must stay green —
	# with and without KATA_TPU_STRICT=1 (the shrink's re-shard path runs
	# under allow_transfer and must stay transfer-guard-clean).
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_chiploss_events.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="decode_dispatch:3:chip_loss:1" KATA_TPU_FAULTS_SEED=13 \
	  $(PY) -m pytest tests/test_degraded.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_chiploss_events_strict.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="decode_dispatch:3:chip_loss:1" KATA_TPU_FAULTS_SEED=13 \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_degraded.py -q
	# Fused × multi-step chaos (ISSUE 13): decode_dispatch faults land
	# MID-MULTI-STEP — every server in the fused suite that reaches round
	# 4 is running chunk × K dispatches (the node-injected K=2 below;
	# explicit-K tests override it), so the fault interrupts a dispatch
	# carrying K decode steps (and, in the fused tests, an admission
	# slice) and recovery must keep outputs bit-identical — both strict
	# modes. sched_tick:3 additionally fires at a fused slice's dispatch
	# prep.
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_fused_events.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="decode_dispatch:4,sched_tick:3" KATA_TPU_FAULTS_SEED=13 \
	KATA_TPU_DECODE_STEPS=2 \
	  $(PY) -m pytest tests/test_fused_decode.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_fused_events_strict.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="decode_dispatch:4,sched_tick:3" KATA_TPU_FAULTS_SEED=13 \
	KATA_TPU_DECODE_STEPS=2 KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_fused_decode.py -q
	# Persistent-decode chaos (ISSUE 20): decode_dispatch faults land
	# MID-WHILE_LOOP — under the node-injected KATA_TPU_PERSISTENT=1
	# every eligible server in the persistent suite runs its decode
	# rounds as one while_loop dispatch (explicit-knob tests override
	# it), so the fault discards a round whose delivered count was never
	# fenced and recovery must replay from the prompt bit-identically —
	# both strict modes. sched_tick:3 fires at a fused slice boundary in
	# the fused × persistent composition test.
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_persistent_events.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="decode_dispatch:4,sched_tick:3" KATA_TPU_FAULTS_SEED=13 \
	KATA_TPU_PERSISTENT=1 \
	  $(PY) -m pytest tests/test_persistent_decode.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_persistent_events_strict.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="decode_dispatch:4,sched_tick:3" KATA_TPU_FAULTS_SEED=13 \
	KATA_TPU_PERSISTENT=1 KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_persistent_decode.py -q
	# KV layout chaos (ISSUE 14): pool_alloc faults land MID-DEMOTION —
	# the pool_alloc seam fires inside the allocation pressure path that
	# drives host-tier demotions — under the node-injected blocks layout,
	# and fence faults interrupt rounds whose resume prefetch is staged;
	# recovery must keep outputs bit-identical and none vanish under
	# drain — both strict modes.
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_kv_events.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="pool_alloc:4,fence:6" KATA_TPU_FAULTS_SEED=13 \
	KATA_TPU_KV_LAYOUT=blocks \
	  $(PY) -m pytest tests/test_kv_layout.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_kv_events_strict.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_FAULTS="pool_alloc:4,fence:6" KATA_TPU_FAULTS_SEED=13 \
	KATA_TPU_KV_LAYOUT=blocks KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_kv_layout.py -q
	# Watchdog chaos (ISSUE 15): the heartbeat/watchdog suite — its
	# chip_loss integration test drives the breach → watchdog flight
	# dump → recovery-clears-alert sequence with an explicit seeded
	# injector (deterministic; the env schedule must not double-fault
	# it), so the pinned KATATPU_FLIGHT_DIR collects a
	# katatpu_flight_watchdog_* postmortem as the chaos artifact — both
	# strict modes.
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_watchdog_events.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	  $(PY) -m pytest tests/test_watchdog.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/chaos_watchdog_events_strict.jsonl \
	KATATPU_FLIGHT_DIR=artifacts/chaos_flight_dumps \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_watchdog.py -q

# Tensor-parallel serving gate (ISSUE 9): the tp suite — topology-env →
# guest-mesh round trip, the tp=N ≡ tp=1 greedy-identity matrix
# (paged/slotted × overlap × prefix-hit), crash recovery over a sharded
# pool, the raise-vs-degrade knob contract — on the virtual 8-device CPU
# host, with and without KATA_TPU_STRICT=1 (the sharded decode window
# must stay transfer-guard-clean too).
tp:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/tp_events.jsonl \
	  $(PY) -m pytest tests/test_tp_serving.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/tp_events_strict.jsonl \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_tp_serving.py -q

# Paged-native decode-attention gate (ISSUE 12): the kernel suite —
# interpret-mode oracle vs xla_reference across ragged/boundary blocks,
# the int8 fused-dequant bit-match, tp=2/4 shard_map identity on the
# virtual 8-device host, and the serving bit-identity matrix re-run with
# the kernel selected — with and without KATA_TPU_STRICT=1 (the kernel
# dispatch window must stay transfer-guard-clean too).
decode-attn:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/decode_attn_events.jsonl \
	  $(PY) -m pytest tests/test_decode_attn_paged.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/decode_attn_events_strict.jsonl \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_decode_attn_paged.py -q

# KV layout + host-tier gate (ISSUE 14): the layout/offload suite on
# the forced-8-device host — heads/blocks/tp=1 greedy bit-identity
# across paged × int8/bf16 × overlap/lockstep × prefix-hit ×
# preemption, the int8 spill/restore round-trip at tp>1, the
# oversubscription matrix (demotion-before-preemption ordering, resume
# prefetch racing the decode dispatch, degraded mesh shrink re-placing
# a block-sharded pool), and the knob raise-vs-degrade contract — with
# and without KATA_TPU_STRICT=1 (demotion D2H / prefetch H2D must ride
# sanctioned allow_transfer paths only); obs JSONL artifacts uploaded.
kv-layout:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/kv_layout_events.jsonl \
	  $(PY) -m pytest tests/test_kv_layout.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/kv_layout_events_strict.jsonl \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_kv_layout.py -q

# Fused scheduling & multi-step decode gate (ISSUE 13): the fused suite
# on the forced-8-device host — the bit-identity matrix (fused vs
# sequential admission, decode_steps K ∈ {1,2,8}) across paged/slotted ×
# overlap/lockstep × tp{1,2} × prefix-hit × mid-scan EOS × seeded fault
# schedules with recovery, the knob degrade/raise contract, and the
# always-present stats/counter schema — with and without
# KATA_TPU_STRICT=1 (the fused dispatch window must stay
# transfer-guard-clean too).
fused:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/fused_events.jsonl \
	  $(PY) -m pytest tests/test_fused_decode.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/fused_events_strict.jsonl \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_fused_decode.py -q

# Persistent decode gate (ISSUE 20): the while_loop-round suite on the
# forced-2-device host — the persistent ≡ lockstep-K=1 greedy-identity
# matrix (paged/slotted × tp{1,2} × fused admissions × tp-overlap),
# executable-level cap/window early-exit bounds, the exit-reason
# partition (cap/done/window ↔ persistent_exit events), seeded
# mid-while_loop fault replay, the knob degrade/raise contract for
# KATA_TPU_PERSISTENT and KATA_TPU_TP_OVERLAP, and the always-present
# stats/heartbeat schema — with and without KATA_TPU_STRICT=1 (the
# persistent fence reads only the delivered count and the trimmed
# tokens; the dispatch window must stay transfer-guard-clean too).
persistent:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/persistent_events.jsonl \
	  $(PY) -m pytest tests/test_persistent_decode.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/persistent_events_strict.jsonl \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_persistent_decode.py -q

# Device-utilization & HBM ledger gate (ISSUE 17): the ledger suite on
# the forced-8-device host — once-per-signature cost capture and MFU
# math, dispatch-gap phase attribution summing exactly to the measured
# gap, memory degrade-by-omission (`hbm_stats_unavailable` once, never
# fake zeros), the device_idle / hbm_headroom_collapse watchdog rules
# with their self-disarm matrix, the profiler double-start fix
# (`profiler_busy` instead of a crash), greedy bit-identity ledger
# on/off, and the aggregator's omission-preserving re-export — with and
# without KATA_TPU_STRICT=1 (the ledger is host arithmetic only; the
# instrumented dispatch window must stay transfer-guard-clean too).
devledger:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/devledger_events.jsonl \
	  $(PY) -m pytest tests/test_devledger.py -q
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	KATATPU_OBS=1 KATATPU_OBS_FILE=artifacts/devledger_events_strict.jsonl \
	KATA_TPU_STRICT=1 \
	  $(PY) -m pytest tests/test_devledger.py -q

# int8-KV promotion gate (ISSUE 12): pooled greedy agreement + first-
# decode-step logit drift vs the bf16 oracle on a fixed prompt set —
# the quality check behind the GenerationServer int8-KV default (exit 1
# on a failing gate; KATA_TPU_KV_QUANT=bf16 is the node-wide opt-out).
eval-kv:
	JAX_PLATFORMS=cpu $(PY) -m tools.eval_quality --cpu

# Opportunistic TPU bench: probe the tunnel every few minutes and run the
# full bench on the first healthy probe, banking a dated committed JSON
# (see scripts/bench_when_healthy.py for why end-of-round-only is not enough).
bench-watch:
	$(PY) scripts/bench_when_healthy.py

# Quantization quality ladder (bf16 vs int8 vs W8A8 vs int8-KV): the
# measurement ops/quant.py's W8A8 docstring prescribes before production.
# On the attached TPU: python scripts/eval_quality.py --config gemma2_2b --dtype bfloat16
eval:
	$(PY) scripts/eval_quality.py --cpu

# End-to-end user journey (train -> preempt -> resume -> LoRA -> merge ->
# quantize -> speculative serving) on the virtual 8-device CPU mesh; drop
# the env pins to run on attached TPU hardware.
demo:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  $(PY) scripts/train_demo.py

# The driver's multi-chip validation, runnable locally: all parallelism
# axes + serving verified on an 8-device virtual CPU mesh.
dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  $(PY) -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

image:
	docker build -t $(IMAGE) .

deploy:
	kubectl apply -f deploy/kata-tpu-device-plugin.yaml

clean:
	rm -rf build dist *.egg-info
	find . -name __pycache__ -type d -exec rm -rf {} +
