"""Tensor-parallel serving over the ICI slice (ISSUE 9).

Three contracts under test, on the virtual 8-device CPU host:

1. The daemon↔guest topology handoff: ``topology.runtime_env`` emission
   → ``guest.tp_serving.tp_from_env`` → ``serving_mesh`` round-trips for
   every family × sub-slice shape; preferred-allocation hints are
   guest-meshable; the ``KATA_TPU_TP`` override rides the allocator env
   path and malformed values degrade with a ``tp_disabled`` event.
2. The serving regex partition rules cover every model family in
   ``models/`` in every serving layout (training, fused, int8, LoRA).
3. Bit-identity — the only oracle that matters: ``GenerationServer
   (tp=N)`` greedy outputs equal ``tp=1`` across paged/slotted × overlap
   × prefix-hit × kv_quant, under preemption spills, and under a seeded
   fault schedule with checkpointed recovery (strict mode rides the
   ``make tp`` second pass via ``KATA_TPU_STRICT=1``).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest import tp_serving
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params
from kata_xpu_device_plugin_tpu.parallel.mesh import AXIS_MODEL
from kata_xpu_device_plugin_tpu.parallel.sharding import (
    SERVING_RULES,
    match_partition_rules,
    serving_param_specs,
)
from kata_xpu_device_plugin_tpu.topology import (
    FAMILIES,
    HostTopology,
    choose_chips,
    guest_meshable_counts,
    runtime_env,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=1, shared=0):
    key = jax.random.PRNGKey(seed)
    head = np.asarray(
        jax.random.randint(key, (shared,), 0, cfg.vocab_size), np.int32
    ) if shared else np.zeros((0,), np.int32)
    out = []
    for i, n in enumerate(lengths):
        tail = np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (n,), 0, cfg.vocab_size
        ), np.int32)
        out.append(np.concatenate([head, tail]))
    return out


def _serve(params, cfg, prompts, budgets=8, **kw):
    srv = GenerationServer(params, cfg, **kw)
    if isinstance(budgets, int):
        budgets = [budgets] * len(prompts)
    rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
    res = srv.run()
    return [res[r] for r in rids], srv


def _capture_events(tmp_path, fn, name="ev.jsonl"):
    sink = obs.EventSink(str(tmp_path / name))
    prev = obs.set_default_sink(sink)
    try:
        result = fn()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    return result, obs.read_events(str(tmp_path / name))


# ----- topology env → tp degree → mesh -------------------------------------


def test_tp_from_env_ladder(monkeypatch):
    # Nothing injected: single-chip.
    assert tp_serving.tp_from_env(env={}) == 1
    # TPU_VISIBLE_CHIPS length is the default degree.
    assert tp_serving.tp_from_env(env={"TPU_VISIBLE_CHIPS": "0,1,2,3"}) == 4
    # Accelerator type falls back to the host-local chip count.
    assert tp_serving.tp_from_env(
        env={"TPU_ACCELERATOR_TYPE": "v5litepod-8"}
    ) == 8
    # KATA_TPU_TP overrides the derived degree; 0/1 pins single-chip.
    env = {"TPU_VISIBLE_CHIPS": "0,1,2,3", "KATA_TPU_TP": "2"}
    assert tp_serving.tp_from_env(env=env) == 2
    assert tp_serving.tp_from_env(
        env={**env, "KATA_TPU_TP": "1"}
    ) == 1
    assert tp_serving.tp_from_env(
        env={**env, "KATA_TPU_TP": "0"}
    ) == 1


def test_tp_from_env_malformed_and_infeasible_degrade(tmp_path):
    # Malformed override: degrade to the DERIVED degree with an event.
    got, events = _capture_events(
        tmp_path,
        lambda: tp_serving.tp_from_env(
            env={"TPU_VISIBLE_CHIPS": "0,1", "KATA_TPU_TP": "lots"},
            label="s1",
        ),
    )
    assert got == 2
    evs = [e for e in events if e.get("name") == "tp_disabled"]
    assert len(evs) == 1 and evs[0]["reason"].startswith("bad_env")
    # More chips promised than devices visible: degrade to 1 with an event.
    got, events = _capture_events(
        tmp_path,
        lambda: tp_serving.tp_from_env(
            env={"KATA_TPU_TP": "64"}, label="s1",
        ),
        name="ev2.jsonl",
    )
    assert got == 1
    evs = [e for e in events if e.get("name") == "tp_disabled"]
    assert len(evs) == 1
    assert evs[0]["reason"].startswith("insufficient_devices")


def test_topology_env_roundtrip_every_family_subslice():
    """The daemon↔guest contract: for every family × requestable
    sub-slice, the exact env block ``topology.runtime_env`` emits
    resolves to the granted chip count and brings up a mesh of exactly
    that size (CPU devices standing in for the chips)."""
    for fam in FAMILIES.values():
        for count in sorted(fam.subslices):
            if count > jax.device_count():
                continue
            suffix = count * 2 if fam.suffix_counts_cores else count
            topo = HostTopology.from_accelerator_type(
                f"{fam.name}-{suffix}"
            )
            env = runtime_env(topo, visible_chips=list(range(count)))
            tp = tp_serving.tp_from_env(env=env)
            assert tp == count, (fam.name, count)
            mesh = tp_serving.serving_mesh(tp)
            assert mesh.shape[AXIS_MODEL] == count
            assert mesh.devices.size == count


def test_serving_mesh_shape_and_validation():
    mesh = tp_serving.serving_mesh(4)
    assert mesh.shape[AXIS_MODEL] == 4
    assert mesh.devices.size == 4
    with pytest.raises(ValueError, match="tp must be"):
        tp_serving.serving_mesh(0)
    with pytest.raises(ValueError, match="devices"):
        tp_serving.serving_mesh(1 + jax.device_count())


def test_preferred_hints_are_guest_meshable():
    """Allocation-hint consistency (ISSUE 9): every ICI-contiguous
    placement GetPreferredAllocation can prefer has a size the guest can
    mesh, and every meshable count yields a contiguous placement on an
    empty host."""
    for fam in FAMILIES.values():
        suffix = (
            fam.chips_per_host * 2 if fam.suffix_counts_cores
            else fam.chips_per_host
        )
        topo = HostTopology.from_accelerator_type(f"{fam.name}-{suffix}")
        meshable = guest_meshable_counts(topo)
        assert meshable == topo.valid_request_counts()
        available = list(range(fam.chips_per_host))
        for count in meshable:
            placement = choose_chips(topo, available, count)
            assert placement.contiguous, (fam.name, count)
            assert len(placement.chips) == count
            # The guest can mesh exactly this grant (device count
            # permitting on the CPU stand-in host).
            if count <= jax.device_count():
                assert tp_serving.serving_mesh(count).devices.size == count


def test_allocator_injects_tp_env_and_config_validates():
    from kata_xpu_device_plugin_tpu.cdi import constants as C
    from kata_xpu_device_plugin_tpu.config import Config
    from kata_xpu_device_plugin_tpu.discovery.tpu import (
        TpuChip,
        TpuInventory,
    )
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator

    inv = TpuInventory(
        chips=(TpuChip(index=0, dev_path="/dev/accel0"),
               TpuChip(index=1, dev_path="/dev/accel1")),
        topology=HostTopology.from_accelerator_type("v5litepod-8"),
        model_suffix="TPU_V5E",
    )
    alive = lambda _chip: True  # noqa: E731 — no real /dev in this test
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive, serving_tp=2,
    ).allocate(["0", "1"])
    assert wired.envs[C.ENV_SERVING_TP] == "2"
    bare = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive
    ).allocate(["0"])
    assert C.ENV_SERVING_TP not in bare.envs
    assert Config(serving_tp=4).serving_tp == 4
    assert Config().serving_tp == 0
    with pytest.raises(ValueError, match="serving-tp"):
        Config(serving_tp=-1)


# ----- partition rules over every family / layout ---------------------------


def test_serving_rules_cover_every_model_family():
    from kata_xpu_device_plugin_tpu.models import (
        gemma2_test_config,
        gemma3_test_config,
        mistral_test_config,
        mixtral_test_config,
        qwen2_test_config,
    )

    for make in (tiny_test_config, gemma2_test_config, gemma3_test_config,
                 mistral_test_config, qwen2_test_config,
                 mixtral_test_config):
        cfg = make()
        shapes = jax.eval_shape(
            lambda cfg=cfg: init_params(jax.random.PRNGKey(0), cfg)
        )
        specs = serving_param_specs(shapes)  # raises on any uncovered path
        flat = dict(_walk(specs))
        # Embeddings replicated, attention/MLP wide axes over model.
        assert AXIS_MODEL not in _axes(flat["embed"])
        assert AXIS_MODEL in _axes(flat["layers.wq"])
        if "layers.w_down" in flat:
            assert AXIS_MODEL in _axes(flat["layers.w_down"])
        if "layers.moe_w_out" in flat:
            assert AXIS_MODEL in _axes(flat["layers.moe_w_out"])


def _walk(tree, prefix=""):
    for k, v in tree.items():
        path = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            yield from _walk(v, path)
        else:
            yield path, v


def _axes(spec):
    import itertools

    def flat(entry):
        return entry if isinstance(entry, tuple) else (entry,)

    try:
        parts = tuple(spec)
    except TypeError:  # QTensor/LoRA wrapper: collect every inner spec
        parts = tuple(itertools.chain.from_iterable(tuple(s) for s in spec))
    return set(itertools.chain.from_iterable(flat(p) for p in parts))


def test_serving_rules_cover_inference_layouts(model):
    from kata_xpu_device_plugin_tpu.ops.lora import apply_lora
    from kata_xpu_device_plugin_tpu.ops.quant import quantize_decoder_params
    from kata_xpu_device_plugin_tpu.models.transformer import (
        fuse_decoder_params,
    )

    cfg, params = model
    for name, p in {
        "fused": fuse_decoder_params(params),
        "fused_int8": quantize_decoder_params(fuse_decoder_params(params)),
        "lora": apply_lora(params, jax.random.PRNGKey(7), rank=2),
    }.items():
        specs = serving_param_specs(p)  # raises on any uncovered path
        assert specs is not None, name


def test_match_partition_rules_unmatched_raises():
    with pytest.raises(ValueError, match="no serving partition rule"):
        match_partition_rules(
            SERVING_RULES, {"layers": {"w_mystery": np.zeros((2, 4))}}
        )
    # Scalars replicate without needing a rule.
    specs = match_partition_rules(SERVING_RULES, {"t": np.zeros(())})
    assert tuple(specs["t"]) == ()


# ----- bit-identity: tp=N ≡ tp=1 -------------------------------------------


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_identity_slotted(model, tp):
    cfg, params = model
    prompts = _prompts(cfg, [4, 9, 6], seed=6)
    ref, _ = _serve(params, cfg, prompts, max_batch=2, max_len=32)
    out, srv = _serve(params, cfg, prompts, max_batch=2, max_len=32, tp=tp)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    assert srv.stats()["tp_degree"] == tp


def test_tp_identity_lockstep_and_kv_quant(model):
    cfg, params = model
    prompts = _prompts(cfg, [5, 7], seed=9)
    for kw in ({"overlap": False}, {"kv_quant": True}):
        ref, _ = _serve(params, cfg, prompts, max_batch=2, max_len=32, **kw)
        out, _ = _serve(
            params, cfg, prompts, max_batch=2, max_len=32, tp=2, **kw
        )
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o, r, err_msg=str(kw))


def test_tp_identity_paged_pool(model, tmp_path):
    """The flipped matrix row: paged × tp serves (head-sharded pool), no
    kv_pool_disabled event, greedy identical to the single-chip pool."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 9, 6, 8], seed=12)
    kw = dict(max_batch=2, max_len=32, prefill_buckets=(16,),
              kv_pool_tokens=512)
    ref, ref_srv = _serve(params, cfg, prompts, **kw)
    assert ref_srv.paged

    def run_tp():
        return _serve(params, cfg, prompts, tp=2, **kw)

    (out, srv), events = _capture_events(tmp_path, run_tp)
    assert srv.paged and srv.kv_pool is not None
    assert not [e for e in events if e.get("name") == "kv_pool_disabled"]
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["tp_degree"] == 2
    assert len(st["kv_pool_shard_occupancy"]) == 2


def test_legacy_mesh_kwarg_now_composes_with_pool(model):
    from kata_xpu_device_plugin_tpu.parallel import build_mesh

    cfg, params = model
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    prompts = _prompts(cfg, [4, 7], seed=13)
    kw = dict(max_batch=2, max_len=32, prefill_buckets=(16,),
              kv_pool_tokens=512)
    ref, _ = _serve(params, cfg, prompts, **kw)
    out, srv = _serve(params, cfg, prompts, mesh=mesh, **kw)
    assert srv.paged  # was kv_pool_disabled(reason="mesh") before ISSUE 9
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_tp_identity_prefix_hits(model):
    """Prefix-store reuse at tp=2 (standalone store AND pool tier) stays
    bit-identical to tp=1, with the second wave actually hitting."""
    cfg, params = model
    shared = _prompts(cfg, [6, 9, 5, 8], seed=21, shared=16)
    for extra in ({"prefix_cache_tokens": 256},
                  {"prefix_cache_tokens": 256, "kv_pool_tokens": 512}):
        kw = dict(max_batch=2, max_len=48, prefill_buckets=(16, 32), **extra)
        ref, ref_srv = _serve(params, cfg, shared, **kw)
        out, srv = _serve(params, cfg, shared, tp=2, **kw)
        assert srv.stats()["prefix_hits"] >= 1, extra
        assert srv.stats()["prefix_hits"] == ref_srv.stats()["prefix_hits"]
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o, r, err_msg=str(extra))


def test_tp_identity_slo_chunked_scheduler(model):
    cfg, params = model
    prompts = _prompts(cfg, [14, 15, 13], seed=23)
    kw = dict(max_batch=2, max_len=48, prefill_buckets=(16,),
              sched_policy="slo_chunked", prefill_chunk=4, itl_slo_ms=0.001)
    ref, _ = _serve(params, cfg, prompts, **kw)
    out, srv = _serve(params, cfg, prompts, tp=2, **kw)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_tp_preemption_spill_restore_identity(model):
    """Pool pressure at tp=2: the youngest lane spills (per-shard gather
    through the sanctioned slow path), requeues FIFO, and restores with
    identical sharding — outputs equal the unpressured tp=1 run."""
    cfg, params = model
    prompts = _prompts(cfg, [12, 12, 12], seed=31)
    base = dict(max_batch=3, max_len=32, prefill_buckets=(16,),
                kv_block_size=8)
    ref, _ = _serve(params, cfg, prompts, kv_pool_tokens=1024, **base)
    tight = 16 * 5  # holds ~1.5 requests: forces preemption under growth
    out, srv = _serve(params, cfg, prompts, tp=2, kv_pool_tokens=tight,
                      **base)
    assert srv.stats()["preemptions"] >= 1
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_tp_crash_recovery_identity(model):
    """The seeded-fault acceptance criterion: a transient decode fault at
    tp=2 over a sharded paged pool — with host checkpoints riding the
    per-shard allow_transfer gather — recovers to outputs bit-identical
    to a fault-free tp=1 run."""
    from kata_xpu_device_plugin_tpu.guest.resilience import (
        FaultInjector,
        FaultSpec,
    )

    cfg, params = model
    prompts = _prompts(cfg, [6, 9, 5], seed=41)
    kw = dict(max_batch=2, max_len=48, prefill_buckets=(16,),
              kv_pool_tokens=512, checkpoint_rounds=2,
              recovery_backoff_s=0.0)
    ref, _ = _serve(params, cfg, prompts, budgets=12, **kw)
    for schedule in ([FaultSpec("decode_dispatch", 2)],
                     [FaultSpec("prefill", 1)]):
        srv = GenerationServer(
            params, cfg, tp=2,
            fault_injector=FaultInjector(schedule, seed=13), **kw,
        )
        rids = [srv.submit(p, 12) for p in prompts]
        res = srv.run()
        assert srv.stats()["recoveries"] >= 1, schedule
        assert srv.stats()["tp_degree"] == 2
        for r, rid in zip(ref, rids):
            np.testing.assert_array_equal(res[rid], r, err_msg=str(schedule))


def test_tp_slotted_checkpoint_recovery_identity(model):
    from kata_xpu_device_plugin_tpu.guest.resilience import (
        FaultInjector,
        FaultSpec,
    )

    cfg, params = model
    prompts = _prompts(cfg, [6, 9], seed=43)
    kw = dict(max_batch=2, max_len=32, checkpoint_rounds=1,
              recovery_backoff_s=0.0)
    ref, _ = _serve(params, cfg, prompts, budgets=10, **kw)
    srv = GenerationServer(
        params, cfg, tp=2,
        fault_injector=FaultInjector([FaultSpec("decode_dispatch", 1)],
                                     seed=7), **kw,
    )
    rids = [srv.submit(p, 10) for p in prompts]
    res = srv.run()
    assert srv.stats()["recoveries"] >= 1
    for r, rid in zip(ref, rids):
        np.testing.assert_array_equal(res[rid], r)


# ----- knob contract: raise vs degrade -------------------------------------


def test_tp_incompatible_modes_raise_on_explicit_arg(model):
    from kata_xpu_device_plugin_tpu.models import mistral_test_config

    cfg, params = model
    with pytest.raises(ValueError, match="speculative"):
        GenerationServer(params, cfg, max_batch=2, max_len=32, tp=2,
                         speculative_k=2, spec_opt_in=True)
    mcfg = mistral_test_config(dtype=jnp.float32)
    mparams = init_params(jax.random.PRNGKey(4), mcfg, dtype=jnp.float32)
    with pytest.raises(ValueError, match="ring_kv"):
        GenerationServer(mparams, mcfg, max_batch=2, max_len=64, tp=2,
                         ring_kv=True)
    with pytest.raises(ValueError, match="tp must be"):
        GenerationServer(params, cfg, max_batch=2, max_len=32, tp=0)
    with pytest.raises(ValueError, match="not both"):
        GenerationServer(params, cfg, max_batch=2, max_len=32, tp=2,
                         mesh=tp_serving.serving_mesh(2))


def test_tp_env_incompatible_modes_degrade_with_event(model, monkeypatch,
                                                      tmp_path):
    from kata_xpu_device_plugin_tpu.models import mistral_test_config

    cfg, params = model
    mcfg = mistral_test_config(dtype=jnp.float32)
    mparams = init_params(jax.random.PRNGKey(4), mcfg, dtype=jnp.float32)
    monkeypatch.setenv("KATA_TPU_TP", "2")

    srv, events = _capture_events(
        tmp_path,
        lambda: GenerationServer(mparams, mcfg, max_batch=2, max_len=64,
                                 ring_kv=True),
    )
    assert srv._tp == 1 and srv._mesh is None
    evs = [e for e in events if e.get("name") == "tp_disabled"]
    assert len(evs) == 1 and evs[0]["reason"] == "ring_kv"

    srv, events = _capture_events(
        tmp_path,
        lambda: GenerationServer(params, cfg, max_batch=2, max_len=32,
                                 speculative_k=2, spec_opt_in=True),
        name="ev2.jsonl",
    )
    assert srv._tp == 1 and srv._mesh is None
    evs = [e for e in events if e.get("name") == "tp_disabled"]
    assert len(evs) == 1 and evs[0]["reason"] == "speculative"
    # The degraded server still serves correctly single-chip.
    prompts = _prompts(cfg, [4, 6], seed=51)
    ref, _ = _serve(params, cfg, prompts, max_batch=2, max_len=32)
    monkeypatch.setenv("KATA_TPU_TP", "not-a-number")
    out, srv = _serve(params, cfg, prompts, max_batch=2, max_len=32)
    assert srv._tp == 1
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_tp_env_default_builds_mesh(model, monkeypatch):
    """A daemon-injected KATA_TPU_TP (no constructor arg) shards the
    server — the node-wide knob actually reaches serving — and outputs
    stay identical."""
    cfg, params = model
    prompts = _prompts(cfg, [5, 7], seed=61)
    ref, _ = _serve(params, cfg, prompts, max_batch=2, max_len=32)
    monkeypatch.setenv("KATA_TPU_TP", "2")
    out, srv = _serve(params, cfg, prompts, max_batch=2, max_len=32)
    assert srv._tp == 2 and srv._mesh is not None
    assert srv._mesh.shape[AXIS_MODEL] == 2
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


# ----- stats / metrics schema ----------------------------------------------


def test_tp_stats_schema_no_branch(model):
    cfg, params = model
    plain = GenerationServer(params, cfg, max_batch=2, max_len=32)
    st = plain.stats()
    assert st["tp_degree"] == 1
    assert st["kv_pool_shard_occupancy"] == [0.0]
    sharded = GenerationServer(params, cfg, max_batch=2, max_len=32, tp=2,
                               prefill_buckets=(16,), kv_pool_tokens=512)
    st = sharded.stats()
    assert st["tp_degree"] == 2
    assert len(st["kv_pool_shard_occupancy"]) == 2
    # arena_bytes stays the real per-shard-summed figure (replicated KV
    # under a non-dividing head count costs tp × the logical bytes).
    assert st["arena_bytes"] > 0


def test_tp_shard_gauges_exported(model):
    from prometheus_client import REGISTRY, generate_latest

    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, tp=2,
                           prefill_buckets=(16,), kv_pool_tokens=512)
    lbl = srv.export_metrics()
    (p,) = _prompts(cfg, [5], seed=71)
    srv.submit(p, 6)
    srv.run()
    text = generate_latest(REGISTRY).decode()
    assert f'kata_tpu_serving_tp_degree{{server="{lbl}"}} 2.0' in text
    assert (f'kata_tpu_serving_kv_pool_shard_occupancy'
            f'{{server="{lbl}",shard="0"}}') in text
    assert (f'kata_tpu_serving_kv_pool_shard_occupancy'
            f'{{server="{lbl}",shard="1"}}') in text
