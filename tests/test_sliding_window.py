"""Sliding-window attention (Mistral-style band mask).

Oracles: a brute-force numpy band-masked softmax for the op, and the
framework's own full-sequence forward for the cached decode path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.serving import serve_batch
from kata_xpu_device_plugin_tpu.models import (
    generate,
    generate_speculative,
    mistral_7b,
    mistral_test_config,
)
from kata_xpu_device_plugin_tpu.models.transformer import (
    forward,
    init_params,
    next_token_loss,
)
from kata_xpu_device_plugin_tpu.ops.attention import reference_attention


def _brute_force(q, k, v, window):
    B, Sq, H, D = q.shape
    KV = k.shape[2]
    G = H // KV
    out = np.zeros_like(np.asarray(q))
    for b in range(B):
        for h in range(H):
            kk = np.asarray(k[b, :, h // G])
            vv = np.asarray(v[b, :, h // G])
            for i in range(Sq):
                logits = np.asarray(q[b, i, h]) @ kk.T / np.sqrt(D)
                for j in range(kk.shape[0]):
                    if j > i or (window > 0 and j <= i - window):
                        logits[j] = -1e30
                w = np.exp(logits - logits.max())
                out[b, i, h] = (w / w.sum()) @ vv
    return out


def test_window_mask_vs_brute_force():
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (1, 12, 4, 8), jnp.float32)
    k = jax.random.normal(keys[1], (1, 12, 2, 8), jnp.float32)
    v = jax.random.normal(keys[2], (1, 12, 2, 8), jnp.float32)
    out = reference_attention(q, k, v, causal=True, window=5)
    np.testing.assert_allclose(
        np.asarray(out), _brute_force(q, k, v, 5), rtol=2e-5, atol=2e-5
    )


def test_window_covering_sequence_equals_causal():
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(keys[0], (2, 10, 4, 8), jnp.float32)
    k = jax.random.normal(keys[1], (2, 10, 2, 8), jnp.float32)
    v = jax.random.normal(keys[2], (2, 10, 2, 8), jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(reference_attention(q, k, v, causal=True, window=10)),
        np.asarray(reference_attention(q, k, v, causal=True)),
    )


@pytest.fixture(scope="module")
def model():
    cfg = mistral_test_config(dtype=jnp.float32)  # window=8
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def test_cached_decode_matches_uncached_forward(model):
    # The KV cache holds ALL positions; only the band mask hides the old
    # ones — greedy generate must match a cache-free re-forward loop.
    cfg, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, cfg.vocab_size)
    steps = 12  # runs well past the window of 8
    out = np.asarray(generate(params, prompt, cfg, steps, max_len=32))

    seq = np.asarray(prompt)
    for _ in range(steps):
        logits = forward(params, jnp.asarray(seq), cfg)
        nxt = int(np.asarray(jnp.argmax(logits[:, -1], axis=-1))[0])
        seq = np.concatenate([seq, [[nxt]]], axis=1)
    np.testing.assert_array_equal(out[0], seq[0, 6:])


def test_window_changes_output(model):
    # Sanity: the band mask must actually bite once the sequence exceeds it.
    cfg, params = model
    from dataclasses import replace

    full_cfg = replace(cfg, sliding_window=0)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 14), 0, cfg.vocab_size)
    a = np.asarray(forward(params, prompt, cfg))
    b = np.asarray(forward(params, prompt, full_cfg))
    assert np.abs(a - b).max() > 1e-4


def test_serving_and_speculative_with_window(model):
    cfg, params = model
    key = jax.random.PRNGKey(3)
    prompts = [
        np.asarray(jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                                      cfg.vocab_size), np.int32)
        for i, n in enumerate((5, 11, 7))
    ]
    served = serve_batch(params, cfg, prompts, max_new_tokens=9,
                         max_batch=2, max_len=32)
    for p, o in zip(prompts, served):
        ref = np.asarray(
            generate(params, jnp.asarray(p)[None], cfg, 9, max_len=32)
        )[0]
        np.testing.assert_array_equal(o, ref)
    # Speculative verification applies the same band mask at ragged offsets.
    prompt = jnp.asarray(np.tile(np.array([4, 9, 2], np.int32), 5)[None, :])
    ref = np.asarray(generate(params, prompt, cfg, 10, max_len=48))
    out = generate_speculative(params, prompt, cfg, 10, k=3, max_len=48)
    np.testing.assert_array_equal(out, ref)


def test_training_with_window(model):
    cfg, params = model
    toks = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, cfg.vocab_size)
    loss, grads = jax.value_and_grad(
        lambda p: next_token_loss(p, toks, cfg)
    )(params)
    assert np.isfinite(float(loss))
    gmax = max(float(jnp.abs(g).max()) for g in jax.tree.leaves(grads))
    assert gmax > 0


def test_flash_kernel_window_interpret():
    # The pallas kernel's band mask + block skip (forward AND backward)
    # against the reference, in interpret mode on CPU.
    from functools import partial

    from kata_xpu_device_plugin_tpu.ops.flash import pallas_flash_attention

    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    B, S, H, KV, D = 1, 512, 2, 1, 64
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    flash = partial(pallas_flash_attention, block_q=128, block_k=128,
                    interpret=True, window=192)
    out = flash(q, k, v)
    ref = reference_attention(q, k, v, causal=True, window=192)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(flash(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True,
                                           window=192) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_sp_window_support(model):
    """BOTH sp strategies accept windowed configs (r5: the r4 rejections
    were lifted). Ring masks the global band and bounds its hops;
    Ulysses forwards the window into the full-sequence inner attention
    its all-to-all produces. Each must match the windowed reference."""
    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import (
        make_ring_attention,
        make_ulysses_attention,
        seq_mesh,
    )

    mesh = seq_mesh(8)
    ring = make_ring_attention(mesh)
    ulysses = make_ulysses_attention(mesh, attn_fn=reference_attention)
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(keys[0], (1, 16, 8, 16), jnp.float32)
    k = jax.random.normal(keys[1], (1, 16, 2, 16), jnp.float32)
    v = jax.random.normal(keys[2], (1, 16, 2, 16), jnp.float32)
    ref = reference_attention(q, k, v, causal=True, window=8)
    for name, fn in (("ring", ring), ("ulysses", ulysses)):
        out = fn(q, k, v, window=8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5, err_msg=name)


def test_mistral_7b_shape():
    cfg = mistral_7b()
    assert cfg.sliding_window == 4096
    assert 7.0e9 < cfg.num_params() < 7.6e9
