"""Steady-state compile/reshard tripwire (the JG4xx runtime twin).

jaxguard's JG401 census proves STATICALLY that the serving dispatch
surface is finite — every jit static arg draws from a bounded source, so
the executable count is ``buckets × K × forms``. This suite proves the
process actually STAYS on that surface: after the warmup drain compiles
it, every further drain must trigger ZERO new XLA compilations and ZERO
unsanctioned ``device_put`` calls, across strict on/off × tp × K ×
kv-layout. The tripwire is telemetry, never numerics: greedy outputs are
bit-identical with it on or off.

The compile side rides ``jax.monitoring``'s backend-compile duration
event (fires once per XLA compile, never on an executable-cache hit);
the reshard side counts lexical ``jax.device_put`` calls outside any
``allow_transfer`` sanction — the same two duals JG401/JG403 check
statically.
"""
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.compat import jaxapi
from kata_xpu_device_plugin_tpu.guest import tp_serving
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=1):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (n,), 0, cfg.vocab_size
        ), np.int32)
        for i, n in enumerate(lengths)
    ]


def _drain(srv, cfg, lengths, seed, new_tokens=6):
    for p in _prompts(cfg, lengths, seed=seed):
        srv.submit(p, max_new_tokens=new_tokens)
    return srv.run()


# ----- compile_tripwire / allow_transfer units -------------------------------


def test_tripwire_counts_compiles_and_lexical_puts():
    @jax.jit
    def f(x):
        return x * 3

    x = jnp.ones((5,))
    with jaxapi.compile_tripwire() as cold:
        f(x)                      # first call: at least one XLA compile
        jax.device_put(jnp.ones(3))   # lexical, unsanctioned
        jnp.asarray(np.ones(3))       # explicit-upload path, NOT counted
        with jaxapi.allow_transfer("unit-test sanction"):
            jax.device_put(jnp.ones(3))
    assert cold.compiles >= 1
    assert cold.transfers == 1
    with jaxapi.compile_tripwire() as warm:
        f(x)                      # executable-cache hit
    assert warm.compiles == 0
    assert warm.transfers == 0


def test_tripwire_disabled_is_noop_and_restores_device_put():
    orig = jax.device_put
    with jaxapi.compile_tripwire(enabled=False) as c:
        jax.device_put(jnp.ones(2))
    assert (c.compiles, c.transfers, c.armed) == (0, 0, False)
    assert jax.device_put is orig


def test_tripwire_restores_device_put_on_error():
    orig = jax.device_put
    with pytest.raises(RuntimeError, match="boom"):
        with jaxapi.compile_tripwire():
            raise RuntimeError("boom")
    assert jax.device_put is orig


def test_allow_transfer_depth_nests_without_guard():
    # The sanction depth must track on the guard-less (old-JAX) path
    # too — the tripwire works even where transfer_guard does not.
    guardless = types.SimpleNamespace()  # no transfer_guard attribute
    assert jaxapi._allow_depth() == 0
    with jaxapi.allow_transfer("outer", jax_mod=guardless):
        assert jaxapi._allow_depth() == 1
        with jaxapi.allow_transfer("inner", jax_mod=guardless):
            assert jaxapi._allow_depth() == 2
        assert jaxapi._allow_depth() == 1
    assert jaxapi._allow_depth() == 0


def test_compile_counter_monotonic_and_fires_on_new_shape():
    before = jaxapi.compile_counter()

    @jax.jit
    def g(x):
        return x + 7

    g(jnp.ones((3,)))
    mid = jaxapi.compile_counter()
    assert mid > before
    g(jnp.ones((3,)))  # cache hit — counter must not move
    assert jaxapi.compile_counter() == mid


# ----- steady state is compile- and reshard-free -----------------------------

# (kwargs, id): tier-1 spans the axes without crossing all of them —
# the full strict × tp × K × layout cross lives in the slow matrix.
_TIER1_CONFIGS = [
    (dict(), "slotted-tp1-k1"),
    (dict(strict=True, decode_steps=4, sched_policy="slo_chunked"),
     "strict-fused-k4"),
    (dict(tp=2, kv_pool_tokens=256, kv_block_size=8, kv_layout="blocks"),
     "paged-tp2-blocks"),
    # ISSUE 20: the persistent while_loop executable is ONE dispatch
    # signature — steady drains must stay compile-free across rounds
    # whose DELIVERED step counts differ (the count is a loop carry,
    # never a static).
    (dict(persistent=True, decode_steps=2), "persistent-k2"),
]


def _make_server(params, cfg, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefill_buckets", (8, 16))
    if kw.pop("tp", 1) > 1:
        kw["mesh"] = tp_serving.serving_mesh(2)
    return GenerationServer(params, cfg, **kw)


@pytest.mark.parametrize(
    "kw", [c for c, _ in _TIER1_CONFIGS],
    ids=[i for _, i in _TIER1_CONFIGS],
)
def test_steady_state_zero_compiles_zero_reshards(model, kw):
    cfg, params = model
    srv = _make_server(params, cfg, **kw)
    _drain(srv, cfg, [4, 6], seed=3)           # warmup: compiles the surface
    st = srv.stats()
    assert st["tripwire_enabled"] == 1
    assert st["tripwire_warmed"] == 1
    assert st["steady_state_compiles"] == 0    # warmup is never counted
    _drain(srv, cfg, [5, 7], seed=9)           # steady: same buckets
    st = srv.stats()
    assert st["steady_state_compiles"] == 0, st["steady_state_compiles"]
    assert st["steady_state_reshards"] == 0, st["steady_state_reshards"]


@pytest.mark.slow
@pytest.mark.parametrize("strict", [False, True])
@pytest.mark.parametrize("tp", [1, 2])
@pytest.mark.parametrize("k_steps", [1, 4])
@pytest.mark.parametrize("layout", ["heads", "blocks"])
def test_steady_state_full_matrix(model, strict, tp, k_steps, layout):
    cfg, params = model
    kw = dict(strict=strict, tp=tp, decode_steps=k_steps)
    if layout == "blocks":
        kw.update(kv_pool_tokens=256, kv_block_size=8, kv_layout="blocks")
    srv = _make_server(params, cfg, **kw)
    _drain(srv, cfg, [4, 6], seed=3)
    _drain(srv, cfg, [5, 7], seed=9)
    st = srv.stats()
    assert st["steady_state_compiles"] == 0, (strict, tp, k_steps, layout)
    assert st["steady_state_reshards"] == 0, (strict, tp, k_steps, layout)


def test_tripwire_detects_exact_mode_recompile(model):
    # Negative control: the counter actually counts. A bucket-less
    # server compiles one prefill per DISTINCT prompt length (the
    # documented exact-mode trade, serving.py's reasoned JG401 pragma),
    # so a new length in the steady drain must register.
    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4)
    _drain(srv, cfg, [4], seed=3)
    _drain(srv, cfg, [9], seed=5)              # new length → new executable
    assert srv.stats()["steady_state_compiles"] > 0


def test_greedy_outputs_bit_identical_tripwire_on_off(model):
    # The acceptance bar: the tripwire is pure observation — greedy
    # outputs across warmup AND steady drains are bit-identical with the
    # counters armed or off.
    cfg, params = model
    outs = {}
    for on in (True, False):
        srv = _make_server(params, cfg, tripwire=on)
        r1 = _drain(srv, cfg, [4, 6], seed=3)
        r2 = _drain(srv, cfg, [5, 7], seed=9)
        st = srv.stats()
        assert st["tripwire_enabled"] == int(on)
        if not on:
            assert st["steady_state_compiles"] == 0  # disarmed: stays 0
        outs[on] = ([r1[r] for r in sorted(r1)], [r2[r] for r in sorted(r2)])
    for a, b in zip(outs[True][0] + outs[True][1],
                    outs[False][0] + outs[False][1]):
        np.testing.assert_array_equal(a, b)


def test_stats_and_heartbeat_carry_tripwire_fields(model):
    cfg, params = model
    srv = _make_server(params, cfg, heartbeat_rounds=1)
    _drain(srv, cfg, [4], seed=3)
    st = srv.stats()
    for field in ("tripwire_enabled", "tripwire_warmed",
                  "steady_state_compiles", "steady_state_reshards"):
        assert field in st
    hb = srv._hb_last
    assert hb, "heartbeat never fired at 1-round cadence"
    assert hb["tripwire_warmed"] == 0  # heartbeats DURING warmup say so
    _drain(srv, cfg, [4], seed=5)
    hb = srv._hb_last
    assert "steady_state_compiles" in hb
    assert "steady_state_reshards" in hb
    assert hb["tripwire_warmed"] == 1
