"""Test configuration.

Host-side tests (cdi, discovery, plugin, topology) never import JAX. JAX-side
tests (models, ops, parallel, guest) run on a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware — the strategy SURVEY.md
§4 prescribes (fake sysfs + fake kubelet for infra; forced host-platform device
count for SPMD).
"""
import os

# Hermetic host-side tests: this machine may itself be a TPU VM exporting
# TPU_* topology vars (observed: TPU_ACCELERATOR_TYPE), which discovery
# legitimately reads in production but must not see under test.
for _k in [k for k in os.environ if k.startswith("TPU_")]:
    del os.environ[_k]

# Must run before any test imports jax. This host's axon TPU plugin ignores
# the JAX_PLATFORMS env var, so force the platform through jax.config (works
# as long as no backend has initialized yet).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
