"""Test configuration.

Host-side tests (cdi, discovery, plugin, topology) never import JAX. JAX-side
tests (models, ops, parallel, guest) run on a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware — the strategy SURVEY.md
§4 prescribes (fake sysfs + fake kubelet for infra; forced host-platform device
count for SPMD).
"""
import os

# Must be set before the first `import jax` anywhere in the test session.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
