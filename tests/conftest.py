"""Test configuration.

Host-side tests (cdi, discovery, plugin, topology) never import JAX. JAX-side
tests (models, ops, parallel, guest) run on a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware — the strategy SURVEY.md
§4 prescribes (fake sysfs + fake kubelet for infra; forced host-platform device
count for SPMD).
"""
import os

# Hermetic host-side tests: this machine may itself be a TPU VM exporting
# TPU_* topology vars (observed: TPU_ACCELERATOR_TYPE), which discovery
# legitimately reads in production but must not see under test.
for _k in [k for k in os.environ if k.startswith("TPU_")]:
    del os.environ[_k]

# Must run before any test imports jax. This host's axon TPU plugin ignores
# the JAX_PLATFORMS env var, so force the platform through jax.config (works
# as long as no backend has initialized yet).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Speculative serving is opt-in (ISSUE 8 satellite: a net loss at the
# measured draft acceptance, demoted behind KATA_TPU_SPEC=1 with a
# spec_disabled degrade). The suite opts in globally so the still-supported
# speculative path keeps its coverage; the tests that pin the DEFAULT
# degrade behavior monkeypatch this env off explicitly.
os.environ.setdefault("KATA_TPU_SPEC", "1")

# int8 KV is the GenerationServer DEFAULT (ISSUE 12, eval_quality-gated).
# The suite pins the bf16 opt-out globally: the serving oracle tests
# compare greedy tokens bit-for-bit against transformer.generate()'s
# unquantized caches, which int8 arenas would break by design (~0.4%
# per-read quantization error — see tests/test_kv_quant.py's agreement
# thresholds). int8 arenas keep their coverage through the explicit
# kv_quant=True matrices; the tests that pin the int8 DEFAULT and the
# env knob contract monkeypatch this env off (tests/test_kv_quant.py).
os.environ.setdefault("KATA_TPU_KV_QUANT", "bf16")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import gc  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _bound_process_accumulation():
    """Clear jax's compiled-executable caches (and collect garbage) after
    each test MODULE. The suite runs ~370 tests in one interpreter that
    also hosts torch (the HF parity oracles); with every compiled
    executable of every module retained, full-suite runs intermittently
    died with a SIGSEGV inside XLA's LLVM compilation late in the run
    (observed twice at ~85%, never reproducible on the same tests in a
    shorter process). Bounding the accumulation costs a few re-compiles
    of shared tiny shapes and removes the corrupting condition."""
    yield
    jax.clear_caches()
    gc.collect()


@pytest.fixture
def capture_events(tmp_path):
    """Run a callable with the obs default sink swapped to a tmp JSONL
    and return ``(result, events)`` — the one sink-capture helper for
    event-contract tests (kv-quant/decode-attn knob tests; older suites
    carry a pre-fixture local copy)."""
    from kata_xpu_device_plugin_tpu import obs

    def _capture(fn, name="ev.jsonl"):
        sink = obs.EventSink(str(tmp_path / name))
        prev = obs.set_default_sink(sink)
        try:
            result = fn()
        finally:
            obs.set_default_sink(prev)
            sink.close()
        return result, obs.read_events(str(tmp_path / name))

    return _capture


@pytest.fixture(autouse=True)
def _flight_dumps_to_tmp(tmp_path):
    """Keep the always-armed crash flight recorder (ISSUE 11) from
    littering the working directory: tests that exercise terminal events
    (chip_loss_fatal, failed drains, non-recoverable faults) dump into
    the test's tmp dir instead. An EXTERNALLY pinned KATATPU_FLIGHT_DIR
    (the chaos CI gate sets one so the dumps upload as artifacts) wins —
    the fixture only fills the default. Each test also gets a fresh ring
    so one test's events can never leak into another's postmortem.

    The env var is managed by hand, NOT via the monkeypatch fixture: an
    autouse dependency on monkeypatch would instantiate it before every
    test-local fixture, flipping finalization order so test patches of
    os-level functions outlive the fixtures (e.g. tmp-tree rmtree in
    test_plugin) that must run unpatched."""
    from kata_xpu_device_plugin_tpu.obs import flight

    prev = os.environ.get(flight.ENV_DIR)
    if not prev:
        os.environ[flight.ENV_DIR] = str(tmp_path / "flight")
    flight.configure_from_env(force=True)
    yield
    if not prev:
        os.environ.pop(flight.ENV_DIR, None)
    flight.configure_from_env(force=True)
