"""Paged KV arena + continuous batching (guest/kv_arena.py, ISSUE 6).

Oracle, as everywhere in serving: the paged pool is a SCHEDULING/memory
optimization — greedy tokens must be bit-identical to the fixed-slot
server for every composition (overlap × kv_quant, prefix hits, COW,
preemption/resume), while the block accounting (refcounts, all-or-nothing
allocation, tier LRU eviction, FIFO requeue) obeys its documented
semantics.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.kv_arena import (
    RESERVED_BLOCKS,
    SCRATCH_BLOCK,
    KVPool,
    PagedPrefixTier,
    pool_gather_rows,
    pool_scatter_rows,
    pool_write_batch,
    pool_write_seq,
)
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params, prefill


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=1, shared=0):
    key = jax.random.PRNGKey(seed)
    head = np.asarray(
        jax.random.randint(key, (shared,), 0, cfg.vocab_size), np.int32
    ) if shared else np.zeros((0,), np.int32)
    out = []
    for i, n in enumerate(lengths):
        tail = np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (n,), 0, cfg.vocab_size
        ), np.int32)
        out.append(np.concatenate([head, tail]))
    return out


def _serve(params, cfg, prompts, budgets=10, **kw):
    srv = GenerationServer(params, cfg, **kw)
    if isinstance(budgets, int):
        budgets = [budgets] * len(prompts)
    rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
    res = srv.run()
    return [res[r] for r in rids], srv


def _events(path):
    from kata_xpu_device_plugin_tpu import obs

    return obs.read_events(str(path))


# ----- KVPool block accounting --------------------------------------------


def test_pool_alloc_is_all_or_nothing(model):
    cfg, _ = model
    pool = KVPool(cfg, pool_tokens=6 * 4, block_size=4)  # 4 usable blocks
    assert pool.blocks_total == 4
    got = pool.try_alloc(3)
    assert got is not None and len(got) == 3
    assert all(b >= RESERVED_BLOCKS for b in got)
    assert pool.try_alloc(2) is None       # only 1 free: no partial grant
    assert pool.blocks_free == 1           # ...and nothing was consumed
    pool.unref(got)
    assert pool.blocks_free == 4


def test_pool_refcount_recycles_exactly_once(model):
    cfg, _ = model
    pool = KVPool(cfg, pool_tokens=6 * 4, block_size=4)
    (b,) = pool.try_alloc(1)
    pool.ref([b])                          # tier + lane share the block
    pool.ref([b])
    pool.unref([b])
    pool.unref([b])
    assert pool.blocks_free == 3           # still held by the last ref
    pool.unref([b])
    assert pool.blocks_free == 4
    with pytest.raises(AssertionError):
        pool.unref([b])                    # over-release is a bug, loudly


def test_pool_too_small_rejected(model):
    cfg, _ = model
    with pytest.raises(ValueError, match="pool_tokens"):
        KVPool(cfg, pool_tokens=RESERVED_BLOCKS * 4, block_size=4)


# ----- device ops ----------------------------------------------------------


def test_pool_write_gather_scatter_roundtrip(model):
    """Scatter a contiguous cache into blocks, gather it back, restore it
    into different blocks: every hop is row-exact, and SCRATCH-masked
    chunks never land."""
    cfg, params = model
    pool = KVPool(cfg, pool_tokens=8 * 4, block_size=4)
    prompt = np.arange(1, 9, dtype=np.int32)   # 8 tokens = 2 blocks
    caches, _, _ = prefill(params, jnp.asarray(prompt)[None, :], cfg, 16,
                           return_logits=True)
    ref_rows = jax.tree.map(lambda c: np.asarray(c[:, 0, :8]), caches)
    table = pool.try_alloc(2)
    pool.arena = pool_write_seq(
        pool.arena, caches, jnp.int32(0),
        jnp.asarray(np.asarray(table, np.int32)), block_size=4,
    )
    got = jax.tree.map(
        np.asarray,
        pool_gather_rows(pool.arena, jnp.asarray(np.asarray(table, np.int32)),
                         block_size=4),
    )
    jax.tree.map(np.testing.assert_array_equal, got, ref_rows)
    # Restore into a fresh pair of blocks; gather must round-trip again.
    table2 = pool.try_alloc(2)
    pool.arena = pool_scatter_rows(
        pool.arena, jax.tree.map(jnp.asarray, got),
        jnp.asarray(np.asarray(table2, np.int32)), block_size=4,
    )
    got2 = jax.tree.map(
        np.asarray,
        pool_gather_rows(pool.arena,
                         jnp.asarray(np.asarray(table2, np.int32)),
                         block_size=4),
    )
    jax.tree.map(np.testing.assert_array_equal, got2, ref_rows)
    # SCRATCH-masked chunk: rewriting block 0's chunk toward SCRATCH must
    # leave the real block untouched.
    before = jax.tree.map(np.asarray, pool.arena)
    pool.arena = pool_write_seq(
        pool.arena, jax.tree.map(lambda c: c * 0 + 1, caches), jnp.int32(0),
        jnp.asarray(np.asarray([SCRATCH_BLOCK, table[1]], np.int32)),
        block_size=4,
    )
    after = jax.tree.map(np.asarray, pool.arena)
    b0 = table[0]
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            x[:, 0, b0 * 4:(b0 + 1) * 4], y[:, 0, b0 * 4:(b0 + 1) * 4]
        ),
        before, after,
    )


def test_pool_write_batch_matches_sequential(model):
    """One batched admission scatter lands exactly what N sequential
    ``pool_write_seq`` calls would: per-row SCRATCH masking holds, and
    SCRATCH-padding a narrower row to the group's width is a no-op."""
    cfg, params = model
    prompts = [np.arange(1, 9, dtype=np.int32),      # 8 tokens = 2 blocks
               np.arange(20, 32, dtype=np.int32)]    # 12 tokens = 3 blocks
    batch = np.zeros((2, 12), np.int32)
    for i, p in enumerate(prompts):
        batch[i, : len(p)] = p
    caches, _, _ = prefill(params, jnp.asarray(batch), cfg, 16,
                           return_logits=True)

    pool_a = KVPool(cfg, pool_tokens=8 * 4, block_size=4)
    pool_b = KVPool(cfg, pool_tokens=8 * 4, block_size=4)
    t0, t1 = pool_a.try_alloc(2), pool_a.try_alloc(3)
    assert [pool_b.try_alloc(2), pool_b.try_alloc(3)] == [t0, t1]
    # Row 0 masks its first block (a tier-shared entry) and is narrower
    # than row 1 — the batched form pads it with SCRATCH to width 3.
    rows = [[SCRATCH_BLOCK, t0[1]], [SCRATCH_BLOCK] + t1[1:]]
    for i, tab in enumerate(rows):
        pool_a.arena = pool_write_seq(
            pool_a.arena, caches, jnp.int32(i),
            jnp.asarray(np.asarray(tab, np.int32)), block_size=4,
        )
    tables = np.full((2, 3), SCRATCH_BLOCK, np.int32)
    for i, tab in enumerate(rows):
        tables[i, : len(tab)] = tab
    pool_b.arena = pool_write_batch(
        pool_b.arena, caches, jnp.asarray(tables), block_size=4,
    )
    for tab in (t0, t1):
        full = jnp.asarray(np.asarray(tab, np.int32))
        jax.tree.map(
            np.testing.assert_array_equal,
            jax.tree.map(np.asarray,
                         pool_gather_rows(pool_a.arena, full, block_size=4)),
            jax.tree.map(np.asarray,
                         pool_gather_rows(pool_b.arena, full, block_size=4)),
        )


# ----- the shared-prefix tier ---------------------------------------------


def _tier(cfg, params, buckets=(4, 8), pool_tokens=10 * 4, bs=4):
    pool = KVPool(cfg, pool_tokens=pool_tokens, block_size=bs)
    return pool, PagedPrefixTier(pool, cfg, buckets)


def _cache_for(params, cfg, prompt, max_len=32):
    caches, _, _ = prefill(params, jnp.asarray(prompt)[None, :], cfg,
                           max_len, return_logits=True)
    return caches


def test_tier_insert_lookup_pin_and_lru_eviction(model):
    cfg, params = model
    pool, tier = _tier(cfg, params, pool_tokens=6 * 4)  # 4 usable blocks
    p1 = np.arange(0, 10, dtype=np.int32)
    p2 = np.arange(40, 50, dtype=np.int32)
    assert tier.insert(p1, _cache_for(params, cfg, p1), 0)   # 8 tok = 2 blk
    hit = tier.lookup(p1)
    assert hit is not None and hit.length == 8
    assert tier.shared_blocks(hit) == hit.segment.blocks[:2]
    # Pool pressure with the segment PINNED: insert skips, never evicts
    # live-referenced rows, never errors.
    held = pool.try_alloc(2)
    assert not tier.insert(p2, _cache_for(params, cfg, p2), 0)
    assert tier.insert_skips == 1 and tier.evictions == 0
    # Release the pin: the same insert now evicts p1's segment LRU-first.
    tier.release(hit)
    assert tier.insert(p2, _cache_for(params, cfg, p2), 0)
    assert tier.evictions == 1
    assert tier.lookup(p1) is None
    pool.unref(held)


def test_tier_cancel_reverses_lookup_counters(model):
    cfg, params = model
    _pool, tier = _tier(cfg, params)
    p = np.arange(0, 10, dtype=np.int32)
    tier.insert(p, _cache_for(params, cfg, p), 0)
    hit = tier.lookup(p)
    assert (tier.hits, tier.tokens_reused) == (1, 8)
    tier.cancel(hit)
    assert (tier.hits, tier.misses, tier.tokens_reused) == (0, 1, 0)
    assert hit.segment.refs == 0


def test_tier_unlookup_leaves_no_trace(model):
    """Head-of-line retry accounting: a failed block reservation unwinds
    the pass's lookup wholesale — hit OR miss — so a request that
    re-offers N times before admission still counts exactly once
    (cancel() would record a tier miss per retry)."""
    cfg, params = model
    _pool, tier = _tier(cfg, params)
    p = np.arange(0, 10, dtype=np.int32)
    assert tier.lookup(p) is None          # miss retry
    tier.unlookup(None)
    assert (tier.hits, tier.misses) == (0, 0)
    tier.insert(p, _cache_for(params, cfg, p), 0)
    hit = tier.lookup(p)                   # hit retry
    tier.unlookup(hit)
    assert (tier.hits, tier.misses, tier.tokens_reused) == (0, 0, 0)
    assert hit.segment.refs == 0           # pin released — evictable again


# ----- serving: paged vs slotted bit-identity ------------------------------


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("overlap", [True, False])
def test_paged_greedy_identical_to_slotted(model, kv_quant, overlap):
    """The acceptance-criteria oracle: greedy outputs bit-identical
    between the paged pool and the fixed slot grid, mixed prompt lengths
    through queue pressure, bf16/fp32 AND int8 arenas, pipelined and
    lock-step."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 9, 6, 12, 3, 7])
    common = dict(max_batch=3, max_len=32, chunk=4, overlap=overlap,
                  kv_quant=kv_quant)
    ref, _ = _serve(params, cfg, prompts, **common)
    out, srv = _serve(params, cfg, prompts, kv_pool_tokens=3 * 32 + 16,
                      kv_block_size=8, **common)
    assert srv.paged
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["kv_blocks_in_use"] == 0      # drained pool: all recycled
    assert st["kv_blocks_total"] > 0


@pytest.mark.parametrize("overlap", [True, False])
def test_paged_preemption_keeps_outputs_and_fifo(model, overlap, tmp_path):
    """A pool barely above one full-length request forces spill/requeue:
    outputs stay bit-identical, preempted requests resume FIFO (nothing
    admits past them — ttft events stay rid-sorted), and the preempt/
    resume events land on the stream."""
    from kata_xpu_device_plugin_tpu import obs

    cfg, params = model
    prompts = _prompts(cfg, [4, 9, 6, 12, 3, 7, 5, 8], seed=2)
    common = dict(max_batch=4, max_len=32, chunk=4, overlap=overlap)
    ref, _ = _serve(params, cfg, prompts, budgets=14, **common)
    sink = obs.EventSink(str(tmp_path / "ev.jsonl"))
    prev = obs.set_default_sink(sink)
    try:
        out, srv = _serve(params, cfg, prompts, budgets=14,
                          kv_pool_tokens=32 + 3 * 8, kv_block_size=8,
                          **common)
    finally:
        obs.set_default_sink(prev)
        sink.close()
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["preemptions"] >= 1
    assert st["preempted_waiting"] == 0
    evs = _events(tmp_path / "ev.jsonl")
    preempts = [e for e in evs if e.get("name") == "kv_preempt"]
    resumes = [e for e in evs if e.get("name") == "kv_resume"]
    assert len(preempts) == st["preemptions"] == len(resumes)
    # Every preempted request resumed at the exact position it spilled.
    assert {e["rid"] for e in preempts} == {e["rid"] for e in resumes}
    # Strict-FIFO requeue: replaying the event stream, every resume must
    # pick the OLDEST (lowest-rid) currently-preempted request — the
    # youngest-first preemption order must not leak into resume order.
    waiting: set = set()
    for e in evs:
        if e.get("name") == "kv_preempt":
            waiting.add(e["rid"])
        elif e.get("name") == "kv_resume":
            assert e["rid"] == min(waiting), "resumed past an older request"
            waiting.remove(e["rid"])
    # A preempted request produces ONE ttft (tokens ride req.out through
    # the spill), so every rid appears exactly once.
    ttft_rids = [e["rid"] for e in evs if e.get("name") == "ttft"]
    assert sorted(ttft_rids) == list(range(len(prompts)))


def test_paged_oversubscribed_completes_more_lanes_than_slots(model):
    """The A/B shape bench-smoke runs: more queued requests than the old
    slot count, twice the lanes over a pool SMALLER than their dense
    footprint — the paged server admits more concurrently than the slot
    grid ever could, and completes with identical tokens."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 6, 9, 5, 7, 8, 3, 10, 6, 4], seed=3)
    ref, _ = _serve(params, cfg, prompts, max_batch=2, max_len=32, chunk=4)
    out, srv = _serve(params, cfg, prompts, max_batch=6, max_len=32,
                      chunk=4, kv_pool_tokens=4 * 32, kv_block_size=8)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    assert srv.paged and srv.max_batch == 6
    # 6 lanes over a 4-request-footprint pool: the dense grid for 6 slots
    # would need 6*32 tokens; the pool held 4*32.
    assert srv.kv_pool.capacity_tokens < 6 * 32


# ----- serving: the prefix tier, sharing, and copy-on-write ---------------


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("overlap", [True, False])
def test_paged_prefix_tier_identity_and_sharing(model, kv_quant, overlap):
    """Shared-prefix traffic through the pool-backed tier: bit-identical
    to the slotted no-store server, hits share tier blocks (refcounted),
    and a block-aligned match copies nothing."""
    cfg, params = model
    prompts = _prompts(cfg, [3, 6, 2, 9, 4, 5], seed=4, shared=10)
    common = dict(max_batch=3, max_len=40, chunk=4, overlap=overlap,
                  kv_quant=kv_quant, prefill_buckets=(8, 16, 24))
    ref, _ = _serve(params, cfg, prompts, **common)
    out, srv = _serve(params, cfg, prompts, kv_pool_tokens=3 * 40 + 32,
                      kv_block_size=8, prefix_cache_tokens=1, **common)
    assert isinstance(srv.prefix_store, PagedPrefixTier)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["prefix_hits"] >= 3
    assert st["cow_copies"] == 0            # matches at 8 = block-aligned
    assert st["prefix_store_tokens"] > 0
    assert st["prefix_store_bytes"] > 0


@pytest.mark.parametrize("overlap", [True, False])
def test_paged_cow_boundary_block(model, overlap):
    """A match that is NOT block-aligned privatizes the boundary block
    copy-on-write: cow_copies counts it, the tier's copy stays resident
    and shared rows are never rewritten (outputs identical)."""
    cfg, params = model
    prompts = _prompts(cfg, [3, 6, 2, 9], seed=5, shared=10)
    common = dict(max_batch=2, max_len=40, chunk=4, overlap=overlap,
                  prefill_buckets=(8, 16, 24))
    ref, _ = _serve(params, cfg, prompts, **common)
    out, srv = _serve(params, cfg, prompts, kv_pool_tokens=2 * 40 + 64,
                      kv_block_size=16,     # match@8 sits mid-block → COW
                      prefix_cache_tokens=1, **common)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["prefix_hits"] >= 1
    assert st["cow_copies"] >= 1
    assert st["cow_copies"] == srv._cow_copies


def test_paged_decode_pressure_evicts_unpinned_tier_lru(model):
    """Decode growth outranks the cache: when lanes need blocks, the
    tier's UNREFERENCED segments evict LRU-first (prefix_evict with
    tier=kv_pool) instead of preempting live requests."""
    from kata_xpu_device_plugin_tpu import obs

    cfg, params = model
    # Small pool + long decode budgets: after cold admissions populate
    # the tier, lane growth must reclaim tier blocks.
    prompts = _prompts(cfg, [9, 9, 9, 9], seed=6, shared=0)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        sink = obs.EventSink(td + "/ev.jsonl")
        prev = obs.set_default_sink(sink)
        try:
            out, srv = _serve(params, cfg, prompts, budgets=20,
                              max_batch=2, max_len=32, chunk=4,
                              kv_pool_tokens=32 + 4 * 8, kv_block_size=8,
                              prefill_buckets=(8,), prefix_cache_tokens=1)
        finally:
            obs.set_default_sink(prev)
            sink.close()
        evs = _events(td + "/ev.jsonl")
    ref, _ = _serve(params, cfg, prompts, budgets=20,
                    max_batch=2, max_len=32, chunk=4)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    tier_evicts = [e for e in evs if e.get("name") == "prefix_evict"
                   and e.get("tier") == "kv_pool"]
    assert tier_evicts, "decode pressure should have reclaimed tier blocks"
    # Retry passes (head-of-line reservation failures) must not inflate
    # the tier's counters: each admission nets exactly one hit or miss.
    tier = srv.prefix_store
    assert tier.hits + tier.misses == len(prompts)


# ----- config / env / degrade ---------------------------------------------


def test_kv_pool_env_default_and_malformed_degrade(model, monkeypatch,
                                                   tmp_path):
    """KATA_TPU_KV_POOL_TOKENS (the env the daemon's --kv-pool-tokens
    knob injects) turns paging on when the caller passes nothing; an
    explicit 0 overrides; malformed or too-small values DEGRADE to the
    fixed-slot path with a kv_pool_disabled event — a node-wide knob
    must never crash a guest."""
    from kata_xpu_device_plugin_tpu import obs

    cfg, params = model
    monkeypatch.setenv("KATA_TPU_KV_POOL_TOKENS", "128")
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32)
    assert srv.paged and srv.kv_pool is not None
    off = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           kv_pool_tokens=0)
    assert not off.paged
    events = []
    for raw, reason_prefix in [("64k", "bad_env"), ("8", "pool_too_small")]:
        monkeypatch.setenv("KATA_TPU_KV_POOL_TOKENS", raw)
        sink = obs.EventSink(str(tmp_path / f"ev_{raw}.jsonl"))
        prev = obs.set_default_sink(sink)
        try:
            bad = GenerationServer(params, cfg, max_batch=2, max_len=32)
        finally:
            obs.set_default_sink(prev)
            sink.close()
        assert not bad.paged and bad.arena is not None
        evs = [e for e in _events(tmp_path / f"ev_{raw}.jsonl")
               if e.get("name") == "kv_pool_disabled"]
        assert len(evs) == 1 and evs[0]["reason"].startswith(reason_prefix)
        events.extend(evs)
    # The degraded server still serves correctly on the slot grid.
    prompts = _prompts(cfg, [4, 6])
    ref, _ = _serve(params, cfg, prompts, max_batch=2, max_len=32)
    monkeypatch.setenv("KATA_TPU_KV_POOL_TOKENS", "not-a-number")
    out, _ = _serve(params, cfg, prompts, max_batch=2, max_len=32)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_kv_pool_incompatible_modes(model, monkeypatch, tmp_path):
    """The compatibility matrix (docs/guest_guide.md): an EXPLICIT
    kv_pool_tokens on an incompatible server raises with the reason; the
    env-injected default degrades with a kv_pool_disabled event carrying
    the same reason."""
    from kata_xpu_device_plugin_tpu import obs
    from kata_xpu_device_plugin_tpu.guest.prefix_cache import PrefixStore
    from kata_xpu_device_plugin_tpu.models import mistral_test_config

    cfg, params = model
    with pytest.raises(ValueError, match="speculative"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         kv_pool_tokens=128, speculative_k=2)
    store = PrefixStore(cfg, 64, (8,))
    with pytest.raises(ValueError, match="injected_prefix_store"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         prefill_buckets=(8,), kv_pool_tokens=128,
                         prefix_store=store)
    mcfg = mistral_test_config(dtype=jnp.float32)
    mparams = init_params(jax.random.PRNGKey(4), mcfg, dtype=jnp.float32)
    with pytest.raises(ValueError, match="ring_kv"):
        GenerationServer(mparams, mcfg, max_batch=2, max_len=64,
                         kv_pool_tokens=256, ring_kv=True)
    # Same conflicts via the node-wide env: degrade + event, not a crash.
    monkeypatch.setenv("KATA_TPU_KV_POOL_TOKENS", "256")
    sink = obs.EventSink(str(tmp_path / "ev.jsonl"))
    prev = obs.set_default_sink(sink)
    try:
        srv = GenerationServer(mparams, mcfg, max_batch=2, max_len=64,
                               ring_kv=True)
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert not srv.paged
    evs = [e for e in _events(tmp_path / "ev.jsonl")
           if e.get("name") == "kv_pool_disabled"]
    assert len(evs) == 1 and evs[0]["reason"] == "ring_kv"


def test_prefix_store_disabled_event_carries_reason(model, tmp_path):
    """PR 5's documented gap, closed: ring_kv/draft servers that disable
    the prefix store say so ONCE per server on the event stream, with the
    reason the compatibility matrix documents."""
    from kata_xpu_device_plugin_tpu import obs
    from kata_xpu_device_plugin_tpu.models import (
        mistral_test_config,
        self_draft,
    )

    cfg, params = model
    mcfg = mistral_test_config(dtype=jnp.float32)
    mparams = init_params(jax.random.PRNGKey(4), mcfg, dtype=jnp.float32)
    sink = obs.EventSink(str(tmp_path / "ev.jsonl"))
    prev = obs.set_default_sink(sink)
    try:
        GenerationServer(mparams, mcfg, max_batch=2, max_len=64,
                         prefill_buckets=(8,), prefix_cache_tokens=64,
                         ring_kv=True)
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         prefill_buckets=(8,), prefix_cache_tokens=64,
                         speculative_k=2, draft=self_draft(params, cfg, 1))
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = [e for e in _events(tmp_path / "ev.jsonl")
           if e.get("name") == "prefix_store_disabled"]
    assert [e["reason"] for e in evs] == ["ring_kv", "draft"]
    assert len({e["server"] for e in evs}) == 2  # once per server


def test_allocator_injects_kv_pool_env():
    """Daemon side of the knob: config.kv_pool_tokens rides the TPU
    AllocateResponse env (plugin/allocators.py), mirroring the
    compile-cache and prefix-cache delivery paths. Host-only — no jax."""
    from kata_xpu_device_plugin_tpu.cdi import constants as C
    from kata_xpu_device_plugin_tpu.discovery.tpu import TpuChip, TpuInventory
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator
    from kata_xpu_device_plugin_tpu.topology.slice import HostTopology

    inv = TpuInventory(
        chips=(TpuChip(index=0, dev_path="/dev/accel0"),),
        topology=HostTopology.from_accelerator_type("v5litepod-8"),
        model_suffix="TPU_V5E",
    )
    alive = lambda _chip: True  # noqa: E731 — no real /dev in this test
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive,
        kv_pool_tokens=262144,
    ).allocate(["0"])
    assert wired.envs[C.ENV_KV_POOL_TOKENS] == "262144"
    bare = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive
    ).allocate(["0"])
    assert C.ENV_KV_POOL_TOKENS not in bare.envs


# ----- stats / metrics schema ---------------------------------------------


def test_stats_paged_fields_always_present(model):
    cfg, params = model
    slotted = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               kv_pool_tokens=0)
    st = slotted.stats()
    assert st["kv_pool_occupancy"] == 0.0
    assert st["kv_blocks_in_use"] == 0
    assert st["preemptions"] == 0 and st["cow_copies"] == 0
    paged = GenerationServer(params, cfg, max_batch=2, max_len=32,
                             kv_pool_tokens=128, kv_block_size=8)
    st = paged.stats()
    assert st["kv_blocks_total"] == 128 // 8 - RESERVED_BLOCKS
    assert st["kv_pool_tokens"] == st["kv_blocks_total"] * 8
    assert st["arena_bytes"] > 0           # the pool IS the arena
    # Latency summaries expose the p99 the bench percentiles read.
    paged.submit(np.arange(1, 5, dtype=np.int32), 6)
    paged.run()
    assert "p99" in paged.stats()["ttft_s"]


def test_export_metrics_includes_pool_gauges(model):
    from prometheus_client import REGISTRY, generate_latest

    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           kv_pool_tokens=128, kv_block_size=8)
    label = srv.export_metrics()
    srv.submit(np.arange(1, 6, dtype=np.int32), 4)
    srv.run()
    text = generate_latest(REGISTRY).decode()
    for gauge in ("kv_pool_occupancy", "kv_blocks_in_use",
                  "preemptions", "cow_copies"):
        assert f'kata_tpu_serving_{gauge}{{server="{label}"}}' in text
    # The rate()-able traffic counters exist alongside the gauges.
    assert f'kata_tpu_serving_kv_preemptions_total{{server="{label}"}}' in text
