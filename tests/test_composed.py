"""Composed pp×fsdp×tp on one mesh (VERDICT r2 item 2): the pipelined loss
must equal the unpipelined loss on the flattened batch, and the composed
train step must track the unpipelined sharded train step."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params, next_token_loss
from kata_xpu_device_plugin_tpu.parallel import composed

M, MB, S = 4, 2, 16


@pytest.fixture(scope="module")
def cfg():
    return tiny_test_config(n_layers=4, dtype=jnp.float32)


def _tokens(cfg):
    return jax.random.randint(
        jax.random.PRNGKey(1), (M, MB, S), 0, cfg.vocab_size, dtype=jnp.int32
    )


@pytest.mark.parametrize("shape", [(2, 2, 2), (4, 2, 1), (2, 1, 4)])
def test_pp_loss_matches_unpipelined(cfg, shape):
    pipe, fsdp, model = shape
    mesh = composed.composed_mesh(pipe, fsdp, model)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = _tokens(cfg)

    pp_tree = composed.to_pp_params(params, pipe)
    pp_params = jax.device_put(
        pp_tree, composed.pp_param_shardings(pp_tree, mesh)
    )
    loss_fn = composed.make_pp_loss(cfg, mesh, n_stages=pipe, num_microbatches=M)
    pp_loss = jax.jit(loss_fn)(pp_params, composed.shard_microbatches(tokens, mesh))
    ref = next_token_loss(params, tokens.reshape(M * MB, S), cfg)
    np.testing.assert_allclose(float(pp_loss), float(ref), rtol=1e-5)


def test_pp_train_step_matches_unpipelined_sharded(cfg):
    """Same init key, same batch: the composed pp×fsdp×tp step and the
    unpipelined dp×fsdp×tp step must produce the same loss trajectory."""
    from kata_xpu_device_plugin_tpu import parallel

    mesh = composed.composed_mesh(2, 2, 2)
    tokens = _tokens(cfg)
    init_state, step = composed.make_pp_train_step(cfg, mesh, 2, M)
    state = init_state(jax.random.PRNGKey(0))
    toks_sh = composed.shard_microbatches(tokens, mesh)

    flat_mesh = parallel.build_mesh(
        {"data": 1, "fsdp": 4, "model": 2}, devices=jax.devices()
    )
    ref_init, ref_step = parallel.make_train_step(cfg, flat_mesh)
    ref_state = ref_init(jax.random.PRNGKey(0))
    flat = parallel.shard_batch(tokens.reshape(M * MB, S), flat_mesh)

    for _ in range(2):
        state, pp_loss = step(state, toks_sh)
        ref_state, ref_loss = ref_step(ref_state, flat)
        np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=2e-4)
    assert int(state["step"]) == 2


def test_pp_requires_divisible_shapes(cfg):
    mesh = composed.composed_mesh(2, 2, 2)
    with pytest.raises(ValueError, match="not divisible"):
        composed.make_pp_loss(cfg, mesh, n_stages=3, num_microbatches=M)
    with pytest.raises(ValueError, match="not divisible"):
        composed.make_pp_loss(cfg, mesh, n_stages=2, num_microbatches=3)


def test_microbatch_block_ownership(cfg):
    """Memory honesty: the [M, mb, S] token array is sharded over pipe — each
    stage device holds M/P microbatches, not all of them."""
    mesh = composed.composed_mesh(4, 2, 1)
    toks = composed.shard_microbatches(_tokens(cfg), mesh)
    for shard in toks.addressable_shards:
        assert shard.data.shape[0] == M // 4
