"""Unit tests for ``tools.analyze`` (jaxguard): one minimal POSITIVE and
one NEAR-MISS negative fixture per rule (JG101-JG104), pragma
suppression, the interprocedural property the analyzer exists for (a
device value produced inside ``jax.jit`` flowing into ``float()`` across
module boundaries), and the acceptance bar — zero unsuppressed findings
over the real tree.

Fixtures are analyzed under repo-relative paths inside the package so
hot roots / scopes resolve exactly as they do on the real code.
"""
import json
import subprocess
import sys

import pytest

from tools.analyze import analyze_source, analyze_sources
from tools.analyze.cli import run

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GUEST = "kata_xpu_device_plugin_tpu/guest/mod_under_test.py"
OPS = "kata_xpu_device_plugin_tpu/ops/mod_under_test.py"


def rules_of(findings):
    return [f.rule for f in findings]


# ----- JG101: implicit host sync in a hot path -------------------------------

_HOT_SYNC = '''
import jax
import numpy as np

@jax.jit
def compute(x):
    return x * 2

def hot_loop(xs):  # jaxguard: hot
    acc = 0.0
    for x in xs:
        acc += float(compute(x))
    return acc
'''


def test_jg101_fires_on_hot_sync():
    findings = analyze_source(_HOT_SYNC, GUEST)
    assert rules_of(findings) == ["JG101"]
    assert "float()" in findings[0].message


def test_jg101_near_miss_not_hot():
    # Same flow, no hot mark and no hot root: the sync is legal.
    src = _HOT_SYNC.replace("  # jaxguard: hot", "")
    assert analyze_source(src, GUEST) == []


def test_jg101_near_miss_host_value():
    # float() of a HOST value in a hot function: no device sync.
    src = '''
def hot_loop(xs):  # jaxguard: hot
    acc = 0.0
    for x in xs:
        acc += float(x) * 2.0
    return acc
'''
    assert analyze_source(src, GUEST) == []


def test_jg101_branching_and_item():
    src = '''
import jax

@jax.jit
def compute(x):
    return x.sum()

def hot(x):  # jaxguard: hot
    y = compute(x)
    if y > 0:
        return y.item()
    return 0
'''
    found = rules_of(analyze_source(src, GUEST))
    assert found == ["JG101", "JG101"]  # the `if` coercion and the .item()


def test_jg101_interprocedural_across_modules():
    """The linter-can't-see-this case: jit result crosses two modules
    before the sync."""
    sources = {
        "kata_xpu_device_plugin_tpu/a.py": (
            "import jax\n\n@jax.jit\ndef compute(x):\n    return x * 2\n"
        ),
        "kata_xpu_device_plugin_tpu/b.py": (
            "from .a import compute\n\ndef mid(x):\n    return compute(x)\n"
        ),
        "kata_xpu_device_plugin_tpu/c.py": (
            "from .b import mid\n\n"
            "def hot(xs):  # jaxguard: hot\n"
            "    return [float(mid(x)) for x in xs]\n"
        ),
    }
    findings = analyze_sources(sources)
    assert rules_of(findings) == ["JG101"]
    assert findings[0].path == "kata_xpu_device_plugin_tpu/c.py"


def test_jg101_hot_root_by_name():
    # GenerationServer.step is a hot root without any marker; a sync in a
    # method it reaches is flagged.
    src = '''
import jax
import numpy as np

@jax.jit
def decode_chunk(caches, tok):
    return caches, tok + 1

class GenerationServer:
    def step(self):
        return self._round()

    def _round(self):
        caches, tok = decode_chunk(self.arena, self.last)
        return np.asarray(tok)
'''
    findings = analyze_source(src, GUEST)
    assert rules_of(findings) == ["JG101"]
    assert "_round" in findings[0].function


# ----- JG102: use-after-donation ---------------------------------------------

_DONATED = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def upd(arena, x):
    return arena + x

def caller(arena, xs):
    out = upd(arena, xs)
    return arena.sum()
'''


def test_jg102_fires_on_read_after_donation():
    findings = analyze_source(_DONATED, GUEST)
    assert rules_of(findings) == ["JG102"]
    assert "donated" in findings[0].message


def test_jg102_near_miss_rebound():
    src = _DONATED.replace(
        "out = upd(arena, xs)\n    return arena.sum()",
        "arena = upd(arena, xs)\n    return arena.sum()",
    )
    assert analyze_source(src, GUEST) == []


def test_jg102_loop_carried_donation():
    # Donated every iteration, never rebound: the next iteration's own
    # call re-donates a deleted buffer.
    src = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def upd(arena, x):
    return arena + x

def caller(arena, xs):
    for x in xs:
        out = upd(arena, x)
    return out
'''
    assert rules_of(analyze_source(src, GUEST)) == ["JG102"]


def test_jg102_donate_argnames_and_self_attr():
    src = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnames=("arena",))
def upd(arena, x):
    return arena + x

class S:
    def go(self, x):
        new = upd(arena=self.arena, x=x)
        return self.arena
'''
    assert rules_of(analyze_source(src, GUEST)) == ["JG102"]


# ----- JG103: tracer leak ----------------------------------------------------

_LEAK = '''
import jax

class M:
    @jax.jit
    def step(self, x):
        y = x * 2
        self.last = y
        return y
'''


def test_jg103_fires_on_self_store_in_jit():
    findings = analyze_source(_LEAK, GUEST)
    assert rules_of(findings) == ["JG103"]


def test_jg103_near_miss_constant_store():
    # Storing a non-traced python constant to self is ugly but not a leak.
    src = _LEAK.replace("self.last = y", "self.last = 3")
    assert analyze_source(src, GUEST) == []


def test_jg103_near_miss_local_store():
    # A traced value in a LOCAL is the normal case.
    src = _LEAK.replace("self.last = y", "z = y")
    assert analyze_source(src, GUEST) == []


def test_jg103_global_and_nested_def():
    src = '''
import jax

TRACE_DUMP = []

@jax.jit
def step(x):
    def inner(c, _):
        TRACE_DUMP.append(c)
        return c * 2, None
    y, _ = jax.lax.scan(inner, x, None, length=3)
    global LAST
    LAST = y
    return y
'''
    found = rules_of(analyze_source(src, GUEST))
    # the append inside the (traced) nested def and the global store
    assert found.count("JG103") == 2


# ----- JG104: recompile hazards ----------------------------------------------

_UNHASHABLE = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("shape",))
def make(x, shape):
    return x.reshape(shape)

def call(x):
    return make(x, [4, 4])
'''


def test_jg104_fires_on_unhashable_static():
    findings = analyze_source(_UNHASHABLE, OPS)
    assert rules_of(findings) == ["JG104"]
    assert "unhashable" in findings[0].message


def test_jg104_near_miss_tuple_static():
    assert analyze_source(_UNHASHABLE.replace("[4, 4]", "(4, 4)"), OPS) == []


def test_jg104_loop_varying_static():
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("steps",))
def scan(x, steps):
    return x * steps

def sweep(x, sizes):
    for n in sizes:
        x = scan(x, steps=n)
    return x
'''
    findings = analyze_source(src, OPS)
    assert rules_of(findings) == ["JG104"]
    assert "loop variable 'n'" in findings[0].message


def test_jg104_near_miss_constant_static_in_loop():
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("steps",))
def scan(x, steps):
    return x * steps

def sweep(x, sizes):
    for n in sizes:
        x = scan(x, steps=8)
    return x
'''
    assert analyze_source(src, OPS) == []


def test_jg104_shape_branch_in_jit():
    src = '''
import jax

@jax.jit
def f(x):
    if x.shape[0] > 4:
        return x * 2
    return x
'''
    findings = analyze_source(src, OPS)
    assert rules_of(findings) == ["JG104"]
    assert "shape-dependent" in findings[0].message


def test_jg104_near_miss_shape_branch_outside_jit():
    src = '''
def f(x):
    if x.shape[0] > 4:
        return 2
    return 1
'''
    assert analyze_source(src, OPS) == []


# ----- JG401: dispatch census ------------------------------------------------

_CENSUS = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def decode(caches, k):
    return caches

@jax.jit
def probe(x):
    return x + 1

class GenerationServer:
    def step(self):
        k = probe(self.last)
        return decode(self.arena, k=k)
'''


def test_jg401_traced_value_feeds_static():
    findings = analyze_source(_CENSUS, GUEST, rules=["JG401"])
    assert rules_of(findings) == ["JG401"]
    assert "traced" in findings[0].message


def test_jg401_near_miss_bounded_sources():
    # Config attrs, constants, pure-host folds of them, and IfExps over
    # them are all BOUNDED: one executable per (bucket, form) — a closed
    # census, no finding.
    src = '''
import jax
from functools import partial

FORMS = ("plain", "fused")

@partial(jax.jit, static_argnames=("k", "form"))
def decode(caches, k, form):
    return caches

class GenerationServer:
    def step(self):
        k = min(self.k, 4)
        form = FORMS[0] if self.fused else FORMS[1]
        return decode(self.arena, k=k, form=form)
'''
    assert analyze_source(src, GUEST, rules=["JG401"]) == []


def test_jg401_loop_varying_static():
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def decode(caches, k):
    return caches

class GenerationServer:
    def step(self):
        for b in self.buckets:
            out = decode(self.arena, k=b)
        return out
'''
    findings = analyze_source(src, GUEST, rules=["JG401"])
    assert rules_of(findings) == ["JG401"]
    assert "loop variable 'b'" in findings[0].message


def test_jg401_while_reassigned_static_varies():
    # ISSUE 20: a host `while` is a loop scope too — a name the body
    # REASSIGNS varies per iteration, so feeding it to a jit static is
    # the same unbounded-census hazard as a `for` target.
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def decode(caches, k):
    return caches

class GenerationServer:
    def step(self):
        k = 1
        while self.busy():
            out = decode(self.arena, k=k)
            k = k + 1
        return out
'''
    findings = analyze_source(src, GUEST, rules=["JG401"])
    assert rules_of(findings) == ["JG401"]
    assert "loop variable 'k'" in findings[0].message


def test_jg401_while_bounded_static_is_one_signature():
    # The persistent-decode form (ISSUE 20): the `lax.while_loop` lives
    # INSIDE the traced executable, and the host-side statics feeding it
    # (the per-server cap) are bounded attrs that no while body
    # reassigns — ONE dispatch signature, no finding, even when the
    # dispatch itself sits under a host `while` round loop.
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("cap",))
def persistent_decode(caches, tok, cap):
    def cond(c):
        return c[1] < cap
    def body(c):
        return (c[0], c[1] + 1)
    return jax.lax.while_loop(cond, body, (caches, tok))

class GenerationServer:
    def step(self):
        while self.busy():
            out = persistent_decode(self.arena, self.last,
                                    cap=self.persistent_cap)
        return out
'''
    assert analyze_source(src, GUEST, rules=["JG401"]) == []


def test_jg401_unbounded_host_source():
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def decode(caches, k):
    return caches

class GenerationServer:
    def step(self, prompt):
        return decode(self.arena, k=len(prompt))
'''
    findings = analyze_source(src, GUEST, rules=["JG401"])
    assert rules_of(findings) == ["JG401"]
    assert "unbounded" in findings[0].message


def test_jg401_only_fires_on_serving_reachable():
    # The same unbounded static OUTSIDE the serving roots is JG104's
    # jurisdiction at most — the census is a serving contract.
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def decode(caches, k):
    return caches

def offline_sweep(caches, prompt):
    return decode(caches, k=len(prompt))
'''
    assert analyze_source(src, GUEST, rules=["JG401"]) == []


# ----- JG402: donation completeness ------------------------------------------

_DONATE_BRANCH = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def fused(arena, tok):
    return arena, tok

@partial(jax.jit, donate_argnums=(0,))
def plain(arena, tok):
    return arena, tok

class GenerationServer:
    def step(self):
        if self.fused:
            self.arena, tok = fused(self.arena, self.last)
        else:
            out = plain(self.arena, self.last)
            tok = out[1]
        return tok
'''


def test_jg402_per_branch_donation_asymmetry():
    # The exact hazard class the pass exists for: one dispatch branch
    # rebinds the donated tree, its sibling leaves it dangling.
    findings = analyze_source(_DONATE_BRANCH, GUEST, rules=["JG402"])
    assert rules_of(findings) == ["JG402"]
    assert "self.arena" in findings[0].message
    assert "plain" in findings[0].message


def test_jg402_near_miss_both_branches_rebind():
    src = _DONATE_BRANCH.replace(
        "out = plain(self.arena, self.last)\n            tok = out[1]",
        "self.arena, tok = plain(self.arena, self.last)",
    )
    assert analyze_source(src, GUEST, rules=["JG402"]) == []


def test_jg402_donate_argnames_on_bound_method():
    # donate_argnames on a jitted METHOD: the self offset shifts the
    # positional map; run() leaves the donated attribute dangling while
    # step() rebinds it.
    src = '''
import jax
from functools import partial

class GenerationServer:
    @partial(jax.jit, donate_argnames=("arena",))
    def _upd(self, arena, tok):
        return arena, tok

    def step(self):
        self.arena, tok = self._upd(self.arena, self.last)
        return tok

    def run(self):
        out = self._upd(self.arena, self.last)
        return out[1]
'''
    findings = analyze_source(src, GUEST, rules=["JG402"])
    assert rules_of(findings) == ["JG402"]
    assert findings[0].function.endswith("run")


def test_jg402_near_miss_donated_local_dies_with_frame():
    # A donated LOCAL that is never read again is fine — nothing
    # persistent dangles (the JG102 dual stays intra-frame).
    src = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def upd(arena, x):
    return arena + x

class GenerationServer:
    def step(self, arena):
        return upd(arena, self.last)
'''
    assert analyze_source(src, GUEST, rules=["JG402"]) == []


# ----- JG403: sharding-spec coverage -----------------------------------------


def test_jg403_shard_map_nested_in_jit_missing_specs():
    src = '''
import jax
from kata_xpu_device_plugin_tpu.compat.jaxapi import shard_map

@jax.jit
def dispatch(x, mesh, spec):
    f = shard_map(lambda a: a * 2, mesh, in_specs=spec)
    return f(x)
'''
    findings = analyze_source(src, GUEST, rules=["JG403"])
    assert rules_of(findings) == ["JG403"]
    assert "out_specs" in findings[0].message


def test_jg403_near_miss_explicit_specs():
    src = '''
import jax
from kata_xpu_device_plugin_tpu.compat.jaxapi import shard_map

@jax.jit
def dispatch(x, mesh, spec):
    f = shard_map(lambda a: a * 2, mesh, in_specs=spec, out_specs=spec)
    return f(x)
'''
    assert analyze_source(src, GUEST, rules=["JG403"]) == []


_KNOBS = '''
ENV_DECODE_STEPS = "KATA_TPU_DECODE_STEPS"
ENV_KV_LAYOUT = "KATA_TPU_KV_LAYOUT"
KV_LAYOUT_HEADS = "heads"
KV_LAYOUT_BLOCKS = "blocks"
KV_LAYOUTS = (KV_LAYOUT_HEADS, KV_LAYOUT_BLOCKS)
'''

_SPEC_PATH = "kata_xpu_device_plugin_tpu/parallel/sharding.py"


def test_jg403_layout_falls_off_the_end():
    spec = '''
def kv_spec(layout):
    if layout == "heads":
        return 1
'''
    findings = analyze_sources({GUEST: _KNOBS, _SPEC_PATH: spec},
                               rules=["JG403"])
    assert rules_of(findings) == ["JG403"]
    assert "blocks" in findings[0].message


def test_jg403_layout_near_miss_terminal_default():
    spec = '''
def kv_spec(layout):
    if layout == "heads":
        return 1
    return 0
'''
    assert analyze_sources({GUEST: _KNOBS, _SPEC_PATH: spec},
                           rules=["JG403"]) == []


def test_jg403_layout_outside_lattice():
    spec = '''
def kv_spec(layout):
    if layout == "rows":
        return 1
    return 0
'''
    findings = analyze_sources({GUEST: _KNOBS, _SPEC_PATH: spec},
                               rules=["JG403"])
    assert rules_of(findings) == ["JG403"]
    assert "'rows'" in findings[0].message


_RESHARD = '''
import jax
from kata_xpu_device_plugin_tpu.compat import jaxapi

class GenerationServer:
    def step(self):
        rows = jax.device_put(self.pending)
        return rows
'''


def test_jg403_unsanctioned_device_put_on_serving_path():
    findings = analyze_source(_RESHARD, GUEST, rules=["JG403"])
    assert rules_of(findings) == ["JG403"]
    assert "allow_transfer" in findings[0].message


def test_jg403_near_miss_lexical_sanction():
    src = _RESHARD.replace(
        "rows = jax.device_put(self.pending)",
        "with jaxapi.allow_transfer(\"staging\"):\n"
        "            rows = jax.device_put(self.pending)",
    )
    assert analyze_source(src, GUEST, rules=["JG403"]) == []


def test_jg403_sanction_inheritance_is_depth_limited():
    # A helper called INSIDE an allow region inherits the sanction up to
    # 2 levels down; a third level must carry its own reasoned
    # allow_transfer (the prefetch-miss class the rule exists for).
    deep = '''
import jax
from kata_xpu_device_plugin_tpu.compat import jaxapi

class GenerationServer:
    def step(self):
        with jaxapi.allow_transfer("admission"):
            self._admit()

    def _admit(self):
        return self._resume()

    def _resume(self):
        return self._upload()

    def _upload(self):
        return jax.device_put(self.kv)
'''
    findings = analyze_source(deep, GUEST, rules=["JG403"])
    assert rules_of(findings) == ["JG403"]
    shallow = deep.replace(
        "    def _admit(self):\n        return self._resume()\n\n", ""
    ).replace("self._admit()", "self._resume()")
    assert analyze_source(shallow, GUEST, rules=["JG403"]) == []


# ----- JG404: stale-pragma audit ---------------------------------------------


def test_jg404_stale_pragma_is_a_finding():
    findings = analyze_source(
        "x = 1  # jaxguard: allow(JG101) fence that no longer exists\n",
        GUEST,
    )
    assert rules_of(findings) == ["JG404"]
    assert "JG101" in findings[0].message


def test_jg404_near_miss_live_pragma():
    # A pragma whose rule STILL fires on its line is doing its job —
    # the finding is suppressed and no staleness is reported.
    src = _HOT_SYNC.replace(
        "acc += float(compute(x))",
        "acc += float(compute(x))  # jaxguard: allow(JG101) demo fence",
    )
    assert analyze_source(src, GUEST) == []


def test_jg404_escape_hatch_allows_defensive_pragma():
    findings = analyze_source(
        "x = 1  # jaxguard: allow(JG101, JG404) defensive: kept on purpose\n",
        GUEST,
    )
    assert findings == []


# ----- knob lattice ----------------------------------------------------------


def test_knob_lattice_derivation():
    from tools.analyze.dispatch import knob_lattice
    from tools.analyze.graph import load_program

    program, errors = load_program([], _REPO_ROOT, sources={GUEST: _KNOBS})
    assert errors == []
    lattice = knob_lattice(program)
    # A choice-tuple knob closes over its choices; a bare env constant is
    # read once per process ("per-process" marker, one census value).
    assert lattice["KATA_TPU_KV_LAYOUT"] == ("heads", "blocks")
    assert lattice["KATA_TPU_DECODE_STEPS"] == "per-process"


# ----- pragmas ---------------------------------------------------------------


def test_pragma_suppresses_on_finding_line():
    src = _HOT_SYNC.replace(
        "acc += float(compute(x))",
        "acc += float(compute(x))  # jaxguard: allow(JG101) demo fence",
    )
    assert analyze_source(src, GUEST) == []


def test_pragma_multi_rule_grammar():
    # Comma-list grammar: JG102 fires and is suppressed; the JG404 leg
    # sanctions keeping the list even though only one rule is live (the
    # stale-pragma audit would otherwise flag the dead half).
    src = _DONATED.replace(
        "return arena.sum()",
        "return arena.sum()  # jaxguard: allow(JG102, JG404) teardown read",
    )
    assert analyze_source(src, GUEST) == []


def test_pragma_wrong_rule_does_not_suppress():
    # The wrong rule both fails to suppress AND is itself reported as
    # stale sanction debt (JG404) — two findings, one bad pragma.
    src = _DONATED.replace(
        "return arena.sum()",
        "return arena.sum()  # jaxguard: allow(JG103) wrong rule",
    )
    assert rules_of(analyze_source(src, GUEST)) == ["JG102", "JG404"]


# ----- acceptance: the real tree ---------------------------------------------


def test_repo_is_jaxguard_clean():
    """The acceptance bar (and the no-false-positive assertion): the
    analyzer exits clean on the default surface — package + bench +
    scripts — with only the documented pragma sanctions."""
    assert run(root=None) == []


def test_repo_is_jg4xx_clean():
    """ISSUE 19 acceptance: the dispatch-surface passes specifically
    report nothing on the real tree — the census is closed, donations
    complete, specs covered, and no pragma is stale."""
    assert run(root=None, rules=["JG401", "JG402", "JG403", "JG404"]) == []


def test_multipass_graph_built_once():
    """Perf pin: one ``run()`` builds the interprocedural fixpoint
    exactly once — the dispatch pass REUSES the dataflow engine's call
    graph instead of re-running it."""
    from tools.analyze import dataflow

    before = dataflow.FIXPOINT_RUNS
    run(root=None)
    assert dataflow.FIXPOINT_RUNS == before + 1


# ----- CLI -------------------------------------------------------------------


def test_cli_red_on_finding_and_json_report(tmp_path):
    bad = tmp_path / "kata_xpu_device_plugin_tpu"
    bad.mkdir()
    (bad / "hot.py").write_text(_HOT_SYNC)
    report = tmp_path / "report.json"
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.analyze",
            "kata_xpu_device_plugin_tpu", "--root", str(tmp_path),
            "--json", str(report),
        ],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 1
    assert "JG101" in proc.stdout
    data = json.loads(report.read_text())
    assert data["tool"] == "jaxguard"
    assert data["summary"]["by_rule"] == {"JG101": 1}
    assert data["findings"][0]["rule"] == "JG101"


def test_cli_json_written_even_when_clean(tmp_path):
    clean = tmp_path / "kata_xpu_device_plugin_tpu"
    clean.mkdir()
    (clean / "ok.py").write_text("x = 1\n")
    report = tmp_path / "report.json"
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.analyze",
            "kata_xpu_device_plugin_tpu", "--root", str(tmp_path),
            "--json", str(report),
        ],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0
    assert json.loads(report.read_text())["summary"]["total"] == 0


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--list-rules"],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0
    for rule in ("JG101", "JG102", "JG103", "JG104",
                 "JG401", "JG402", "JG403", "JG404"):
        assert rule in proc.stdout


def test_cli_rule_family_filter(tmp_path):
    # --rule JG4xx expands to the whole dispatch family: the JG101 sync
    # in hot.py is out of selection, the stale pragma in stale.py is in.
    pkg = tmp_path / "kata_xpu_device_plugin_tpu"
    pkg.mkdir()
    (pkg / "hot.py").write_text(_HOT_SYNC)
    (pkg / "stale.py").write_text(
        "x = 1  # jaxguard: allow(JG102) long-gone donation read\n"
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.analyze",
            "kata_xpu_device_plugin_tpu", "--root", str(tmp_path),
            "--rule", "JG4xx",
        ],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 1
    assert "JG404" in proc.stdout
    assert "JG101" not in proc.stdout


def test_cli_rule_family_unknown_digit_is_usage_error():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--rule", "JG9xx"],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr


def test_cli_baseline_diff_mode(tmp_path):
    # Diff mode fails ONLY on findings new versus the committed report:
    # the pre-existing JG101 rides, a second one introduced after the
    # baseline was banked is flagged as new.
    pkg = tmp_path / "kata_xpu_device_plugin_tpu"
    pkg.mkdir()
    (pkg / "hot.py").write_text(_HOT_SYNC)
    baseline = tmp_path / "jaxguard_report.json"
    cmd = [
        sys.executable, "-m", "tools.analyze",
        "kata_xpu_device_plugin_tpu", "--root", str(tmp_path),
    ]
    proc = subprocess.run(
        cmd + ["--json", str(baseline)],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 1
    proc = subprocess.run(
        cmd + ["--baseline", str(baseline)],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0
    assert "0 new vs baseline" in proc.stderr
    (pkg / "hot2.py").write_text(_HOT_SYNC)
    proc = subprocess.run(
        cmd + ["--baseline", str(baseline)],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 1
    assert "[new vs baseline]" in proc.stdout
    assert "hot2.py" in proc.stdout
    assert "hot.py:" not in proc.stdout.replace("hot2.py:", "")


def test_cli_baseline_unreadable_is_usage_error(tmp_path):
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.analyze",
            "--baseline", str(tmp_path / "missing.json"),
        ],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 2
    assert "unreadable baseline" in proc.stderr


def test_syntax_error_reported_not_raised():
    findings = analyze_source("def broken(:\n", GUEST)
    assert rules_of(findings) == ["E999"]


def test_syntax_error_survives_rule_filter():
    # A file the analyzer could not parse is never "out of scope" of a
    # --rule selection — dropping E999 would report broken code as clean.
    findings = analyze_source("def broken(:\n", GUEST, rules=["JG101"])
    assert rules_of(findings) == ["E999"]


def test_empty_surface_errors_instead_of_passing(tmp_path):
    # A gate that analyzed nothing must not report clean: no default
    # target under root means wrong cwd/root, not hazard-free code.
    with pytest.raises(FileNotFoundError, match="no analyzable files"):
        run(root=str(tmp_path))
