"""Unit tests for ``tools.analyze`` (jaxguard): one minimal POSITIVE and
one NEAR-MISS negative fixture per rule (JG101-JG104), pragma
suppression, the interprocedural property the analyzer exists for (a
device value produced inside ``jax.jit`` flowing into ``float()`` across
module boundaries), and the acceptance bar — zero unsuppressed findings
over the real tree.

Fixtures are analyzed under repo-relative paths inside the package so
hot roots / scopes resolve exactly as they do on the real code.
"""
import json
import subprocess
import sys

import pytest

from tools.analyze import analyze_source, analyze_sources
from tools.analyze.cli import run

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GUEST = "kata_xpu_device_plugin_tpu/guest/mod_under_test.py"
OPS = "kata_xpu_device_plugin_tpu/ops/mod_under_test.py"


def rules_of(findings):
    return [f.rule for f in findings]


# ----- JG101: implicit host sync in a hot path -------------------------------

_HOT_SYNC = '''
import jax
import numpy as np

@jax.jit
def compute(x):
    return x * 2

def hot_loop(xs):  # jaxguard: hot
    acc = 0.0
    for x in xs:
        acc += float(compute(x))
    return acc
'''


def test_jg101_fires_on_hot_sync():
    findings = analyze_source(_HOT_SYNC, GUEST)
    assert rules_of(findings) == ["JG101"]
    assert "float()" in findings[0].message


def test_jg101_near_miss_not_hot():
    # Same flow, no hot mark and no hot root: the sync is legal.
    src = _HOT_SYNC.replace("  # jaxguard: hot", "")
    assert analyze_source(src, GUEST) == []


def test_jg101_near_miss_host_value():
    # float() of a HOST value in a hot function: no device sync.
    src = '''
def hot_loop(xs):  # jaxguard: hot
    acc = 0.0
    for x in xs:
        acc += float(x) * 2.0
    return acc
'''
    assert analyze_source(src, GUEST) == []


def test_jg101_branching_and_item():
    src = '''
import jax

@jax.jit
def compute(x):
    return x.sum()

def hot(x):  # jaxguard: hot
    y = compute(x)
    if y > 0:
        return y.item()
    return 0
'''
    found = rules_of(analyze_source(src, GUEST))
    assert found == ["JG101", "JG101"]  # the `if` coercion and the .item()


def test_jg101_interprocedural_across_modules():
    """The linter-can't-see-this case: jit result crosses two modules
    before the sync."""
    sources = {
        "kata_xpu_device_plugin_tpu/a.py": (
            "import jax\n\n@jax.jit\ndef compute(x):\n    return x * 2\n"
        ),
        "kata_xpu_device_plugin_tpu/b.py": (
            "from .a import compute\n\ndef mid(x):\n    return compute(x)\n"
        ),
        "kata_xpu_device_plugin_tpu/c.py": (
            "from .b import mid\n\n"
            "def hot(xs):  # jaxguard: hot\n"
            "    return [float(mid(x)) for x in xs]\n"
        ),
    }
    findings = analyze_sources(sources)
    assert rules_of(findings) == ["JG101"]
    assert findings[0].path == "kata_xpu_device_plugin_tpu/c.py"


def test_jg101_hot_root_by_name():
    # GenerationServer.step is a hot root without any marker; a sync in a
    # method it reaches is flagged.
    src = '''
import jax
import numpy as np

@jax.jit
def decode_chunk(caches, tok):
    return caches, tok + 1

class GenerationServer:
    def step(self):
        return self._round()

    def _round(self):
        caches, tok = decode_chunk(self.arena, self.last)
        return np.asarray(tok)
'''
    findings = analyze_source(src, GUEST)
    assert rules_of(findings) == ["JG101"]
    assert "_round" in findings[0].function


# ----- JG102: use-after-donation ---------------------------------------------

_DONATED = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def upd(arena, x):
    return arena + x

def caller(arena, xs):
    out = upd(arena, xs)
    return arena.sum()
'''


def test_jg102_fires_on_read_after_donation():
    findings = analyze_source(_DONATED, GUEST)
    assert rules_of(findings) == ["JG102"]
    assert "donated" in findings[0].message


def test_jg102_near_miss_rebound():
    src = _DONATED.replace(
        "out = upd(arena, xs)\n    return arena.sum()",
        "arena = upd(arena, xs)\n    return arena.sum()",
    )
    assert analyze_source(src, GUEST) == []


def test_jg102_loop_carried_donation():
    # Donated every iteration, never rebound: the next iteration's own
    # call re-donates a deleted buffer.
    src = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def upd(arena, x):
    return arena + x

def caller(arena, xs):
    for x in xs:
        out = upd(arena, x)
    return out
'''
    assert rules_of(analyze_source(src, GUEST)) == ["JG102"]


def test_jg102_donate_argnames_and_self_attr():
    src = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnames=("arena",))
def upd(arena, x):
    return arena + x

class S:
    def go(self, x):
        new = upd(arena=self.arena, x=x)
        return self.arena
'''
    assert rules_of(analyze_source(src, GUEST)) == ["JG102"]


# ----- JG103: tracer leak ----------------------------------------------------

_LEAK = '''
import jax

class M:
    @jax.jit
    def step(self, x):
        y = x * 2
        self.last = y
        return y
'''


def test_jg103_fires_on_self_store_in_jit():
    findings = analyze_source(_LEAK, GUEST)
    assert rules_of(findings) == ["JG103"]


def test_jg103_near_miss_constant_store():
    # Storing a non-traced python constant to self is ugly but not a leak.
    src = _LEAK.replace("self.last = y", "self.last = 3")
    assert analyze_source(src, GUEST) == []


def test_jg103_near_miss_local_store():
    # A traced value in a LOCAL is the normal case.
    src = _LEAK.replace("self.last = y", "z = y")
    assert analyze_source(src, GUEST) == []


def test_jg103_global_and_nested_def():
    src = '''
import jax

TRACE_DUMP = []

@jax.jit
def step(x):
    def inner(c, _):
        TRACE_DUMP.append(c)
        return c * 2, None
    y, _ = jax.lax.scan(inner, x, None, length=3)
    global LAST
    LAST = y
    return y
'''
    found = rules_of(analyze_source(src, GUEST))
    # the append inside the (traced) nested def and the global store
    assert found.count("JG103") == 2


# ----- JG104: recompile hazards ----------------------------------------------

_UNHASHABLE = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("shape",))
def make(x, shape):
    return x.reshape(shape)

def call(x):
    return make(x, [4, 4])
'''


def test_jg104_fires_on_unhashable_static():
    findings = analyze_source(_UNHASHABLE, OPS)
    assert rules_of(findings) == ["JG104"]
    assert "unhashable" in findings[0].message


def test_jg104_near_miss_tuple_static():
    assert analyze_source(_UNHASHABLE.replace("[4, 4]", "(4, 4)"), OPS) == []


def test_jg104_loop_varying_static():
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("steps",))
def scan(x, steps):
    return x * steps

def sweep(x, sizes):
    for n in sizes:
        x = scan(x, steps=n)
    return x
'''
    findings = analyze_source(src, OPS)
    assert rules_of(findings) == ["JG104"]
    assert "loop variable 'n'" in findings[0].message


def test_jg104_near_miss_constant_static_in_loop():
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("steps",))
def scan(x, steps):
    return x * steps

def sweep(x, sizes):
    for n in sizes:
        x = scan(x, steps=8)
    return x
'''
    assert analyze_source(src, OPS) == []


def test_jg104_shape_branch_in_jit():
    src = '''
import jax

@jax.jit
def f(x):
    if x.shape[0] > 4:
        return x * 2
    return x
'''
    findings = analyze_source(src, OPS)
    assert rules_of(findings) == ["JG104"]
    assert "shape-dependent" in findings[0].message


def test_jg104_near_miss_shape_branch_outside_jit():
    src = '''
def f(x):
    if x.shape[0] > 4:
        return 2
    return 1
'''
    assert analyze_source(src, OPS) == []


# ----- pragmas ---------------------------------------------------------------


def test_pragma_suppresses_on_finding_line():
    src = _HOT_SYNC.replace(
        "acc += float(compute(x))",
        "acc += float(compute(x))  # jaxguard: allow(JG101) demo fence",
    )
    assert analyze_source(src, GUEST) == []


def test_pragma_multi_rule_grammar():
    src = _DONATED.replace(
        "return arena.sum()",
        "return arena.sum()  # jaxguard: allow(JG101, JG102) teardown read",
    )
    assert analyze_source(src, GUEST) == []


def test_pragma_wrong_rule_does_not_suppress():
    src = _DONATED.replace(
        "return arena.sum()",
        "return arena.sum()  # jaxguard: allow(JG103) wrong rule",
    )
    assert rules_of(analyze_source(src, GUEST)) == ["JG102"]


# ----- acceptance: the real tree ---------------------------------------------


def test_repo_is_jaxguard_clean():
    """The acceptance bar (and the no-false-positive assertion): the
    analyzer exits clean on the default surface — package + bench +
    scripts — with only the documented pragma sanctions."""
    assert run(root=None) == []


# ----- CLI -------------------------------------------------------------------


def test_cli_red_on_finding_and_json_report(tmp_path):
    bad = tmp_path / "kata_xpu_device_plugin_tpu"
    bad.mkdir()
    (bad / "hot.py").write_text(_HOT_SYNC)
    report = tmp_path / "report.json"
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.analyze",
            "kata_xpu_device_plugin_tpu", "--root", str(tmp_path),
            "--json", str(report),
        ],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 1
    assert "JG101" in proc.stdout
    data = json.loads(report.read_text())
    assert data["tool"] == "jaxguard"
    assert data["summary"]["by_rule"] == {"JG101": 1}
    assert data["findings"][0]["rule"] == "JG101"


def test_cli_json_written_even_when_clean(tmp_path):
    clean = tmp_path / "kata_xpu_device_plugin_tpu"
    clean.mkdir()
    (clean / "ok.py").write_text("x = 1\n")
    report = tmp_path / "report.json"
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.analyze",
            "kata_xpu_device_plugin_tpu", "--root", str(tmp_path),
            "--json", str(report),
        ],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0
    assert json.loads(report.read_text())["summary"]["total"] == 0


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--list-rules"],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0
    for rule in ("JG101", "JG102", "JG103", "JG104"):
        assert rule in proc.stdout


def test_syntax_error_reported_not_raised():
    findings = analyze_source("def broken(:\n", GUEST)
    assert rules_of(findings) == ["E999"]


def test_syntax_error_survives_rule_filter():
    # A file the analyzer could not parse is never "out of scope" of a
    # --rule selection — dropping E999 would report broken code as clean.
    findings = analyze_source("def broken(:\n", GUEST, rules=["JG101"])
    assert rules_of(findings) == ["E999"]


def test_empty_surface_errors_instead_of_passing(tmp_path):
    # A gate that analyzed nothing must not report clean: no default
    # target under root means wrong cwd/root, not hazard-free code.
    with pytest.raises(FileNotFoundError, match="no analyzable files"):
        run(root=str(tmp_path))
