"""Gemma-2-style features: alternating attention windows (cycle scan),
pre+post norms, and soft-capped attention logits.

Oracle for the cycle scan: an unscanned python loop over layers calling
the same `_layer` with each layer's own window.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.serving import serve_batch
from kata_xpu_device_plugin_tpu.models import (
    gemma2_2b,
    gemma2_9b,
    gemma2_test_config,
    generate,
    generate_speculative,
)
from kata_xpu_device_plugin_tpu.models.transformer import (
    _layer,
    embed,
    forward,
    init_params,
    next_token_loss,
    unembed,
)
from kata_xpu_device_plugin_tpu.ops.attention import reference_attention


@pytest.fixture(scope="module")
def model():
    cfg = gemma2_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def test_post_norm_params_exist(model):
    cfg, params = model
    assert params["layers"]["post_attn_norm"].shape == (cfg.n_layers, cfg.d_model)
    assert params["layers"]["post_mlp_norm"].shape == (cfg.n_layers, cfg.d_model)
    assert cfg.num_params() > gemma2_test_config(post_norms=False).num_params()


def test_cycle_scan_matches_layer_loop(model):
    # forward()'s grouped scan vs an explicit per-layer loop with each
    # layer's own window — must agree exactly.
    cfg, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size)
    out = np.asarray(forward(params, tokens, cfg))

    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = embed(params, tokens, cfg)
    for i in range(cfg.n_layers):
        layer_i = jax.tree.map(lambda a: a[i], params["layers"])
        x, _, _ = _layer(cfg, reference_attention, x, layer_i, positions,
                         window=cfg.layer_window(i))
    ref = np.asarray(unembed(params, x, cfg))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_alternation_matters(model):
    # Windowed-everywhere and global-everywhere must both differ from the
    # alternating config once the sequence exceeds the window.
    cfg, params = model
    from dataclasses import replace

    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 14), 0, cfg.vocab_size)
    alt = np.asarray(forward(params, tokens, cfg))
    all_local = np.asarray(
        forward(params, tokens, replace(cfg, attn_windows=(6, 6)))
    )
    all_global = np.asarray(
        forward(params, tokens, replace(cfg, attn_windows=(0, 0)))
    )
    assert np.abs(alt - all_local).max() > 1e-4
    assert np.abs(alt - all_global).max() > 1e-4


def test_attn_softcap_matters(model):
    cfg, params = model
    from dataclasses import replace

    # Blow up one q/k pair so raw logits far exceed the cap.
    big = dict(params)
    big["layers"] = dict(params["layers"])
    big["layers"]["wq"] = params["layers"]["wq"] * 30.0
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0, cfg.vocab_size)
    capped = np.asarray(forward(big, tokens, cfg))
    uncapped = np.asarray(
        forward(big, tokens, replace(cfg, attn_logits_softcap=0.0))
    )
    assert np.abs(capped - uncapped).max() > 1e-3


def test_generate_decode_matches_uncached_loop(model):
    # Cached decode through the cycle scan vs cache-free re-forward.
    cfg, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 5), 0, cfg.vocab_size)
    steps = 10
    out = np.asarray(generate(params, prompt, cfg, steps, max_len=24))

    seq = np.asarray(prompt)
    for _ in range(steps):
        logits = forward(params, jnp.asarray(seq), cfg)
        nxt = int(np.asarray(jnp.argmax(logits[:, -1], axis=-1))[0])
        seq = np.concatenate([seq, [[nxt]]], axis=1)
    np.testing.assert_array_equal(out[0], seq[0, 5:])


def test_serving_and_speculative_gemma2(model):
    cfg, params = model
    key = jax.random.PRNGKey(5)
    prompts = [
        np.asarray(jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                                      cfg.vocab_size), np.int32)
        for i, n in enumerate((4, 9))
    ]
    served = serve_batch(params, cfg, prompts, max_new_tokens=7,
                         max_batch=2, max_len=24)
    for p, o in zip(prompts, served):
        ref = np.asarray(
            generate(params, jnp.asarray(p)[None], cfg, 7, max_len=24)
        )[0]
        np.testing.assert_array_equal(o, ref)
    prompt = jnp.asarray(np.tile(np.array([3, 7], np.int32), 5)[None, :])
    ref = np.asarray(generate(params, prompt, cfg, 8, max_len=32))
    out = generate_speculative(params, prompt, cfg, 8, k=3, max_len=32)
    np.testing.assert_array_equal(out, ref)


def test_training_grads_flow_through_post_norms(model):
    cfg, params = model
    toks = jax.random.randint(jax.random.PRNGKey(6), (2, 12), 0, cfg.vocab_size)
    loss, grads = jax.value_and_grad(
        lambda p: next_token_loss(p, toks, cfg)
    )(params)
    assert np.isfinite(float(loss))
    for k in ("post_attn_norm", "post_mlp_norm"):
        assert float(jnp.abs(grads["layers"][k]).max()) > 0


def test_sharded_train_step_with_post_norms(model):
    # PARAM_RULES must cover the Gemma-2 post-norm params or GSPMD init
    # dies with a KeyError before the first step.
    from kata_xpu_device_plugin_tpu.parallel import build_mesh, make_train_step

    cfg, _ = model
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    init_state, step = make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)
    from kata_xpu_device_plugin_tpu.parallel import shard_batch

    state, loss = step(state, shard_batch(toks, mesh))
    assert np.isfinite(float(loss))


def test_softcap_runs_flash_and_best_attention(model):
    # The softcap no longer pins the reference path: flash_attention and
    # the best_attention alias (the documented framework default) both
    # carry the cap — on CPU they dispatch to the reference internally,
    # and all three must agree exactly.
    from kata_xpu_device_plugin_tpu.ops.attention import (
        best_attention,
        flash_attention,
        reference_attention,
    )

    cfg, params = model
    toks = jax.random.randint(jax.random.PRNGKey(3), (1, 16), 0, cfg.vocab_size)
    ref = forward(params, toks, cfg, attn_fn=reference_attention)
    for fn in (flash_attention, best_attention):
        out = forward(params, toks, cfg, attn_fn=fn)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_softcap_rejects_custom_attn_fn(model):
    cfg, params = model
    toks = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)

    def custom_attn(q, k, v, causal=True, q_offset=None, window=0):
        return jnp.zeros_like(q)

    with pytest.raises(ValueError, match="softcap"):
        forward(params, toks, cfg, attn_fn=custom_attn)


def test_layer_count_must_divide_cycle():
    with pytest.raises(ValueError, match="divisible"):
        init_params(jax.random.PRNGKey(0), gemma2_test_config(n_layers=3))


def test_gemma2_2b_shape():
    cfg = gemma2_2b()
    assert cfg.attn_windows == (4096, 0)
    assert cfg.post_norms and cfg.attn_logits_softcap == 50.0
    assert 2.4e9 < cfg.num_params() < 2.9e9


def test_gemma2_9b_shape():
    cfg = gemma2_9b()
    assert cfg.n_layers % len(cfg.attn_windows) == 0
    assert 8.5e9 < cfg.num_params() < 10.0e9
