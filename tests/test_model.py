"""Model core tests (CPU, virtual 8-device mesh from conftest)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.models import (
    forward,
    generate,
    init_kv_caches,
    init_params,
    next_token_loss,
    tiny_test_config,
)


@pytest.fixture(scope="module")
def cfg():
    return tiny_test_config()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(0), cfg)


def test_forward_shapes_and_finite(cfg, params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_causality(cfg, params):
    # Changing a future token must not change past logits.
    t1 = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0, cfg.vocab_size)
    t2 = t1.at[0, 8].set((t1[0, 8] + 1) % cfg.vocab_size)
    l1 = forward(params, t1, cfg)
    l2 = forward(params, t2, cfg)
    np.testing.assert_allclose(l1[0, :8], l2[0, :8], rtol=2e-2, atol=2e-3)
    assert not np.allclose(l1[0, 8:], l2[0, 8:], atol=1e-4)


def test_kv_cache_matches_full_forward(cfg, params):
    # Prefill+decode through the cache must equal the full forward pass.
    B, S = 2, 12
    tokens = jax.random.randint(jax.random.PRNGKey(3), (B, S), 0, cfg.vocab_size)
    full = forward(params, tokens, cfg)

    caches = init_kv_caches(cfg, B, S)
    prefill_len = 8
    logits_p, caches = forward(
        params, tokens[:, :prefill_len], cfg,
        kv_caches=caches, cache_offset=jnp.int32(0),
    )
    np.testing.assert_allclose(logits_p, full[:, :prefill_len], rtol=2e-2, atol=2e-3)
    for i in range(prefill_len, S):
        positions = jnp.full((B, 1), i, jnp.int32)
        logits_i, caches = forward(
            params, tokens[:, i:i + 1], cfg, positions=positions,
            kv_caches=caches, cache_offset=jnp.int32(i),
        )
        np.testing.assert_allclose(
            logits_i[:, 0], full[:, i], rtol=2e-2, atol=2e-3
        )


def test_generate_greedy_consistency(cfg, params):
    # generate() must reproduce step-by-step greedy argmax over full forwards.
    B, S, steps = 1, 4, 6
    prompt = jax.random.randint(jax.random.PRNGKey(4), (B, S), 0, cfg.vocab_size)
    out = generate(params, prompt, cfg, steps=steps)
    assert out.shape == (B, steps)

    seq = prompt
    expected = []
    for _ in range(steps):
        logits = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        expected.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert [int(t) for t in out[0]] == expected


def test_loss_decreases_under_training(cfg):
    # Single-device sanity: a few SGD steps reduce next-token loss.
    import optax

    params = init_params(jax.random.PRNGKey(5), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0, cfg.vocab_size)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(p, tokens, cfg)
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_num_params_gemma2b():
    from kata_xpu_device_plugin_tpu.models import gemma_2b

    n = gemma_2b().num_params()
    assert 2.4e9 < n < 2.6e9  # Gemma-2B is ~2.5B params incl. embeddings


def test_generate_zero_steps(cfg, params):
    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 4), 0, cfg.vocab_size)
    out = generate(params, prompt, cfg, steps=0)
    assert out.shape == (2, 0)


def test_fused_param_layout_matches_unfused():
    """fuse_decoder_params (wqkv / w_gateup inference layout) must be a pure
    relayout: forward and generate outputs are identical."""
    from kata_xpu_device_plugin_tpu.models import tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import (
        forward,
        fuse_decoder_params,
        generate,
        init_params,
    )

    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    fused = fuse_decoder_params(params)
    assert "wqkv" in fused["layers"] and "wq" not in fused["layers"]
    assert fuse_decoder_params(fused) is fused  # idempotent

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size)
    np.testing.assert_allclose(
        np.asarray(forward(params, tokens, cfg)),
        np.asarray(forward(fused, tokens, cfg)),
        rtol=1e-5, atol=1e-6,
    )
    # Decode path: compare LOGITS and cache contents with tolerance — greedy
    # token trajectories could flip on a 1-ulp near-tie, so exact token
    # equality would be flaky by construction.
    from kata_xpu_device_plugin_tpu.models.transformer import init_kv_caches

    caches = init_kv_caches(cfg, 2, 16)
    lu, cu = forward(params, tokens, cfg, kv_caches=caches,
                     cache_offset=jnp.int32(0), prefill=True)
    lf, cf = forward(fused, tokens, cfg, kv_caches=caches,
                     cache_offset=jnp.int32(0), prefill=True)
    np.testing.assert_allclose(np.asarray(lu), np.asarray(lf), rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(cu), jax.tree.leaves(cf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    out = generate(fused, tokens, cfg, steps=4, max_len=16)
    assert out.shape == (2, 4)


def test_remat_matches_no_remat():
    """jax.checkpoint over the layer scan must not change loss or grads."""
    from kata_xpu_device_plugin_tpu.models import tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import (
        init_params,
        next_token_loss,
    )

    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    l0, g0 = jax.value_and_grad(lambda p: next_token_loss(p, tokens, cfg))(params)
    l1, g1 = jax.value_and_grad(
        lambda p: next_token_loss(p, tokens, cfg, remat=True)
    )(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        g0, g1,
    )


def test_sampled_generation():
    """Temperature/top-k sampling: valid tokens, deterministic per key,
    different keys explore, temperature=0 reduces to greedy."""
    from kata_xpu_device_plugin_tpu.models import tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import generate, init_params

    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)

    greedy = generate(params, prompt, cfg, steps=6, max_len=16)
    greedy_keyed = generate(
        params, prompt, cfg, steps=6, max_len=16, key=jax.random.PRNGKey(5)
    )
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(greedy_keyed))

    s1 = generate(params, prompt, cfg, steps=6, max_len=16,
                  temperature=1.0, top_k=16, key=jax.random.PRNGKey(2))
    s1b = generate(params, prompt, cfg, steps=6, max_len=16,
                   temperature=1.0, top_k=16, key=jax.random.PRNGKey(2))
    s2 = generate(params, prompt, cfg, steps=6, max_len=16,
                  temperature=1.0, top_k=16, key=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s1b))
    assert not np.array_equal(np.asarray(s1), np.asarray(s2))
    assert bool(jnp.all((s1 >= 0) & (s1 < cfg.vocab_size)))


def test_sampling_requires_key():
    from kata_xpu_device_plugin_tpu.models import tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import generate, init_params

    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="PRNG key"):
        generate(params, prompt, cfg, steps=2, temperature=0.8)
