"""Daemon-side guest heartbeat aggregation (ISSUE 15).

The allocator points every allocation's ``KATATPU_OBS_FILE`` at a
per-allocation JSONL under ``--guest-events-dir``; the manager's
:class:`HeartbeatAggregator` tails those files incrementally
(rotation-safe ``obs.tail_events``) and re-exports per-allocation
serving gauges on the daemon's existing /metrics endpoint — the upward
twin of the ISSUE 11 daemon→guest trace handoff. Host-side, jax-free."""
import json
import os
import time

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.plugin.manager import HeartbeatAggregator
from kata_xpu_device_plugin_tpu.utils import metrics


def _write_events(path, events, mode="a"):
    with open(path, mode, encoding="utf-8") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")


def _hb(server="server0", chips="0,1", **kw):
    base = {
        "ts": 1700000000.0, "kind": "serving", "name": "serving_heartbeat",
        "server": server, "chips": chips, "tokens_per_s": 123.4,
        "itl_p99_ms": 12.5, "queued": 3, "batch_occupancy": 0.75,
        "kv_pool_occupancy": 0.5, "kv_host_occupancy": 0.25,
    }
    base.update(kw)
    return base


def _gauge(g, **labels):
    return g.labels(**labels)._value.get()


def test_aggregator_exports_per_allocation_gauges(tmp_path):
    d = str(tmp_path)
    _write_events(os.path.join(d, "guest_0-1.jsonl"), [
        {"kind": "serving", "name": "serving_config", "server": "server0"},
        _hb(tokens_per_s=50.0),
        _hb(tokens_per_s=123.4, queued=3),
    ])
    agg = HeartbeatAggregator(d, poll_interval_s=0.01)
    assert agg.poll_once() == 2
    labels = {"allocation": "0,1", "server": "server0"}
    assert _gauge(metrics.guest_tokens_per_s, **labels) == 123.4
    assert _gauge(metrics.guest_itl_p99_ms, **labels) == 12.5
    assert _gauge(metrics.guest_queue_depth, **labels) == 3
    assert _gauge(metrics.guest_batch_occupancy, **labels) == 0.75
    assert _gauge(metrics.guest_kv_pool_occupancy, **labels) == 0.5
    assert _gauge(metrics.guest_kv_host_occupancy, **labels) == 0.25
    assert _gauge(metrics.guest_last_heartbeat_ts, **labels) == 1700000000.0
    # Incremental: a second poll with nothing new consumes nothing.
    assert agg.poll_once() == 0
    _write_events(os.path.join(d, "guest_0-1.jsonl"), [
        _hb(tokens_per_s=99.0)
    ])
    assert agg.poll_once() == 1
    assert _gauge(metrics.guest_tokens_per_s, **labels) == 99.0
    snap = agg.snapshot()
    assert snap["0,1/server0"]["tokens_per_s"] == 99.0


def test_aggregator_allocation_falls_back_to_file_naming(tmp_path):
    # Events predating the heartbeat's own "chips" field (or emitted
    # outside an allocation) label by the allocator's file naming.
    d = str(tmp_path)
    _write_events(os.path.join(d, "guest_2-3.jsonl"), [
        _hb(server="srvX", chips="", tokens_per_s=7.0),
    ])
    agg = HeartbeatAggregator(d)
    assert agg.poll_once() == 1
    assert _gauge(
        metrics.guest_tokens_per_s, allocation="2,3", server="srvX"
    ) == 7.0


def test_aggregator_reemits_guest_alerts_host_side(tmp_path, capsys):
    d = str(tmp_path)
    path = os.path.join(d, "guest_4.jsonl")
    # Live tailing: the aggregator (daemon) is up BEFORE the guest
    # emits — its construction stamp is the catch-up horizon.
    agg = HeartbeatAggregator(d)
    now = time.time()
    _write_events(path, [
        _hb(server="s1", chips="4", ts=now),
        {"ts": now, "kind": "serving", "name": "watchdog_alert",
         "server": "s1", "chips": "4", "alert": "slo_burn",
         "reason": "burn_rate=1.00", "dump": "/tmp/dump.jsonl",
         "trace": "abc"},
    ])
    sink_path = os.path.join(d, "daemon_events.jsonl")
    sink = obs.EventSink(sink_path)
    prev = obs.set_default_sink(sink)
    try:
        agg.poll_once()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    labels = {"allocation": "4", "server": "s1"}
    assert _gauge(metrics.guest_watchdog_active, **labels) == 1
    assert metrics.guest_alerts_total.labels(
        allocation="4", server="s1", kind="slo_burn"
    )._value.get() == 1
    host_events = obs.read_events(sink_path)
    alerts = [e for e in host_events if e["name"] == "guest_alert"]
    assert alerts and alerts[0]["allocation"] == "4"
    assert alerts[0]["alert"] == "slo_burn"
    assert alerts[0]["dump"] == "/tmp/dump.jsonl"
    # The guest's clear drops the active gauge back to healthy.
    _write_events(path, [
        {"ts": time.time(), "kind": "serving", "name": "watchdog_clear",
         "server": "s1", "chips": "4", "alert": "slo_burn"},
    ])
    agg.poll_once()
    assert _gauge(metrics.guest_watchdog_active, **labels) == 0
    assert "4/s1" in agg.snapshot()
    assert agg.snapshot()["4/s1"]["active_alerts"] == []


def test_aggregator_restart_replay_restores_state_without_news(tmp_path):
    """Daemon restart: the hostPath stream outlives the pod, so the
    first poll re-reads history. State (gauges, active alerts,
    snapshot) must be restored; NEWS (counter increments, guest_alert
    re-emission) must not replay — a day of old incidents is catch-up,
    not a fresh burst."""
    d = str(tmp_path)
    old = time.time() - 3600  # history from before this "daemon" started
    _write_events(os.path.join(d, "guest_7.jsonl"), [
        _hb(server="s7", chips="7", ts=old, tokens_per_s=42.0),
        {"ts": old, "kind": "serving", "name": "watchdog_alert",
         "server": "s7", "chips": "7", "alert": "preempt_storm",
         "reason": "old", "dump": ""},
    ])
    sink_path = os.path.join(d, "daemon_events.jsonl")
    sink = obs.EventSink(sink_path)
    prev = obs.set_default_sink(sink)
    try:
        labels = {"allocation": "7", "server": "s7"}
        before = metrics.guest_alerts_total.labels(
            allocation="7", server="s7", kind="preempt_storm"
        )._value.get()
        hb_before = metrics.guest_heartbeats_total.labels(
            **labels
        )._value.get()
        agg = HeartbeatAggregator(d)
        assert agg.poll_once() == 1
        # State restored: last heartbeat's gauges + the still-active
        # alert (the guest never cleared it before the restart).
        assert _gauge(metrics.guest_tokens_per_s, **labels) == 42.0
        assert _gauge(metrics.guest_watchdog_active, **labels) == 1
        assert agg.snapshot()["7/s7"]["active_alerts"] == ["preempt_storm"]
        # No news: counters unchanged, nothing re-emitted host-side.
        assert metrics.guest_alerts_total.labels(
            allocation="7", server="s7", kind="preempt_storm"
        )._value.get() == before
        assert metrics.guest_heartbeats_total.labels(
            **labels
        )._value.get() == hb_before
    finally:
        obs.set_default_sink(prev)
        sink.close()
    # Nothing re-emitted host-side: the sink never even opened (its
    # file is created lazily on first emit).
    assert not os.path.exists(sink_path) or not [
        e for e in obs.read_events(sink_path) if e["name"] == "guest_alert"
    ]


def test_aggregator_truncates_streams_past_the_cap(tmp_path):
    """Rotator of last resort: the guest's full event stream grows
    unbounded on the hostPath, so once the consumed prefix passes the
    cap the aggregator truncates it — and the truncation-restart logic
    keeps tailing the stream's continuation from byte 0."""
    d = str(tmp_path)
    path = os.path.join(d, "guest_5.jsonl")
    agg = HeartbeatAggregator(d, max_stream_bytes=200)
    now = time.time()
    _write_events(path, [_hb(server="s5", chips="5", ts=now)] * 3)
    assert os.path.getsize(path) > 200
    assert agg.poll_once() == 3
    assert os.path.getsize(path) == 0  # consumed prefix dropped
    _write_events(path, [_hb(server="s5", chips="5", ts=now,
                             tokens_per_s=9.0)])
    assert agg.poll_once() == 1  # the continuation tails from byte 0
    assert _gauge(
        metrics.guest_tokens_per_s, allocation="5", server="s5"
    ) == 9.0


def test_aggregator_survives_junk_and_missing_dir(tmp_path):
    agg = HeartbeatAggregator(str(tmp_path / "missing"))
    assert agg.poll_once() == 0
    d = str(tmp_path)
    with open(os.path.join(d, "guest_9.jsonl"), "w") as fh:
        fh.write("not json\n")
        fh.write('{"kind": "serving", "name": "serving_heartbeat"')  # torn
    with open(os.path.join(d, "notes.txt"), "w") as fh:
        fh.write("ignored — not a .jsonl stream\n")
    agg2 = HeartbeatAggregator(d)
    assert agg2.poll_once() == 0  # junk consumed, torn tail left alone
