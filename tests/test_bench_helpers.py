"""Unit tests for bench.py's pure helpers.

The measurement pipeline itself is exercised on hardware (the bench-watch
watchdog banks real runs; `--smoke` validates the harness end-to-end), but
the chip-spec lookup that converts a device kind into roofline/MFU
denominators is pure logic and belongs in the suite: a wrong denominator
silently corrupts every `vs_baseline`/`train_mfu` the round banks.
"""
from __future__ import annotations

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench


class _Dev:
    def __init__(self, kind):
        self.device_kind = kind


@pytest.mark.parametrize(
    "kind, gbps, tflops",
    [
        ("TPU v5p", 2765.0, 459.0),
        ("TPU v4", 1228.0, 275.0),
        ("TPU v6e", 1640.0, 918.0),
        ("tpu v5e-8", 819.0, 197.0),
    ],
)
def test_detect_known_generations(kind, gbps, tflops):
    assert bench.detect_hbm_gbps(_Dev(kind)) == gbps
    assert bench.detect_mxu_tflops(_Dev(kind)) == tflops


def test_detect_unknown_kind_falls_back_by_backend(monkeypatch):
    """'TPU v5 lite' (the axon relay's kind string) matches no table key;
    the fallback keys off on_tpu(). Both tables must take the SAME branch —
    that is the point of the shared helper."""
    import kata_xpu_device_plugin_tpu.ops.attention as attention

    monkeypatch.setattr(attention, "on_tpu", lambda: True)
    assert bench.detect_hbm_gbps(_Dev("TPU v5 lite")) == bench.HBM_GBPS["v5e"]
    assert bench.detect_mxu_tflops(_Dev("TPU v5 lite")) == bench.MXU_TFLOPS["v5e"]

    # A kind matching no table key ("cpu" included), so the branch under
    # test is really the on_tpu()==False fallback, not a substring hit.
    monkeypatch.setattr(attention, "on_tpu", lambda: False)
    assert bench.detect_hbm_gbps(_Dev("Radeon")) == bench.HBM_GBPS["cpu"]
    assert bench.detect_mxu_tflops(_Dev("Radeon")) == bench.MXU_TFLOPS["cpu"]


def test_spec_tables_cover_same_generations():
    """A generation added to one table but not the other would make the
    decode roofline and the train MFU disagree about what chip this is."""
    assert set(bench.HBM_GBPS) == set(bench.MXU_TFLOPS)
