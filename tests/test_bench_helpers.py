"""Unit tests for bench.py's pure helpers.

The measurement pipeline itself is exercised on hardware (the bench-watch
watchdog banks real runs; `--smoke` validates the harness end-to-end), but
the chip-spec lookup that converts a device kind into roofline/MFU
denominators is pure logic and belongs in the suite: a wrong denominator
silently corrupts every `vs_baseline`/`train_mfu` the round banks.
"""
from __future__ import annotations

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench


class _Dev:
    def __init__(self, kind):
        self.device_kind = kind


@pytest.mark.parametrize(
    "kind, gbps, tflops",
    [
        ("TPU v5p", 2765.0, 459.0),
        ("TPU v4", 1228.0, 275.0),
        ("TPU v6e", 1640.0, 918.0),
        ("tpu v5e-8", 819.0, 197.0),
    ],
)
def test_detect_known_generations(kind, gbps, tflops):
    assert bench.detect_hbm_gbps(_Dev(kind)) == gbps
    assert bench.detect_mxu_tflops(_Dev(kind)) == tflops


def test_detect_unknown_kind_falls_back_by_backend(monkeypatch):
    """'TPU v5 lite' (the axon relay's kind string) matches no table key;
    the fallback keys off on_tpu(). Both tables must take the SAME branch —
    that is the point of the shared helper."""
    import kata_xpu_device_plugin_tpu.ops.attention as attention

    monkeypatch.setattr(attention, "on_tpu", lambda: True)
    assert bench.detect_hbm_gbps(_Dev("TPU v5 lite")) == bench.HBM_GBPS["v5e"]
    assert bench.detect_mxu_tflops(_Dev("TPU v5 lite")) == bench.MXU_TFLOPS["v5e"]

    # A kind matching no table key ("cpu" included), so the branch under
    # test is really the on_tpu()==False fallback, not a substring hit.
    monkeypatch.setattr(attention, "on_tpu", lambda: False)
    assert bench.detect_hbm_gbps(_Dev("Radeon")) == bench.HBM_GBPS["cpu"]
    assert bench.detect_mxu_tflops(_Dev("Radeon")) == bench.MXU_TFLOPS["cpu"]


def test_spec_tables_cover_same_generations():
    """A generation added to one table but not the other would make the
    decode roofline and the train MFU disagree about what chip this is."""
    assert set(bench.HBM_GBPS) == set(bench.MXU_TFLOPS)


# ----- bench-trend (ISSUE 11 satellite) --------------------------------------


def _bank(path, stamp, **fields):
    import json

    d = {"metric": "decode", "unit": "tok/s", "note": "x",
         "attempts": 1, "_all_lines": ["{}"],
         "phases": {"decode": {"count": 1}}}
    d.update(fields)
    p = path / f"BENCH_TPU_{stamp}.json"
    p.write_text(json.dumps(d))
    return p


def test_bench_trend_flags_headline_regression(tmp_path, capsys):
    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=1000.0,
          serving_tok_per_s=200.0, decode_s=0.5)
    _bank(tmp_path, "20260102T000000Z", value=800.0,  # -20%: regression
          serving_tok_per_s=205.0, decode_s=0.4)
    rc = bench_trend.main(["--dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "regression" in out and "value" in out
    # Context metrics (decode_s) are reported but never flagged.
    assert "1 regression(s)" in out


def test_bench_trend_flat_and_clean(tmp_path, capsys):
    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=1303.8, e2e_tok_per_s=1100.0)
    _bank(tmp_path, "20260102T000000Z", value=1303.8, e2e_tok_per_s=1150.0)
    rc = bench_trend.main(["--dir", str(tmp_path)])
    out = capsys.readouterr().out
    # The whole point of the tool: a bit-identical headline is marked
    # flat — the number nobody has moved — and is not a failure.
    assert rc == 0 and "flat" in out


def test_bench_trend_tripwire_nonzero_never_ages_into_baseline(tmp_path,
                                                               capsys):
    # ISSUE 19: the steady-state tripwire metrics gate on the NEW value
    # alone — two equal nonzero banks are still a regression, never
    # "flat", and the 10% threshold does not apply.
    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=1000.0,
          serving_steady_state_compiles=2.0)
    _bank(tmp_path, "20260102T000000Z", value=1000.0,
          serving_steady_state_compiles=2.0)
    rc = bench_trend.main(["--dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "serving_steady_state_compiles" in out


def test_bench_trend_tripwire_zero_ladder():
    # new==0 is the only passing value: recovery (nonzero → 0) reads
    # "improved", holding at zero reads "flat".
    from tools import bench_trend

    rows = bench_trend.compare(
        {"serving_steady_state_compiles": 3.0,
         "serving_steady_state_reshards": 0.0},
        {"serving_steady_state_compiles": 0.0,
         "serving_steady_state_reshards": 0.0},
    )
    by = {r["metric"]: r["status"] for r in rows}
    assert by["serving_steady_state_compiles"] == "improved"
    assert by["serving_steady_state_reshards"] == "flat"


def test_bench_trend_newest_two_and_sparse_banks(tmp_path, capsys):
    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=1.0)
    _bank(tmp_path, "20260102T000000Z", value=2000.0, int8_tok_per_s=5.0)
    _bank(tmp_path, "20260103T000000Z", value=2000.0)  # int8 vanished
    rc = bench_trend.main(["--dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    # Only the two NEWEST compare; fields present in one bank only are
    # skipped rather than crashing the comparison.
    assert "int8_tok_per_s" not in out
    assert "20260102" in out and "20260103" in out


def test_bench_trend_layout_flip_is_not_a_regression(tmp_path, capsys):
    # ISSUE 14 satellite: banks that flipped a *_layout config field
    # between rounds (an intentional heads → blocks A/B) print that
    # family's moved headline as "layout" — a fact, not a perf alarm —
    # and the flip itself is rendered; unrelated headline regressions
    # still flag.
    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=1000.0,
          serving_kv_layout="heads", serving_kv_sessions=4.0)
    _bank(tmp_path, "20260102T000000Z", value=1000.0,
          serving_kv_layout="blocks", serving_kv_sessions=32.0)
    rc = bench_trend.main(["--dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "layout change: serving_kv_layout heads -> blocks" in out
    assert "0 regression(s)" in out
    # The moved family metric carries the layout status, not improved.
    line = next(ln for ln in out.splitlines()
                if ln.startswith("serving_kv_sessions"))
    assert line.rstrip().endswith("layout")
    # A genuine regression elsewhere still fails even with a flip.
    _bank(tmp_path, "20260103T000000Z", value=500.0,
          serving_kv_layout="heads", serving_kv_sessions=4.0)
    assert bench_trend.main(["--dir", str(tmp_path)]) == 1


def test_bench_trend_single_bank_is_not_a_failure(tmp_path, capsys):
    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=1.0)
    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    assert "no trend yet" in capsys.readouterr().out


def test_bench_trend_zero_banks_notes_and_exits_zero(tmp_path, capsys):
    # ISSUE 13 satellite: an empty workspace degrades to the "no trend
    # yet" note on stdout and exit 0 — the CI step must be non-blocking
    # by CONTENT, not because continue-on-error masks a crash.
    from tools import bench_trend

    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    assert "no trend yet" in capsys.readouterr().out


def test_bench_trend_corrupt_bank_degrades(tmp_path, capsys):
    # A truncated/corrupt newest bank (a half-written file from an
    # interrupted bench round) is SKIPPED with a note, never a
    # traceback: with only one readable bank left the tool prints the
    # "no trend yet" note and exits 0; with two readable banks the
    # corrupt one is simply not part of the comparison.
    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=1.0)
    (tmp_path / "BENCH_TPU_20260102T000000Z.json").write_text('{"value": 1.1')
    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    cap = capsys.readouterr()
    assert "no trend yet" in cap.out
    assert "skipping unreadable bank" in cap.err
    # A non-dict bank (e.g. a JSONL list dumped by mistake) is the same
    # degrade class.
    (tmp_path / "BENCH_TPU_20260102T000000Z.json").write_text('[1, 2]')
    _bank(tmp_path, "20260103T000000Z", value=1.5)
    rc = bench_trend.main(["--dir", str(tmp_path)])
    cap = capsys.readouterr()
    assert rc == 0
    assert "20260101" in cap.out and "20260103" in cap.out


def test_bench_trend_fused_headline_present():
    # The fused serving tok/s is part of the headline set (ISSUE 13):
    # a >threshold drop must flag as a regression like the other
    # throughput headlines.
    from tools import bench_trend

    assert "serving_fused_tok_per_s" in bench_trend.HEADLINE_METRICS
    rows = bench_trend.compare(
        {"serving_fused_tok_per_s": 100.0},
        {"serving_fused_tok_per_s": 80.0},
    )
    assert rows[0]["status"] == "regression"


def test_bench_trend_numeric_metrics_filter():
    from tools import bench_trend

    rows = bench_trend.numeric_metrics({
        "value": 1.0, "note": "s", "_all_lines": [1], "attempts": 3,
        "phases": {"a": 1}, "ok": True, "serving_s": 2.5,
    })
    assert rows == {"value": 1.0, "serving_s": 2.5}


def test_bench_trend_analyzer_footer_from_report(tmp_path, capsys):
    # ISSUE 16 satellite: when a jaxguard_report.json sits next to the
    # banks (make analyze writes one), the trend footer carries the
    # findings count + by-rule breakdown — a pragma-heavy PR is visible
    # in the same place the perf trajectory is.
    import json as _json

    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=100.0)
    _bank(tmp_path, "20260102T000000Z", value=101.0)
    (tmp_path / "jaxguard_report.json").write_text(_json.dumps({
        "tool": "jaxguard",
        "summary": {"total": 3, "by_rule": {"JG201": 2, "JG304": 1}},
        "findings": [],
    }))
    rc = bench_trend.main(["--dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "jaxguard: 3 finding(s) (JG201=2, JG304=1)" in out


def test_bench_trend_analyzer_footer_absent_without_report(tmp_path, capsys):
    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=100.0)
    _bank(tmp_path, "20260102T000000Z", value=101.0)
    assert bench_trend.main(["--dir", str(tmp_path)]) == 0
    assert "jaxguard" not in capsys.readouterr().out


def test_bench_trend_analyzer_footer_in_json_and_corrupt_report(tmp_path,
                                                                capsys):
    import json as _json

    from tools import bench_trend

    _bank(tmp_path, "20260101T000000Z", value=100.0)
    _bank(tmp_path, "20260102T000000Z", value=101.0)
    (tmp_path / "jaxguard_report.json").write_text("{ truncated")
    assert bench_trend.main(["--dir", str(tmp_path), "--json"]) == 0
    data = _json.loads(capsys.readouterr().out)
    assert data["analyzer"] is None  # unreadable report degrades to None
    (tmp_path / "jaxguard_report.json").write_text(_json.dumps({
        "summary": {"total": 0, "by_rule": {}},
    }))
    assert bench_trend.main(["--dir", str(tmp_path), "--json"]) == 0
    data = _json.loads(capsys.readouterr().out)
    assert data["analyzer"] == {"total": 0, "by_rule": {}}
