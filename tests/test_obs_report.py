"""Offline telemetry reporter (ISSUE 15): report assembly, markdown
rendering, and the schema-drift gate — all over synthetic events, so the
suite needs no serving run (the jax-touching ``--generate`` path is the
``make obs-report`` smoke gate's job; its output schema is pinned here
by construction because both go through ``build_report``)."""
import json

from tools.obs_report import (
    SCHEMA_VERSION,
    build_report,
    check_schema,
    main,
    render_markdown,
)


def _events():
    evs = [
        {"ts": 10.0, "kind": "span", "name": "serving.prefill",
         "dur_s": 0.5},
        {"ts": 10.2, "kind": "span", "name": "serving.prefill",
         "dur_s": 0.3},
        {"ts": 10.4, "kind": "span", "name": "serving.decode_chunk",
         "dur_s": 1.2},
        {"ts": 11.0, "kind": "serving", "name": "serving_heartbeat",
         "server": "server0", "round": 4, "tokens_per_s": 100.0,
         "itl_p99_ms": 9.0, "batch_occupancy": 1.0,
         "kv_pool_occupancy": 0.5, "kv_host_occupancy": 0.0, "queued": 2,
         "phase_admit_s": 0.2, "phase_dispatch_s": 0.5,
         # Device ledger fields (ISSUE 17) — schema v2 requires at
         # least one server whose heartbeats carry them.
         "mfu": 0.1, "device_busy_frac": 0.8, "dispatch_gap_ms": 1.0,
         "dispatches_delta": 4, "dispatch_gap_admit_ms": 0.6,
         "dispatch_gap_other_ms": 0.4, "hbm_headroom_bytes": 1000},
        {"ts": 12.0, "kind": "serving", "name": "serving_heartbeat",
         "server": "server0", "round": 8, "tokens_per_s": 200.0,
         "itl_p99_ms": 7.0, "batch_occupancy": 0.5,
         "kv_pool_occupancy": 0.25, "kv_host_occupancy": 0.0, "queued": 0,
         "phase_admit_s": 0.1, "phase_dispatch_s": 0.6,
         "mfu": 0.3, "device_busy_frac": 1.0, "dispatch_gap_ms": 0.5,
         "dispatches_delta": 8, "dispatch_gap_admit_ms": 0.3,
         "dispatch_gap_other_ms": 0.2, "hbm_headroom_bytes": 500},
        {"ts": 12.5, "kind": "serving", "name": "request_trace",
         "server": "server0", "rid": 7, "outcome": "completed",
         "wall_s": 2.5, "tokens": 64, "prompt_len": 128, "replays": 0,
         "queue_s": 0.5, "prefill_s": 0.4, "decode_s": 1.6,
         "preempted_s": 0.0},
        {"ts": 12.6, "kind": "serving", "name": "request_trace",
         "server": "server0", "rid": 8, "outcome": "failed",
         "reason": "quarantined", "wall_s": 4.0, "tokens": 3,
         "prompt_len": 16, "replays": 2, "queue_s": 1.0, "recovery_s": 3.0},
        {"ts": 12.7, "kind": "serving", "name": "watchdog_alert",
         "server": "server0", "alert": "slo_burn",
         "reason": "burn_rate=0.83", "dump": "artifacts/d.jsonl",
         "round": 9},
        {"ts": 12.9, "kind": "serving", "name": "watchdog_clear",
         "server": "server0", "alert": "slo_burn", "round": 12},
        {"ts": 12.95, "kind": "serving", "name": "recovery",
         "server": "server0", "restored": 1},
    ]
    return evs


def test_build_report_sections():
    rep = build_report(_events(), source="synthetic", top=1)
    assert rep["schema"] == SCHEMA_VERSION
    assert rep["events"]["count"] == len(_events())
    assert rep["phases"]["serving.prefill"]["count"] == 2
    hb = rep["heartbeats"]["servers"]["server0"]
    assert hb["count"] == 2
    assert hb["tokens_per_s"] == {"min": 100.0, "mean": 150.0, "max": 200.0}
    assert hb["loop_phase_s"] == {"admit": 0.3, "dispatch": 1.1}
    assert len(hb["timeline"]) == 2
    # Utilization summary (ISSUE 17): min/mean/max over the carrying
    # heartbeats, gap-phase means weighted by dispatches_delta
    # ((4*0.6 + 8*0.3)/12 = 0.4 for admit), headroom present because
    # the stream carried it.
    util = hb["utilization"]
    assert util["count"] == 2
    assert util["mfu"] == {"min": 0.1, "mean": 0.2, "max": 0.3}
    assert util["dispatch_gap_ms"]["max"] == 1.0
    assert util["gap_phase_ms"] == {
        "admit": 0.4, "other": round((4 * 0.4 + 8 * 0.2) / 12, 4)
    }
    assert util["hbm_headroom_bytes"]["min"] == 500
    # top=1 keeps only the SLOWEST request; the failed 4.0s one wins.
    assert rep["requests"]["total_traces"] == 2
    (slow,) = rep["requests"]["slowest"]
    assert slow["rid"] == 8 and slow["outcome"] == "failed"
    assert slow["phases"] == {"queue": 1.0, "recovery": 3.0}
    inc = rep["incidents"]
    assert [a["alert"] for a in inc["alerts"]] == ["slo_burn"]
    assert [c["alert"] for c in inc["clears"]] == ["slo_burn"]
    assert inc["event_counts"]["recovery"] == 1
    assert check_schema(rep, require_data=True) == []


def test_markdown_renders_waterfall_requests_incidents():
    md = render_markdown(build_report(_events(), source="synthetic"))
    assert "## Phase waterfall" in md
    assert "serving.decode_chunk" in md and "█" in md
    assert "## Serving heartbeats" in md and "| 4 | 100.0 |" in md
    assert "rid     8" in md and "failed(quarantined)" in md
    assert "recovery 3.000s" in md
    assert "**slo_burn**" in md and "artifacts/d.jsonl" in md
    assert "cleared **slo_burn**" in md


def test_empty_stream_renders_without_data():
    rep = build_report([], source="empty")
    assert check_schema(rep) == []  # structurally valid...
    errs = check_schema(rep, require_data=True)  # ...but fails the smoke bar
    assert any("waterfall" in e for e in errs)
    assert any("heartbeat" in e for e in errs)
    md = render_markdown(rep)
    assert "no span events" in md and "no watchdog alerts" in md


def test_check_schema_catches_drift():
    rep = build_report(_events())
    del rep["incidents"]
    assert any("incidents" in e for e in check_schema(rep))
    rep2 = build_report(_events())
    rep2["schema"] = 99
    assert any("schema version" in e for e in check_schema(rep2))
    rep3 = build_report(_events())
    for r in rep3["requests"]["slowest"]:
        del r["phases"]
    assert any("missing phases" in e for e in check_schema(rep3))


def test_cli_round_trip(tmp_path, capsys):
    events_path = tmp_path / "ev.jsonl"
    with open(events_path, "w") as fh:
        for ev in _events():
            fh.write(json.dumps(ev) + "\n")
    json_path = tmp_path / "rep.json"
    md_path = tmp_path / "rep.md"
    rc = main([
        str(events_path), "--json", str(json_path), "--md", str(md_path),
        "--check", "--quiet",
    ])
    assert rc == 0
    assert "schema ok" in capsys.readouterr().err
    rep = json.loads(json_path.read_text())
    assert check_schema(rep, require_data=True) == []
    assert "## Phase waterfall" in md_path.read_text()


def test_cli_check_fails_on_dataless_stream(tmp_path, capsys):
    events_path = tmp_path / "empty.jsonl"
    events_path.write_text("")
    rc = main([str(events_path), "--check", "--quiet"])
    assert rc == 2
    assert "SCHEMA DRIFT" in capsys.readouterr().err
