"""Speculative decoding (models/speculative.py).

Oracle: greedy speculative decoding is LOSSLESS — output must equal
vanilla greedy `generate()` token for token, for any draft quality.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.models import (
    generate,
    generate_speculative,
    tiny_test_config,
)
from kata_xpu_device_plugin_tpu.models import speculative as spec_mod
from kata_xpu_device_plugin_tpu.models.speculative import ngram_propose
from kata_xpu_device_plugin_tpu.models.transformer import init_params


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def test_ngram_propose():
    hist = np.array([5, 9, 7, 3, 9, 8, 2], np.int32)
    # Most recent 9 is at index 4 → following tokens are 8, 2, then pad.
    np.testing.assert_array_equal(ngram_propose(hist, 9, 4), [8, 2, 9, 9])
    # Absent token: pure padding.
    np.testing.assert_array_equal(ngram_propose(hist, 6, 3), [6, 6, 6])
    # Match at the very end: nothing follows, pure padding.
    np.testing.assert_array_equal(ngram_propose(hist, 2, 2), [2, 2])


@pytest.mark.parametrize("k", [1, 3, 5])
def test_lossless_vs_greedy_random_prompt(model, k):
    cfg, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, cfg.vocab_size)
    ref = np.asarray(generate(params, prompt, cfg, 14, max_len=40))
    out = generate_speculative(params, prompt, cfg, 14, k=k, max_len=40)
    np.testing.assert_array_equal(out, ref)


def test_lossless_and_faster_on_repetitive_prompt(model, monkeypatch):
    # A periodic prompt makes the n-gram drafts accept, so the host loop
    # must finish in FEWER verify rounds than tokens (that is the point).
    cfg, params = model
    pattern = np.array([11, 23, 5, 17], np.int32)
    prompt = jnp.asarray(np.tile(pattern, 6)[None, :])  # [1, 24]
    steps = 16

    calls = {"n": 0}
    real = spec_mod.verify_step

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(spec_mod, "verify_step", counting)
    ref = np.asarray(generate(params, prompt, cfg, steps, max_len=64))
    out = generate_speculative(params, prompt, cfg, steps, k=4, max_len=64)
    np.testing.assert_array_equal(out, ref)
    # Greedy continuation of a periodic prompt may not itself be periodic,
    # but SOME drafts must land: strictly fewer rounds than tokens.
    assert calls["n"] < steps, calls


@pytest.mark.parametrize("k", [2, 4])
def test_lossless_on_gemma2_softcap_window_cycle(k):
    """Speculative verification on a Gemma-2-style config: the [B, k+1]
    multi-token verify forward crosses both softcaps AND the alternating
    local/global window cycle — still token-for-token equal to greedy."""
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config

    cfg = gemma2_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(11), cfg, dtype=jnp.float32)
    prompt = jax.random.randint(jax.random.PRNGKey(12), (2, 8), 0, cfg.vocab_size)
    ref = np.asarray(generate(params, prompt, cfg, 12, max_len=40))
    out = generate_speculative(params, prompt, cfg, 12, k=k, max_len=40)
    np.testing.assert_array_equal(out, ref)


def test_ragged_acceptance_across_batch(model):
    # One repetitive row (drafts accept) + one random row (drafts mostly
    # reject): rows advance at different rates — the ragged position path.
    cfg, params = model
    rep = np.tile(np.array([3, 19], np.int32), 8)
    rnd = np.asarray(
        jax.random.randint(jax.random.PRNGKey(2), (16,), 0, cfg.vocab_size),
        np.int32,
    )
    prompt = jnp.asarray(np.stack([rep, rnd]))
    ref = np.asarray(generate(params, prompt, cfg, 12, max_len=48))
    out = generate_speculative(params, prompt, cfg, 12, k=3, max_len=48)
    np.testing.assert_array_equal(out, ref)


def test_validation(model):
    cfg, params = model
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="k must be"):
        generate_speculative(params, prompt, cfg, 4, k=0)
    with pytest.raises(ValueError, match="headroom"):
        generate_speculative(params, prompt, cfg, 8, k=4, max_len=12)


# ----- draft-model speculation ----------------------------------------------


@pytest.mark.parametrize("k", [1, 3])
def test_draft_model_lossless(model, k):
    """Draft-MODEL speculation (a depth-truncated self-draft) must be
    token-identical to vanilla greedy — losslessness is independent of
    what proposes the drafts (VERDICT r4 weak #4)."""
    from kata_xpu_device_plugin_tpu.models import self_draft

    cfg, params = model
    draft = self_draft(params, cfg, 1)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 9), 0, cfg.vocab_size)
    ref = np.asarray(generate(params, prompt, cfg, 14, max_len=48))
    out = generate_speculative(params, prompt, cfg, 14, k=k, max_len=48,
                               draft=draft)
    np.testing.assert_array_equal(out, ref)


def test_draft_model_full_acceptance_covers_cache_hole(model):
    """A draft that IS the target accepts every draft — the adversarial
    case for the draft cache: every round advances the full k+1, so a
    missing k/v at pos+k (a k-step scan's unwritten last token) would
    poison later rounds. The k+1-step scan covers it; output must still
    be exactly greedy, and every round must accept all drafts."""
    cfg, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(6), (1, 8), 0, cfg.vocab_size)
    steps, k = 15, 3
    ref = np.asarray(generate(params, prompt, cfg, steps, max_len=64))
    out = generate_speculative(params, prompt, cfg, steps, k=k, max_len=64,
                               draft=(params, cfg))
    np.testing.assert_array_equal(out, ref)


def test_draft_model_lossless_gemma2_cycle():
    """Draft-model speculation across Gemma-2's softcap + window cycle:
    the self-draft depth must stay cycle-aligned (self_draft enforces it),
    and the draft's own cycle-aware cache tracks positions correctly."""
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config, self_draft

    cfg = gemma2_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(11), cfg, dtype=jnp.float32)
    draft = self_draft(params, cfg, len(cfg.window_cycle))
    prompt = jax.random.randint(jax.random.PRNGKey(12), (2, 8), 0, cfg.vocab_size)
    ref = np.asarray(generate(params, prompt, cfg, 12, max_len=40))
    out = generate_speculative(params, prompt, cfg, 12, k=2, max_len=40,
                               draft=draft)
    np.testing.assert_array_equal(out, ref)


def test_self_draft_validation(model):
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config, self_draft

    cfg, params = model
    with pytest.raises(ValueError, match="depth"):
        self_draft(params, cfg, cfg.n_layers)
    with pytest.raises(ValueError, match="depth"):
        self_draft(params, cfg, 0)
    g2 = gemma2_test_config()
    g2_params = init_params(jax.random.PRNGKey(0), g2)
    if len(g2.window_cycle) > 1:
        with pytest.raises(ValueError, match="cycle"):
            self_draft(g2_params, g2, 1)
    dp, dc = self_draft(params, cfg, 1)
    assert dc.n_layers == 1
    assert dp["layers"]["wq"].shape[0] == 1


def test_draft_vocab_mismatch_rejected(model):
    from dataclasses import replace

    cfg, params = model
    bad_cfg = replace(cfg, vocab_size=cfg.vocab_size + 1)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="vocab"):
        generate_speculative(params, prompt, cfg, 4, k=2, draft=(params, bad_cfg))


# ----- speculative SAMPLING (temperature > 0, lossless rejection scheme) ----


def test_sample_accept_row_distribution():
    """The fundamental lemma of speculative sampling: whatever the
    proposal q, the FIRST emitted token is distributed exactly as the
    target p[0]. Verified empirically (fixed seed, 40k trials → TV
    noise ≈ 0.008; threshold 0.025 gives 3× headroom)."""
    from kata_xpu_device_plugin_tpu.models.speculative import sample_accept_row

    rng = np.random.default_rng(0)
    V, k = 6, 2
    p = np.array([[.4, .3, .1, .1, .05, .05],
                  [.1, .1, .5, .1, .1, .1],
                  [.2, .2, .2, .2, .1, .1]])
    q = np.array([[.3, .3, .2, .1, .05, .05],
                  [.25, .25, .1, .2, .1, .1]])
    N = 40000
    counts = np.zeros(V)
    for _ in range(N):
        drafts = np.array([rng.choice(V, p=q[i]) for i in range(k)])
        counts[sample_accept_row(drafts, q, p, rng)[0]] += 1
    tv = 0.5 * np.abs(counts / N - p[0]).sum()
    assert tv < 0.025, tv


def test_sample_accept_row_perfect_proposal_accepts_all():
    """q == p: every draft accepts (ratio 1) and the bonus token samples
    from p[k] — the output length is always k+1."""
    from kata_xpu_device_plugin_tpu.models.speculative import sample_accept_row

    rng = np.random.default_rng(1)
    V, k = 5, 3
    p = np.tile(np.array([.3, .3, .2, .1, .1]), (k + 1, 1))
    q = p[:k]
    for _ in range(200):
        drafts = np.array([rng.choice(V, p=q[i]) for i in range(k)])
        out = sample_accept_row(drafts, q, p, rng)
        assert len(out) == k + 1
        assert out[:k] == list(drafts)


def test_speculative_sampling_generate(model):
    """temperature>0 speculative generation: reproducible per seed,
    varies across seeds, works with draft-model AND n-gram proposals."""
    from kata_xpu_device_plugin_tpu.models import self_draft

    cfg, params = model
    draft = self_draft(params, cfg, 1)
    prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 8), 0,
                                cfg.vocab_size)
    kw = dict(steps=12, k=3, max_len=40, temperature=0.8)
    a = generate_speculative(params, prompt, cfg, draft=draft, seed=5, **kw)
    b = generate_speculative(params, prompt, cfg, draft=draft, seed=5, **kw)
    c = generate_speculative(params, prompt, cfg, draft=draft, seed=6, **kw)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    d = generate_speculative(params, prompt, cfg, seed=5, **kw)  # n-gram q
    assert d.shape == (2, 12)


def test_sample_accept_device_matches_target_distribution():
    """The on-device rejection kernel (what serving actually runs): over
    40k vectorized rows of one fixed (p, q), the first emitted token's
    empirical distribution must match p[0] — same lemma, device RNG."""
    from kata_xpu_device_plugin_tpu.models.speculative import (
        sample_accept_device,
    )

    V, k, N = 6, 2, 40000
    p = np.array([[.4, .3, .1, .1, .05, .05],
                  [.1, .1, .5, .1, .1, .1],
                  [.2, .2, .2, .2, .1, .1]], np.float32)
    q = np.array([[.3, .3, .2, .1, .05, .05],
                  [.25, .25, .1, .2, .1, .1]], np.float32)
    key = jax.random.PRNGKey(0)
    k_d, k_a = jax.random.split(key)
    # Drafts sampled from q per row (the proposal the proof requires).
    drafts = jnp.stack([
        jax.random.categorical(jax.random.fold_in(k_d, i),
                               jnp.log(jnp.asarray(q[i]))[None, :]
                               .repeat(N, 0))
        for i in range(k)
    ], axis=1).astype(jnp.int32)  # [N, k]
    # logits whose temperature-1 softmax is exactly p, tiled per row.
    logits = jnp.log(jnp.asarray(p))[None].repeat(N, 0)  # [N, k+1, V]
    toks, counts = sample_accept_device(
        drafts, jnp.asarray(q)[None].repeat(N, 0), logits,
        jnp.float32(1.0), k_a, k,
    )
    first = np.asarray(toks[:, 0])
    emp = np.bincount(first, minlength=V) / N
    tv = 0.5 * np.abs(emp - p[0]).sum()
    assert tv < 0.025, tv
    assert np.all((np.asarray(counts) >= 1) & (np.asarray(counts) <= k + 1))
