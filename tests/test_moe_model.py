"""MoE wired into the model stack (VERDICT r2 item 3): a Mixtral-style
config must flow through forward / next_token_loss / make_train_step with
experts sharded over the mesh, and match the per-token reference expert
computation when capacity is ample."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import parallel
from kata_xpu_device_plugin_tpu.models import mixtral_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    forward,
    generate,
    init_params,
    next_token_loss,
)
from kata_xpu_device_plugin_tpu.ops import moe as moe_mod


@pytest.fixture(scope="module")
def cfg():
    return mixtral_test_config(dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(0), cfg)


def _tokens(cfg, shape=(2, 16)):
    return jax.random.randint(
        jax.random.PRNGKey(1), shape, 0, cfg.vocab_size, dtype=jnp.int32
    )


def test_moe_forward_matches_per_token_reference(cfg, params, monkeypatch):
    """At ample capacity the dispatch machinery must equal computing each
    token's top-k experts directly (reference_moe)."""
    tokens = _tokens(cfg)
    out = forward(params, tokens, cfg)

    real_moe_ffn = moe_mod.moe_ffn

    def via_reference(p, x, mcfg, mesh=None, axis=None):
        del mesh, axis
        return moe_mod.reference_moe(p, x, mcfg), jnp.float32(0.0)

    monkeypatch.setattr(moe_mod, "moe_ffn", via_reference)
    ref = forward(params, tokens, cfg)
    monkeypatch.setattr(moe_mod, "moe_ffn", real_moe_ffn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_moe_aux_loss_plumbed(cfg, params):
    """The load-balancing aux term must reach the training loss."""
    from dataclasses import replace

    tokens = _tokens(cfg)
    with_aux = next_token_loss(params, tokens, cfg)
    without = next_token_loss(params, tokens, replace(cfg, moe_aux_weight=0.0))
    # aux_loss >= 1.0 by construction (E * sum f_i p_i minimized at uniform),
    # so the weighted difference must be positive and roughly aux_weight-sized.
    diff = float(with_aux - without)
    assert diff > 0.5 * cfg.moe_aux_weight, diff


def test_moe_train_step_ep_fsdp(cfg):
    """An ep×fsdp train step: experts shard over the model axis, tokens over
    data/fsdp; loss is finite and decreases."""
    mesh = parallel.build_mesh(
        {"data": 1, "fsdp": 2, "model": 4}, devices=jax.devices()
    )
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    # Expert-major tensors really are sharded over the model axis.
    w = state["params"]["layers"]["moe_w_gate"]
    assert w.sharding.spec[1] == "model"
    tokens = parallel.shard_batch(_tokens(cfg, (8, 16)), mesh)
    losses = []
    for _ in range(3):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_moe_generate_runs(cfg, params):
    out = generate(params, _tokens(cfg, (2, 8)), cfg, steps=4, max_len=16)
    assert out.shape == (2, 4)
    assert bool(jnp.all((out >= 0) & (out < cfg.vocab_size)))


def test_moe_param_count_formula(cfg):
    params = init_params(jax.random.PRNGKey(0), cfg)
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_moe_sharded_dispatch_matches_reference():
    """VERDICT r2 item 4: token-sharded dispatch on a 2-D (data × expert)
    mesh — per-shard sort/scatter, all_to_all capacity buffers — must equal
    the per-token reference at ample capacity, and each device must hold
    only its T/n token shard of the dispatch work."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    mcfg = moe_mod.MoEConfig(
        d_model=16, d_ff=32, num_experts=4, capacity_factor=8.0, top_k=2
    )
    mesh = Mesh(mesh_utils.create_device_mesh((2, 4)), ("data", "expert"))
    mparams = moe_mod.init_moe_params(jax.random.PRNGKey(0), mcfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, mcfg.d_model))

    ref = moe_mod.reference_moe(mparams, x, mcfg)
    y, aux = jax.jit(lambda p, t: moe_mod.moe_ffn_sharded(p, t, mcfg, mesh))(
        mparams, x
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4, atol=1e-5)
    assert float(aux) > 0

    # Global-formula aux and sharded-global aux agree (same routing).
    _, aux_global = moe_mod.moe_ffn(mparams, x, mcfg)
    np.testing.assert_allclose(float(aux), float(aux_global), rtol=1e-5)


def test_moe_sharded_rejects_indivisible():
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    mcfg = moe_mod.MoEConfig(d_model=16, d_ff=32, num_experts=3, top_k=1)
    mesh = Mesh(mesh_utils.create_device_mesh((2, 4)), ("data", "expert"))
    mparams = moe_mod.init_moe_params(jax.random.PRNGKey(0), mcfg)
    x = jnp.zeros((2, 16, 16))
    with pytest.raises(ValueError, match="not divisible"):
        moe_mod.moe_ffn_sharded(mparams, x, mcfg, mesh)  # E=3, ep=4
    mcfg4 = moe_mod.MoEConfig(d_model=16, d_ff=32, num_experts=4, top_k=1)
    mparams4 = moe_mod.init_moe_params(jax.random.PRNGKey(0), mcfg4)
    with pytest.raises(ValueError, match="not divisible"):
        moe_mod.moe_ffn_sharded(mparams4, jnp.zeros((3, 3, 16)), mcfg4, mesh)


def test_moe_indivisible_batch_falls_back_to_global_dispatch():
    """A batch that is valid for the dense model must train for MoE too:
    when T doesn't divide the mesh, the layer falls back to the GSPMD global
    dispatch instead of raising."""
    cfg = mixtral_test_config(dtype=jnp.float32)
    mesh = parallel.build_mesh(
        {"data": 1, "fsdp": 2, "model": 4}, devices=jax.devices()
    )
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    # B=4, S=16 → T = 4*15 = 60, not divisible by 8.
    tokens = parallel.shard_batch(_tokens(cfg, (4, 16)), mesh)
    state, loss = step(state, tokens)
    assert np.isfinite(float(loss))


def test_moe_rejected_by_pipeline():
    cfg = mixtral_test_config(dtype=jnp.float32)
    mesh = parallel.composed_mesh(2, 2, 2)
    with pytest.raises(ValueError, match="aux loss"):
        parallel.make_pp_loss(cfg, mesh, n_stages=2, num_microbatches=4)
