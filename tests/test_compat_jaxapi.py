"""Unit tests for the JAX-version compat shim.

The resolvers in ``compat.jaxapi`` each take the ``jax`` module as a
parameter, so both sides of every version gate are driven here with FAKE
module surfaces — an "old" 0.4.x-shaped jax (``experimental.shard_map``,
no ``AxisType``, ``check_rep``/``auto`` spellings) and a "new" stable-line
jax (``jax.shard_map``, typed mesh axes) — regardless of which JAX is
actually installed. The installed-jax integration (the module-level
exports) is covered at the end.
"""
from types import SimpleNamespace

import pytest

from kata_xpu_device_plugin_tpu.compat import jaxapi


# ----- fake surfaces ---------------------------------------------------------


def _record(**defaults):
    """A callable that records how it was called and returns its kwargs."""
    calls = []

    def fn(*args, **kwargs):
        calls.append((args, kwargs))
        return SimpleNamespace(args=args, kwargs={**defaults, **kwargs})

    fn.calls = calls
    return fn


class _FakeMesh:
    axis_names = ("pipe", "fsdp", "model")


def make_old_jax():
    """0.4.x shape: shard_map lives in jax.experimental.shard_map with
    check_rep/auto; jax.sharding has no AxisType; make_mesh takes no
    axis_types; lax has neither pvary nor axis_size (psum idiom)."""
    raw_shard_map = _record()
    make_mesh = _record()
    # mirror the real 0.4.x signature (no axis_types parameter)
    make_mesh.__signature__ = None

    def old_make_mesh(axis_shapes, axis_names, *, devices=None):
        return SimpleNamespace(
            axis_shapes=axis_shapes, axis_names=axis_names, devices=devices
        )

    psum_calls = []

    def psum(x, name):
        psum_calls.append((x, name))
        return 8  # concrete trace-time value, as on the real 0.4.x line

    return SimpleNamespace(
        __version__="0.4.37",
        __name__="fake_old_jax",
        experimental=SimpleNamespace(
            shard_map=SimpleNamespace(shard_map=raw_shard_map)
        ),
        sharding=SimpleNamespace(
            Mesh=_FakeMesh, NamedSharding=object, PartitionSpec=tuple
        ),
        make_mesh=old_make_mesh,
        lax=SimpleNamespace(psum=psum),
        tree=SimpleNamespace(map=min, leaves=max, flatten=sum, unflatten=any),
        tree_util=SimpleNamespace(tree_map_with_path=all),
        config=SimpleNamespace(jax_threefry_partitionable=False,
                               update=_record()),
        _raw_shard_map=raw_shard_map,
        _psum_calls=psum_calls,
    )


def make_new_jax():
    """Stable-line shape: jax.shard_map with check_vma/axis_names; typed
    mesh axes; lax.pvary; make_mesh takes axis_types."""
    stable_shard_map = _record()

    class AxisType:
        Auto = "auto"
        Explicit = "explicit"

    def new_make_mesh(axis_shapes, axis_names, *, axis_types=None, devices=None):
        return SimpleNamespace(
            axis_shapes=axis_shapes, axis_names=axis_names,
            axis_types=axis_types, devices=devices,
        )

    pvary_calls = []

    def pvary(x, axes):
        pvary_calls.append((x, axes))
        return x

    return SimpleNamespace(
        __version__="0.8.0",
        __name__="fake_new_jax",
        shard_map=stable_shard_map,
        sharding=SimpleNamespace(
            Mesh=_FakeMesh, NamedSharding=object, PartitionSpec=tuple,
            AxisType=AxisType,
        ),
        make_mesh=new_make_mesh,
        lax=SimpleNamespace(pvary=pvary, axis_size=lambda name: 4),
        tree=SimpleNamespace(map=min, leaves=max, flatten=sum, unflatten=any),
        tree_util=SimpleNamespace(tree_map_with_path=all),
        _pvary_calls=pvary_calls,
    )


# ----- shard_map -------------------------------------------------------------


def test_shard_map_resolves_stable_on_new():
    new = make_new_jax()
    fn, style = jaxapi.resolve_shard_map(new)
    assert style == "stable" and fn is new.shard_map


def test_shard_map_resolves_experimental_on_old():
    old = make_old_jax()
    fn, style = jaxapi.resolve_shard_map(old)
    assert style == "experimental" and fn is old._raw_shard_map


def test_shard_map_missing_raises_with_version_hint():
    bare = SimpleNamespace(__version__="0.4.1", __name__="fake_bare",
                           experimental=SimpleNamespace())
    with pytest.raises(jaxapi.JaxCompatError) as err:
        jaxapi.resolve_shard_map(bare)
    assert "shard_map" in str(err.value)
    assert "0.4.26" in str(err.value)  # names the minimum version


def test_shard_map_wrapper_translates_kwargs_on_old():
    """check_vma → check_rep, axis_names (manual set) → auto (complement)."""
    old = make_old_jax()
    raw, style = jaxapi.resolve_shard_map(old)
    sm = jaxapi.build_shard_map(raw, style)
    mesh = _FakeMesh()
    body = lambda x: x  # noqa: E731

    sm(body, mesh=mesh, in_specs=(), out_specs=(), check_vma=False)
    _, kwargs = raw.calls[-1]
    assert kwargs["check_rep"] is False and "check_vma" not in kwargs

    sm(body, mesh=mesh, in_specs=(), out_specs=(), axis_names={"pipe"})
    _, kwargs = raw.calls[-1]
    assert kwargs["auto"] == frozenset({"fsdp", "model"})
    assert "axis_names" not in kwargs


def test_shard_map_wrapper_native_kwargs_on_new():
    """Stable line: kwargs forward under their native names, and None means
    'use the version default' — the raw fn must NOT receive check_vma=None
    (its own default is True; a literal None would silently disable it)."""
    new = make_new_jax()
    raw, style = jaxapi.resolve_shard_map(new)
    sm = jaxapi.build_shard_map(raw, style)
    body = lambda x: x  # noqa: E731

    sm(body, mesh=_FakeMesh(), in_specs=(), out_specs=())
    _, kwargs = raw.calls[-1]
    assert "check_vma" not in kwargs and "axis_names" not in kwargs

    sm(body, mesh=_FakeMesh(), in_specs=(), out_specs=(),
       check_vma=False, axis_names={"pipe"})
    _, kwargs = raw.calls[-1]
    assert kwargs["check_vma"] is False
    assert kwargs["axis_names"] == {"pipe"}
    assert "check_rep" not in kwargs and "auto" not in kwargs


# ----- AxisType / make_mesh --------------------------------------------------


def test_axis_type_native_on_new_fallback_on_old():
    new, old = make_new_jax(), make_old_jax()
    assert jaxapi.resolve_axis_type(new) is new.sharding.AxisType
    fallback = jaxapi.resolve_axis_type(old)
    assert fallback is jaxapi._FallbackAxisType
    assert {t.name for t in fallback} >= {"Auto", "Explicit", "Manual"}


def test_make_mesh_forwards_axis_types_on_new():
    new = make_new_jax()
    at = jaxapi.resolve_axis_type(new)
    mm = jaxapi.build_make_mesh(new, at)
    mesh = mm((2, 2), ("a", "b"), axis_types=(at.Auto, at.Auto), devices=[1, 2, 3, 4])
    assert mesh.axis_types == (at.Auto, at.Auto)


def test_make_mesh_drops_auto_rejects_explicit_on_old():
    old = make_old_jax()
    at = jaxapi.resolve_axis_type(old)
    mm = jaxapi.build_make_mesh(old, at)
    # Auto is the 0.4.x default semantics — silently dropped.
    mesh = mm((2, 2), ("a", "b"), axis_types=(at.Auto, at.Auto), devices=[1, 2, 3, 4])
    assert mesh.axis_names == ("a", "b")
    # Anything else cannot be honored on untyped meshes — loud failure.
    with pytest.raises(jaxapi.JaxCompatError, match="AxisType.Auto"):
        mm((2, 2), ("a", "b"), axis_types=(at.Explicit, at.Auto))


# ----- pvary / axis_size -----------------------------------------------------


def test_pvary_native_on_new_noop_on_old():
    new, old = make_new_jax(), make_old_jax()
    pv_new = jaxapi.resolve_pvary(new)
    sentinel = object()
    assert pv_new(sentinel, ("pipe",)) is sentinel
    assert new._pvary_calls == [(sentinel, ("pipe",))]
    pv_old = jaxapi.resolve_pvary(old)
    assert pv_old(sentinel, ("pipe",)) is sentinel  # no-op, no error


def test_axis_size_native_on_new_psum_idiom_on_old():
    new, old = make_new_jax(), make_old_jax()
    assert jaxapi.resolve_axis_size(new)("i") == 4
    assert jaxapi.resolve_axis_size(old)("i") == 8
    assert old._psum_calls == [(1, "i")]


# ----- sharding types / tree utils ------------------------------------------


def test_sharding_types_resolve_and_missing_raises():
    old = make_old_jax()
    mesh_cls, named, pspec = jaxapi.resolve_sharding_types(old)
    assert mesh_cls is _FakeMesh and pspec is tuple
    with pytest.raises(jaxapi.JaxCompatError, match="Mesh"):
        jaxapi.resolve_sharding_types(
            SimpleNamespace(sharding=SimpleNamespace())
        )


def test_tree_utils_prefer_jax_tree_then_tree_util():
    old = make_old_jax()
    utils = jaxapi.resolve_tree_utils(old)
    assert utils["tree_map"] is min and utils["tree_map_with_path"] is all
    # jax.tree absent → the tree_util spellings back it up
    tu_only = SimpleNamespace(
        tree_util=SimpleNamespace(
            tree_map=min, tree_leaves=max, tree_flatten=sum,
            tree_unflatten=any, tree_map_with_path=all,
        )
    )
    utils = jaxapi.resolve_tree_utils(tu_only)
    assert utils["tree_flatten"] is sum
    with pytest.raises(jaxapi.JaxCompatError, match="tree_map"):
        jaxapi.resolve_tree_utils(SimpleNamespace())


# ----- config normalizers ----------------------------------------------------


def test_normalize_rng_config_flips_only_when_off():
    old = make_old_jax()
    assert jaxapi.normalize_rng_config(old) is True
    assert old.config.update.calls[-1][0] == ("jax_threefry_partitionable", True)
    on = SimpleNamespace(
        config=SimpleNamespace(jax_threefry_partitionable=True, update=_record())
    )
    assert jaxapi.normalize_rng_config(on) is False
    assert on.config.update.calls == []
    # newer lines that removed the flag entirely: nothing to do
    assert jaxapi.normalize_rng_config(SimpleNamespace(config=SimpleNamespace())) is False


def test_parse_version():
    assert jaxapi.parse_version("0.4.37") == (0, 4, 37)
    assert jaxapi.parse_version("0.5.0.dev20250101") == (0, 5, 0)
    assert jaxapi.parse_version("0.8") == (0, 8, 0)


# ----- pallas compiler params ------------------------------------------------


def test_pallas_compiler_params_prefers_new_name():
    new_mod = SimpleNamespace(CompilerParams=dict, TPUCompilerParams=list)
    assert jaxapi.resolve_pallas_compiler_params(new_mod) is dict
    old_mod = SimpleNamespace(TPUCompilerParams=list)
    assert jaxapi.resolve_pallas_compiler_params(old_mod) is list
    with pytest.raises(jaxapi.JaxCompatError, match="CompilerParams"):
        jaxapi.resolve_pallas_compiler_params(SimpleNamespace())


# ----- installed-jax integration --------------------------------------------


def test_module_exports_resolve_against_installed_jax():
    """Whatever JAX the image ships, every export must have resolved."""
    import jax

    assert jaxapi.JAX_VERSION == jaxapi.parse_version(jax.__version__)
    assert jaxapi.SHARD_MAP_STYLE in ("stable", "experimental")
    assert callable(jaxapi.shard_map)
    assert callable(jaxapi.make_mesh)
    assert callable(jaxapi.tree_map)
    assert jaxapi.Mesh is jax.sharding.Mesh
    assert jaxapi.P is jax.sharding.PartitionSpec
    # the RNG normalization must have left sharded-init == eager-init
    assert jax.config.jax_threefry_partitionable is True


def test_installed_shard_map_runs_a_psum():
    """End-to-end: the wrapped shard_map actually executes on the installed
    line, including the check_vma spelling."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    mesh = jaxapi.Mesh(np.array(devs[: min(4, len(devs))]), ("i",))
    n = len(mesh.devices)

    out = jaxapi.shard_map(
        lambda x: jax.lax.psum(x, "i"),
        mesh=mesh,
        in_specs=jaxapi.P("i"),
        out_specs=jaxapi.P(),
        check_vma=False,
    )(jnp.arange(float(n)))
    # each device contributes its single-element shard; psum replicates [sum]
    assert float(out[0]) == sum(range(n))
