"""The fit() training loop (parallel/trainer.py).

The contract under test: a preempted-and-resumed run replays the
uninterrupted run exactly — same batches, same losses, bit-identical
final state — because the loader cursor checkpoints with the train state.
"""
import jax
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.models import llama3_train_test
from kata_xpu_device_plugin_tpu.parallel import (
    build_mesh,
    fit,
    make_loader,
    make_train_step,
)

TOKENS = np.arange(4096, dtype=np.int32) % 500


@pytest.fixture(scope="module")
def setup():
    cfg = llama3_train_test()
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    init_state, step = make_train_step(cfg, mesh)
    return cfg, mesh, init_state, step


def _loader(mesh, seed=5):
    return make_loader(TOKENS, batch=8, seq_len=31, mesh=mesh, seed=seed)


def test_fit_runs_and_returns_losses(setup):
    cfg, mesh, init_state, step = setup
    state, losses = fit(init_state, step, _loader(mesh), steps=3,
                        key=jax.random.PRNGKey(0))
    assert len(losses) == 3
    assert all(np.isfinite(l) for l in losses)
    assert int(state["step"]) == 3


def test_fit_on_seq_composed_mesh():
    """The long-context training story end to end: fit() + the resumable
    loader over a mesh with a seq axis — ring attention runs inside the
    train step, batches shard (batch over data axes, sequence over seq),
    and the loss goes down."""
    cfg = llama3_train_test()
    mesh = build_mesh({"data": 1, "fsdp": 2, "model": 2, "seq": 2})
    init_state, step = make_train_step(cfg, mesh)
    # seq_len=31 → 32-token windows (inputs+targets): the window, not
    # seq_len, is what must divide the mesh's seq axis.
    loader = make_loader(TOKENS, batch=4, seq_len=31, mesh=mesh, seed=7)
    state, losses = fit(init_state, step, loader, steps=4,
                        key=jax.random.PRNGKey(2))
    assert len(losses) == 4 and all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_resume_replays_uninterrupted_run(setup, tmp_path):
    cfg, mesh, init_state, step = setup
    key = jax.random.PRNGKey(1)

    # Uninterrupted reference run (no checkpointing).
    ref_state, ref_losses = fit(init_state, step, _loader(mesh), steps=6,
                                key=key)

    # Interrupted run: checkpoint every 2 steps, "preempt" after 3.
    d = str(tmp_path / "ckpt")
    state_a, losses_a = fit(init_state, step, _loader(mesh), steps=3,
                            key=key, ckpt_dir=d, ckpt_every=2)
    # Resume with a FRESH loader and fresh everything: fit() must restore
    # train state + loader cursor from step 2 and land on the same run.
    state_b, losses_b = fit(init_state, step, _loader(mesh), steps=6,
                            key=key, ckpt_dir=d, ckpt_every=2)
    # Resumed run re-executes steps 3..6 (start at checkpointed step 2).
    assert len(losses_b) == 4
    np.testing.assert_allclose(losses_a[:2], ref_losses[:2], rtol=1e-6)
    np.testing.assert_allclose(losses_b, ref_losses[2:], rtol=1e-6)
    assert int(state_b["step"]) == 6
    # Bit-identical FINAL STATE, not just losses: a restore that silently
    # re-initialized (say) the optimizer moments could still match losses
    # over a few steps.
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        state_b, ref_state,
    )


def test_on_step_callback_and_validation(setup):
    cfg, mesh, init_state, step = setup
    seen = []
    fit(init_state, step, _loader(mesh), steps=2, on_step=lambda s, l: seen.append(s))
    assert seen == [1, 2]
    with pytest.raises(ValueError, match="ckpt_dir"):
        fit(init_state, step, _loader(mesh), steps=1, ckpt_every=2)
