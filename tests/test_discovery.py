"""Discovery tests against fake sysfs/dev trees (SURVEY §4 unit strategy)."""
import pytest

from kata_xpu_device_plugin_tpu import discovery
from kata_xpu_device_plugin_tpu.discovery import pciids, sysfs


@pytest.fixture
def fake(tmp_path):
    return sysfs.FakeSysfsBuilder(root=str(tmp_path))


def _v5e8_host(fake):
    """A v5e-8 host: 8 accel chips with Google PCIe endpoints."""
    for i in range(8):
        fake.add_accel_chip(i)
        fake.add_pci_function(
            f"0000:0{i}:01.0", vendor="1ae0", device="0063", numa_node=i // 4
        )
    return fake


def test_scan_tpus_v5e8(fake):
    _v5e8_host(fake)
    inv = discovery.scan_tpus(fake.sysfs, fake.dev, env={})
    assert inv.count == 8
    assert [c.index for c in inv.chips] == list(range(8))
    assert inv.chips[0].dev_path.endswith("/dev/accel0")
    assert inv.chips[3].pci_address == "0000:03:01.0"
    assert inv.chips[5].numa_node == 1
    assert inv.model_suffix == "TPU_V5E"
    assert inv.topology.accelerator_type == "v5litepod-8"
    assert inv.topology.local_chips == 8
    assert not inv.topology.is_multi_host


def test_scan_tpus_accel_without_pci(fake):
    # GKE guests may hide PCI topology: /dev/accel alone must still work.
    for i in range(4):
        fake.add_accel_chip(i)
    inv = discovery.scan_tpus(fake.sysfs, fake.dev, env={})
    assert inv.count == 4
    assert inv.chips[0].pci_address is None
    assert inv.model_suffix == "TPU"
    assert inv.topology.accelerator_type == "v5litepod-4"


def test_scan_tpus_respects_env_accel_type(fake):
    for i in range(4):
        fake.add_accel_chip(i)
    inv = discovery.scan_tpus(
        fake.sysfs, fake.dev, env={"TPU_ACCELERATOR_TYPE": "v5p-8", "TPU_WORKER_ID": "1"}
    )
    assert inv.topology.accelerator_type == "v5p-8"
    assert inv.topology.total_chips == 4
    assert inv.topology.worker_id == 1


def test_scan_tpus_filters_gve_nic(fake):
    fake.add_accel_chip(0)
    fake.add_pci_function("0000:00:01.0", vendor="1ae0", device="0063")
    fake.add_pci_function("0000:00:04.0", vendor="1ae0", device="0042", driver="gve")
    inv = discovery.scan_tpus(fake.sysfs, fake.dev, env={})
    assert inv.count == 1
    assert inv.chips[0].pci_device == "0063"


def test_scan_tpus_empty_host(fake):
    # BASELINE configs[0]: 0-chip dry run must not blow up.
    inv = discovery.scan_tpus(fake.sysfs, fake.dev, env={})
    assert inv.count == 0


def test_scan_vfio_groups_and_models(fake):
    # Two GPUs of one model in separate groups + a multi-function board
    # sharing group 3 + one non-vfio device that must be ignored.
    fake.add_pci_function("0000:01:00.0", "10de", "2203", driver="vfio-pci", iommu_group="1")
    fake.add_pci_function("0000:02:00.0", "10de", "2203", driver="vfio-pci", iommu_group="2")
    fake.add_pci_function("0000:03:00.0", "10de", "2204", driver="vfio-pci", iommu_group="3")
    fake.add_pci_function("0000:03:00.1", "10de", "1aef", driver="vfio-pci", iommu_group="3")
    fake.add_pci_function("0000:04:00.0", "10de", "2203", driver="nvidia", iommu_group="4")
    inv = discovery.scan_vfio(fake.sysfs, vendors=("10de",))
    assert sorted(inv.groups) == ["1", "2", "3"]
    assert len(inv.groups["3"]) == 2
    assert inv.models[("10de", "2203")] == ["1", "2"]
    assert inv.groups["1"][0].vfio_node == "/dev/vfio/1"


def test_scan_vfio_vendor_filter_open(fake):
    # TPU chips bound to vfio-pci for whole-VM passthrough are discoverable
    # through the generalized path too.
    fake.add_pci_function("0000:05:00.0", "1ae0", "0063", driver="vfio-pci", iommu_group="7")
    inv = discovery.scan_vfio(fake.sysfs)
    assert list(inv.models) == [("1ae0", "0063")]
    assert inv.model_suffix(("1ae0", "0063")) == "TPU_V5E"


def test_pciids_parse_and_fallbacks():
    db = pciids.PciIds.parse(
        "# comment\n"
        "10de  NVIDIA Corporation\n"
        "\t2203  GA102 [GeForce RTX 3090 Ti]\n"
        "\t\t10de 1234  Some subsystem\n"
        "C 03  Display controller\n"
        "\t00  VGA compatible controller\n"
    )
    assert db.vendor_name("10de") == "NVIDIA Corporation"
    assert db.device_name("10de", "2203") == "GA102 [GeForce RTX 3090 Ti]"
    # class-section device lines must not leak into vendor tables
    assert db.device_name("10de", "00") is None
    assert pciids.resource_suffix("10de", "2203", db) == "GA102_GEFORCE_RTX_3090_TI"
    assert pciids.resource_suffix("10de", "ffff", db) == "ffff"  # raw-hex fallback
    assert pciids.resource_suffix("1ae0", "0063") == "TPU_V5E"  # builtin, no db
    assert pciids.resource_suffix("1ae0", "9999") == "TPU"  # unknown TPU id


def test_shipped_data_file_parses():
    import os

    path = os.path.join(
        os.path.dirname(__file__), os.pardir, "kata_xpu_device_plugin_tpu", "data", "pci.ids"
    )
    with open(path) as f:
        db = pciids.PciIds.parse(f.read())
    assert db.vendor_name("1ae0") == "Google, Inc."
    assert db.device_name("1ae0", "0063") == "Cloud TPU v5e"


def test_sanitize_name():
    assert pciids.sanitize_name("GA102 [GeForce RTX 3090]") == "GA102_GEFORCE_RTX_3090"
    assert pciids.sanitize_name("  weird--name!! ") == "WEIRD_NAME"


def test_load_prefers_system_db_then_authored_fallback(tmp_path, monkeypatch):
    """The image build installs the full pci.ids at the ladder's first
    system path (Dockerfile); load() must prefer it over the 24-line
    authored table — and fall back to the authored table when no system
    DB exists (offline / PCI_IDS_FETCH=0 builds)."""
    system = tmp_path / "pci.ids"
    system.write_text(
        "8086  Intel Corporation\n"
        "\t10fb  82599ES 10-Gigabit SFI/SFP+\n"
        "1ae0  Google, Inc.\n"
    )
    monkeypatch.setattr(pciids, "SYSTEM_PCIIDS_PATHS", (str(system),))
    db = pciids.PciIds.load()
    # Content only the (fake) full system DB has — proves which file won.
    assert db.vendor_name("8086") == "Intel Corporation"
    assert pciids.resource_suffix("8086", "10fb", db) == "82599ES_10_GIGABIT_SFI_SFP"

    # No system DB → the authored in-package table serves.
    monkeypatch.setattr(
        pciids, "SYSTEM_PCIIDS_PATHS", (str(tmp_path / "missing"),)
    )
    fallback = pciids.PciIds.load()
    # The authored table names vendors but carries no non-TPU devices.
    assert fallback.device_name("8086", "10fb") is None
    assert fallback.device_name("1ae0", "0063") == "Cloud TPU v5e"


def test_scan_tpus_env_isolation(fake, monkeypatch):
    # An explicit empty env must NOT fall back to os.environ.
    monkeypatch.setenv("TPU_WORKER_ID", "3")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "a,b")
    fake.add_accel_chip(0)
    inv = discovery.scan_tpus(fake.sysfs, fake.dev, env={})
    assert inv.topology.worker_id == 0
    assert inv.topology.worker_hostnames == ()


def test_scan_tpus_pci_correlation_survives_missing_node(fake):
    # /dev/accel1 gone but all PCI functions present: accel2 must keep ITS
    # BDF (index-based correlation), not shift onto chip 1's.
    _v5e8_host(fake)
    fake.remove_dev_node("accel1")
    inv = discovery.scan_tpus(fake.sysfs, fake.dev, env={})
    assert [c.index for c in inv.chips] == [0, 2, 3, 4, 5, 6, 7]
    assert inv.chip(2).pci_address == "0000:02:01.0"
    assert inv.chip(7).pci_address == "0000:07:01.0"


def test_pciids_explicit_path_must_exist(tmp_path):
    with pytest.raises(OSError):
        pciids.PciIds.load(str(tmp_path / "nope.ids"))


def test_scan_tpus_ignores_unbound_nic_with_unknown_id(fake):
    # A momentarily-unbound gVNIC (vendor 1ae0, unknown device id) must not
    # shift chips onto the wrong BDF: strict known-id filter wins.
    _v5e8_host(fake)
    fake.add_pci_function("0000:00:00.5", "1ae0", "0042")  # sorts first, no driver
    inv = discovery.scan_tpus(fake.sysfs, fake.dev, env={})
    assert inv.count == 8
    assert inv.chip(0).pci_address == "0000:00:01.0"


def test_detect_family_from_pci_id(fake):
    # v5p host (4 chips, device id 0062) without env: must NOT be labelled
    # v5litepod — wrong slice dimensionality.
    for i in range(4):
        fake.add_accel_chip(i)
        fake.add_pci_function(f"0000:0{i}:01.0", "1ae0", "0062")
    inv = discovery.scan_tpus(fake.sysfs, fake.dev, env={})
    assert inv.topology.accelerator_type == "v5p-8"
    assert inv.topology.family.name == "v5p"
