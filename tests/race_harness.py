"""Barrier-driven runtime race harness — the dynamic twin of the JG2xx
static lock-discipline pass (``tools/analyze/concurrency.py``).

The static pass proves the lock DISCIPLINE; this harness hammers the
actual shared structures the daemon runs hot — the
:class:`AllocationJournal` (concurrent Allocate handlers), the
:class:`HeartbeatAggregator` (tail loop vs. the SIGUSR1 snapshot
thread), the flight ring (every emitting thread vs. a mid-flight dump),
and the :class:`MetricsRegistry` (idempotent factory under concurrent
first-use) — and asserts the two properties a race would break first:

- **parse-back integrity**: every on-disk artifact (journal JSON, flight
  dump JSONL) re-reads as complete, well-formed records — no torn lines,
  no interleaved writes;
- **counter conservation**: N threads × M ops in, exactly N×M effects
  out — no lost journal entries, no dropped heartbeats, no double- or
  under-counted metric increments.

All scheduling is deterministic-seeded: every worker gets its own
``random.Random(seed, tid)`` and jitters between ops, so a failing
iteration is re-runnable by seed. Not collected by pytest (the filename
carries no ``test_`` prefix on purpose — 200 iterations belong in the
``make race`` CI job, see ``tests/test_jaxguard_concurrency.py`` for
the single-iteration smoke wrappers). Run directly::

    RACE_ITERS=200 python tests/race_harness.py

Environment:

- ``RACE_ITERS``     — iterations (default 200; each varies the seed)
- ``RACE_SEED``      — base seed (default 0); a failure prints its seed,
  so ``RACE_SEED=<seed> RACE_ITERS=1`` replays that schedule alone
- ``RACE_THREADS``   — workers per scenario (default 4)
- ``RACE_OPS``       — ops per worker (default 16)
- ``RACE_ARTIFACTS`` — dir for the event-stream artifacts of the LAST
  iteration (default ``artifacts``; empty string disables)

jax-free: the daemon-side structures under stress import no jax, so the
harness runs in the no-jax CI lane.
"""
from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from prometheus_client import CollectorRegistry  # noqa: E402

from kata_xpu_device_plugin_tpu.obs.flight import FlightRecorder  # noqa: E402
from kata_xpu_device_plugin_tpu.obs.metrics import MetricsRegistry  # noqa: E402
from kata_xpu_device_plugin_tpu.plugin.manager import (  # noqa: E402
    AllocationJournal,
    HeartbeatAggregator,
)

DEFAULT_THREADS = 4
DEFAULT_OPS = 16
_JITTER_S = 0.0003


def run_threads(n: int, worker, seed: int) -> None:
    """Start ``n`` workers behind one barrier, join them, re-raise the
    first failure. ``worker(tid, rng)`` gets a per-thread seeded RNG —
    interleavings vary by seed, never by wall clock."""
    barrier = threading.Barrier(n)
    errors: list = []

    def body(tid: int) -> None:
        rng = random.Random(seed * 1009 + tid)
        try:
            barrier.wait(timeout=30)
            worker(tid, rng)
        except BaseException as exc:  # noqa: BLE001 — reported below
            errors.append((tid, exc))

    threads = [
        threading.Thread(target=body, args=(tid,), name=f"race-{tid}")
        for tid in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    alive = [t.name for t in threads if t.is_alive()]
    if alive:
        raise AssertionError(f"workers wedged (deadlock?): {alive}")
    if errors:
        tid, exc = errors[0]
        raise AssertionError(
            f"{len(errors)} worker(s) failed; first: thread {tid}: "
            f"{type(exc).__name__}: {exc}"
        ) from exc


# ----- scenarios -----------------------------------------------------------


def stress_journal(workdir: str, threads: int = DEFAULT_THREADS,
                   ops: int = DEFAULT_OPS, seed: int = 0) -> dict:
    """Concurrent ``record()`` (the Allocate-handler path): every entry
    must survive, and the journal file must parse back whole."""
    path = os.path.join(workdir, "journal.json")
    journal = AllocationJournal(path)

    def worker(tid: int, rng: random.Random) -> None:
        for i in range(ops):
            journal.record("google.com/tpu", [f"tpu-{tid}-{i}"])
            time.sleep(rng.random() * _JITTER_S)

    run_threads(threads, worker, seed)
    expect = threads * ops
    with open(path, encoding="utf-8") as fh:
        on_disk = json.load(fh)  # raises on a torn/interleaved write
    devices = on_disk["devices"]
    assert len(devices) == expect, (
        f"journal lost entries: {len(devices)}/{expect} on disk"
    )
    reread = AllocationJournal(path)
    groups = reread.allocations("google.com/tpu")
    assert len(groups) == expect, (
        f"parse-back lost groups: {len(groups)}/{expect}"
    )
    return {"scenario": "journal", "entries": len(devices),
            "expected": expect}


def stress_aggregator(workdir: str, threads: int = DEFAULT_THREADS,
                      ops: int = DEFAULT_OPS, seed: int = 0) -> dict:
    """Writers append guest heartbeats (one stream file per allocation,
    append-mode like the real sink) while the tail loop polls and a
    debug thread snapshots CONCURRENTLY: every written heartbeat is
    consumed exactly once, and snapshot() never observes a torn poll."""
    events_dir = os.path.join(workdir, "guest-events")
    os.makedirs(events_dir, exist_ok=True)
    agg = HeartbeatAggregator(events_dir, poll_interval_s=0.001)
    consumed = [0]
    writers_left = [threads]
    count_lock = threading.Lock()
    writers_done = threading.Event()

    def writer(tid: int, rng: random.Random) -> None:
        path = os.path.join(events_dir, f"guest_{tid}.jsonl")
        with open(path, "a", encoding="utf-8") as fh:
            for i in range(ops):
                fh.write(json.dumps({
                    "ts": time.time(), "kind": "serving",
                    "name": "serving_heartbeat", "server": f"s{tid}",
                    "round": i, "tokens_per_s": 100.0 + i,
                    "queued": 0, "interval_rounds": 1,
                }) + "\n")
                fh.flush()
                time.sleep(rng.random() * _JITTER_S)
        with count_lock:
            writers_left[0] -= 1
            if writers_left[0] == 0:
                writers_done.set()

    def worker(tid: int, rng: random.Random) -> None:
        if tid < threads:
            writer(tid, rng)
        elif tid == threads:
            # Poller races the writers live; sole poll_once caller, so
            # the aggregator's offset map sees one consuming thread.
            while not writers_done.is_set():
                got = agg.poll_once()
                with count_lock:
                    consumed[0] += got
                time.sleep(rng.random() * _JITTER_S)
        else:
            # Snapshotter: the SIGUSR1 debug-report path, mid-poll.
            while not writers_done.is_set():
                snap = agg.snapshot()  # must never raise mid-poll
                assert isinstance(snap, dict)
                time.sleep(rng.random() * _JITTER_S)

    run_threads(threads + 2, worker, seed)
    # Final single-threaded drain: whatever the racing poller missed
    # between the last writers' flush and their done-signal.
    consumed[0] += agg.poll_once()
    expect = threads * ops
    assert consumed[0] == expect, (
        f"heartbeats lost or double-consumed: {consumed[0]}/{expect}"
    )
    snap = agg.snapshot()
    assert len(snap) == threads, (
        f"snapshot lost servers: {len(snap)}/{threads}"
    )
    return {"scenario": "aggregator", "consumed": consumed[0],
            "expected": expect, "servers": len(snap)}


def stress_flight(workdir: str, threads: int = DEFAULT_THREADS,
                  ops: int = DEFAULT_OPS, seed: int = 0) -> dict:
    """Concurrent ``record()`` against the bounded ring with dumps taken
    MID-RACE: every dump file parses line-complete, and the final dump
    holds exactly min(capacity, N×M) events."""
    from kata_xpu_device_plugin_tpu.obs import flight

    rec = FlightRecorder(capacity=threads * ops)
    dump_paths: list = []

    def worker(tid: int, rng: random.Random) -> None:
        for i in range(ops):
            rec.record({
                "ts": time.time(), "kind": "serving", "name": "tok",
                "tid": tid, "i": i,
            })
            if tid == 0 and i == ops // 2:
                path = rec.dump("race_mid")
                if path:
                    dump_paths.append(path)
            time.sleep(rng.random() * _JITTER_S)

    prev_dir = os.environ.get(flight.ENV_DIR)
    os.environ[flight.ENV_DIR] = workdir  # keep dumps in this iteration
    try:
        run_threads(threads, worker, seed)
        final = rec.dump("race_final")
    finally:
        if prev_dir is None:
            os.environ.pop(flight.ENV_DIR, None)
        else:
            os.environ[flight.ENV_DIR] = prev_dir
    assert final is not None
    dump_paths.append(final)
    expect = threads * ops
    final_count = 0
    for path in dump_paths:
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        events = [json.loads(line) for line in lines]  # torn line raises
        assert all("name" in ev for ev in events)
        if path == final:
            final_count = len(events)
    assert final_count == expect, (
        f"flight ring lost events: final dump {final_count}/{expect}"
    )
    return {"scenario": "flight", "events": final_count,
            "expected": expect, "dumps": list(dump_paths)}


def stress_metrics(workdir: str, threads: int = DEFAULT_THREADS,
                   ops: int = DEFAULT_OPS, seed: int = 0) -> dict:
    """Concurrent factory use + increments on one fresh registry: the
    idempotent ``counter()`` cache must hand every thread the SAME
    collector, and no increment may be lost."""
    reg = MetricsRegistry(CollectorRegistry())
    collectors: list = []

    def worker(tid: int, rng: random.Random) -> None:
        for i in range(ops):
            c = reg.counter("race_ops", "harness ops", ["tid"])
            collectors.append(c)
            c.labels(tid=str(tid)).inc()
            time.sleep(rng.random() * _JITTER_S)

    run_threads(threads, worker, seed)
    assert len(set(map(id, collectors))) == 1, (
        "factory returned distinct collectors for one name"
    )
    total = 0.0
    for tid in range(threads):
        total += collectors[0].labels(tid=str(tid))._value.get()
    expect = threads * ops
    assert total == expect, f"increments lost: {total}/{expect}"
    return {"scenario": "metrics", "total": int(total), "expected": expect}


SCENARIOS = (stress_journal, stress_aggregator, stress_flight,
             stress_metrics)


def run_iteration(seed: int, threads: int = DEFAULT_THREADS,
                  ops: int = DEFAULT_OPS,
                  keep_dir: str = "") -> list:
    """One pass over every scenario in a throwaway workdir; returns the
    per-scenario stats. ``keep_dir`` preserves the workdir's event
    artifacts (journal, guest streams, flight dumps) there."""
    results = []
    workdir = tempfile.mkdtemp(prefix=f"race_{seed}_")
    try:
        for scenario in SCENARIOS:
            sub = os.path.join(workdir, scenario.__name__)
            os.makedirs(sub, exist_ok=True)
            results.append(scenario(sub, threads=threads, ops=ops,
                                     seed=seed))
        if keep_dir:
            os.makedirs(keep_dir, exist_ok=True)
            for name in ("stress_journal/journal.json",):
                src = os.path.join(workdir, name)
                if os.path.exists(src):
                    shutil.copy(src, os.path.join(
                        keep_dir, "race_journal.json"
                    ))
            streams = os.path.join(workdir, "stress_aggregator",
                                   "guest-events")
            if os.path.isdir(streams):
                for fname in sorted(os.listdir(streams)):
                    shutil.copy(
                        os.path.join(streams, fname),
                        os.path.join(keep_dir, f"race_{fname}"),
                    )
            for res in results:
                for dump in res.get("dumps", ()):
                    if os.path.exists(dump):
                        shutil.copy(dump, os.path.join(
                            keep_dir, os.path.basename(dump)
                        ))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return results


def main() -> int:
    iters = int(os.environ.get("RACE_ITERS", "200"))
    threads = int(os.environ.get("RACE_THREADS", str(DEFAULT_THREADS)))
    ops = int(os.environ.get("RACE_OPS", str(DEFAULT_OPS)))
    seed0 = int(os.environ.get("RACE_SEED", "0"))  # replay a failure
    artifacts = os.environ.get("RACE_ARTIFACTS", "artifacts")
    t0 = time.time()
    for it in range(iters):
        seed = seed0 + it
        keep = artifacts if it == iters - 1 else ""
        try:
            results = run_iteration(seed=seed, threads=threads, ops=ops,
                                    keep_dir=keep)
        except AssertionError as exc:
            print(f"race harness FAILED at iteration {it} (seed={seed} — "
                  f"replay with RACE_SEED={seed} RACE_ITERS=1): {exc}",
                  file=sys.stderr)
            return 1
        if (it + 1) % 50 == 0 or it == iters - 1:
            print(f"race harness: {it + 1}/{iters} iterations clean "
                  f"({time.time() - t0:.1f}s)")
    if artifacts:
        os.makedirs(artifacts, exist_ok=True)
        summary = {
            "iterations": iters, "threads": threads, "ops": ops,
            "strict": os.environ.get("KATA_TPU_STRICT", ""),
            "elapsed_s": round(time.time() - t0, 2),
            "last_iteration": results,
        }
        with open(os.path.join(artifacts, "race_summary.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(summary, fh, indent=2)
            fh.write("\n")
    print(f"race harness: {iters} iterations × {threads} threads × "
          f"{ops} ops — zero lost/torn events or journal entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
