"""Device-utilization & HBM ledger (ISSUE 17).

Three layers under test. UNIT: the ledger's cost capture (once per
executable signature, degrade-by-event when the backend reports no
FLOPs), the gap attribution (phase shares sum to the measured gap BY
CONSTRUCTION), the memory poll (omission — never fake zeros — on
backends without ``memory_stats``; component attribution + signed
residual when present), and the two new watchdog rules
(``device_idle`` / ``hbm_headroom_collapse``) on the existing
sustain/clear machinery. SERVER: the heartbeat carries the full
utilization field set on CPU with the ``hbm_*`` fields omitted and the
degrade announced, the ``KATA_TPU_DEVLEDGER=0`` kill switch, and greedy
outputs BIT-IDENTICAL ledger on/off (``make devledger`` runs this file
under both strict modes). HOST: the daemon aggregator re-exports
``guest_mfu`` / ``guest_hbm_headroom_bytes`` omission-preserving (no
gauge child for guests whose heartbeats lack the fields) and restart
replay restores state without re-announcing history. Plus the ISSUE 17
bug-risk fix: a second profiler hook racing an armed window degrades to
one ``profiler_busy`` event instead of raising out of the loop."""
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest.serving import (
    LOOP_PHASES,
    GenerationServer,
    _PhaseClock,
)
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params
from kata_xpu_device_plugin_tpu.obs import devledger as dl_mod
from kata_xpu_device_plugin_tpu.obs import profiler as prof_mod
from kata_xpu_device_plugin_tpu.obs.devledger import DeviceLedger
from kata_xpu_device_plugin_tpu.obs.watchdog import (
    ALERT_DEVICE_IDLE,
    ALERT_HBM_HEADROOM_COLLAPSE,
    SLOBurnWatchdog,
    WatchdogConfig,
)

UTIL_FIELDS = (
    {"mfu", "device_busy_frac", "dispatch_gap_ms", "dispatches_delta"}
    | {f"dispatch_gap_{p}_ms" for p in LOOP_PHASES}
)


# ----- unit: ledger mechanics ------------------------------------------------


class _FakeLowered:
    def __init__(self, cost):
        self._cost = cost

    def cost_analysis(self):
        return self._cost

    def compile(self):
        raise RuntimeError("unit ledger must not compile")


class _FakeFn:
    """Stands in for a jitted executable: counts lowerings."""

    def __init__(self, cost):
        self.cost = cost
        self.lowered = 0

    def lower(self, *args, **kwargs):
        self.lowered += 1
        return _FakeLowered(self.cost)


class _FakeDevice:
    platform = "cpu"
    device_kind = "cpu"

    def __init__(self, stats=None):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def _ledger(evs, **kw):
    kw.setdefault("device", _FakeDevice())
    kw.setdefault("gap_phases", LOOP_PHASES)
    return DeviceLedger(
        armed=True,
        emit=lambda name, **f: evs.append({"name": name, **f}),
        **kw,
    )


def test_cost_captured_once_per_signature():
    evs = []
    led = _ledger(evs)
    fn = _FakeFn({"flops": 2.0e9, "bytes accessed": 1.0e6})
    for _ in range(3):
        led.on_dispatch(("plain", True, 2), fn, (), {})
        led.note_retire()
    assert fn.lowered == 1
    led.on_dispatch(("plain", True, 4), fn, (), {})
    led.note_retire()
    assert fn.lowered == 2
    st = led.stats_fields()["devledger"]
    assert st["cost_signatures"] == 2
    assert st["cost_unavailable"] == 0
    assert st["dispatches"] == 4 and st["retired"] == 4
    # MFU math: interval FLOPs over wall × peak (cpu 0.1 TFLOP/s × tp=1).
    fields = led.heartbeat_fields(interval_s=2.0)
    assert fields["mfu"] == round(4 * 2.0e9 / (2.0 * 0.1e12), 6)
    assert fields["dispatches_delta"] == 4
    assert not [e for e in evs if e["name"] == "cost_unavailable"]


def test_persistent_flops_scaled_by_delivered_steps():
    # ISSUE 20: cost_analysis on the while_loop executable reports the
    # WHOLE loop's FLOPs at the static cap — a round that exited after
    # ``delivered`` of ``cap`` steps must credit delivered/cap of them,
    # never the full loop (which would double-count work the device
    # never did and flatter MFU).
    evs = []
    led = _ledger(evs)
    fn = _FakeFn({"flops": 8.0e9})
    led.on_dispatch(("persistent", True, 8), fn, (), {}, loop_cap=8)
    led.note_retire(delivered_steps=2)           # early exit: 2/8 of the loop
    st = led.stats_fields()["devledger"]
    fields = led.heartbeat_fields(interval_s=1.0)
    assert fields["mfu"] == round(8.0e9 * (2 / 8) / (1.0 * 0.1e12), 6)
    assert st["cost_signatures"] == 1
    # Full-cap round credits the full loop; out-of-range delivered
    # counts clamp to [0, cap].
    led.on_dispatch(("persistent", True, 8), fn, (), {}, loop_cap=8)
    led.note_retire(delivered_steps=8)
    led.on_dispatch(("persistent", True, 8), fn, (), {}, loop_cap=8)
    led.note_retire(delivered_steps=99)
    fields = led.heartbeat_fields(interval_s=1.0)
    assert fields["mfu"] == round(2 * 8.0e9 / (1.0 * 0.1e12), 6)
    assert fn.lowered == 1                       # one signature, one lowering


def test_fixed_step_dispatch_ignores_delivered_steps():
    # A fixed-step dispatch (no loop_cap) keeps whole-signature credit
    # even if a caller passes delivered_steps — the scale rides ONLY on
    # pending entries that declared a cap.
    evs = []
    led = _ledger(evs)
    fn = _FakeFn({"flops": 3.0e9})
    led.on_dispatch(("plain", True, 2), fn, (), {})
    led.note_retire(delivered_steps=1)
    fields = led.heartbeat_fields(interval_s=1.0)
    assert fields["mfu"] == round(3.0e9 / (1.0 * 0.1e12), 6)


def test_cost_unavailable_degrades_once_per_signature():
    evs = []
    led = _ledger(evs)

    class _Raising:
        def lower(self, *a, **kw):
            raise TypeError("no lowering for you")

    fn = _Raising()
    led.on_dispatch(("k1",), fn, (), {})
    led.note_retire()
    led.on_dispatch(("k1",), fn, (), {})  # cached None: never re-lowers
    led.note_retire()
    unavail = [e for e in evs if e["name"] == "cost_unavailable"]
    assert len(unavail) == 1
    assert unavail[0]["reason"].startswith("lower_failed:TypeError")
    assert unavail[0]["signature"] == repr(("k1",))
    fields = led.heartbeat_fields(interval_s=1.0)
    assert fields["mfu"] == 0.0  # degraded, not faked
    assert fields["device_busy_frac"] >= 0.0
    assert led.stats_fields()["devledger"]["cost_unavailable"] == 1


def test_no_flops_cost_degrades():
    evs = []
    led = _ledger(evs)

    class _Lowered:
        def cost_analysis(self):
            return {"bytes accessed": 5.0}  # no flops key

        def compile(self):
            raise RuntimeError("backend refuses")

    class _Fn:
        def lower(self, *a, **kw):
            return _Lowered()

    led.on_dispatch(("k",), _Fn(), (), {})
    assert [e["reason"] for e in evs if e["name"] == "cost_unavailable"] \
        == ["no_flops"]


def test_gap_attribution_sums_to_gap_exactly():
    clock = _PhaseClock(armed=True)
    evs = []
    led = _ledger(evs, clock=clock, gap_phases=LOOP_PHASES)
    fn = _FakeFn({"flops": 1.0e6})
    led.on_dispatch(("k",), fn, (), {})
    led.note_retire()
    # Host work between retire and the next dispatch, split across
    # phases the clock knows plus untracked time (→ "other").
    clock.push("admit")
    time.sleep(0.004)
    clock.pop()
    time.sleep(0.002)  # untracked
    clock.push("host_transfer")
    time.sleep(0.003)
    clock.pop()
    led.on_dispatch(("k",), fn, (), {})
    led.note_retire()
    fields = led.heartbeat_fields(interval_s=1.0)
    gap = fields["dispatch_gap_ms"]
    assert gap > 0
    parts = {p: fields[f"dispatch_gap_{p}_ms"] for p in LOOP_PHASES}
    # Shares sum to the measured gap by construction (rescale +
    # residual→other); tolerance is the 4-decimal field rounding only.
    assert abs(sum(parts.values()) - gap) <= 1e-3 * len(parts)
    assert parts["admit"] > 0
    assert parts["host_transfer"] > 0
    assert parts["other"] > 0  # the untracked sleep
    assert parts["dispatch"] == 0.0


def test_first_dispatch_has_no_gap():
    evs = []
    led = _ledger(evs, clock=_PhaseClock(armed=True),
                  gap_phases=LOOP_PHASES)
    led.on_dispatch(("k",), _FakeFn({"flops": 1.0}), (), {})
    fields = led.heartbeat_fields(interval_s=1.0)
    assert fields["dispatch_gap_ms"] == 0.0  # no retire→dispatch window yet


def test_memory_poll_omits_fields_and_announces_once():
    evs = []
    led = _ledger(evs, device=_FakeDevice(stats=None))
    assert led.poll_memory() == {}
    assert led.poll_memory() == {}
    unavail = [e for e in evs if e["name"] == "hbm_stats_unavailable"]
    assert len(unavail) == 1
    assert unavail[0]["reason"] == "memory_stats_none"
    fields = led.heartbeat_fields(interval_s=1.0)
    assert UTIL_FIELDS <= set(fields)  # full util set, zeros included
    assert not [k for k in fields if k.startswith("hbm_")]
    assert led.hbm_headroom() is None
    assert led.stats_fields()["devledger"]["hbm_stats_available"] == 0


def test_memory_poll_attributes_components_and_tracks_watermark():
    evs = []
    dev = _FakeDevice(stats={
        "bytes_in_use": 1000, "bytes_limit": 4000,
        "peak_bytes_in_use": 1200,
    })
    led = _ledger(
        evs, device=dev,
        components=lambda: {"params": 600, "kv_arena": 300,
                            "prefix_store": 0},
    )
    out = led.poll_memory()
    assert out["hbm_used_bytes"] == 1000
    assert out["hbm_limit_bytes"] == 4000
    assert out["hbm_headroom_bytes"] == 3000
    assert out["hbm_peak_bytes"] == 1200
    assert out["hbm_params_bytes"] == 600
    assert out["hbm_kv_arena_bytes"] == 300
    assert out["hbm_attributed_bytes"] == 900
    assert out["hbm_unattributed_bytes"] == 100  # the visible residual
    # Watermark is cumulative across polls, even when the backend's own
    # peak resets.
    dev._stats = {"bytes_in_use": 3500, "bytes_limit": 4000,
                  "peak_bytes_in_use": 0}
    out = led.poll_memory()
    assert out["hbm_peak_bytes"] == 3500
    assert out["hbm_headroom_bytes"] == 500
    fields = led.heartbeat_fields(interval_s=1.0)
    assert led.hbm_headroom() == fields["hbm_headroom_bytes"]
    assert not [e for e in evs if e["name"] == "hbm_stats_unavailable"]


def test_disarmed_ledger_is_inert():
    evs = []
    led = DeviceLedger(armed=False,
                       emit=lambda name, **f: evs.append(name))
    led.on_dispatch(("k",), _FakeFn({"flops": 1.0}), (), {})
    led.note_retire()
    assert led.heartbeat_fields(interval_s=1.0) == {}
    assert led.poll_memory() == {}
    st = led.stats_fields()
    assert st["mfu"] == 0.0 and st["devledger"]["armed"] == 0
    assert evs == []


# ----- unit: watchdog rules --------------------------------------------------


def _hb(**kw):
    base = dict(
        round=1, interval_rounds=4, interval_s=1.0, tokens_per_s=100.0,
        itl_p99_ms=10.0, preemptions_delta=0, recoveries_delta=0,
        prefix_hits_delta=0, prefix_misses_delta=0, kv_host_tokens=0,
    )
    base.update(kw)
    return base


def _watchdog(cfg, evs, dumps=None):
    dump = (
        (lambda reason: dumps.append(reason) or f"/dev/null/{reason}")
        if dumps is not None else None
    )
    return SLOBurnWatchdog(
        cfg,
        emit=lambda name, **f: evs.append({"name": name, **f}),
        dump=dump,
    )


def test_device_idle_sustain_clear_no_refire():
    evs, dumps = [], []
    wd = _watchdog(
        WatchdogConfig(slo_ms=1000.0, sustain=2, clear=2,
                       min_samples=2, gap_ratio=3.0, gap_min_ms=1.0),
        evs, dumps,
    )
    healthy = _hb(dispatch_gap_ms=2.0, dispatches_delta=4)
    idle = _hb(dispatch_gap_ms=50.0, dispatches_delta=4)
    # Baseline builds on healthy samples only.
    assert wd.observe(healthy) == []
    assert wd.observe(healthy) == []
    assert wd.observe(idle) == []                  # streak 1 < sustain
    assert wd.observe(idle) == [ALERT_DEVICE_IDLE]
    assert wd.observe(idle) == []                  # active: no refire
    assert wd.active == (ALERT_DEVICE_IDLE,)
    # The sustained idle period must NOT have been folded into the
    # baseline: one healthy streak clears at the original EWMA.
    wd.observe(healthy)
    assert wd.observe(healthy) == []
    assert wd.active == ()
    clears = [e for e in evs if e["name"] == "watchdog_clear"]
    assert [e["alert"] for e in clears] == [ALERT_DEVICE_IDLE]
    assert dumps  # the alert dumped the flight ring


def test_device_idle_self_disarms_without_ledger_fields():
    evs = []
    wd = _watchdog(
        WatchdogConfig(slo_ms=1000.0, sustain=1, min_samples=1,
                       gap_ratio=2.0, gap_min_ms=0.5),
        evs,
    )
    wd.observe(_hb(dispatch_gap_ms=1.0, dispatches_delta=2))
    # Kill switch / pre-ledger stream: no gap fields → rule untouched.
    assert wd.observe(_hb()) == []
    # An interval with zero dispatches carries no gap signal either.
    assert wd.observe(_hb(dispatch_gap_ms=500.0, dispatches_delta=0)) == []
    # Sub-floor gaps never fire however large the ratio.
    assert wd.observe(_hb(dispatch_gap_ms=0.4, dispatches_delta=2)) == []


def test_hbm_headroom_collapse_rule():
    evs, dumps = [], []
    wd = _watchdog(
        WatchdogConfig(slo_ms=1000.0, sustain=2, clear=1,
                       headroom_floor_frac=0.1),
        evs, dumps,
    )
    low = _hb(hbm_headroom_bytes=50, hbm_peak_bytes=1000)
    ok = _hb(hbm_headroom_bytes=500, hbm_peak_bytes=1000)
    assert wd.observe(low) == []
    assert wd.observe(low) == [ALERT_HBM_HEADROOM_COLLAPSE]
    alert = [e for e in evs if e["name"] == "watchdog_alert"][-1]
    assert "floor=100B" in alert["reason"]
    assert wd.observe(ok) == []
    assert wd.active == ()
    # Omission self-disarms (CPU guests): the active alert would heal,
    # and a fresh watchdog never arms on field-less heartbeats.
    wd2 = _watchdog(
        WatchdogConfig(slo_ms=1000.0, sustain=1, headroom_floor_frac=0.9),
        [],
    )
    assert wd2.observe(_hb()) == []
    assert wd2.observe(_hb(hbm_headroom_bytes=0, hbm_peak_bytes=0)) == []


# ----- unit: profiler double-start fix (ISSUE 17 bug-risk) -------------------


def test_profiler_second_hook_degrades_to_busy_event(tmp_path,
                                                     capture_events):
    d1, d2, d3 = (str(tmp_path / n) for n in ("t1", "t2", "t3"))

    def run():
        h1 = prof_mod.ProfilerHook(d1, start_step=1, num_steps=2)
        h2 = prof_mod.ProfilerHook(d2, start_step=1, num_steps=2)
        h1.on_step(1)          # wins the process-wide trace slot
        h2.on_step(1)          # loses: degrade, NOT a raise
        assert h2._done and not h2._active
        h2.on_step(2)          # done: never retries into the live trace
        h1.on_step(2)          # window closes, slot released
        assert not h1._active
        h3 = prof_mod.ProfilerHook(d3, start_step=1, num_steps=2)
        h3.on_step(1)          # slot free again
        h3.stop()

    _, events = capture_events(run)
    busy = [e for e in events if e.get("name") == "profiler_busy"]
    assert len(busy) == 1
    assert busy[0]["dir"] == d2
    assert busy[0]["reason"] == f"owned:{d1}"
    traces = [e for e in events if e.get("name") == "jax_trace"]
    assert [t["dir"] for t in traces] == [d1, d3]


def test_profiler_raw_trace_collision_degrades(tmp_path, capture_events):
    # Someone started jax.profiler WITHOUT a hook (bench --profile-dir):
    # start_trace itself raises; the hook releases the slot and degrades.
    jax.profiler.start_trace(str(tmp_path / "raw"))
    try:
        def run():
            h = prof_mod.ProfilerHook(str(tmp_path / "hook"),
                                      start_step=1, num_steps=2)
            h.on_step(1)
            assert h._done and not h._active
        _, events = capture_events(run)
    finally:
        jax.profiler.stop_trace()
    busy = [e for e in events if e.get("name") == "profiler_busy"]
    assert len(busy) == 1
    assert busy[0]["reason"].startswith("start_trace:")
    assert prof_mod._trace_owner is None  # slot not leaked


# ----- server: heartbeat + stats + bit-identity ------------------------------


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=5):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


def _server(params, cfg, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 48)
    kw.setdefault("chunk", 2)
    kw.setdefault("kv_quant", False)
    return GenerationServer(params, cfg, **kw)


def test_server_heartbeat_carries_ledger_fields(model, capture_events):
    cfg, params = model

    def run():
        srv = _server(params, cfg, heartbeat_rounds=2)
        for p in _prompts(cfg, [6, 8, 6, 8]):
            srv.submit(p, 8)
        srv.run()
        return srv

    srv, events = capture_events(run)
    hbs = [e for e in events if e.get("name") == "serving_heartbeat"]
    assert hbs
    for hb in hbs:
        # Full utilization field set on every heartbeat — no schema
        # branch on what the interval happened to observe.
        assert UTIL_FIELDS <= set(hb)
        # CPU: memory fields degrade by OMISSION, never fake zeros.
        assert not [k for k in hb if k.startswith("hbm_")]
        # ACCEPTANCE: the phase-attributed gap shares sum to the mean
        # inter-dispatch gap within 5% (the residual→other +
        # rescale-to-gap construction makes this exact up to field
        # rounding).
        parts = sum(hb[f"dispatch_gap_{p}_ms"] for p in LOOP_PHASES)
        gap = hb["dispatch_gap_ms"]
        assert abs(parts - gap) <= max(0.05 * gap, 1e-3 * len(LOOP_PHASES))
    assert any(hb["dispatches_delta"] > 0 for hb in hbs)
    assert any(hb["device_busy_frac"] > 0 for hb in hbs)
    # The degrade is announced exactly once per server.
    unavail = [e for e in events if e.get("name") == "hbm_stats_unavailable"]
    assert len(unavail) == 1
    # serving_config carries the armed flag.
    scfg = [e for e in events if e.get("name") == "serving_config"]
    assert scfg and scfg[0]["devledger"] == 1
    # stats(): always-present top-level numerics + the detail dict.
    st = srv.stats()
    assert st["mfu"] >= 0.0
    assert 0.0 <= st["device_busy_frac"] <= 1.0
    assert st["dispatch_gap_ms"] >= 0.0
    led = st["devledger"]
    assert led["armed"] == 1
    assert led["dispatches"] > 0 and led["retired"] == led["dispatches"]
    assert led["cost_signatures"] >= 1
    assert led["peak_flops"] > 0
    assert led["hbm_stats_available"] == 0  # CPU


def test_server_overlapped_rounds_feed_ledger(model, capture_events):
    cfg, params = model

    def run():
        srv = _server(params, cfg, heartbeat_rounds=2, overlap=True)
        for p in _prompts(cfg, [6, 8, 6]):
            srv.submit(p, 8)
        srv.run()
        return srv

    srv, _events = capture_events(run)
    led = srv.stats()["devledger"]
    assert led["dispatches"] > 0
    # Pipelined retires drain the pending FIFO completely on a clean run.
    assert led["retired"] == led["dispatches"]


def test_devledger_kill_switch(model, capture_events, monkeypatch):
    cfg, params = model
    monkeypatch.setenv(dl_mod.ENV_DEVLEDGER, "0")

    def run():
        srv = _server(params, cfg, heartbeat_rounds=2)
        for p in _prompts(cfg, [6, 8]):
            srv.submit(p, 6)
        srv.run()
        return srv

    srv, events = capture_events(run)
    hbs = [e for e in events if e.get("name") == "serving_heartbeat"]
    assert hbs
    assert all("mfu" not in hb for hb in hbs)  # disarmed: fields absent
    scfg = [e for e in events if e.get("name") == "serving_config"]
    assert scfg and scfg[0]["devledger"] == 0
    st = srv.stats()
    assert st["mfu"] == 0.0 and st["devledger"]["armed"] == 0
    assert not [e for e in events if e.get("name") == "cost_unavailable"]


def test_greedy_bit_identical_ledger_on_off(model, monkeypatch):
    # The ledger is pure host arithmetic + aval-only lowering: greedy
    # outputs must be bit-identical armed vs disarmed (run under both
    # strict modes by `make devledger`).
    cfg, params = model

    def serve(env: str):
        monkeypatch.setenv(dl_mod.ENV_DEVLEDGER, env)
        srv = _server(params, cfg, heartbeat_rounds=2)
        rids = [srv.submit(p, 8) for p in _prompts(cfg, [6, 8, 6, 8])]
        out = srv.run()
        return [list(map(int, out[r])) for r in rids]

    assert serve("1") == serve("0")


# ----- host: aggregator re-export -------------------------------------------


def _write_events(path, events):
    with open(path, "a", encoding="utf-8") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")


def _guest_hb(ts, server="server0", chips="0,1", **kw):
    base = {
        "ts": ts, "kind": "serving", "name": "serving_heartbeat",
        "server": server, "chips": chips, "tokens_per_s": 10.0,
        "itl_p99_ms": 5.0, "queued": 0, "batch_occupancy": 0.5,
        "kv_pool_occupancy": 0.0, "kv_host_occupancy": 0.0,
    }
    base.update(kw)
    return base


def test_aggregator_reexports_ledger_gauges_omission_preserving(tmp_path):
    from kata_xpu_device_plugin_tpu.plugin.manager import (
        HeartbeatAggregator,
    )
    from kata_xpu_device_plugin_tpu.utils import metrics

    d = str(tmp_path)
    now = time.time()
    _write_events(os.path.join(d, "guest_0-1.jsonl"), [
        _guest_hb(now, mfu=0.37, hbm_headroom_bytes=123456,
                  device_busy_frac=0.9),
    ])
    # A CPU guest (or pre-ledger stream): NO ledger fields at all.
    _write_events(os.path.join(d, "guest_2.jsonl"), [
        _guest_hb(now, server="cpu0", chips="2"),
    ])
    agg = HeartbeatAggregator(d, poll_interval_s=0.01)
    assert agg.poll_once() == 2
    assert metrics.guest_mfu.labels(
        allocation="0,1", server="server0"
    )._value.get() == 0.37
    assert metrics.guest_hbm_headroom_bytes.labels(
        allocation="0,1", server="server0"
    )._value.get() == 123456
    # Omission-preserving: the field-less guest got NO child — a fake 0
    # would read as "out of memory" on the mfu-style dashboards.
    assert ("2", "cpu0") not in metrics.guest_mfu._metrics
    assert ("2", "cpu0") not in metrics.guest_hbm_headroom_bytes._metrics


def test_aggregator_restart_replay_restores_ledger_state(tmp_path):
    from kata_xpu_device_plugin_tpu.plugin.manager import (
        HeartbeatAggregator,
    )
    from kata_xpu_device_plugin_tpu.utils import metrics

    d = str(tmp_path)
    stale_ts = time.time() - 3600.0
    path = os.path.join(d, "guest_4-5.jsonl")
    _write_events(path, [
        _guest_hb(stale_ts, server="s1", chips="4,5", mfu=0.11,
                  hbm_headroom_bytes=777),
    ])
    labels = {"allocation": "4,5", "server": "s1"}
    before = metrics.guest_heartbeats_total.labels(**labels)._value.get()
    agg = HeartbeatAggregator(d)  # "restarted" daemon: t0 > event ts
    assert agg.poll_once() == 1
    # Replay restored STATE (the gauges) ...
    assert metrics.guest_mfu.labels(**labels)._value.get() == 0.11
    assert metrics.guest_hbm_headroom_bytes.labels(
        **labels)._value.get() == 777
    # ... without re-announcing history (no counter increment).
    assert metrics.guest_heartbeats_total.labels(
        **labels)._value.get() == before
