"""Ring (rolling-buffer) KV cache for sliding-window decode.

Oracle: the full-cache sliding-window path — the ring holds exactly the
band the full cache masks down to, so outputs must match.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.models import generate, mistral_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    init_params,
    ring_caches_from_prefill,
    ring_positions,
    tiny_test_config,
)


def test_ring_positions():
    # After 10 tokens (positions 0..9) in a 4-slot ring: slot s holds the
    # latest position ≡ s (mod 4) that is ≤ 9.
    np.testing.assert_array_equal(
        np.asarray(ring_positions(jnp.int32(9), 4)), [8, 9, 6, 7]
    )
    # Early: position 1 written, slots 2..3 untouched → negative.
    np.testing.assert_array_equal(
        np.asarray(ring_positions(jnp.int32(1), 4)), [0, 1, -2, -1]
    )


def test_ring_fold_from_prefill():
    cfg = tiny_test_config()
    L, B, S = cfg.n_layers, 1, 10
    full = (
        jnp.arange(L * B * S * cfg.n_kv_heads * cfg.head_dim, dtype=jnp.float32)
        .reshape(L, B, S, cfg.n_kv_heads, cfg.head_dim),
        jnp.zeros((L, B, S, cfg.n_kv_heads, cfg.head_dim), jnp.float32),
    )
    W = 4
    rk, _ = ring_caches_from_prefill(full, jnp.int32(10), W)
    assert rk.shape == (L, B, W, cfg.n_kv_heads, cfg.head_dim)
    # Slot s holds position 9 - ((9 - s) % 4): [8, 9, 6, 7].
    for s, p in enumerate([8, 9, 6, 7]):
        np.testing.assert_array_equal(
            np.asarray(rk[:, :, s]), np.asarray(full[0][:, :, p])
        )
    # Short prefill: unwritten slots zero out.
    rk2, _ = ring_caches_from_prefill(full, jnp.int32(2), W)
    np.testing.assert_array_equal(np.asarray(rk2[:, :, 2]), 0.0)
    np.testing.assert_array_equal(np.asarray(rk2[:, :, 3]), 0.0)


@pytest.fixture(scope="module")
def model():
    cfg = mistral_test_config(dtype=jnp.float32)  # window=8
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


@pytest.mark.parametrize("prompt_len,steps", [
    (5, 18),   # short prompt: ring warms up during decode, then wraps
    (14, 12),  # prompt longer than the window: fold drops old positions
])
def test_ring_generate_matches_full_cache(model, prompt_len, steps):
    cfg, params = model
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (2, prompt_len), 0, cfg.vocab_size
    )
    ref = np.asarray(generate(params, prompt, cfg, steps, max_len=64))
    out = np.asarray(generate(params, prompt, cfg, steps, ring_kv=True))
    np.testing.assert_array_equal(out, ref)


def test_ring_decode_unbounded_by_cache_length(model):
    # steps far beyond the window: a full cache would need max_len >= S+steps;
    # the ring stays 8 slots and just keeps wrapping.
    cfg, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 0, cfg.vocab_size)
    ref = np.asarray(generate(params, prompt, cfg, 40, max_len=64))
    out = np.asarray(generate(params, prompt, cfg, 40, ring_kv=True))
    np.testing.assert_array_equal(out, ref)


def test_ring_requires_window(model):
    cfg, params = model
    from dataclasses import replace

    full_cfg = replace(cfg, sliding_window=0)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="sliding-window"):
        generate(params, prompt, full_cfg, 4, ring_kv=True)


@pytest.mark.parametrize("prompt_len,steps", [
    (4, 14),   # ring warms up during decode, wraps past window=6
    (11, 9),   # prompt longer than the local window: fold drops positions
])
def test_cycle_arena_gemma2_matches_full_cache(prompt_len, steps):
    """Gemma-2's alternating local/global cycle under ring_kv: local layers
    decode from a window-slot ring, global layers from a max_len arena —
    tokens must equal the full-cache run exactly (the full cache's band
    mask hides exactly what the ring dropped)."""
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config

    cfg = gemma2_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    prompt = jax.random.randint(
        jax.random.PRNGKey(4), (2, prompt_len), 0, cfg.vocab_size
    )
    ref = np.asarray(generate(params, prompt, cfg, steps, max_len=64))
    out = np.asarray(generate(params, prompt, cfg, steps, max_len=64,
                              ring_kv=True))
    np.testing.assert_array_equal(out, ref)


def test_cycle_arena_degenerate_cycles():
    from dataclasses import replace

    from kata_xpu_device_plugin_tpu.models import gemma2_test_config

    # Length-1 attn_windows cycle == a uniform window: forward runs P == 1
    # (no cycle arena), so the fold must take the uniform-ring path.
    cfg1 = replace(gemma2_test_config(dtype=jnp.float32), attn_windows=(6,))
    p1 = init_params(jax.random.PRNGKey(7), cfg1, dtype=jnp.float32)
    prompt = jax.random.randint(jax.random.PRNGKey(8), (1, 5), 0, cfg1.vocab_size)
    ref = np.asarray(generate(p1, prompt, cfg1, 12, max_len=32))
    out = np.asarray(generate(p1, prompt, cfg1, 12, ring_kv=True))
    np.testing.assert_array_equal(out, ref)

    # All-windowed cycle (no global layers): every position is a ring, so
    # decode is unbounded by max_len — steps far beyond it must work.
    cfg2 = replace(gemma2_test_config(dtype=jnp.float32), attn_windows=(4, 8))
    p2 = init_params(jax.random.PRNGKey(9), cfg2, dtype=jnp.float32)
    ref2 = np.asarray(generate(p2, prompt, cfg2, 40, max_len=64))
    out2 = np.asarray(generate(p2, prompt, cfg2, 40, max_len=16, ring_kv=True))
    np.testing.assert_array_equal(out2, ref2)


def test_cycle_arena_kv_quant_matches_quantized_full_cache():
    # int8 KV caches ride the cycle arena too (QTensor leaves fold/pad
    # through the same tree maps).
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config

    cfg = gemma2_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(5), cfg, dtype=jnp.float32)
    prompt = jax.random.randint(jax.random.PRNGKey(6), (1, 7), 0, cfg.vocab_size)
    ref = np.asarray(generate(params, prompt, cfg, 10, max_len=32,
                              kv_quantized=True))
    out = np.asarray(generate(params, prompt, cfg, 10, max_len=32,
                              kv_quantized=True, ring_kv=True))
    np.testing.assert_array_equal(out, ref)
