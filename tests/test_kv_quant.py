"""int8 KV cache (ops/quant.py quantize_kv/dequantize_kv + cache plumbing).

Oracle: the framework's own bf16-cache path. int8 per-vector KV introduces
~0.4% relative error per attention read, so token streams are compared by
broad agreement and logits by norm, not bit-identity.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.serving import serve_batch
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    decode,
    generate,
    init_kv_caches,
    init_params,
    prefill,
)
from kata_xpu_device_plugin_tpu.ops.quant import (
    QTensor,
    dequantize_kv,
    params_hbm_bytes,
    quantize_kv,
)


def test_quantize_kv_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 2, 32), jnp.float32)
    qt = quantize_kv(x)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (2, 16, 2, 1)
    err = np.abs(np.asarray(dequantize_kv(qt, jnp.float32)) - np.asarray(x))
    bound = np.asarray(qt.scale) / 2 + 1e-6
    assert (err <= bound).all()
    # dequantize_kv is the identity on plain arrays.
    assert dequantize_kv(x, jnp.float32) is x


def test_init_quantized_caches_structure_and_size():
    cfg = tiny_test_config()
    ck, cv = init_kv_caches(cfg, batch=2, max_len=32, quantized=True)
    assert isinstance(ck, QTensor) and isinstance(cv, QTensor)
    assert ck.q.shape == (cfg.n_layers, 2, 32, cfg.n_kv_heads, cfg.head_dim)
    assert ck.q.dtype == jnp.int8
    bf16 = init_kv_caches(cfg, batch=2, max_len=32)
    assert params_hbm_bytes((ck, cv)) < params_hbm_bytes(bf16)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def test_prefill_decode_with_int8_cache_tracks_bf16(model):
    cfg, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, cfg.vocab_size)

    def run(kv_quantized):
        caches, last, pos = prefill(params, prompt, cfg, 24,
                                    kv_quantized=kv_quantized)
        return np.asarray(decode(params, caches, last, int(pos), cfg, 12))

    ref, out = run(False), run(True)
    assert out.shape == ref.shape
    agreement = (out == ref).mean()
    assert agreement >= 0.75, f"token agreement {agreement}"


def test_generate_kv_quantized(model):
    cfg, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)
    ref = np.asarray(generate(params, prompt, cfg, 10, max_len=24))
    out = np.asarray(generate(params, prompt, cfg, 10, max_len=24,
                              kv_quantized=True))
    assert out.shape == ref.shape == (1, 10)
    assert (out == ref).mean() >= 0.7


def test_mesh_serving_with_int8_arena(model):
    # mesh × kv_quant composition: leaf-wise NamedSharding over the QTensor
    # arena (int8 q + fp32 scale), donated through _write_slot/_serve_decode.
    from kata_xpu_device_plugin_tpu.parallel import build_mesh

    cfg, params = model
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    key = jax.random.PRNGKey(6)
    prompts = [
        np.asarray(jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                                      cfg.vocab_size), np.int32)
        for i, n in enumerate((4, 7))
    ]
    ref = serve_batch(params, cfg, prompts, max_new_tokens=6,
                      max_batch=2, max_len=24, kv_quant=True)
    out = serve_batch(params, cfg, prompts, max_new_tokens=6,
                      max_batch=2, max_len=24, kv_quant=True, mesh=mesh)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_int8_kv_is_the_server_default(model, monkeypatch):
    """ISSUE 12: with no explicit argument and no env, GenerationServer
    resolves int8 KV (the conftest pins KATA_TPU_KV_QUANT=bf16 suite-wide
    because the generate() oracles compare bit-for-bit — this test undoes
    the pin to observe the shipped default)."""
    from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

    cfg, params = model
    monkeypatch.delenv("KATA_TPU_KV_QUANT", raising=False)
    srv = GenerationServer(params, cfg, max_batch=1, max_len=16)
    assert srv.kv_quant is True
    assert isinstance(srv.arena[0], QTensor)


def test_kv_quant_env_knob_and_explicit_override(model, monkeypatch, capture_events):
    from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

    cfg, params = model
    # env bf16 opts out; env int8 pins the default explicitly.
    monkeypatch.setenv("KATA_TPU_KV_QUANT", "bf16")
    assert GenerationServer(params, cfg, max_batch=1,
                            max_len=16).kv_quant is False
    monkeypatch.setenv("KATA_TPU_KV_QUANT", "int8")
    assert GenerationServer(params, cfg, max_batch=1,
                            max_len=16).kv_quant is True
    # An explicit argument always wins over the env.
    assert GenerationServer(params, cfg, max_batch=1, max_len=16,
                            kv_quant=False).kv_quant is False
    monkeypatch.setenv("KATA_TPU_KV_QUANT", "bf16")
    assert GenerationServer(params, cfg, max_batch=1, max_len=16,
                            kv_quant=True).kv_quant is True
    # A malformed node-wide env degrades to the int8 DEFAULT with one
    # kv_quant_invalid event — never a crash.
    monkeypatch.setenv("KATA_TPU_KV_QUANT", "fp4")
    srv, events = capture_events(
        lambda: GenerationServer(params, cfg, max_batch=1, max_len=16),
    )
    assert srv.kv_quant is True
    bad = [e for e in events if e.get("name") == "kv_quant_invalid"]
    assert len(bad) == 1 and bad[0]["reason"].startswith("bad_env:")


def test_int8_default_quality_gate(model):
    """The promotion gate behind the int8 default (tools/eval_quality):
    pooled greedy agreement and first-decode-step logit drift vs the
    bf16 oracle must clear the shipped thresholds on the fixed prompt
    set — the tier-1 mirror of `make eval-kv`."""
    from tools.eval_quality import (
        _default_prompts,
        evaluate_kv_quant,
        gate,
    )

    cfg, params = model
    result = evaluate_kv_quant(
        params, cfg, _default_prompts(cfg, 4), steps=12,
    )
    assert gate(result), result
    assert 0.0 <= result["greedy_match"] <= 1.0
    assert result["logit_max_abs_err"] >= 0.0


def test_serving_with_int8_arena(model):
    cfg, params = model
    key = jax.random.PRNGKey(3)
    prompts = [
        np.asarray(jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                                      cfg.vocab_size), np.int32)
        for i, n in enumerate((4, 9, 6))
    ]
    ref = serve_batch(params, cfg, prompts, max_new_tokens=8,
                      max_batch=2, max_len=32)
    out = serve_batch(params, cfg, prompts, max_new_tokens=8,
                      max_batch=2, max_len=32, kv_quant=True)
    assert all(len(o) == 8 for o in out)
    total = np.concatenate(out), np.concatenate(ref)
    assert (total[0] == total[1]).mean() >= 0.75
