"""LoRA adapters (ops/lora.py): zero-init identity, frozen base, adapter-only
training, merge equivalence, QLoRA, and generation through adapted params.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    forward,
    fuse_decoder_params,
    generate,
    init_params,
    next_token_loss,
)
from kata_xpu_device_plugin_tpu.ops import (
    LoRAWeight,
    apply_lora,
    make_lora_train_step,
    merge_lora,
    quantize_decoder_params,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _tokens(cfg, shape, seed=1):
    return jax.random.randint(jax.random.PRNGKey(seed), shape, 0, cfg.vocab_size)


def test_fresh_adapter_is_identity(model):
    # b = 0 ⇒ adapted forward EXACTLY equals the base forward.
    cfg, params = model
    adapted = apply_lora(params, jax.random.PRNGKey(1), rank=4)
    toks = _tokens(cfg, (2, 12))
    np.testing.assert_array_equal(
        np.asarray(forward(adapted, toks, cfg)),
        np.asarray(forward(params, toks, cfg)),
    )


def test_training_moves_adapters_only(model):
    cfg, params = model
    adapted = apply_lora(params, jax.random.PRNGKey(2), rank=4)
    init_state, step = make_lora_train_step(cfg, lr=1e-3)
    state = init_state(adapted)
    toks = _tokens(cfg, (4, 16), seed=3)
    losses = []
    for _ in range(10):
        state, loss = step(state, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # The frozen base is bit-identical; the adapters moved.
    for k, v in state["params"]["layers"].items():
        orig = params["layers"][k]
        if isinstance(v, LoRAWeight):
            np.testing.assert_array_equal(np.asarray(v.base), np.asarray(orig))
            assert np.abs(np.asarray(v.b)).max() > 0  # b left zero-init
        else:
            np.testing.assert_array_equal(np.asarray(v), np.asarray(orig))
    np.testing.assert_array_equal(
        np.asarray(state["params"]["embed"]), np.asarray(params["embed"])
    )


def test_merge_matches_adapted_forward(model):
    cfg, params = model
    adapted = apply_lora(params, jax.random.PRNGKey(4), rank=4)
    # Give the adapters nonzero weights via a couple of train steps.
    init_state, step = make_lora_train_step(cfg, lr=1e-3)
    state = init_state(adapted)
    for _ in range(3):
        state, _ = step(state, _tokens(cfg, (4, 16), seed=5))
    trained = state["params"]
    merged = merge_lora(trained)
    assert not any(
        isinstance(v, LoRAWeight) for v in merged["layers"].values()
    )
    toks = _tokens(cfg, (2, 12), seed=6)
    np.testing.assert_allclose(
        np.asarray(forward(merged, toks, cfg)),
        np.asarray(forward(trained, toks, cfg)),
        rtol=2e-5, atol=2e-5,
    )


def test_qlora_int8_base(model):
    # Adapters over an int8-quantized FUSED base: the QLoRA layout.
    cfg, params = model
    qbase = quantize_decoder_params(fuse_decoder_params(params))
    adapted = apply_lora(qbase, jax.random.PRNGKey(7), rank=4,
                         targets=("wqkv", "w_gateup"))
    toks = _tokens(cfg, (2, 10), seed=8)
    np.testing.assert_array_equal(
        np.asarray(forward(adapted, toks, cfg)),
        np.asarray(forward(qbase, toks, cfg)),
    )
    init_state, step = make_lora_train_step(cfg, lr=1e-3)
    state = init_state(adapted)
    qlosses = []
    for _ in range(6):
        state, ql = step(state, _tokens(cfg, (4, 16), seed=9))
        qlosses.append(float(ql))
    assert qlosses[-1] < qlosses[0], qlosses
    # int8 base untouched by training.
    np.testing.assert_array_equal(
        np.asarray(state["params"]["layers"]["wqkv"].base.q),
        np.asarray(qbase["layers"]["wqkv"].q),
    )


def test_mesh_lora_training_matches_single_device(model):
    """Multi-chip fine-tuning: make_lora_train_step(mesh=...) shards the
    adapted tree by its layout-aware specs (base fsdp/tp-sharded, a/b on
    the base's axes) and the GSPMD step must produce the same losses as
    the single-device step — including the QLoRA (int8 fused base)
    layout."""
    from kata_xpu_device_plugin_tpu.parallel import build_mesh, shard_batch

    cfg, params = model
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})

    for name, adapted in {
        "plain": apply_lora(params, jax.random.PRNGKey(2), rank=4),
        "qlora_fused": apply_lora(
            quantize_decoder_params(fuse_decoder_params(params)),
            jax.random.PRNGKey(2), rank=4, targets=("wqkv", "w_gateup"),
        ),
    }.items():
        init_ref, step_ref = make_lora_train_step(cfg, lr=1e-3)
        init_m, step_m = make_lora_train_step(cfg, lr=1e-3, mesh=mesh)
        s_ref, s_m = init_ref(adapted), init_m(adapted)
        # Adapters actually sharded, not replicated-by-accident: the base's
        # wide axis rides the model axis.
        if name == "plain":
            wq = s_m["params"]["layers"]["wq"]
            assert "model" in str(wq.base.sharding.spec), wq.base.sharding
        for i in range(3):
            toks = _tokens(cfg, (4, 16), seed=20 + i)
            s_ref, l_ref = step_ref(s_ref, toks)
            s_m, l_m = step_m(s_m, shard_batch(toks, mesh))
            np.testing.assert_allclose(
                float(l_m), float(l_ref), rtol=2e-5,
                err_msg=f"{name} step {i}"
            )


def test_generate_through_adapters(model):
    cfg, params = model
    adapted = apply_lora(params, jax.random.PRNGKey(10), rank=2)
    prompt = _tokens(cfg, (1, 6), seed=11)
    out = np.asarray(generate(adapted, prompt, cfg, 8, max_len=16))
    ref = np.asarray(generate(params, prompt, cfg, 8, max_len=16))
    np.testing.assert_array_equal(out, ref)  # zero-init adapters


def test_apply_lora_validation(model):
    cfg, params = model
    with pytest.raises(ValueError, match="targets"):
        apply_lora(params, jax.random.PRNGKey(0), targets=("nope",))
    with pytest.raises(ValueError, match="fuse_decoder_params first"):
        fuse_decoder_params(apply_lora(params, jax.random.PRNGKey(0)))
    # Quantizing around live adapters would silently skip the wrapped
    # (dominant) weights — refused, with both correct orders named.
    with pytest.raises(ValueError, match="merge_lora"):
        quantize_decoder_params(apply_lora(params, jax.random.PRNGKey(0)))
    # (Mesh serving now ACCEPTS live adapters — layout-aware specs shard
    # a/b along the base weight's axes; locked token-identical in
    # tests/test_serving.py::test_mesh_serving_fused_int8_lora_layouts...)


def test_grad_loss_matches_full_param_loss(model):
    # stop_gradient must not change the VALUE of the loss.
    cfg, params = model
    adapted = apply_lora(params, jax.random.PRNGKey(12), rank=4)
    toks = _tokens(cfg, (2, 12), seed=13)
    np.testing.assert_allclose(
        float(next_token_loss(adapted, toks, cfg)),
        float(next_token_loss(params, toks, cfg)),
        rtol=1e-6,
    )
