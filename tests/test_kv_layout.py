"""Block-sharded paged KV pool + host-RAM offload tier (ISSUE 14).

Oracle, as everywhere in serving: the pool LAYOUT is a placement
decision and the host tier a memory tier — greedy tokens must be
bit-identical across ``blocks`` ≡ ``heads`` ≡ ``tp=1`` for every
composition (paged × int8/bf16 × overlap/lockstep × prefix-hit ×
preemption × fault-recovery), while the block accounting (per-shard
sub-pools, lane → (shard, physical block) mapping), the host-tier
ordering contract (demotion BEFORE preemption, LRU within the tier,
pinned session spills never dropped), and the knob raise-vs-degrade
contract obey their documented semantics. ``make kv-layout`` runs this
file with and without ``KATA_TPU_STRICT=1`` (demotion D2H / prefetch
H2D must ride sanctioned ``allow_transfer`` paths only), and ``make
chaos`` re-runs it under a seeded ``pool_alloc``/``fence`` schedule
with the blocks layout node-injected — so every server here that needs
a quiet schedule pins a disarmed injector explicitly.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest import tp_serving
from kata_xpu_device_plugin_tpu.guest.kv_arena import (
    RESERVED_BLOCKS,
    HostKVTier,
    KVPool,
)
from kata_xpu_device_plugin_tpu.guest.resilience import (
    FaultInjector,
    FaultSpec,
)
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=1, shared=0):
    key = jax.random.PRNGKey(seed)
    head = np.asarray(
        jax.random.randint(key, (shared,), 0, cfg.vocab_size), np.int32
    ) if shared else np.zeros((0,), np.int32)
    out = []
    for i, n in enumerate(lengths):
        tail = np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (n,), 0, cfg.vocab_size
        ), np.int32)
        out.append(np.concatenate([head, tail]))
    return out


def _serve(params, cfg, prompts, budgets=10, injector=None, **kw):
    srv = GenerationServer(
        params, cfg,
        fault_injector=injector if injector is not None else FaultInjector(),
        **kw,
    )
    if isinstance(budgets, int):
        budgets = [budgets] * len(prompts)
    rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
    res = srv.run()
    return [res[r] for r in rids], srv


def _capture_events(tmp_path, fn, name="ev.jsonl"):
    sink = obs.EventSink(str(tmp_path / name))
    prev = obs.set_default_sink(sink)
    try:
        result = fn()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    return result, obs.read_events(str(tmp_path / name))


# ----- KVPool per-shard sub-pools -------------------------------------------


def test_pool_blocks_sharding_accounting(model):
    cfg, _ = model
    # 4 shards: 35 raw blocks round DOWN to 32 — whole blocks per shard.
    pool = KVPool(cfg, 35 * 8, 8, shards=4)
    assert pool.num_blocks == 32 and pool.shard_blocks == 8
    assert pool.blocks_total == 32 - RESERVED_BLOCKS
    # The lane → (shard, physical block) mapping: block t lives WHOLE on
    # shard t // shard_blocks.
    assert pool.shard_of(0) == 0 and pool.shard_of(7) == 0
    assert pool.shard_of(8) == 1 and pool.shard_of(31) == 3
    # Both reserved blocks land on shard 0, so its usable count is short.
    occ = pool.shard_occupancy()
    assert occ == [0.0, 0.0, 0.0, 0.0]
    # Allocation balances by FREE count: after 8 grants the per-shard
    # free lists are level (shard 0 starts two short — the reserved
    # blocks — so it is drawn from last).
    got = pool.try_alloc(8)
    assert all(sum(pool.shard_of(b) == s for b in got) > 0
               for s in range(1, 4))
    free_lens = [len(f) for f in pool._free]
    assert max(free_lens) - min(free_lens) <= 1
    assert all(o > 0 for o in pool.shard_occupancy())
    # unref returns each block to ITS shard's free list.
    pool.unref(got)
    assert pool.blocks_free == pool.blocks_total
    assert pool.shard_occupancy() == [0.0, 0.0, 0.0, 0.0]
    # shards=1 keeps the historical single-free-list behavior.
    one = KVPool(cfg, 35 * 8, 8)
    assert one.shards == 1 and one.num_blocks == 35


def test_pool_blocks_rounding_too_small(model):
    cfg, _ = model
    # 7 raw blocks round to 4 with 4 shards: 2 usable — fine; 3 raw
    # blocks round to 0 — must refuse, not build an empty pool.
    KVPool(cfg, 7 * 8, 8, shards=4)
    with pytest.raises(ValueError):
        KVPool(cfg, 3 * 8, 8, shards=4)
    with pytest.raises(ValueError):
        KVPool(cfg, 64, 8, shards=0)


# ----- HostKVTier ----------------------------------------------------------


def test_host_tier_capacity_lru_and_pinned():
    tier = HostKVTier(100, 8)
    assert tier.put("a", 40) and tier.put("b", 40)
    # Over capacity unpinned: refused (callers evict their own LRU).
    assert not tier.put("c", 40)
    assert tier.room(20) and not tier.room(21)
    # Pinned entries always land — correctness outranks the budget.
    assert tier.put(("spill", 1), 40, pinned=True)
    assert tier.tokens_used == 120 and tier.entries == 3
    # LRU among unpinned only; get() refreshes recency.
    tier.get("a")
    assert tier.lru_unpinned() == "b"
    assert tier.pop("b").tokens == 40
    # drop_unpinned clears cache entries, keeps pinned session spills.
    assert tier.drop_unpinned() == 1
    assert tier.entries == 1 and tier.get(("spill", 1)).pinned
    with pytest.raises(ValueError):
        HostKVTier(0, 8)


# ----- placement specs ------------------------------------------------------


def test_kv_specs_by_layout(model):
    from kata_xpu_device_plugin_tpu.compat.jaxapi import P
    from kata_xpu_device_plugin_tpu.parallel.mesh import AXIS_MODEL

    cfg, _ = model  # n_kv_heads=2
    # heads: divide-or-replicate on the head axis (position 3).
    assert tp_serving.kv_cache_spec(cfg, 2) == P(
        None, None, None, AXIS_MODEL, None)
    assert tp_serving.kv_cache_spec(cfg, 8) == P()
    # blocks: the TOKEN axis (position 2) shards for EVERY model — the
    # GQA replication cliff does not exist.
    assert tp_serving.kv_cache_spec(cfg, 8, layout="blocks") == P(
        None, None, AXIS_MODEL, None, None)
    assert tp_serving.kv_cache_spec(cfg, 1, layout="blocks") == P()
    # blocks spills upload replicated (lane-table widths need not divide
    # the mesh); heads keeps the arena-matching row spec.
    assert tp_serving.kv_rows_spec(cfg, 2, head_axis=2) == P(
        None, None, AXIS_MODEL, None)
    assert tp_serving.kv_rows_spec(cfg, 2, head_axis=2,
                                   layout="blocks") == P()
    # The decode kernel's shard_map specs follow the same split.
    from kata_xpu_device_plugin_tpu.parallel.sharding import decode_attn_specs

    q, kv, out = decode_attn_specs(cfg, 8, quantized=False,
                                   kv_layout="blocks")
    assert kv == P(None, AXIS_MODEL, None, None)
    assert q == P(None, None, None, None) == out


# ----- knob contract --------------------------------------------------------


def test_kv_layout_env_select_and_malformed_degrade(model, monkeypatch,
                                                    tmp_path):
    cfg, params = model
    pool = dict(kv_pool_tokens=256, kv_block_size=8, max_batch=2,
                max_len=32)
    monkeypatch.setenv("KATA_TPU_KV_LAYOUT", "blocks")
    srv = GenerationServer(params, cfg, **pool)
    assert srv.stats()["kv_layout"] == "blocks"
    monkeypatch.setenv("KATA_TPU_KV_LAYOUT", "banana")
    srv, events = _capture_events(
        tmp_path, lambda: GenerationServer(params, cfg, **pool)
    )
    assert srv.stats()["kv_layout"] == "heads"
    assert any(e.get("name") == "kv_layout_invalid" for e in events)
    monkeypatch.delenv("KATA_TPU_KV_LAYOUT")
    # An explicit argument always wins over the env.
    monkeypatch.setenv("KATA_TPU_KV_LAYOUT", "heads")
    srv = GenerationServer(params, cfg, kv_layout="blocks", **pool)
    assert srv.stats()["kv_layout"] == "blocks"


def test_kv_layout_explicit_invalid_raises(model):
    cfg, params = model
    with pytest.raises(ValueError, match="kv_layout"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         kv_pool_tokens=256, kv_layout="banana")


def test_blocks_layout_requires_paged(model, monkeypatch, tmp_path):
    cfg, params = model
    # Explicit blocks on a slotted server: raise.
    with pytest.raises(ValueError, match="paged"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         kv_pool_tokens=0, kv_layout="blocks")
    # Node-injected env on a slotted server: degrade with an event.
    monkeypatch.setenv("KATA_TPU_KV_LAYOUT", "blocks")
    srv, events = _capture_events(
        tmp_path,
        lambda: GenerationServer(params, cfg, max_batch=2, max_len=32,
                                 kv_pool_tokens=0),
    )
    assert srv.stats()["kv_layout"] == "heads"
    assert any(
        e.get("name") == "kv_layout_disabled" and e["reason"] == "not_paged"
        for e in events
    )


def test_kv_host_knob_contract(model, monkeypatch, tmp_path):
    cfg, params = model
    with pytest.raises(ValueError, match="kv_host_tokens"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         kv_pool_tokens=256, kv_host_tokens=-1)
    with pytest.raises(ValueError, match="paged"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         kv_pool_tokens=0, kv_host_tokens=512)
    monkeypatch.setenv("KATA_TPU_KV_HOST_TOKENS", "16k")
    srv, events = _capture_events(
        tmp_path,
        lambda: GenerationServer(params, cfg, max_batch=2, max_len=32,
                                 kv_pool_tokens=256),
    )
    assert srv.stats()["kv_host_tokens"] == 0
    assert any(e.get("name") == "kv_host_invalid" for e in events)
    # A node-wide host tier on a slotted server degrades with an event.
    monkeypatch.setenv("KATA_TPU_KV_HOST_TOKENS", "512")
    srv, events = _capture_events(
        tmp_path,
        lambda: GenerationServer(params, cfg, max_batch=2, max_len=32,
                                 kv_pool_tokens=0),
    )
    assert srv.stats()["kv_host_tokens"] == 0
    assert any(
        e.get("name") == "kv_host_disabled" and e["reason"] == "not_paged"
        for e in events
    )


# ----- layout events --------------------------------------------------------


def test_kv_layout_event_once_per_server(model, tmp_path):
    cfg, params = model
    srv, events = _capture_events(
        tmp_path,
        lambda: GenerationServer(
            params, cfg, max_batch=2, max_len=32, kv_pool_tokens=256,
            kv_block_size=8, kv_layout="blocks", kv_host_tokens=512,
        ),
    )
    kv = [e for e in events if e.get("name") == "kv_layout"]
    assert len(kv) == 1
    assert kv[0]["layout"] == "blocks"
    assert kv[0]["shards"] == 1  # tp=1: one sub-pool
    assert kv[0]["per_shard_bytes"] > 0
    assert kv[0]["host_tier_tokens"] == 512


def test_kv_replicated_only_under_heads_layout(model, tmp_path):
    if jax.device_count() < 8:
        pytest.skip("needs the forced 8-device CPU host")
    cfg, params = model  # n_kv_heads=2 does not divide tp=8
    pool = dict(max_batch=2, max_len=32, kv_pool_tokens=8 * 64,
                kv_block_size=8, tp=8)

    _, events = _capture_events(
        tmp_path,
        lambda: GenerationServer(params, cfg, kv_layout="heads", **pool),
        name="heads.jsonl",
    )
    assert any(e.get("name") == "kv_replicated" for e in events)

    srv, events = _capture_events(
        tmp_path,
        lambda: GenerationServer(params, cfg, kv_layout="blocks", **pool),
        name="blocks.jsonl",
    )
    assert not any(e.get("name") == "kv_replicated" for e in events)
    kv = [e for e in events if e.get("name") == "kv_layout"]
    assert kv and kv[0]["shards"] == 8
    # Real per-shard sub-pools: the occupancy list has 8 entries.
    assert len(srv.stats()["kv_pool_shard_occupancy"]) == 8
    assert srv.kv_pool.shards == 8


# ----- bit-identity across layouts ------------------------------------------


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("overlap", [False, True])
def test_blocks_identity_matrix(model, kv_quant, overlap):
    """The acceptance criterion: blocks ≡ heads ≡ tp=1, greedy
    bit-identical, across int8/bf16 × overlap/lockstep × prefix-hit ×
    preemption pressure (tight pool), on the forced-8-device host."""
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    cfg, params = model
    prompts = _prompts(cfg, [10, 12, 9, 11, 8, 10], shared=16)
    kw = dict(
        max_batch=3, max_len=64, chunk=4, prefill_buckets=(16, 32),
        prefix_cache_tokens=1, kv_quant=kv_quant, overlap=overlap,
        kv_block_size=8, kv_pool_tokens=8 * 14,  # tight: preempts
    )
    ref, rsrv = _serve(params, cfg, prompts, budgets=24, **kw, tp=1)
    assert rsrv.stats()["preemptions"] > 0, "matrix must exercise pressure"
    for layout in ("heads", "blocks"):
        got, srv = _serve(params, cfg, prompts, budgets=24, **kw, tp=2,
                          kv_layout=layout)
        assert srv.stats()["kv_layout"] == layout
        for i, r in enumerate(ref):
            np.testing.assert_array_equal(got[i], r)


def test_blocks_identity_with_paged_kernel(model):
    """The blocks layout through the SHARD-LOCAL kernel form: each shard
    DMAs only its own blocks, cross-shard lanes recombine through the
    online-softmax merge — greedy tokens equal the tp=1 XLA path."""
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    cfg, params = model
    prompts = _prompts(cfg, [6, 9, 7, 5])
    kw = dict(max_batch=2, max_len=48, chunk=4, prefill_buckets=(16,),
              kv_block_size=8, kv_pool_tokens=512, prefix_cache_tokens=0)
    ref, _ = _serve(params, cfg, prompts, **kw, tp=1)
    for kv_quant in (False, True):
        got, srv = _serve(
            params, cfg, prompts, **kw, tp=2, kv_layout="blocks",
            kv_quant=kv_quant, decode_attn="pallas_paged",
        )
        assert srv.stats()["decode_backend"] == "pallas_paged"
        if not kv_quant:
            for i, r in enumerate(ref):
                np.testing.assert_array_equal(got[i], r)
        else:
            # int8 arenas round each cache write; the kernel's fused
            # dequant is value-identical to the gather path, so compare
            # against the tp=1 int8 ORACLE instead of the bf16 ref.
            ref8, _ = _serve(params, cfg, prompts, **kw, tp=1,
                             kv_quant=True)
            for i, r in enumerate(ref8):
                np.testing.assert_array_equal(got[i], r)


def test_int8_spill_restore_roundtrip_blocks_tp(model):
    """ISSUE 14 bug-risk satellite: preempting an int8 QTensor pool at
    tp>1 under the BLOCKS layout spills payload+scale rows whose blocks
    straddle shard boundaries (lane tables freely mix shards, spill
    widths need not divide tp); the host round-trip must restore them
    verbatim — greedy outputs bit-identical to the never-preempted run,
    with and without strict mode."""
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    cfg, params = model
    prompts = _prompts(cfg, [10, 12, 9, 11, 8, 10])
    for strict in (False, True):
        kw = dict(
            max_batch=3, max_len=64, chunk=4, prefill_buckets=(16,),
            kv_quant=True, kv_block_size=8, strict=strict,
            prefix_cache_tokens=0,
        )
        ref, _ = _serve(params, cfg, prompts, budgets=24, **kw, tp=1,
                        kv_pool_tokens=512)
        got, srv = _serve(params, cfg, prompts, budgets=24, **kw, tp=2,
                          kv_layout="blocks", kv_pool_tokens=8 * 14)
        assert srv.stats()["preemptions"] > 0, "must exercise the spill"
        for i, r in enumerate(ref):
            np.testing.assert_array_equal(got[i], r)


# ----- host tier: demotion / prefetch semantics -----------------------------


def _session_heads(cfg, n=2, seed=5):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (16,), 0, cfg.vocab_size
        ), np.int32)
        for i in range(n)
    ]


def _lineage_server(params, cfg, host_tokens, **kw):
    return GenerationServer(
        params, cfg, max_batch=1, max_len=48, chunk=4,
        prefill_buckets=(16, 32), prefix_cache_tokens=1, kv_block_size=8,
        kv_pool_tokens=8 * 8, kv_host_tokens=host_tokens,
        fault_injector=FaultInjector(), **kw,
    )


def test_demotion_before_preemption_and_survival(model, tmp_path):
    """Pool pressure demotes unpinned prefix segments to host RAM
    BEFORE any lane is preempted, the demoted segment's later hit
    prefetches it back, and outputs stay bit-identical to the
    tier-less run."""
    cfg, params = model
    h1, h2 = _session_heads(cfg)

    def burst(host_tokens):
        srv = _lineage_server(params, cfg, host_tokens)
        outs = []
        for i, head in enumerate([h1, h2, h1, h2, h1]):
            p = np.concatenate([head, np.asarray([50 + i] * 4, np.int32)])
            r = srv.submit(p, 8)
            outs.append(srv.run()[r])
        return outs, srv

    (ref, cold), _ = _capture_events(tmp_path, lambda: burst(0),
                                     name="cold.jsonl")
    (out, srv), events = _capture_events(tmp_path, lambda: burst(1024),
                                         name="host.jsonl")
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a, b)
    st = srv.stats()
    # Demotions happened, preemption never did: the tier absorbed the
    # pressure (demotion-before-preemption), and the parked segments
    # came back as hits the tier-less run lost to eviction.
    assert st["kv_demotions"] > 0 and st["kv_prefetches"] > 0
    assert st["preemptions"] == 0
    assert st["prefix_hits"] > cold.stats()["prefix_hits"]
    names = [e.get("name") for e in events]
    assert "kv_demote" in names and "kv_prefetch" in names
    # The tier-less run evicted (dropped) instead.
    assert cold.prefix_store.stats()["evictions"] > 0
    assert cold.stats()["kv_demotions"] == 0


@pytest.mark.parametrize("overlap", [False, True])
def test_resume_prefetch_races_decode_dispatch(model, overlap, tmp_path):
    """Preempted sessions resume through the staged H2D prefetch — the
    upload starts while a decode chunk is in flight (overlap) or ahead
    of the next round (lockstep) — with outputs bit-identical to the
    tier-less baseline and the prefetch visible on kv_resume events."""
    cfg, params = model
    prompts = _prompts(cfg, [10, 12, 9, 11, 8, 10], seed=3)
    kw = dict(max_batch=3, max_len=64, chunk=4, prefill_buckets=(16,),
              kv_block_size=8, kv_pool_tokens=8 * 14, overlap=overlap,
              prefix_cache_tokens=0)
    ref, rsrv = _serve(params, cfg, prompts, budgets=24, **kw)
    assert rsrv.stats()["preemptions"] > 0
    (got, srv), events = _capture_events(
        tmp_path,
        lambda: _serve(params, cfg, prompts, budgets=24,
                       kv_host_tokens=2048, **kw),
    )
    for i, r in enumerate(ref):
        np.testing.assert_array_equal(got[i], r)
    st = srv.stats()
    assert st["preemptions"] > 0 and st["kv_prefetches"] > 0
    resumes = [e for e in events if e.get("name") == "kv_resume"]
    assert resumes and any(e.get("prefetched") for e in resumes)


def test_degrade_mesh_replaces_block_sharded_pool(model):
    """Chip loss at tp=4 under the BLOCKS layout: the shrink re-places
    the pool onto the tp=2 mesh with matching per-shard sub-pools and
    the replayed load finishes bit-identically."""
    if jax.device_count() < 4:
        pytest.skip("needs the forced 8-device CPU host")
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    kw = dict(max_batch=2, max_len=48, chunk=4, prefill_buckets=(16,),
              kv_block_size=8, kv_pool_tokens=8 * 16, kv_layout="blocks",
              prefix_cache_tokens=0)
    ref, _ = _serve(params, cfg, prompts, **kw, tp=4)
    got, srv = _serve(
        params, cfg, prompts, **kw, tp=4,
        injector=FaultInjector(
            [FaultSpec("decode_dispatch", 2, "chip_loss", 1)], seed=3
        ),
    )
    for i, r in enumerate(ref):
        np.testing.assert_array_equal(got[i], r)
    st = srv.stats()
    assert st["tp_degraded"] == 1 and st["tp_degree"] == 2
    assert srv.failures() == {}
    # The rebuilt pool's sub-pools match the shrunken mesh.
    assert srv.kv_pool.shards == 2
    assert len(st["kv_pool_shard_occupancy"]) == 2


def test_seeded_faults_mid_demotion_recover_bit_identical(model):
    """pool_alloc faults fire INSIDE the allocation-pressure path that
    drives demotions, and a fence fault interrupts rounds with spilled
    sessions pending — recovery must keep greedy outputs bit-identical
    and fail nothing."""
    cfg, params = model
    h1, h2 = _session_heads(cfg, seed=11)

    def burst(injector):
        srv = GenerationServer(
            params, cfg, max_batch=1, max_len=48, chunk=4,
            prefill_buckets=(16, 32), prefix_cache_tokens=1,
            kv_block_size=8, kv_pool_tokens=8 * 8,
            kv_host_tokens=1024, fault_injector=injector,
        )
        outs = []
        for i, head in enumerate([h1, h2, h1, h2]):
            p = np.concatenate([head, np.asarray([60 + i] * 4, np.int32)])
            r = srv.submit(p, 8)
            outs.append(srv.run()[r])
        return outs, srv

    ref, refsrv = burst(FaultInjector())
    assert refsrv.stats()["kv_demotions"] > 0, "must exercise demotion"
    out, srv = burst(FaultInjector(
        [FaultSpec("pool_alloc", 2), FaultSpec("fence", 1)], seed=7,
    ))
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["recoveries"] >= 1
    assert srv.failures() == {}


def test_none_vanish_under_drain_with_host_tier(model):
    """A drain over a host-tier server with spilled (host-resident)
    sessions: started work finishes, the tail fails loudly, every rid
    ends in exactly one of results/failures, and failed spills release
    their host-tier accounting."""
    cfg, params = model
    prompts = _prompts(cfg, [9, 7, 8, 6, 9, 7], seed=9)
    srv = GenerationServer(
        params, cfg, max_batch=2, max_len=48, chunk=4,
        prefill_buckets=(16,), kv_block_size=8, kv_pool_tokens=8 * 14,
        kv_host_tokens=2048, fault_injector=FaultInjector(),
        prefix_cache_tokens=0,
    )
    rids = [srv.submit(p, 16) for p in prompts]
    # A few rounds so lanes fill and pressure spills someone to host.
    for _ in range(6):
        if not srv.step():
            break
    results = srv.drain(reason="test")
    failures = srv.failures()
    for r in rids:
        assert (r in results) != (r in failures), f"rid {r} vanished"
    # Terminal spills released their pinned host entries; live-completed
    # ones released at resume — nothing leaks.
    if srv._kv_host is not None:
        assert all(
            not (isinstance(k, tuple) and k[0] == "spill")
            or srv._kv_host.get(k) is None
            for k in list(srv._kv_host._entries)
        )


# ----- stats / metrics / daemon plumbing ------------------------------------


def test_stats_schema_always_present(model):
    cfg, params = model
    slotted = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               kv_pool_tokens=0, kv_layout=None)
    st = slotted.stats()
    assert st["kv_layout"] == "heads" and st["kv_pool_shards"] == 1
    assert st["kv_host_tokens"] == 0 and st["kv_host_blocks"] == 0
    assert st["kv_demotions"] == 0 and st["kv_prefetches"] == 0
    paged = GenerationServer(params, cfg, max_batch=2, max_len=32,
                             kv_pool_tokens=256, kv_block_size=8,
                             kv_layout="blocks", kv_host_tokens=512)
    st = paged.stats()
    assert st["kv_layout"] == "blocks"
    assert st["kv_host_tokens"] == 512


def test_export_metrics_includes_host_tier_gauges(model):
    from prometheus_client import REGISTRY, generate_latest

    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           kv_pool_tokens=256, kv_block_size=8,
                           kv_host_tokens=512)
    label = srv.export_metrics()
    text = generate_latest(REGISTRY).decode()
    assert f'kata_tpu_serving_kv_host_blocks{{server="{label}"}}' in text
    for ctr in ("kv_demotions_total", "kv_prefetches_total"):
        assert f'kata_tpu_serving_{ctr}{{server="{label}"}}' in text


def test_allocator_injects_kv_layout_env():
    """Daemon side of the knobs: config.kv_layout / kv_host_tokens ride
    the TPU AllocateResponse env (plugin/allocators.py), the same
    delivery path as the pool/quant knobs. Host-only — no jax."""
    from kata_xpu_device_plugin_tpu.cdi import constants as C
    from kata_xpu_device_plugin_tpu.discovery.tpu import TpuChip, TpuInventory
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator
    from kata_xpu_device_plugin_tpu.topology.slice import HostTopology

    inv = TpuInventory(
        chips=(TpuChip(index=0, dev_path="/dev/accel0"),),
        topology=HostTopology.from_accelerator_type("v5litepod-8"),
        model_suffix="TPU_V5E",
    )
    alive = lambda _chip: True  # noqa: E731 — no real /dev in this test
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive,
        kv_layout="blocks", kv_host_tokens=1 << 20,
    ).allocate(["0"])
    assert wired.envs[C.ENV_KV_LAYOUT] == "blocks"
    assert wired.envs[C.ENV_KV_HOST_TOKENS] == str(1 << 20)
    bare = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive
    ).allocate(["0"])
    assert C.ENV_KV_LAYOUT not in bare.envs
    assert C.ENV_KV_HOST_TOKENS not in bare.envs


def test_config_validates_layout_and_host_tokens():
    from kata_xpu_device_plugin_tpu.config import Config

    assert Config(kv_layout="blocks", kv_host_tokens=4096).kv_layout == \
        "blocks"
    with pytest.raises(ValueError, match="kv-layout"):
        Config(kv_layout="banana")
    with pytest.raises(ValueError, match="kv-host-tokens"):
        Config(kv_host_tokens=-1)
