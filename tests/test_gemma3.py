"""Gemma-3 family mechanics beyond HF parity (tests/test_hf_convert.py):
the per-layer dual-rope/QK-norm config through generate, serving, and the
cycle-arena ring KV — all paths that must honor per-cycle-position rope.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from dataclasses import replace

from kata_xpu_device_plugin_tpu.guest.serving import serve_batch
from kata_xpu_device_plugin_tpu.models import (
    gemma3_test_config,
    generate,
    init_params,
)


@pytest.fixture(scope="module")
def setup():
    cfg = replace(gemma3_test_config(), dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(30), cfg)
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(31), (12,), 0, cfg.vocab_size)
    )
    ref = np.asarray(
        generate(params, jnp.asarray(prompt)[None], cfg, steps=8)
    )[0]
    return cfg, params, prompt, ref


def test_generate_uses_cycle_rope(setup):
    """The dual-rope config must actually change the output: zeroing the
    theta cycle back to uniform rope produces different tokens (guards
    against the cycle silently not reaching the layers)."""
    cfg, params, prompt, ref = setup
    uniform = replace(cfg, rope_theta_cycle=(), rope_linear_cycle=())
    out_u = np.asarray(
        generate(params, jnp.asarray(prompt)[None], uniform, steps=8)
    )[0]
    assert not np.array_equal(out_u, ref)


def test_serving_matches_generate(setup):
    cfg, params, prompt, ref = setup
    out = serve_batch(params, cfg, [prompt], 8, max_batch=2, max_len=32)[0]
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_cycle_arena_ring_kv_matches_full_cache(setup):
    """Gemma-3's window cycle rides the Gemma-2 cycle arena: local layers
    ring at their window, the global layer keeps max_len — token-identical
    to the full-cache path."""
    cfg, params, prompt, ref = setup
    out = serve_batch(
        params, cfg, [prompt], 8, max_batch=2, max_len=32, ring_kv=True
    )[0]
    np.testing.assert_array_equal(np.asarray(out), ref)
