"""ICI topology model + preferred-allocation tests."""
import pytest

from kata_xpu_device_plugin_tpu import topology
from kata_xpu_device_plugin_tpu.topology import slice as tslice


def topo(accel="v5litepod-8", **kw):
    return topology.HostTopology.from_accelerator_type(accel, **kw)


def test_parse_accelerator_types():
    fam, chips = tslice.parse_accelerator_type("v5litepod-8")
    assert fam.name == "v5litepod" and chips == 8
    fam, chips = tslice.parse_accelerator_type("v4-8")  # cores → 4 chips
    assert fam.name == "v4" and chips == 4
    fam, chips = tslice.parse_accelerator_type("v5p-32")
    assert chips == 16
    for bad in ("v99-8", "v5litepod", "v4-x"):
        with pytest.raises(ValueError):
            tslice.parse_accelerator_type(bad)


def test_host_topology_single_host():
    t = topo("v5litepod-8")
    assert t.local_chips == 8 and t.num_hosts == 1
    assert t.local_grid() == (2, 4, 1)
    assert t.chips_per_host_bounds_str() == "2,4,1"
    assert t.host_bounds_str() == "1,1,1"
    assert t.valid_request_counts() == [1, 2, 4, 8]


def test_host_topology_subhost():
    t = topo("v5litepod-4")
    assert t.local_chips == 4
    assert t.local_grid() == (2, 2, 1)


def test_host_topology_multi_host():
    t = topo("v5p-32", worker_id=1, worker_hostnames=["h0", "h1", "h2", "h3"])
    assert t.num_hosts == 4 and t.local_chips == 4
    assert t.is_multi_host
    assert t.valid_request_counts() == [4]  # whole host only
    assert t.host_bounds_str() == "1,1,4"
    env = topology.runtime_env(t, visible_chips=[0, 1, 2, 3])
    assert env["TPU_WORKER_ID"] == "1"
    assert env["TPU_WORKER_HOSTNAMES"] == "h0,h1,h2,h3"
    assert env["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
    assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"


def test_v5e_multihost_bounds():
    t = topo("v5litepod-16")
    assert t.num_hosts == 2 and t.local_chips == 8
    assert t.host_bounds_str() == "1,2,1"


def test_chip_coords_roundtrip():
    fam = tslice.FAMILIES["v5litepod"]
    coords = [tslice.chip_coord(fam, i) for i in range(8)]
    assert coords[0] == (0, 0, 0) and coords[1] == (1, 0, 0) and coords[2] == (0, 1, 0)
    assert len(set(coords)) == 8
    for i in range(8):
        assert tslice.coord_chip(fam, coords[i]) == i


def test_choose_chips_contiguous_2x2():
    t = topo("v5litepod-8")
    p = topology.choose_chips(t, available=list(range(8)), count=4)
    assert p.contiguous and p.chips == (0, 1, 2, 3)  # the low 2x2 box


def test_choose_chips_avoids_fragmented_box():
    t = topo("v5litepod-8")
    # chips 1 and 2 taken: low 2x2 (0,1,2,3) unavailable; upper box (4,5,6,7) is.
    p = topology.choose_chips(t, available=[0, 3, 4, 5, 6, 7], count=4)
    assert p.contiguous and p.chips == (4, 5, 6, 7)


def test_choose_chips_pair_either_axis():
    t = topo("v5litepod-8")
    # 2-chip slice along y: chips 0 and 2 are (0,0) and (0,1).
    p = topology.choose_chips(t, available=[0, 2, 5], count=2)
    assert p.contiguous and p.chips == (0, 2)


def test_choose_chips_must_include():
    t = topo("v5litepod-8")
    p = topology.choose_chips(t, available=list(range(8)), count=4, must_include=[6])
    assert p.contiguous and 6 in p.chips and p.chips == (4, 5, 6, 7)


def test_choose_chips_fallback_non_contiguous():
    t = topo("v5litepod-8")
    # No 2x2 box fits in {0, 3, 5, 6}: falls back, still returns 4 chips.
    p = topology.choose_chips(t, available=[0, 3, 5, 6], count=4)
    assert not p.contiguous and len(p.chips) == 4


def test_choose_chips_errors():
    t = topo("v5litepod-8")
    with pytest.raises(ValueError):
        topology.choose_chips(t, available=[0, 1], count=4)
    with pytest.raises(ValueError):
        topology.choose_chips(t, available=[0, 1], count=1, must_include=[7])


def test_alignment_score():
    t = topo("v5litepod-8")
    assert topology.alignment_score(t, [0, 1, 2, 3]) == 1.0
    assert topology.alignment_score(t, [0, 3, 5, 6]) == 0.0


def test_detect_accelerator_type_rounds_up():
    # 3 healthy chips of a 4-chip host must yield a type with a valid grid.
    assert tslice.detect_accelerator_type({}, chip_count=3) == "v5litepod-4"
    assert tslice.detect_accelerator_type({}, chip_count=6) == "v5litepod-8"
    assert tslice.detect_accelerator_type({}, chip_count=12) == "v5litepod-16"
    assert tslice.detect_accelerator_type({}, chip_count=0) == "v5litepod-1"
    t = topo(tslice.detect_accelerator_type({}, chip_count=3))
    assert t.local_grid() == (2, 2, 1)  # does not raise


def test_choose_chips_must_include_exceeding_count():
    t = topo("v5litepod-8")
    with pytest.raises(ValueError):
        topology.choose_chips(t, available=[0, 1, 2, 3], count=1, must_include=[0, 2])


def test_host_bounds_2d_vs_3d_families():
    assert topo("v3-32").host_bounds_str() == "1,4,1"  # 2D torus: stack in y
    assert topo("v5p-32").host_bounds_str() == "1,1,4"  # 3D torus: stack in z
    assert topo("v5litepod-32").host_bounds_str() == "1,4,1"


def test_detect_accelerator_type_unknown_id_warns(caplog):
    import logging

    with caplog.at_level(logging.WARNING, logger="katatpu.topology"):
        t = tslice.detect_accelerator_type({}, chip_count=4, pci_device_id="beef")
    assert t == "v5litepod-4"
    assert any("assuming v5litepod" in r.getMessage() for r in caplog.records)

    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="katatpu.topology"):
        assert tslice.detect_accelerator_type(
            {}, chip_count=4, pci_device_id="0062"
        ).startswith("v5p")
        assert (
            tslice.detect_accelerator_type({"TPU_ACCELERATOR_TYPE": "v4-8"}) == "v4-8"
        )
    assert not caplog.records  # known id / env: no warning
