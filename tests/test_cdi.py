"""CDI model/writer tests (SURVEY §4: golden-file tests of the spec shapes)."""
import os

import pytest
import yaml

from kata_xpu_device_plugin_tpu import cdi
from kata_xpu_device_plugin_tpu.cdi import constants as C


def _tpu_spec() -> cdi.Spec:
    spec = cdi.Spec(kind="google.com/tpu", cdi_version=C.CDI_VERSION)
    spec.container_edits.add_env(f"{C.ENV_TPU_SKIP_MDS_QUERY}", "true")
    spec.container_edits.mounts.append(
        cdi.Mount(host_path="/usr/lib/tpu/libtpu.so", container_path=C.LIBTPU_CONTAINER_PATH)
    )
    for i in range(2):
        dev = cdi.Device(
            name=str(i),
            annotations={C.ANNOTATION_BDF: f"0000:0{i}:00.0"},
            container_edits=cdi.ContainerEdits(
                device_nodes=[cdi.DeviceNode(path=f"/dev/accel{i}", type="c", permissions="rw")],
                env=[f"{C.ENV_TPU_VISIBLE_CHIPS}={i}"],
            ),
        )
        spec.add_device(dev)
    return spec


GOLDEN_YAML = """\
cdiVersion: 0.6.0
kind: google.com/tpu
devices:
- name: '0'
  annotations:
    bdf: '0000:00:00.0'
  containerEdits:
    env:
    - TPU_VISIBLE_CHIPS=0
    deviceNodes:
    - path: /dev/accel0
      type: c
      permissions: rw
- name: '1'
  annotations:
    bdf: '0000:01:00.0'
  containerEdits:
    env:
    - TPU_VISIBLE_CHIPS=1
    deviceNodes:
    - path: /dev/accel1
      type: c
      permissions: rw
containerEdits:
  env:
  - TPU_SKIP_MDS_QUERY=true
  mounts:
  - hostPath: /usr/lib/tpu/libtpu.so
    containerPath: /usr/lib/tpu/libtpu.so
    options:
    - ro
    - nosuid
    - nodev
    - bind
    type: bind
"""


def test_golden_yaml_shape():
    assert cdi.render(_tpu_spec(), cdi.FORMAT_YAML) == GOLDEN_YAML


def test_yaml_and_json_roundtrip(tmp_path):
    spec = _tpu_spec()
    for fmt in (cdi.FORMAT_YAML, cdi.FORMAT_JSON):
        path = cdi.save(spec, str(tmp_path), fmt)
        assert os.path.basename(path) == f"google.com-tpu.{'json' if fmt == 'json' else 'yaml'}"
        loaded = cdi.load(path)
        assert loaded.to_dict() == spec.to_dict()


def test_atomic_write_leaves_no_tmp(tmp_path):
    cdi.save(_tpu_spec(), str(tmp_path))
    leftovers = [f for f in os.listdir(tmp_path) if f.startswith(".cdi-")]
    assert leftovers == []


def test_per_kind_filenames_do_not_collide(tmp_path):
    # Fixes reference quirk 7 (hardcoded single filename, device_plugin.go:79).
    cdi.save(cdi.Spec(kind="google.com/tpu"), str(tmp_path))
    cdi.save(cdi.Spec(kind="google.com/vfio"), str(tmp_path))
    names = sorted(os.listdir(tmp_path))
    assert names == ["google.com-tpu.yaml", "google.com-vfio.yaml"]


def test_qualified_names():
    qn = cdi.qualified_name("google.com", "tpu", "3")
    assert qn == "google.com/tpu=3"
    assert cdi.parse_qualified_name(qn) == ("google.com", "tpu", "3")
    assert cdi.is_qualified_name("google.com/tpu=0")
    assert not cdi.is_qualified_name("google.com/tpu")
    assert not cdi.is_qualified_name("no-slash=0")
    with pytest.raises(ValueError):
        cdi.qualified_name("google.com", "tpu", "bad name")


def test_invalid_kind_and_duplicate_device():
    with pytest.raises(ValueError):
        cdi.Spec(kind="noslash")
    spec = cdi.Spec(kind="google.com/tpu")
    spec.add_device(cdi.Device(name="0"))
    with pytest.raises(ValueError):
        spec.add_device(cdi.Device(name="0"))


def test_empty_fields_pruned():
    spec = cdi.Spec(kind="google.com/tpu")
    d = spec.to_dict()
    assert "devices" not in d and "annotations" not in d and "containerEdits" not in d
    doc = yaml.safe_load(cdi.render(spec))
    assert doc == {"cdiVersion": "0.6.0", "kind": "google.com/tpu"}


def test_remove(tmp_path):
    spec = _tpu_spec()
    cdi.save(spec, str(tmp_path))
    cdi.remove(str(tmp_path), spec.kind)
    assert os.listdir(tmp_path) == []
