"""Serving heartbeat + SLO-burn watchdog (ISSUE 15).

Three layers under test. UNIT: the watchdog's burn/anomaly rules over
synthetic heartbeats — sustain-before-alert, clear-after-healthy, the
flight dump naming, the bounded profiler window. SERVER: the heartbeat's
cadence, field schema, interval-delta correctness, the knob contract
(explicit raises / env degrades), and the uninstrumented path at
cadence 0. INTEGRATION: a seeded ``chip_loss`` mid-decode at tp=2 must
produce breach → flight dump carrying the watchdog reason →
recovery-clears-alert, with greedy outputs BIT-IDENTICAL to a fault-free
run — deterministic in both strict modes (the breach signal is the
recovery COUNTER, not wall-clock timing)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest.resilience import (
    FaultInjector,
    FaultSpec,
)
from kata_xpu_device_plugin_tpu.guest.serving import (
    DEFAULT_HEARTBEAT_ROUNDS,
    ENV_HEARTBEAT_ROUNDS,
    LOOP_PHASES,
    GenerationServer,
)
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params
from kata_xpu_device_plugin_tpu.obs import watchdog as wd_mod
from kata_xpu_device_plugin_tpu.obs.watchdog import (
    ALERT_HOST_HIT_COLLAPSE,
    ALERT_PREEMPT_STORM,
    ALERT_RECOVERY_STORM,
    ALERT_SLO_BURN,
    ALERT_TOKENS_REGRESSION,
    SLOBurnWatchdog,
    WatchdogConfig,
)


# ----- unit: rule mechanics over synthetic heartbeats ------------------------


def _hb(**kw):
    base = dict(
        round=1, interval_rounds=4, interval_s=1.0, tokens_per_s=100.0,
        itl_p99_ms=10.0, preemptions_delta=0, recoveries_delta=0,
        prefix_hits_delta=0, prefix_misses_delta=0, kv_host_tokens=0,
    )
    base.update(kw)
    return base


def _watchdog(cfg, evs, dumps=None):
    dump = (
        (lambda reason: dumps.append(reason) or f"/dev/null/{reason}")
        if dumps is not None else None
    )
    return SLOBurnWatchdog(
        cfg,
        emit=lambda name, **f: evs.append({"name": name, **f}),
        dump=dump,
    )


def test_slo_burn_fires_after_window_and_sustain_then_clears():
    evs, dumps = [], []
    wd = _watchdog(
        WatchdogConfig(slo_ms=50.0, window=2, sustain=2, clear=2),
        evs, dumps,
    )
    slow, fast = _hb(itl_p99_ms=120.0), _hb(itl_p99_ms=5.0)
    assert wd.observe(slow) == []      # window not yet full
    assert wd.observe(slow) == []      # burn=1.0, streak 1 < sustain
    assert wd.observe(slow) == [ALERT_SLO_BURN]
    assert wd.active == (ALERT_SLO_BURN,)
    assert dumps == [f"watchdog_{ALERT_SLO_BURN}"]
    alert = [e for e in evs if e["name"] == "watchdog_alert"][0]
    assert alert["alert"] == ALERT_SLO_BURN
    assert "burn_rate=1.00" in alert["reason"]
    assert alert["dump"].endswith(ALERT_SLO_BURN)
    # An active alert never re-fires while it stays breaching.
    assert wd.observe(slow) == []
    assert wd.stats()["alerts"] == 1
    # One fast heartbeat still leaves burn at 0.5 >= threshold (window
    # 2); the second empties the window of breaches and starts the
    # healthy streak — clear after two healthy evaluations.
    wd.observe(fast)
    wd.observe(fast)
    wd.observe(fast)
    assert wd.active == ()
    clears = [e for e in evs if e["name"] == "watchdog_clear"]
    assert clears and clears[0]["alert"] == ALERT_SLO_BURN


def test_anomaly_rules_fire_on_their_signals():
    evs = []
    wd = _watchdog(
        WatchdogConfig(
            slo_ms=0.0, sustain=1, clear=1, preempt_storm=4,
            recovery_storm=2, hit_floor=0.5, min_lookups=4,
        ),
        evs,
    )
    assert wd.observe(_hb(preemptions_delta=4)) == [ALERT_PREEMPT_STORM]
    assert wd.observe(_hb(recoveries_delta=2)) == [ALERT_RECOVERY_STORM]
    # Hit collapse needs the host tier armed AND real lookup traffic.
    assert wd.observe(
        _hb(prefix_hits_delta=1, prefix_misses_delta=9)
    ) == []  # tier off: not a host-tier signal
    assert wd.observe(
        _hb(prefix_hits_delta=1, prefix_misses_delta=9,
            kv_host_tokens=1024)
    ) == [ALERT_HOST_HIT_COLLAPSE]
    # Healthy heartbeats clear all three (clear=1).
    wd.observe(_hb())
    assert wd.active == ()


def test_tokens_regression_against_own_ewma():
    evs = []
    wd = _watchdog(
        WatchdogConfig(slo_ms=0.0, sustain=1, clear=1, min_samples=3,
                       regress_ratio=0.5),
        evs,
    )
    for _ in range(4):
        assert wd.observe(_hb(tokens_per_s=100.0)) == []
    # 30 < 0.5 × ewma(100): breach. The slump must NOT be folded into
    # the baseline — a second slumped heartbeat still breaches.
    assert wd.observe(_hb(tokens_per_s=30.0)) == [ALERT_TOKENS_REGRESSION]
    wd.observe(_hb(tokens_per_s=100.0))  # clears
    assert wd.observe(_hb(tokens_per_s=30.0)) == [ALERT_TOKENS_REGRESSION]
    # Idle heartbeats (no rounds) never count as regression.
    assert wd.observe(
        _hb(tokens_per_s=0.0, interval_rounds=0)
    ) == []


def test_watchdog_dump_reason_names_the_postmortem(tmp_path):
    """The default dump path goes through the always-armed flight ring:
    the postmortem file name carries watchdog_<kind> — the on-disk
    artifact the chaos gate asserts on."""
    from kata_xpu_device_plugin_tpu.obs import flight

    rec = flight.FlightRecorder(capacity=64)
    prev = flight.set_default_recorder(rec)
    prev_dir = os.environ.get(flight.ENV_DIR)
    os.environ[flight.ENV_DIR] = str(tmp_path)
    try:
        evs = []
        wd = _watchdog(
            WatchdogConfig(slo_ms=0.0, sustain=1, preempt_storm=1), evs
        )
        rec.record({"kind": "serving", "name": "warmup"})  # ring non-empty
        assert wd.observe(_hb(preemptions_delta=1)) == [ALERT_PREEMPT_STORM]
        alert = [e for e in evs if e["name"] == "watchdog_alert"][0]
        assert alert["dump"]
        assert os.path.exists(alert["dump"])
        assert f"watchdog_{ALERT_PREEMPT_STORM}" in os.path.basename(
            alert["dump"]
        )
        assert wd.stats()["last_dump"] == alert["dump"]
    finally:
        if prev_dir is None:
            os.environ.pop(flight.ENV_DIR, None)
        else:
            os.environ[flight.ENV_DIR] = prev_dir
        flight.set_default_recorder(prev)


def test_watchdog_profiler_window_bounded(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    evs = []
    wd = _watchdog(
        WatchdogConfig(slo_ms=0.0, sustain=1, clear=1, preempt_storm=1,
                       profile_dir=str(tmp_path), profile_steps=2),
        evs, dumps=[],
    )
    wd.observe(_hb(preemptions_delta=1))       # alert → window opens
    assert calls == [("start", str(tmp_path))]
    wd.observe(_hb())                           # step 1
    wd.observe(_hb())                           # step 2 → window closes
    assert calls[-1] == ("stop",)
    assert len(calls) == 2
    # close() after the window already stopped is a no-op.
    wd.close()
    assert len(calls) == 2


def test_watchdog_observe_never_raises():
    wd = SLOBurnWatchdog(
        WatchdogConfig(slo_ms=50.0, sustain=1),
        emit=lambda name, **f: None,
        dump=lambda reason: None,
    )
    assert wd.observe({"itl_p99_ms": "garbage", "interval_rounds": "x"}) == []
    assert wd.observe({}) == []


def test_config_from_env_degrades_malformed(monkeypatch):
    monkeypatch.setenv(wd_mod.ENV_WINDOW, "not-a-number")
    monkeypatch.setenv(wd_mod.ENV_BURN_THRESHOLD, "0.9")
    cfg = WatchdogConfig.from_env(slo_ms=25.0)
    assert cfg.window == WatchdogConfig().window  # malformed → default
    assert cfg.burn_threshold == 0.9
    assert cfg.slo_ms == 25.0


# ----- server: heartbeat emission -------------------------------------------


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=5):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


def _server(params, cfg, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 48)
    kw.setdefault("chunk", 2)
    kw.setdefault("kv_quant", False)
    kw.setdefault("fault_injector", FaultInjector())
    kw.setdefault("recovery_backoff_s", 0.0)
    return GenerationServer(params, cfg, **kw)


def test_heartbeat_cadence_fields_and_deltas(model, capture_events):
    cfg, params = model

    def run():
        srv = _server(params, cfg, heartbeat_rounds=2)
        for p in _prompts(cfg, [6, 8, 6, 8]):
            srv.submit(p, 8)
        return srv, srv.run()

    (srv2, results2), events = capture_events(run)
    hbs = [e for e in events if e.get("name") == "serving_heartbeat"]
    assert hbs, "no heartbeats at cadence 2"
    # Cadence: every non-final heartbeat covers exactly 2 rounds; the
    # final flush may carry a shorter tail interval.
    assert all(hb["interval_rounds"] <= 2 for hb in hbs)
    assert sum(hb["interval_rounds"] for hb in hbs) == srv2.stats()["rounds"]
    # Interval token deltas sum to the cumulative decoded total.
    decoded = srv2.stats()["tokens_emitted"] - srv2.stats()["prefills"]
    assert sum(hb["tokens_delta"] for hb in hbs) == decoded
    # Schema: every heartbeat carries the full field set (no branches).
    required = {
        "round", "interval_rounds", "interval_s", "tokens_per_s",
        "slots_busy", "queued", "batch_occupancy", "kv_pool_occupancy",
        "kv_pool_shard_occupancy", "kv_host_occupancy", "kv_host_blocks",
        "prefix_store_occupancy", "prefix_hit_rate", "kv_demotions_delta",
        "kv_prefetches_delta", "preemptions_delta", "recoveries_delta",
        "slo_violations_delta", "itl_p50_ms", "itl_p99_ms", "ttft_p50_ms",
        "ttft_p99_ms", "slo_ms", "tp", "tp_degraded", "decode_steps",
        "chips", "admission_wait_p50_ms", "admission_wait_p99_ms",
    } | {f"phase_{p}_s" for p in LOOP_PHASES}
    assert required <= set(hbs[0])
    st = srv2.stats()
    assert st["heartbeats"] == len(hbs)
    assert st["heartbeat_rounds"] == 2
    assert st["heartbeat_tokens_per_s"] == hbs[-1]["tokens_per_s"]
    assert set(st["loop_phase_s"]) == set(LOOP_PHASES[:-1])
    # The loop actually spent time in admit and dispatch.
    assert st["loop_phase_s"]["admit"] > 0
    assert st["loop_phase_s"]["dispatch"] > 0


def test_heartbeat_disabled_is_uninstrumented(model, capture_events):
    cfg, params = model

    def run():
        srv = _server(params, cfg, heartbeat_rounds=0)
        for p in _prompts(cfg, [6, 8]):
            srv.submit(p, 6)
        srv.run()
        return srv

    srv, events = capture_events(run)
    assert not [e for e in events if e.get("name") == "serving_heartbeat"]
    assert srv._watchdog is None
    assert not srv._clock.armed
    st = srv.stats()
    assert st["heartbeats"] == 0
    assert st["watchdog_alerts"] == 0
    assert all(v == 0.0 for v in st["loop_phase_s"].values())


def test_heartbeat_outputs_bit_identical_on_off(model):
    cfg, params = model
    outs = []
    for hb in (0, 1):
        srv = _server(params, cfg, heartbeat_rounds=hb)
        rids = [srv.submit(p, 8) for p in _prompts(cfg, [6, 8, 6])]
        res = srv.run()
        outs.append([res[r].tolist() for r in rids])
    assert outs[0] == outs[1]


def test_heartbeat_knob_contract(model, capture_events, monkeypatch):
    cfg, params = model
    # Explicit nonsense raises.
    with pytest.raises(ValueError, match="heartbeat_rounds"):
        _server(params, cfg, heartbeat_rounds=-1)
    with pytest.raises(ValueError, match="watchdog requires"):
        _server(params, cfg, heartbeat_rounds=0, watchdog=True)
    # Malformed env degrades to the default with an event.
    monkeypatch.setenv(ENV_HEARTBEAT_ROUNDS, "sometimes")
    srv, events = capture_events(lambda: _server(params, cfg))
    assert srv._hb_every == DEFAULT_HEARTBEAT_ROUNDS
    assert any(e.get("name") == "heartbeat_invalid" for e in events)
    # Parseable nonsense degrades too.
    monkeypatch.setenv(ENV_HEARTBEAT_ROUNDS, "-3")
    srv2, events2 = capture_events(lambda: _server(params, cfg))
    assert srv2._hb_every == DEFAULT_HEARTBEAT_ROUNDS
    assert any(e.get("name") == "heartbeat_invalid" for e in events2)
    # The watchdog kill switch disarms without touching the heartbeat.
    monkeypatch.setenv(ENV_HEARTBEAT_ROUNDS, "4")
    monkeypatch.setenv(wd_mod.ENV_WATCHDOG, "0")
    srv3 = _server(params, cfg)
    assert srv3._hb_every == 4
    assert srv3._watchdog is None


def test_serving_config_event_carries_heartbeat_shape(model, capture_events):
    cfg, params = model
    srv, events = capture_events(
        lambda: _server(params, cfg, heartbeat_rounds=7)
    )
    sc = [e for e in events if e.get("name") == "serving_config"][0]
    assert sc["heartbeat_rounds"] == 7
    assert sc["watchdog"] == 1


# ----- integration: chip_loss → breach → dump → clear ------------------------


@pytest.mark.skipif(jax.device_count() < 2, reason="needs 2 devices")
def test_chip_loss_breach_dump_and_clear_bit_identical(model, capture_events):
    """The ISSUE 15 chaos acceptance: a seeded ``chip_loss`` mid-decode
    at tp=2 shrinks the mesh (ISSUE 10); the recovery shows up in the
    next heartbeat's ``recoveries_delta``, the watchdog fires
    ``recovery_storm`` (sustain 1), dumps the flight ring with the
    watchdog reason, and — once recovered rounds flow — clears the
    alert. Greedy outputs stay bit-identical to a fault-free run, and
    the whole sequence is counter-driven (deterministic in both strict
    modes)."""
    cfg, params = model
    prompts = _prompts(cfg, [8, 6, 8], seed=11)

    def serve(injector):
        wd = SLOBurnWatchdog(
            WatchdogConfig(slo_ms=0.0, sustain=1, clear=1,
                           recovery_storm=1),
        )
        srv = _server(
            params, cfg, tp=2, tp_min=1, heartbeat_rounds=1,
            watchdog=wd, fault_injector=injector, max_len=32, chunk=4,
        )
        rids = [srv.submit(p, 8) for p in prompts]
        res = srv.run()
        return srv, [res[r].tolist() for r in rids]

    clean_srv, clean_out = serve(FaultInjector())
    assert clean_srv.stats()["watchdog_alerts"] == 0

    def faulted():
        return serve(FaultInjector(
            [FaultSpec("decode_dispatch", 2, "chip_loss", 1)], seed=3
        ))

    (srv, out), events = capture_events(faulted)
    # Degraded recovery happened and outputs are bit-identical.
    assert srv.stats()["tp_shrinks"] == 1
    assert out == clean_out
    # Breach: the watchdog fired on the recovery counter and dumped.
    alerts = [e for e in events if e.get("name") == "watchdog_alert"]
    assert [a["alert"] for a in alerts] == [ALERT_RECOVERY_STORM]
    dump = alerts[0]["dump"]
    assert dump and os.path.exists(dump)
    assert "watchdog_recovery_storm" in os.path.basename(dump)
    # The postmortem carries the incident: the tp_degraded/recovery
    # events leading into the breach and the alert itself as context.
    dumped = obs.read_events(dump)
    names = {e.get("name") for e in dumped}
    assert "tp_degraded" in names
    assert "serving_heartbeat" in names
    # Recovery clears the alert before the run ends.
    clears = [e for e in events if e.get("name") == "watchdog_clear"]
    assert [c["alert"] for c in clears] == [ALERT_RECOVERY_STORM]
    assert srv.stats()["watchdog_active"] == 0
    assert srv.stats()["watchdog"]["last_dump"] == dump
    # Ordering: alert strictly before its clear.
    ts = [e.get("name") for e in events
          if e.get("name") in ("watchdog_alert", "watchdog_clear")]
    assert ts == ["watchdog_alert", "watchdog_clear"]
