"""Fault-injection harness + watchdog fence units (guest/resilience.py,
ISSUE 7).

Oracle: the injector is DETERMINISTIC — (seed, schedule) fully determines
the fired sequence and its event stream — and every env knob follows the
repo's degrade contract (malformed node-injected values fall back with an
event, never crash a guest). The recovery matrix itself lives in
tests/test_recovery.py; this file pins the primitives it builds on.
"""
import time

import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest import resilience
from kata_xpu_device_plugin_tpu.guest.resilience import (
    KIND_HANG,
    KIND_OOM,
    KIND_TRANSIENT,
    SEAMS,
    DeviceStallError,
    FaultInjector,
    FaultSpec,
    InjectedOom,
    TransientFault,
    fence_with_timeout,
    parse_schedule,
    recoverable,
)


def _events(path):
    return obs.read_events(str(path))


def _capture(tmp_path, name="ev.jsonl"):
    sink = obs.EventSink(str(tmp_path / name))
    prev = obs.set_default_sink(sink)
    return sink, prev


# ----- schedule grammar ----------------------------------------------------


def test_parse_schedule_grammar():
    specs, bad = parse_schedule(
        "decode_dispatch:2,fence:0:hang,prefill:1:raise-oom, pool_alloc:3 "
    )
    assert specs == [
        FaultSpec("decode_dispatch", 2, KIND_TRANSIENT),
        FaultSpec("fence", 0, KIND_HANG),
        FaultSpec("prefill", 1, KIND_OOM),
        FaultSpec("pool_alloc", 3, KIND_TRANSIENT),
    ]
    assert bad == []


def test_parse_schedule_rejects_malformed_entries_individually():
    specs, bad = parse_schedule(
        "bogus_seam:1,prefill:x,prefill:1:weird,fence:-2,prefill,"
        "decode_dispatch:0"
    )
    # The one valid entry survives; each malformed one is reported.
    assert specs == [FaultSpec("decode_dispatch", 0, KIND_TRANSIENT)]
    assert sorted(bad) == sorted(
        ["bogus_seam:1", "prefill:x", "prefill:1:weird", "fence:-2",
         "prefill"]
    )


def test_from_env_degrades_malformed_entries_with_event(monkeypatch,
                                                        tmp_path):
    monkeypatch.setenv("KATA_TPU_FAULTS", "prefill:0,garbage:9,fence:zzz")
    sink, prev = _capture(tmp_path)
    try:
        inj = FaultInjector.from_env(label="t")
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert inj.armed
    evs = [e for e in _events(tmp_path / "ev.jsonl")
           if e.get("name") == "fault_schedule_error"]
    assert sorted(e["entry"] for e in evs) == ["fence:zzz", "garbage:9"]
    # The valid entry still fires.
    with pytest.raises(TransientFault):
        inj.fire("prefill")


def test_constructor_rejects_unknown_seam_and_kind():
    with pytest.raises(ValueError, match="seam"):
        FaultInjector([FaultSpec("nope", 0)])
    with pytest.raises(ValueError, match="kind"):
        FaultInjector([FaultSpec("prefill", 0, "explode")])


# ----- deterministic firing ------------------------------------------------


def _drive(inj, sequence):
    """Cross seams in order, recording what each crossing did."""
    log = []
    for seam in sequence:
        try:
            inj.fire(seam)
            log.append((seam, None))
        except (TransientFault, InjectedOom, DeviceStallError) as e:
            log.append((seam, type(e).__name__))
    return log


def test_injector_same_seed_schedule_same_sequence(tmp_path):
    """The replay contract: same seed + schedule ⇒ same fired sequence
    AND the same event stream, crossing for crossing."""
    schedule = [
        FaultSpec("prefill", 1),
        FaultSpec("decode_dispatch", 2, KIND_OOM),
        FaultSpec("fence", 0, KIND_HANG),
    ]
    sequence = (["prefill"] * 3 + ["decode_dispatch"] * 4 + ["fence"]
                + ["prefill"])
    runs = []
    for trial in range(2):
        sink, prev = _capture(tmp_path, f"run{trial}.jsonl")
        try:
            inj = FaultInjector(schedule, seed=7, label="det")
            log = _drive(inj, sequence)
        finally:
            obs.set_default_sink(prev)
            sink.close()
        evs = [
            {k: v for k, v in e.items() if k != "ts"}
            for e in _events(tmp_path / f"run{trial}.jsonl")
        ]
        runs.append((log, list(inj.fired), evs))
    assert runs[0] == runs[1]
    log, fired, _ = runs[0]
    # Round counts are per-seam invocation indexes, 0-based.
    assert fired == [
        ("prefill", 1, KIND_TRANSIENT),
        ("decode_dispatch", 2, KIND_OOM),
        ("fence", 0, KIND_HANG),
    ]
    assert log[1] == ("prefill", "TransientFault")
    assert log[5] == ("decode_dispatch", "InjectedOom")
    assert log[7] == ("fence", "DeviceStallError")
    # Each entry fires exactly once; every other crossing is a no-op.
    assert sum(1 for _s, err in log if err) == 3


def test_fire_each_entry_once_and_disarm():
    inj = FaultInjector([FaultSpec("prefill", 0)])
    assert inj.armed
    with pytest.raises(TransientFault):
        inj.fire("prefill")
    assert not inj.armed
    inj.fire("prefill")  # consumed: never fires again


def test_injected_oom_carries_resource_exhausted_marker():
    inj = FaultInjector([FaultSpec("pool_alloc", 0, KIND_OOM)])
    with pytest.raises(InjectedOom, match="RESOURCE_EXHAUSTED"):
        inj.fire("pool_alloc")


def test_hang_emits_device_stall_event(tmp_path):
    sink, prev = _capture(tmp_path)
    try:
        inj = FaultInjector([FaultSpec("fence", 0, KIND_HANG)], label="h")
        with pytest.raises(DeviceStallError):
            inj.fire("fence")
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = _events(tmp_path / "ev.jsonl")
    assert [e["name"] for e in evs] == ["fault_injected", "device_stall"]
    assert evs[1]["injected"] is True


# ----- the watchdog fence --------------------------------------------------


def test_fence_with_timeout_passthrough_without_deadline():
    # Default path: no deadline → inline call, value returned verbatim.
    assert fence_with_timeout(lambda: 41 + 1) == 42


def test_fence_with_timeout_raises_after_deadline(tmp_path):
    sink, prev = _capture(tmp_path)
    try:
        with pytest.raises(DeviceStallError, match="watchdog"):
            fence_with_timeout(
                lambda: time.sleep(5.0), timeout_s=0.05, seam="fence",
                server="t",
            )
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = [e for e in _events(tmp_path / "ev.jsonl")
           if e.get("name") == "device_stall"]
    assert len(evs) == 1 and evs[0]["injected"] is False
    assert evs[0]["seam"] == "fence"


def test_fence_with_timeout_relays_wait_errors_and_values():
    def boom():
        raise KeyError("inner")

    with pytest.raises(KeyError, match="inner"):
        fence_with_timeout(boom, timeout_s=2.0)
    assert fence_with_timeout(lambda: "ok", timeout_s=2.0) == "ok"


# ----- the recoverable predicate -------------------------------------------


def test_recoverable_predicate():
    assert recoverable(TransientFault("x"))
    assert recoverable(InjectedOom("RESOURCE_EXHAUSTED: y"))
    assert recoverable(DeviceStallError("z"))
    assert not recoverable(ValueError("user bug"))
    assert not recoverable(AssertionError())

    # Real XLA errors route by status marker, matched by type NAME so the
    # predicate works without importing jaxlib.
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    assert recoverable(XlaRuntimeError("RESOURCE_EXHAUSTED: hbm oom"))
    assert recoverable(XlaRuntimeError("UNAVAILABLE: transport dead"))
    # A strict-mode transfer-guard trip must NOT be swallowed.
    assert not recoverable(
        XlaRuntimeError("Disallowed host-to-device transfer")
    )


# ----- env knob degrade contract -------------------------------------------


def test_env_int_and_float_degrade_with_event(monkeypatch, tmp_path):
    monkeypatch.setenv("KT_TEST_INT", "not-a-number")
    monkeypatch.setenv("KT_TEST_FLOAT", "12.5")
    sink, prev = _capture(tmp_path)
    try:
        assert resilience.env_int(
            "KT_TEST_INT", 3, event="checkpoint_disabled", server="t"
        ) == 3
        assert resilience.env_float("KT_TEST_FLOAT", 0.0) == 12.5
        assert resilience.env_int("KT_TEST_UNSET", 9) == 9
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = [e for e in _events(tmp_path / "ev.jsonl")
           if e.get("name") == "checkpoint_disabled"]
    assert len(evs) == 1
    assert evs[0]["reason"].startswith("bad_env:")


def test_seams_cover_the_documented_surface():
    # docs/resilience.md documents exactly these; a drifted set is a doc
    # bug or a silent loss of chaos coverage.
    assert SEAMS == ("decode_dispatch", "prefill", "admission_commit",
                     "fence", "pool_alloc", "store_gather", "sched_tick")
