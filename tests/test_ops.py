"""Op tests: pallas flash attention numerics (interpret mode on CPU) and the
guest probe ladder."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
from kata_xpu_device_plugin_tpu.ops.flash import pallas_flash_attention


@pytest.mark.parametrize("kv_heads", [1, 2])  # MQA (Gemma) and GQA
@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(kv_heads, causal):
    B, S, H, D = 1, 256, 2, 64
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, kv_heads, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, kv_heads, D), jnp.float32)
    out = pallas_flash_attention(
        q, k, v, causal=causal, block_q=128, block_k=128, interpret=True
    )
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("window", [0, 96])
def test_flash_softcap_matches_reference_fwd_and_grads(window):
    """Gemma-2 logit softcap on the flash path: forward AND q/k/v gradients
    must match the reference's cap (the backward kernels model the 1−tanh²
    factor), including combined with the sliding-window band."""
    B, S, H, D, cap = 1, 256, 2, 64, 4.0  # small cap so tanh bites hard
    keys = jax.random.split(jax.random.PRNGKey(9), 4)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, 1, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, 1, D), jnp.float32)
    dout = jax.random.normal(keys[3], q.shape, jnp.float32)

    out = pallas_flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True,
        window=window, softcap=cap,
    )
    ref = reference_attention(q, k, v, causal=True, window=window,
                              logits_softcap=cap)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    def f_flash(q, k, v):
        o = pallas_flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128, interpret=True,
            window=window, softcap=cap,
        )
        return jnp.sum(o * dout)

    def f_ref(q, k, v):
        o = reference_attention(q, k, v, causal=True, window=window,
                                logits_softcap=cap)
        return jnp.sum(o * dout)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
            err_msg=f"d{name} (window={window})"
        )


def test_flash_rejects_offset():
    q = jnp.zeros((1, 128, 2, 64))
    with pytest.raises(ValueError):
        pallas_flash_attention(q, q, q, q_offset=jnp.int32(4))


def test_reference_attention_decode_offset():
    # Decode: 1 query at absolute position 5 attending into an 8-long cache
    # where only the first 6 slots are real. Must equal full-sequence attention.
    B, S, H, D = 1, 6, 2, 16
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q_full = jax.random.normal(keys[0], (B, S, H, D))
    k_full = jax.random.normal(keys[1], (B, S, H, D))
    v_full = jax.random.normal(keys[2], (B, S, H, D))
    full = reference_attention(q_full, k_full, v_full, causal=True)

    cache_k = jnp.concatenate([k_full, jnp.zeros((B, 2, H, D))], axis=1)
    cache_v = jnp.concatenate([v_full, jnp.zeros((B, 2, H, D))], axis=1)
    out = reference_attention(
        q_full[:, 5:6], cache_k, cache_v, causal=True, q_offset=jnp.int32(5)
    )
    np.testing.assert_allclose(
        np.asarray(out[:, 0]), np.asarray(full[:, 5]), rtol=1e-5, atol=1e-6
    )


def test_guest_probe_ladder():
    from kata_xpu_device_plugin_tpu.guest import probe_all_reduce, probe_compute, probe_devices

    d = probe_devices(expected=8)
    assert d["ok"] and d["platform"] == "cpu"
    assert probe_compute()["ok"]
    ar = probe_all_reduce()
    assert ar["ok"] and ar["devices"] == 8


def test_flash_block_picking():
    from kata_xpu_device_plugin_tpu.ops.flash import pick_block, supports

    assert pick_block(2048, 512) == 512
    assert pick_block(768, 512) == 384  # not 512: must divide
    assert pick_block(640, 512) == 320
    assert pick_block(127, 512) is None
    assert supports(768, 768, 256)
    assert not supports(100, 100, 256)


def test_flash_non_divisible_seq_interpret():
    # 384-length sequence: block shrinks to a divisor instead of asserting.
    B, S, H, D = 1, 384, 2, 64
    keys = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, 1, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, 1, D), jnp.float32)
    out = pallas_flash_attention(q, k, v, causal=True, interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("kv_heads,n_heads", [(1, 8), (2, 8), (4, 4)])
def test_decode_kernel_matches_reference(kv_heads, n_heads):
    from kata_xpu_device_plugin_tpu.ops.decode_attn import (
        pallas_decode_attention,
        supports_decode,
    )

    B, S, D = 3, 256, 64
    assert supports_decode(1, S, D)
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(keys[0], (B, 1, n_heads, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, kv_heads, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, kv_heads, D), jnp.float32)
    for pos in [0, 5, 130, 255]:
        # Zero the unwritten tail like a real cache (the kernel must not
        # read it anyway: blocks past pos are skipped entirely).
        mask = (jnp.arange(S) <= pos)[None, :, None, None]
        out = pallas_decode_attention(
            q, k * mask, v * mask, jnp.int32(pos), interpret=True
        )
        ref = reference_attention(
            q, k * mask, v * mask, causal=True, q_offset=jnp.int32(pos)
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
        )


def test_decode_kernel_support_gate():
    from kata_xpu_device_plugin_tpu.ops.decode_attn import supports_decode

    assert supports_decode(1, 256, 128)
    assert not supports_decode(2, 256, 128)  # multi-token q is flash's job
    assert not supports_decode(1, 100, 128)  # cache not block-aligned
    assert not supports_decode(1, 256, 96)  # head_dim not lane-aligned


@pytest.mark.parametrize("kv_heads,causal", [(1, True), (2, False), (2, True)])
def test_flash_backward_matches_reference(kv_heads, causal):
    """custom_vjp backward (blockwise recompute from the saved logsumexp)
    must match reference-attention gradients for q, k and v."""
    B, S, H, D = 1, 256, 4 if kv_heads == 2 else 2, 64
    keys = jax.random.split(jax.random.PRNGKey(5), 4)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, kv_heads, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, kv_heads, D), jnp.float32)
    dout = jax.random.normal(keys[3], q.shape, jnp.float32)

    def f_flash(q, k, v):
        out = pallas_flash_attention(
            q, k, v, causal=causal, block_q=128, block_k=128, interpret=True
        )
        return jnp.sum(out * dout)

    def f_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * dout)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4, err_msg=f"d{name}"
        )


def test_training_through_flash_attention():
    """A full next-token-loss gradient with the pallas kernel as attn_fn
    (interpret mode) matches the reference path — the train step can take
    attn_fn=flash_attention without materializing [S, S]."""
    from functools import partial

    from kata_xpu_device_plugin_tpu.models.transformer import (
        init_params,
        next_token_loss,
        tiny_test_config,
    )

    cfg = tiny_test_config(n_layers=1, n_heads=2, n_kv_heads=1, head_dim=64,
                           d_ff=64, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    # next_token_loss forwards the FULL sequence (last logit dropped), so a
    # flash-tileable length is passed directly — under the old sliced-input
    # formulation a power-of-2 batch would silently lose flash eligibility.
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 256), 0, cfg.vocab_size)

    flash = partial(pallas_flash_attention, block_q=128, block_k=128, interpret=True)
    lf, gf = jax.value_and_grad(
        lambda p: next_token_loss(p, tokens, cfg, attn_fn=flash)
    )(params)
    lr, gr = jax.value_and_grad(
        lambda p: next_token_loss(p, tokens, cfg)
    )(params)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-3, atol=3e-4
        ),
        gf, gr,
    )


def test_decode_kernel_opt_in(monkeypatch):
    from kata_xpu_device_plugin_tpu.ops.attention import decode_eligible, on_tpu

    # The fused decode kernel measured SLOWER than the XLA path on v5e
    # (per-launch overhead × layers × steps — see decode_eligible), so it is
    # opt-in: off by default, off when =0, live only under =1 on TPU.
    monkeypatch.delenv("KATA_TPU_DECODE_KERNEL", raising=False)
    assert decode_eligible(1, 256, 128, True, 0) is False
    monkeypatch.setenv("KATA_TPU_DECODE_KERNEL", "0")
    assert decode_eligible(1, 256, 128, True, 0) is False
    monkeypatch.setenv("KATA_TPU_DECODE_KERNEL", "1")
    assert decode_eligible(1, 256, 128, True, 0) == (on_tpu() and True)
