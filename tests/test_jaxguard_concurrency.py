"""Unit tests for jaxguard v2: the lock-discipline pass (JG201-JG203),
the knob-contract pass (JG301-JG304), pragma grammar coverage for the
new families, smoke wrappers over the runtime race harness
(``tests/race_harness.py``), and targeted regressions for the true
positives the passes flagged in ``plugin/`` and ``obs/``.

Fixture style follows ``test_jaxguard.py``: one minimal POSITIVE and one
NEAR-MISS negative per rule, analyzed under repo-relative paths inside
the package so thread-entry detection and the knob module paths resolve
exactly as on the real tree. The knob fixtures carry their own fake
``cdi/constants.py`` / ``config.py`` / injection module / doc text, each
test breaking exactly one leg of the five-leg contract.
"""
import os
import subprocess
import sys
import threading

from tools.analyze import analyze_source, analyze_sources
from tools.analyze.model import (
    KNOB_CONFIG_PATH,
    KNOB_CONSTANTS_PATH,
    KNOB_DOC_PATH,
)

from tests import race_harness

from kata_xpu_device_plugin_tpu.obs.watchdog import (
    SLOBurnWatchdog,
    WatchdogConfig,
)
from kata_xpu_device_plugin_tpu.plugin.health import HealthWatcher

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PLUGIN = "kata_xpu_device_plugin_tpu/plugin/mod_under_test.py"
OBSMOD = "kata_xpu_device_plugin_tpu/obs/mod_under_test.py"


def rules_of(findings):
    return [f.rule for f in findings]


# ----- JG201: guarded attribute accessed without its lock --------------------

_GUARD_ELSEWHERE = '''
import threading

class Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self._events = []
        self._t = threading.Thread(target=self.emit)

    def emit(self, ev):
        self._events.append(ev)

    def flush(self):
        with self._lock:
            self._events.clear()
'''


def test_jg201_fires_on_bare_access_to_guarded_attr():
    findings = analyze_source(_GUARD_ELSEWHERE, PLUGIN)
    assert rules_of(findings) == ["JG201"]
    assert "_events" in findings[0].message
    assert findings[0].function.endswith("Sink.emit")


def test_jg201_near_miss_access_under_lock():
    src = _GUARD_ELSEWHERE.replace(
        "    def emit(self, ev):\n        self._events.append(ev)",
        "    def emit(self, ev):\n        with self._lock:\n"
        "            self._events.append(ev)",
    )
    assert analyze_source(src, PLUGIN) == []


def test_jg201_fires_on_bare_write_in_lock_owning_class():
    # Trigger (ii): the class owns a lock, a thread-entry method writes
    # shared state bare — even though no other method guards that attr.
    src = '''
import threading

class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []
        self._t = threading.Thread(target=self.record)

    def record(self, ev):
        self._buf.append(ev)
'''
    findings = analyze_source(src, PLUGIN)
    assert rules_of(findings) == ["JG201"]
    assert "without any lock" in findings[0].message


def test_jg201_near_miss_class_without_lock():
    # No lock attribute → the class has no discipline to enforce; the
    # runtime harness, not the static pass, is the net for these.
    src = '''
import threading

class Ring:
    def __init__(self):
        self._buf = []
        self._t = threading.Thread(target=self.record)

    def record(self, ev):
        self._buf.append(ev)
'''
    assert analyze_source(src, PLUGIN) == []


def test_jg201_near_miss_not_thread_reachable():
    # Same bare access, but no thread entry reaches it: single-threaded
    # use of a lock-owning class is legal (the lock may guard OTHER
    # methods' cross-thread paths).
    src = _GUARD_ELSEWHERE.replace(
        "        self._t = threading.Thread(target=self.emit)\n", ""
    )
    assert analyze_source(src, PLUGIN) == []


def test_jg201_inherited_lock_through_private_helper():
    # _save is only ever called with the lock held → its writes inherit
    # the guard (the _save_locked pattern in plugin.manager).
    src = '''
import threading

class Journal:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._t = threading.Thread(target=self.record)

    def record(self, k, v):
        with self._lock:
            self._save(k, v)

    def _save(self, k, v):
        self._entries[k] = v
'''
    assert analyze_source(src, PLUGIN) == []


# ----- JG202: lock-order inversion / re-acquisition --------------------------

_INVERTED = '''
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def ba(self):
        with self._b:
            with self._a:
                return 2
'''


def test_jg202_fires_on_inverted_order():
    findings = analyze_source(_INVERTED, PLUGIN)
    assert "JG202" in rules_of(findings)
    assert any("order" in f.message for f in findings)


def test_jg202_near_miss_consistent_order():
    src = _INVERTED.replace(
        "with self._b:\n            with self._a:",
        "with self._a:\n            with self._b:",
    )
    assert analyze_source(src, PLUGIN) == []


def test_jg202_fires_on_reacquisition():
    src = '''
import threading

class Once:
    def __init__(self):
        self._lock = threading.Lock()

    def stats(self):
        with self._lock:
            return self._both()

    def _both(self):
        with self._lock:
            return 1
'''
    findings = analyze_source(src, PLUGIN)
    assert "JG202" in rules_of(findings)
    assert any("re-acquired" in f.message for f in findings)


def test_jg202_near_miss_sequential_not_nested():
    src = '''
import threading

class Once:
    def __init__(self):
        self._lock = threading.Lock()

    def stats(self):
        with self._lock:
            a = 1
        with self._lock:
            return a
'''
    assert analyze_source(src, PLUGIN) == []


# ----- JG203: blocking call under a hot-path lock ----------------------------

_BLOCKING = '''
import threading
import time

class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self.poll)

    def poll(self):
        with self._lock:
            time.sleep(1.0)
'''


def test_jg203_fires_on_sleep_under_lock():
    findings = analyze_source(_BLOCKING, OBSMOD)
    assert rules_of(findings) == ["JG203"]
    assert "time.sleep" in findings[0].message


def test_jg203_near_miss_io_outside_lock():
    src = _BLOCKING.replace(
        "        with self._lock:\n            time.sleep(1.0)",
        "        with self._lock:\n            pass\n        time.sleep(1.0)",
    )
    assert analyze_source(src, OBSMOD) == []


def test_jg203_near_miss_not_thread_reachable():
    src = _BLOCKING.replace(
        "        self._t = threading.Thread(target=self.poll)\n", ""
    )
    assert analyze_source(src, OBSMOD) == []


# ----- pragma grammar over the new families ----------------------------------


def test_pragma_suppresses_jg201():
    src = _GUARD_ELSEWHERE.replace(
        "self._events.append(ev)",
        "self._events.append(ev)  # jaxguard: allow(JG201) sanctioned demo",
    )
    assert analyze_source(src, PLUGIN) == []


def test_pragma_suppresses_jg203_but_not_other_rules():
    # A wrong-family pragma suppresses nothing — JG203 still fires, and
    # since ISSUE 19 the unused allow(JG201) is itself a JG404 stale-
    # pragma finding (a dead sanction hides the next real finding).
    src = _BLOCKING.replace(
        "time.sleep(1.0)",
        "time.sleep(1.0)  # jaxguard: allow(JG201) wrong family",
    )
    assert rules_of(analyze_source(src, OBSMOD)) == ["JG203", "JG404"]


def test_pragma_multi_rule_covers_new_families():
    # Comma-list grammar across families: JG203 fires and is suppressed;
    # listing JG404 rides the stale-audit escape hatch (ISSUE 19), so a
    # list that would otherwise carry a never-firing id stays clean.
    src = _BLOCKING.replace(
        "time.sleep(1.0)",
        "time.sleep(1.0)  # jaxguard: allow(JG203, JG404) startup only",
    )
    assert analyze_source(src, OBSMOD) == []


# ----- JG301-JG304: the five-leg knob contract -------------------------------

_INJECT_PATH = "kata_xpu_device_plugin_tpu/plugin/inject_under_test.py"
_PARSE_PATH = "kata_xpu_device_plugin_tpu/guest/parse_under_test.py"


def _knob_sources(**replace):
    """A knob whose five legs all hold; tests break one leg each."""
    sources = {
        KNOB_CONSTANTS_PATH: 'ENV_FOO = "KATA_TPU_FOO"\n',
        KNOB_CONFIG_PATH: (
            "class Config:\n"
            "    foo: int = 0\n"
        ),
        _INJECT_PATH: (
            "from ..cdi import constants\n\n"
            "def build(cfg):\n"
            "    return {constants.ENV_FOO: str(cfg.foo)}\n"
        ),
        _PARSE_PATH: (
            "import os\n\n"
            "def read():\n"
            '    raw = os.environ.get("KATA_TPU_FOO", "")\n'
            "    try:\n"
            "        return int(raw or 0)\n"
            "    except ValueError:\n"
            "        return 0\n"
        ),
        KNOB_DOC_PATH: "| `KATA_TPU_FOO` | `foo` | clamps to default |\n",
    }
    sources.update(replace)
    return sources


def test_knob_all_legs_green():
    assert analyze_sources(_knob_sources()) == []


def test_jg301_fires_on_missing_config_field():
    sources = _knob_sources(**{
        KNOB_CONFIG_PATH: "class Config:\n    bar: int = 0\n",
    })
    findings = analyze_sources(sources)
    assert rules_of(findings) == ["JG301"]
    assert findings[0].path == KNOB_CONSTANTS_PATH
    assert "ENV_FOO" in findings[0].message


def test_jg301_near_miss_field_by_convention():
    # KATA_TPU_FOO ↔ Config.foo is the convention; nothing else needed.
    assert analyze_sources(_knob_sources()) == []


def test_jg302_fires_on_uninjected_knob():
    sources = _knob_sources(**{
        _INJECT_PATH: "def build(cfg):\n    return {}\n",
    })
    findings = analyze_sources(sources)
    assert rules_of(findings) == ["JG302"]
    assert "ENV_FOO" in findings[0].message


def test_jg302_near_miss_injected_via_attribute_ref():
    # The base fixture injects via `constants.ENV_FOO` — an Attribute
    # leaf, the dominant real-repo spelling.
    assert analyze_sources(_knob_sources()) == []


def test_jg303_fires_on_unprotected_parse():
    sources = _knob_sources(**{
        _PARSE_PATH: (
            "import os\n\n"
            "def read():\n"
            '    raw = os.environ.get("KATA_TPU_FOO", "0")\n'
            "    return int(raw)\n"
        ),
    })
    findings = analyze_sources(sources)
    assert rules_of(findings) == ["JG303"]
    assert findings[0].path == _PARSE_PATH


def test_jg303_near_miss_parse_inside_try():
    # The base fixture parses inside try/except ValueError: degrading,
    # as the contract requires.
    assert analyze_sources(_knob_sources()) == []


def test_jg304_fires_on_undocumented_knob():
    sources = _knob_sources(**{
        KNOB_DOC_PATH: "| `KATA_TPU_OTHER` | `other` | n/a |\n",
    })
    findings = analyze_sources(sources)
    assert rules_of(findings) == ["JG304"]
    assert "KATA_TPU_FOO" in findings[0].message


def test_jg304_near_miss_documented():
    assert analyze_sources(_knob_sources()) == []


def test_jg3xx_pragma_on_constant_line():
    sources = _knob_sources(**{
        KNOB_CONSTANTS_PATH: (
            'ENV_FOO = "KATA_TPU_FOO"'
            "  # jaxguard: allow(JG301, JG302, JG304) internal knob\n"
        ),
        KNOB_CONFIG_PATH: "class Config:\n    bar: int = 0\n",
        _INJECT_PATH: "def build(cfg):\n    return {}\n",
        KNOB_DOC_PATH: "nothing documented\n",
    })
    assert analyze_sources(sources) == []


# ----- CLI: new families are in the catalogue --------------------------------


def test_cli_list_rules_includes_new_families():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--list-rules"],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0
    for rule in ("JG201", "JG202", "JG203", "JG301", "JG302", "JG303",
                 "JG304"):
        assert rule in proc.stdout


# ----- runtime twin: one-iteration smoke per harness scenario ----------------


def test_race_harness_journal_smoke(tmp_path):
    stats = race_harness.stress_journal(str(tmp_path), threads=2, ops=8,
                                        seed=1)
    assert stats["entries"] == stats["expected"] == 16


def test_race_harness_aggregator_smoke(tmp_path):
    stats = race_harness.stress_aggregator(str(tmp_path), threads=2,
                                           ops=8, seed=2)
    assert stats["consumed"] == stats["expected"] == 16
    assert stats["servers"] == 2


def test_race_harness_flight_smoke(tmp_path):
    stats = race_harness.stress_flight(str(tmp_path), threads=2, ops=8,
                                       seed=3)
    assert stats["events"] == stats["expected"] == 16
    assert stats["dumps"]


def test_race_harness_metrics_smoke(tmp_path):
    stats = race_harness.stress_metrics(str(tmp_path), threads=2, ops=8,
                                        seed=4)
    assert stats["total"] == stats["expected"] == 16


def test_race_harness_full_iteration(tmp_path):
    results = race_harness.run_iteration(seed=7, threads=2, ops=4,
                                         keep_dir=str(tmp_path / "art"))
    assert [r["scenario"] for r in results] == [
        "journal", "aggregator", "flight", "metrics",
    ]
    kept = os.listdir(tmp_path / "art")
    assert any(name.startswith("race_guest_") for name in kept)
    assert "race_journal.json" in kept


# ----- regressions for the true positives the passes flagged -----------------


def test_watchdog_observe_vs_stats_threads():
    """JG201 regression (obs/watchdog.py): stats()/active on the debug
    thread must never tear mid-observe — hammer both concurrently."""
    wd = SLOBurnWatchdog(
        WatchdogConfig(slo_ms=50.0, window=4, sustain=2, clear=2),
        emit=lambda name, **f: None, dump=lambda reason: None,
    )
    stop = threading.Event()
    errors = []

    def observer():
        r = 0
        while not stop.is_set():
            r += 1
            wd.observe({
                "round": r, "interval_rounds": 1, "interval_s": 1.0,
                "tokens_per_s": 100.0, "itl_p99_ms": 100.0 if r % 2 else 1.0,
                "preemptions_delta": 0, "recoveries_delta": 0,
                "prefix_hits_delta": 0, "prefix_misses_delta": 0,
                "kv_host_tokens": 0,
            })

    def reader():
        while not stop.is_set():
            try:
                s = wd.stats()
                assert isinstance(s["active"], list)
                _ = wd.active
            except Exception as exc:  # noqa: BLE001 — recorded for assert
                errors.append(exc)
                return

    threads = [threading.Thread(target=observer),
               threading.Thread(target=reader)]
    for t in threads:
        t.start()
    stop.wait(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors, f"stats() raced observe(): {errors[0]}"
    wd.close()


def test_health_restart_backoff_state_consistent_under_threads():
    """JG201 regression (plugin/health.py): _restart_state is now under
    the watcher lock — concurrent restart offers must keep the
    (failures, not_before) pair coherent and never double-clear."""

    class _Plugin:
        resource_name = "google.com/tpu"

        def __init__(self):
            self.calls = 0
            self._l = threading.Lock()

        def restart(self):
            with self._l:
                self.calls += 1
            raise RuntimeError("socket gone")

    now = [0.0]
    watcher = HealthWatcher([], use_inotify=False, clock=lambda: now[0])
    plugin = _Plugin()

    def offer():
        for _ in range(20):
            watcher._try_restart(plugin)

    threads = [threading.Thread(target=offer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    fails, not_before = watcher._restart_state[id(plugin)]
    # Every recorded failure came from a real restart() call, the pair
    # is coherent, and backoff gating kept most offers from calling in.
    assert 1 <= fails <= plugin.calls
    assert not_before > 0.0
    # Advance past any backoff: one more failure increments exactly once.
    now[0] = not_before + 1.0
    before = plugin.calls
    watcher._try_restart(plugin)
    assert plugin.calls == before + 1


def test_aggregator_offset_map_consistent_under_snapshot(tmp_path):
    """JG201 regression (plugin/manager.py): poll_once's offset map is
    read/written under the lock — concurrent snapshot() calls never see
    a torn poll, and no heartbeat is consumed twice."""
    stats = race_harness.stress_aggregator(str(tmp_path), threads=3,
                                           ops=10, seed=11)
    assert stats["consumed"] == 30
