"""Fused prefill+decode dispatch + multi-step decode (ISSUE 13).

Oracle — FUSION AND MULTI-STEP ARE INVISIBLE IN THE OUTPUT: batching an
admission slice into the decode dispatch composes the SAME
``prefill_suffix`` and ``_decode_scan`` callees into one executable, and
``decode_steps=K`` only multiplies the per-dispatch scan (the on-device
EOS/budget mask freezes finished lanes into value-identical rewrites),
so greedy outputs must be BIT-IDENTICAL to the ``fifo_batch`` K=1
baseline across fused-vs-sequential admission × K ∈ {1,2,8} ×
paged/slotted × overlap/lockstep × tp{1,2} × prefix-hit × mid-scan EOS ×
seeded fault schedules with recovery (± ``KATA_TPU_STRICT=1`` via
``make fused``). The visible surfaces are pinned separately: the
per-lane-query-length kernel form, the masked scan's freeze semantics,
the env/daemon knob degrade contract (``decode_steps_invalid`` /
``fused_disabled`` events, never a crashed guest), the explicit-arg
raise, the always-present stats schema, and the
``kata_tpu_serving_fused_admissions_total`` counter.

Under ``make chaos`` this file also runs with
``KATA_TPU_FAULTS=decode_dispatch:4,sched_tick:3`` and a node-injected
``KATA_TPU_DECODE_STEPS=2`` — faults land MID-multi-step-dispatch and
recovery must stay invisible in every assertion below (tests pinning the
K default monkeypatch the env off).
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest.resilience import (
    FaultInjector,
    FaultSpec,
)
from kata_xpu_device_plugin_tpu.guest.serving import (
    ENV_DECODE_STEPS,
    ENV_FUSED,
    GenerationServer,
)
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    _decode_scan,
    init_kv_caches,
    init_params,
    prefill,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=3):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


# Staggered budgets (the scheduler-test precedent): equal ones
# synchronize lane finishes, so admissions would always run against an
# idle arena and neither chunking nor fusion would ever engage.
_LENS = [14, 9, 12, 7, 15, 11]
_BUDGETS = [6, 12, 9, 5, 11, 7]


def _serve(params, cfg, policy, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("recovery_backoff_s", 0.0)
    if policy == "slo_chunked":
        # slo_ms=0 forces deferral the moment estimates exist — the
        # deterministic maximal-chunking (and maximal-fusion) config.
        kw.setdefault("prefill_chunk", 4)
        kw.setdefault("itl_slo_ms", 0.0)
    srv = GenerationServer(params, cfg, sched_policy=policy, **kw)
    prompts = _prompts(cfg, _LENS)
    rids = [srv.submit(p, m) for p, m in zip(prompts, _BUDGETS)]
    res = srv.run()
    return [res[r] for r in rids], srv


def _events(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def _capture(tmp_path, name="ev.jsonl"):
    sink = obs.EventSink(str(tmp_path / name))
    return sink, obs.set_default_sink(sink)


# ----- kernel: per-lane query lengths (ops/decode_attn.py) -------------------


def test_paged_kernel_multi_query_matches_reference():
    # The mixed-batch kernel form (interpret mode — the CPU harness):
    # SQ > 1 right-aligned queries with RAGGED per-lane q_lens must match
    # the XLA reference attention computed per lane over the same pool
    # view; SQ == 1 must stay the single-token kernel bit-for-bit.
    from kata_xpu_device_plugin_tpu.ops.attention import (
        reference_attention,
    )
    from kata_xpu_device_plugin_tpu.ops.decode_attn import (
        pallas_paged_decode_attention,
    )

    rng = np.random.default_rng(0)
    B, H, KV, D, bs, NB = 3, 4, 2, 16, 8, 4
    NT = bs * (NB * B + 2)
    paged_len = NB * bs
    k = jnp.asarray(rng.standard_normal((1, NT, KV, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, NT, KV, D)), jnp.float32)
    tables = jnp.asarray(
        [[2 + b * NB + j for j in range(NB)] for b in range(B)], jnp.int32
    )
    view_idx = (
        (tables * bs)[:, :, None] + jnp.arange(bs)[None, None, :]
    ).reshape(B, -1)[:, :paged_len]
    kv_view, vv_view = k[0][view_idx], v[0][view_idx]

    q1 = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    pos1 = jnp.asarray([5, 17, 30], jnp.int32)
    out1 = pallas_paged_decode_attention(
        q1, k, v, tables, pos1, block_size=bs, paged_len=paged_len,
        interpret=True,
    )
    ref1 = reference_attention(q1, kv_view, vv_view, causal=True,
                               q_offset=pos1)
    np.testing.assert_allclose(out1, ref1, atol=1e-5)

    SQ = 4
    q = jnp.asarray(rng.standard_normal((B, SQ, H, D)), jnp.float32)
    q_lens = jnp.asarray([1, 4, 2], jnp.int32)
    pos = jnp.asarray([6, 19, 30], jnp.int32)  # last-query positions
    out = pallas_paged_decode_attention(
        q, k, v, tables, pos, q_lens, block_size=bs, paged_len=paged_len,
        interpret=True,
    )
    for b in range(B):
        ql, p = int(q_lens[b]), int(pos[b])
        ref = reference_attention(
            q[b:b + 1, SQ - ql:], kv_view[b:b + 1], vv_view[b:b + 1],
            causal=True, q_offset=jnp.asarray([p - ql + 1], jnp.int32),
        )
        np.testing.assert_allclose(out[b, SQ - ql:], ref[0], atol=1e-5)


def test_paged_kernel_multi_query_int8():
    # int8 QTensor pools dequantize in-kernel for the multi-query form
    # exactly like the single-token one: value-identical to gathering +
    # dequantize_kv then attending.
    from kata_xpu_device_plugin_tpu.ops.attention import (
        reference_attention,
    )
    from kata_xpu_device_plugin_tpu.ops.decode_attn import (
        pallas_paged_decode_attention,
    )
    from kata_xpu_device_plugin_tpu.ops.quant import (
        dequantize_kv,
        quantize_kv,
    )

    rng = np.random.default_rng(1)
    B, H, KV, D, bs, NB = 2, 4, 2, 16, 8, 3
    NT = bs * (NB * B + 2)
    paged_len = NB * bs
    k = quantize_kv(jnp.asarray(
        rng.standard_normal((1, NT, KV, D)), jnp.float32))
    v = quantize_kv(jnp.asarray(
        rng.standard_normal((1, NT, KV, D)), jnp.float32))
    tables = jnp.asarray(
        [[2 + b * NB + j for j in range(NB)] for b in range(B)], jnp.int32
    )
    SQ = 3
    q = jnp.asarray(rng.standard_normal((B, SQ, H, D)), jnp.float32)
    q_lens = jnp.asarray([3, 2], jnp.int32)
    pos = jnp.asarray([10, 20], jnp.int32)
    out = pallas_paged_decode_attention(
        q, k, v, tables, pos, q_lens, block_size=bs, paged_len=paged_len,
        interpret=True,
    )
    view_idx = (
        (tables * bs)[:, :, None] + jnp.arange(bs)[None, None, :]
    ).reshape(B, -1)[:, :paged_len]
    from kata_xpu_device_plugin_tpu.ops.quant import QTensor

    kd = dequantize_kv(QTensor(k.q[0][view_idx], k.scale[0][view_idx]),
                       jnp.float32)
    vd = dequantize_kv(QTensor(v.q[0][view_idx], v.scale[0][view_idx]),
                       jnp.float32)
    for b in range(B):
        ql, p = int(q_lens[b]), int(pos[b])
        ref = reference_attention(
            q[b:b + 1, SQ - ql:], kd[b:b + 1], vd[b:b + 1], causal=True,
            q_offset=jnp.asarray([p - ql + 1], jnp.int32),
        )
        np.testing.assert_allclose(out[b, SQ - ql:], ref[0], atol=1e-5)


# ----- transformer: masked scan + mixed-batch paged spans --------------------


def test_masked_scan_freezes_at_budget_and_eos(model):
    cfg, params = model
    B, max_len = 2, 32
    prompts = np.array([[5, 6, 7, 8], [9, 10, 11, 12]], np.int32)
    caches, last, pos = prefill(
        params, jnp.asarray(prompts), cfg, max_len, return_logits=False
    )
    pos_v = jnp.full((B,), int(pos), jnp.int32)

    def scan(**kw):
        return _decode_scan(
            params, jax.tree.map(jnp.copy, caches), last, pos_v, cfg, 8,
            None, False, 0, jnp.float32(0.0), jax.random.PRNGKey(1),
            return_state=True, **kw,
        )

    toks_a, _, _, pos_a = scan()
    toks_b, _, _, pos_b = scan(budget=jnp.asarray([3, 8], jnp.int32))
    ta, tb = np.asarray(toks_a), np.asarray(toks_b)
    # Live prefix bit-identical; frozen lane pins token and position.
    np.testing.assert_array_equal(ta[0, :3], tb[0, :3])
    assert (tb[0, 3:] == tb[0, 2]).all()
    np.testing.assert_array_equal(ta[1], tb[1])
    assert int(np.asarray(pos_b)[0]) == int(pos_v[0]) + 3
    assert int(np.asarray(pos_b)[1]) == int(pos_v[1]) + 8
    # EOS freeze: the lane pins the eos token the step after emitting it.
    eos = int(ta[1, 3])
    toks_c, _, _, _ = scan(eos_id=eos, budget=jnp.asarray([8, 8], jnp.int32))
    tc = np.asarray(toks_c)
    np.testing.assert_array_equal(tc[1, :4], ta[1, :4])
    assert (tc[1, 4:] == eos).all() or eos in tc[1, :4].tolist()


def test_paged_multi_token_span_matches_dense(model):
    # The mixed-batch branch (transformer paged S > 1): per-lane spans
    # written through block tables + per-row query offsets must equal the
    # dense ragged path bit-for-bit — gather path AND the multi-query
    # kernel (interpret).
    from kata_xpu_device_plugin_tpu.models.transformer import forward
    from kata_xpu_device_plugin_tpu.ops.attention import (
        make_decode_attn_fn,
    )

    cfg, params = model
    B, S, max_len = 2, 3, 32
    bs_blk, NB = 8, 4
    NT = bs_blk * (2 + NB * B)
    dense = init_kv_caches(cfg, B, max_len)
    off = jnp.asarray([4, 6], jnp.int32)
    span = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    positions = off[:, None] + jnp.arange(S)[None, :]
    logits_d, _ = forward(
        params, span, cfg, positions=positions, kv_caches=dense,
        cache_offset=off,
    )
    tables = jnp.asarray(
        [[2 + b * NB + j for j in range(NB)] for b in range(B)], jnp.int32
    )
    pool = (
        jnp.zeros((cfg.n_layers, 1, NT, cfg.n_kv_heads, cfg.head_dim),
                  cfg.dtype),
        jnp.zeros((cfg.n_layers, 1, NT, cfg.n_kv_heads, cfg.head_dim),
                  cfg.dtype),
    )
    logits_p, _ = forward(
        params, span, cfg, positions=positions, kv_caches=pool,
        cache_offset=off, block_tables=tables, block_size=bs_blk,
        paged_len=NB * bs_blk,
    )
    np.testing.assert_array_equal(np.asarray(logits_p),
                                  np.asarray(logits_d))
    # Kernel path: the unsharded wrapper advertises multi_query and the
    # S > 1 branch routes through it.
    fn = make_decode_attn_fn(
        cfg, paged=True, block_size=bs_blk, paged_len=NB * bs_blk,
        interpret=True,
    )
    assert getattr(fn, "multi_query", False)
    logits_k, _ = forward(
        params, span, cfg, positions=positions, kv_caches=pool,
        cache_offset=off, block_tables=tables, block_size=bs_blk,
        paged_len=NB * bs_blk, decode_kernel_fn=fn,
    )
    np.testing.assert_allclose(np.asarray(logits_k),
                               np.asarray(logits_d), atol=1e-4)


# ----- the oracle: fusion and K are invisible in greedy output ---------------


@pytest.mark.parametrize("overlap", [True, False])
@pytest.mark.parametrize("paged", [True, False])
def test_fused_greedy_identity(model, overlap, paged):
    cfg, params = model
    extra = {"kv_pool_tokens": 320} if paged else {}
    # decode_steps pinned to 1 on every side: the fused-vs-sequential A/B
    # must isolate FUSION (K has its own identity tests below), and the
    # chaos gate's node-injected KATA_TPU_DECODE_STEPS=2 would otherwise
    # shorten the decode phase enough that fusion rarely engages.
    base, _ = _serve(params, cfg, "fifo_batch", overlap=overlap,
                     decode_steps=1, **extra)
    seq, _ = _serve(params, cfg, "slo_chunked", overlap=overlap,
                    fused=False, decode_steps=1, **extra)
    out, srv = _serve(params, cfg, "slo_chunked", overlap=overlap,
                      fused=True, decode_steps=1, **extra)
    for a, b, c in zip(base, seq, out):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
    st = srv.stats()
    assert st["fused_enabled"] == 1
    assert st["fused_admissions"] > 0, "fusion never engaged — dead A/B"
    assert st["sched_chunks"] > 0


@pytest.mark.parametrize("k_steps", [2, 8])
@pytest.mark.parametrize("paged", [True, False])
def test_multi_step_greedy_identity(model, k_steps, paged):
    cfg, params = model
    extra = {"kv_pool_tokens": 320} if paged else {}
    base, _ = _serve(params, cfg, "fifo_batch", **extra)
    for policy in ("fifo_batch", "slo_chunked"):
        out, srv = _serve(params, cfg, policy, decode_steps=k_steps,
                          **extra)
        for a, b in zip(base, out):
            np.testing.assert_array_equal(a, b)
        assert srv.stats()["decode_steps"] == k_steps


@pytest.mark.parametrize("overlap", [True, False])
def test_multi_step_overlap_identity(model, overlap):
    cfg, params = model
    base, _ = _serve(params, cfg, "fifo_batch", overlap=overlap)
    out, srv = _serve(params, cfg, "slo_chunked", overlap=overlap,
                      decode_steps=2, fused=True)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["decode_steps"] == 2


def test_fused_identity_tp2(model):
    # tp=2 over the forced-8-device host (PR 9 invariance) × fused × K=2:
    # sharding never changes computed values, fusion/K never change what
    # is computed — the composition must still be bit-identical to the
    # single-chip fifo baseline.
    cfg, params = model
    if jax.device_count() < 2:
        pytest.skip("needs the forced multi-device host")
    base, _ = _serve(params, cfg, "fifo_batch", tp=1)
    for paged in (True, False):
        extra = {"kv_pool_tokens": 320} if paged else {}
        out, srv = _serve(params, cfg, "slo_chunked", tp=2,
                          decode_steps=2, fused=True, **extra)
        for a, b in zip(base, out):
            np.testing.assert_array_equal(a, b)
        assert srv.stats()["tp_degree"] == 2


def test_fused_prefix_hit_identity(model):
    cfg, params = model
    key = jax.random.PRNGKey(9)
    shared = np.asarray(
        jax.random.randint(key, (8,), 0, cfg.vocab_size), np.int32
    )
    tails = _prompts(cfg, [4] * 6, seed=10)
    prompts = [np.concatenate([shared, t]) for t in tails]

    def run(policy, **kw):
        srv = GenerationServer(
            params, cfg, max_batch=2, max_len=64, chunk=4,
            prefill_buckets=(4, 8, 12), prefix_cache_tokens=64,
            sched_policy=policy, prefill_chunk=3, itl_slo_ms=0.0,
            fault_injector=FaultInjector(), **kw,
        )
        rids = [srv.submit(p, m) for p, m in zip(prompts, _BUDGETS)]
        res = srv.run()
        return [res[r] for r in rids], srv

    base, _ = run("fifo_batch")
    out, srv = run("slo_chunked", fused=True, decode_steps=2)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    st = srv.stats()
    assert st["prefix_hits"] > 0 and st["sched_chunks"] > 0


def test_mid_scan_eos_identity(model):
    # eos arriving mid-multi-step-dispatch: the on-device mask freezes
    # the lane inside the scan; the host trim must yield the same
    # outputs as the K=1 unfused server seeing the same eos.
    cfg, params = model
    probe, _ = _serve(params, cfg, "fifo_batch")
    eos = int(probe[1][3])  # a token the baseline actually emits mid-run
    base, _ = _serve(params, cfg, "fifo_batch", eos_id=eos)
    out, srv = _serve(params, cfg, "slo_chunked", eos_id=eos,
                      decode_steps=8, fused=True)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["decode_steps"] == 8


def test_fused_recovery_identity(model):
    # A decode_dispatch fault interrupting a fused multi-step round (and
    # a sched_tick fault at a fused slice's dispatch prep): the partial's
    # donated caches die with the failed dispatch, the request replays
    # from its prompt strict-FIFO, and recovered greedy outputs stay
    # bit-identical — the PR 7 contract at dispatch-boundary granularity.
    cfg, params = model
    base, _ = _serve(params, cfg, "fifo_batch")
    inj = FaultInjector(schedule=(
        FaultSpec(seam="decode_dispatch", round=3),
        FaultSpec(seam="sched_tick", round=2),
    ), seed=7)
    out, srv = _serve(params, cfg, "slo_chunked", fused=True,
                      decode_steps=2, fault_injector=inj,
                      checkpoint_rounds=0)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["recoveries"] >= 1
    assert not srv.failures()


def test_fused_slice_joins_quarantine_blame(model, tmp_path):
    # A poison prompt whose slice rides a fused dispatch must join the
    # failed dispatch's BLAME cohort (it shares the executable with the
    # decode lanes), so it accrues quarantine strikes instead of
    # replaying forever while innocents are failed around it. With
    # quarantine_after=1, the partial active at the sched_tick fault —
    # identified as the last fused sched_defer's rid before the recovery
    # — must land in failures(); pre-fix it would replay and complete.
    cfg, params = model
    inj = FaultInjector(schedule=(
        FaultSpec(seam="sched_tick", round=1),
    ), seed=5)
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(
            params, cfg, max_batch=2, max_len=64, chunk=4,
            prefill_buckets=(16,), sched_policy="slo_chunked",
            prefill_chunk=4, itl_slo_ms=0.0, fused=True,
            quarantine_after=1, recovery_backoff_s=0.0,
            fault_injector=inj,
        )
        prompts = _prompts(cfg, _LENS)
        rids = [srv.submit(p, m) for p, m in zip(prompts, _BUDGETS)]
        res = srv.run()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    fails = srv.failures()
    assert srv.stats()["recoveries"] >= 1
    # None vanish: every rid ends in exactly one of results/failures.
    assert set(res) | set(fails) == set(rids)
    evs = _events(tmp_path / "ev.jsonl")
    rec_i = next(i for i, e in enumerate(evs) if e.get("name") == "recovery")
    partial_rid = next(
        e["rid"] for e in reversed(evs[:rec_i])
        if e.get("name") == "sched_defer" and e.get("fused")
    )
    assert partial_rid in fails, (
        "the fused slice's request escaped the blame cohort"
    )
    quarantined = [e["rid"] for e in evs
                   if e.get("name") == "request_failed"
                   and e.get("reason") == "quarantined"]
    assert partial_rid in quarantined


# ----- knob contract ---------------------------------------------------------


def test_env_decode_steps_selects(model, monkeypatch):
    cfg, params = model
    monkeypatch.setenv(ENV_DECODE_STEPS, "2")
    out, srv = _serve(params, cfg, "fifo_batch")
    assert srv.stats()["decode_steps"] == 2
    base, _ = _serve(params, cfg, "fifo_batch", decode_steps=1)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)


def test_env_malformed_decode_steps_degrades(model, monkeypatch, tmp_path):
    cfg, params = model
    sink, prev = _capture(tmp_path)
    try:
        for bad in ("zebra", "-3"):
            monkeypatch.setenv(ENV_DECODE_STEPS, bad)
            srv = GenerationServer(
                params, cfg, max_batch=2, max_len=32,
                fault_injector=FaultInjector(),
            )
            assert srv.stats()["decode_steps"] == 1
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = [e for e in _events(tmp_path / "ev.jsonl")
           if e.get("name") == "decode_steps_invalid"]
    assert len(evs) == 2
    assert all(e["reason"].startswith("bad_env:") for e in evs)


def test_env_malformed_fused_degrades(model, monkeypatch, tmp_path):
    cfg, params = model
    monkeypatch.setenv(ENV_FUSED, "banana")
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(
            params, cfg, max_batch=2, max_len=32,
            sched_policy="slo_chunked", prefill_chunk=4, itl_slo_ms=0.0,
            fault_injector=FaultInjector(),
        )
        # Malformed value degrades to the DEFAULT (fused on).
        assert srv.stats()["fused_enabled"] == 1
        monkeypatch.setenv(ENV_FUSED, "0")
        srv2 = GenerationServer(
            params, cfg, max_batch=2, max_len=32,
            sched_policy="slo_chunked", prefill_chunk=4, itl_slo_ms=0.0,
            fault_injector=FaultInjector(),
        )
        assert srv2.stats()["fused_enabled"] == 0
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = [e for e in _events(tmp_path / "ev.jsonl")
           if e.get("name") == "fused_disabled"]
    assert len(evs) == 1 and evs[0]["reason"].startswith("bad_env:")


def test_explicit_bad_args_raise(model, monkeypatch):
    cfg, params = model
    monkeypatch.delenv(ENV_DECODE_STEPS, raising=False)
    with pytest.raises(ValueError, match="decode_steps"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         decode_steps=0)
    with pytest.raises(ValueError, match="fused"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         sched_policy="fifo_batch", fused=True)
    # Incompatible modes: explicit K > 1 raises, env-injected degrades.
    with pytest.raises(ValueError, match="decode_steps"):
        GenerationServer(params, cfg, max_batch=2, max_len=32,
                         speculative_k=2, spec_opt_in=True, decode_steps=4)
    monkeypatch.setenv(ENV_DECODE_STEPS, "4")
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           speculative_k=2, spec_opt_in=True,
                           fault_injector=FaultInjector())
    assert srv.stats()["decode_steps"] == 1


def test_config_decode_steps_validation():
    # The daemon half of the knob (the AllocateResponse env injection is
    # pinned host-side in tests/test_plugin.py): Config validates the
    # flag, 0 leaves the guest default.
    from kata_xpu_device_plugin_tpu.config import Config

    with pytest.raises(ValueError, match="decode-steps"):
        Config(decode_steps=-1)
    assert Config(decode_steps=4).decode_steps == 4
    assert Config().decode_steps == 0


# ----- observability ---------------------------------------------------------


def test_stats_schema_always_present(model, monkeypatch):
    cfg, params = model
    monkeypatch.delenv(ENV_DECODE_STEPS, raising=False)
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           fault_injector=FaultInjector())
    st = srv.stats()
    assert st["decode_steps"] == 1
    assert st["fused_admissions"] == 0
    assert st["fused_enabled"] == 0  # fifo_batch: fusion is inert
    srv2 = GenerationServer(
        params, cfg, max_batch=2, max_len=32, sched_policy="slo_chunked",
        prefill_chunk=4, itl_slo_ms=0.0, decode_steps=2,
        fault_injector=FaultInjector(),
    )
    st2 = srv2.stats()
    assert st2["decode_steps"] == 2 and st2["fused_enabled"] == 1


def test_serving_config_event_once(model, tmp_path):
    cfg, params = model
    sink, prev = _capture(tmp_path)
    try:
        out, srv = _serve(params, cfg, "slo_chunked", fused=True,
                          decode_steps=2, fault_injector=FaultInjector())
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = [e for e in _events(tmp_path / "ev.jsonl")
           if e.get("name") == "serving_config"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["decode_steps"] == 2 and ev["fused"] == 1
    assert ev["sched_policy"] == "slo_chunked"
    assert ev["dispatch_steps"] == ev["chunk"] * ev["decode_steps"]


def test_fused_counter_exported(model):
    from prometheus_client import REGISTRY, generate_latest

    cfg, params = model
    out, srv = _serve(params, cfg, "slo_chunked", fused=True,
                      fault_injector=FaultInjector())
    label = srv.export_metrics()
    text = generate_latest(REGISTRY).decode()
    assert "kata_tpu_serving_fused_admissions_total" in text
    assert (
        f'kata_tpu_serving_fused_admissions_total{{server="{label}"}}'
        in text
    )
