"""Plugin server + manager integration tests against the fake kubelet and a
fake sysfs host (SURVEY §4 integration strategy). No Kubernetes needed."""
from __future__ import annotations

import os
import shutil
import tempfile
import time

import grpc
import pytest

from kata_xpu_device_plugin_tpu import cdi
from kata_xpu_device_plugin_tpu.cdi import constants as C
from kata_xpu_device_plugin_tpu.config import Config
from kata_xpu_device_plugin_tpu.discovery.sysfs import FakeSysfsBuilder
from kata_xpu_device_plugin_tpu.plugin import (
    HealthWatcher,
    PluginManager,
)
from kata_xpu_device_plugin_tpu.plugin.api import deviceplugin_pb2 as pb
from kata_xpu_device_plugin_tpu.plugin.api import glue

from .fake_kubelet import FakeKubelet


@pytest.fixture
def short_dir():
    # unix socket paths are capped (~108 chars); pytest tmp_path is too deep.
    d = tempfile.mkdtemp(prefix="kt-", dir="/tmp")
    yield d
    shutil.rmtree(d, ignore_errors=True)


@pytest.fixture
def kubelet(short_dir):
    fk = FakeKubelet(os.path.join(short_dir, "kubelet")).start()
    yield fk
    fk.stop()


@pytest.fixture
def v5e8(short_dir):
    fake = FakeSysfsBuilder(root=os.path.join(short_dir, "host"))
    for i in range(8):
        fake.add_accel_chip(i)
        fake.add_pci_function(f"0000:0{i}:01.0", "1ae0", "0063", numa_node=i // 4)
    return fake


def make_config(fake, kubelet, short_dir, **overrides) -> Config:
    kw = dict(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=os.path.join(short_dir, "cdi"),
        kubelet_socket_dir=kubelet.socket_dir,
        rescan_interval_s=0,  # tests drive rescans explicitly
        health_poll_interval_s=3600,  # tests drive evaluate() explicitly
        metrics_port=0,
        libtpu_host_path="",
    )
    kw.update(overrides)
    return Config(**kw)


@pytest.fixture
def manager(v5e8, kubelet, short_dir):
    mgr = PluginManager(make_config(v5e8, kubelet, short_dir))
    mgr.start()
    yield mgr
    mgr.stop()


def test_registration_and_options(manager, kubelet):
    assert kubelet.registered.wait(5)
    (reg,) = kubelet.registrations
    assert reg.resource_name == "google.com/tpu"
    assert reg.version == "v1beta1"
    assert reg.options.get_preferred_allocation_available
    ch, stub = kubelet.plugin_stub(reg.endpoint)
    with ch:
        opts = stub.GetDevicePluginOptions(pb.Empty())
        assert opts.get_preferred_allocation_available


def test_list_and_watch_initial(manager, kubelet):
    ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
    with ch:
        stream = stub.ListAndWatch(pb.Empty())
        first = next(stream)
        assert [d.id for d in first.devices] == [str(i) for i in range(8)]
        assert all(d.health == glue.HEALTHY for d in first.devices)
        assert first.devices[5].topology.nodes[0].id == 1  # NUMA propagated
        stream.cancel()


def test_health_transition_streams_update(manager, kubelet, v5e8):
    plugin = manager.plugins()[0]
    watcher = HealthWatcher([plugin], use_inotify=False)
    ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
    with ch:
        stream = stub.ListAndWatch(pb.Empty())
        next(stream)  # initial
        v5e8.remove_dev_node("accel3")
        watcher.evaluate()
        update = next(stream)
        sick = {d.id: d.health for d in update.devices}
        assert sick["3"] == glue.UNHEALTHY
        assert sick["2"] == glue.HEALTHY
        v5e8.add_accel_chip(3)
        watcher.evaluate()
        update = next(stream)
        assert {d.id: d.health for d in update.devices}["3"] == glue.HEALTHY
        stream.cancel()


def test_allocate_cdi_cri(manager, kubelet):
    ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
    with ch:
        resp = stub.Allocate(
            pb.AllocateRequest(
                container_requests=[pb.ContainerAllocateRequest(device_ids=["0", "1", "2", "3"])]
            )
        )
        (cresp,) = resp.container_responses
        assert [d.name for d in cresp.cdi_devices] == [
            f"google.com/tpu={i}" for i in range(4)
        ]
        assert cresp.envs[C.ENV_CDI_VENDOR_CLASS] == "google.com/tpu"
        assert cresp.envs[C.ENV_TPU_VISIBLE_CHIPS] == "0,1,2,3"
        # No compile_cache_dir configured → no env injected (the guest
        # falls back to its own default resolution).
        assert C.ENV_COMPILE_CACHE_DIR not in cresp.envs


def test_tpu_allocator_injects_decode_steps_env(v5e8):
    # config.decode_steps (ISSUE 13) rides the AllocateResponse env: the
    # daemon's --decode-steps knob sets the in-guest multi-step decode
    # multiplier node-wide; unset (or 1) injects nothing and the guest
    # default (K=1) applies.
    from kata_xpu_device_plugin_tpu.discovery import scan_tpus
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator

    inv = scan_tpus(v5e8.sysfs, v5e8.dev, env={})
    bare = TpuAllocator(lambda: inv, "google.com", "tpu").allocate(["0"])
    assert C.ENV_DECODE_STEPS not in bare.envs
    one = TpuAllocator(
        lambda: inv, "google.com", "tpu", decode_steps=1,
    ).allocate(["0"])
    assert C.ENV_DECODE_STEPS not in one.envs
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", decode_steps=4,
    ).allocate(["0"])
    assert wired.envs[C.ENV_DECODE_STEPS] == "4"


def test_tpu_allocator_injects_guest_events_env(v5e8):
    # config.guest_events_dir (ISSUE 15) rides the AllocateResponse env:
    # the daemon switches the guest's JSONL stream on and points it at a
    # per-allocation file its heartbeat aggregator tails; the file name
    # carries the granted chip set. heartbeat_rounds > 0 additionally
    # pins the in-guest cadence. Unset injects nothing (guest defaults).
    from kata_xpu_device_plugin_tpu.discovery import scan_tpus
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator

    inv = scan_tpus(v5e8.sysfs, v5e8.dev, env={})
    bare = TpuAllocator(lambda: inv, "google.com", "tpu").allocate(["0"])
    assert C.ENV_OBS not in bare.envs
    assert C.ENV_OBS_FILE not in bare.envs
    assert C.ENV_HEARTBEAT_ROUNDS not in bare.envs
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu",
        guest_events_dir="/run/kata-tpu/guest-events", heartbeat_rounds=16,
    ).allocate(["0", "1"])
    assert wired.envs[C.ENV_OBS] == "1"
    assert wired.envs[C.ENV_OBS_FILE] == (
        "/run/kata-tpu/guest-events/guest_0-1.jsonl"
    )
    assert wired.envs[C.ENV_HEARTBEAT_ROUNDS] == "16"


def test_tpu_allocator_injects_kv_quant_env(v5e8):
    # config.kv_quant (ISSUE 12) rides the AllocateResponse env: the
    # daemon's --kv-quant knob opts a node out of (or pins) the guest's
    # int8-KV default; unset injects nothing and the guest default
    # applies.
    from kata_xpu_device_plugin_tpu.discovery import scan_tpus
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator

    inv = scan_tpus(v5e8.sysfs, v5e8.dev, env={})
    bare = TpuAllocator(lambda: inv, "google.com", "tpu").allocate(["0"])
    assert C.ENV_KV_QUANT not in bare.envs
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", kv_quant="bf16",
    ).allocate(["0"])
    assert wired.envs[C.ENV_KV_QUANT] == "bf16"


def test_tpu_allocator_injects_compile_cache_env(v5e8):
    # config.compile_cache_dir (ISSUE 3) rides the AllocateResponse env:
    # every granted workload points jax's persistent compilation cache at
    # the node's shared directory (compat.jaxapi.enable_compilation_cache
    # reads KATA_TPU_COMPILE_CACHE_DIR in-guest).
    from kata_xpu_device_plugin_tpu.discovery import scan_tpus
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator

    inv = scan_tpus(v5e8.sysfs, v5e8.dev, env={})
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu",
        compile_cache_dir="/var/cache/kata-tpu/xla",
    ).allocate(["0"])
    assert wired.envs[C.ENV_COMPILE_CACHE_DIR] == "/var/cache/kata-tpu/xla"


def test_allocate_telemetry_span_and_latency(manager, kubelet, tmp_path):
    """ISSUE 2: an Allocate call emits one span event (trace id, device
    ids) into the JSONL sink and a sample into the gRPC latency histogram;
    a ListAndWatch update records under its own method label."""
    from prometheus_client import REGISTRY, generate_latest

    from kata_xpu_device_plugin_tpu import obs

    sink = obs.EventSink(str(tmp_path / "plugin.jsonl"))
    prev = obs.set_default_sink(sink)
    try:
        ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
        with ch:
            stream = stub.ListAndWatch(pb.Empty())
            next(stream)
            stub.Allocate(
                pb.AllocateRequest(
                    container_requests=[
                        pb.ContainerAllocateRequest(device_ids=["0", "1"]),
                        pb.ContainerAllocateRequest(device_ids=["2", "3"]),
                    ]
                )
            )
            stream.cancel()
    finally:
        sink.close()
        obs.set_default_sink(prev)

    evs = obs.read_events(str(tmp_path / "plugin.jsonl"))
    (alloc,) = [e for e in evs if e["name"] == "plugin.Allocate"]
    # ALL containers' ids — the span is the join record for the whole call.
    assert alloc["devices"] == "0,1,2,3"
    assert alloc["containers"] == 2
    assert alloc["resource"] == "google.com/tpu"
    assert alloc["trace"] and alloc["span"]  # the log join key
    assert alloc["dur_s"] > 0
    updates = [e for e in evs if e["name"] == "plugin.ListAndWatch_update"]
    assert updates and all(u["devices"] == 8 for u in updates)

    text = generate_latest(REGISTRY).decode()
    assert (
        'kata_tpu_device_plugin_grpc_handler_seconds_count'
        '{method="Allocate",resource="google.com/tpu"}'
    ) in text
    assert 'method="ListAndWatch_update"' in text


def test_allocate_unknown_and_unhealthy(manager, kubelet, v5e8):
    ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
    with ch:
        with pytest.raises(grpc.RpcError) as exc:
            stub.Allocate(
                pb.AllocateRequest(
                    container_requests=[pb.ContainerAllocateRequest(device_ids=["42"])]
                )
            )
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT

        plugin = manager.plugins()[0]
        v5e8.remove_dev_node("accel1")
        HealthWatcher([plugin], use_inotify=False).evaluate()
        with pytest.raises(grpc.RpcError) as exc:
            stub.Allocate(
                pb.AllocateRequest(
                    container_requests=[pb.ContainerAllocateRequest(device_ids=["1"])]
                )
            )
        assert exc.value.code() == grpc.StatusCode.UNAVAILABLE


def test_allocate_revalidates_dev_node(kubelet, v5e8, short_dir):
    # Node vanishes between health pass and Allocate: must fail closed
    # (the reference's live sysfs re-validation, done against /dev/accel).
    # A standalone server (no health watcher) isolates the re-validation seam.
    from kata_xpu_device_plugin_tpu.discovery import scan_tpus
    from kata_xpu_device_plugin_tpu.plugin import DevicePluginServer, DeviceState, TpuAllocator
    from kata_xpu_device_plugin_tpu.plugin.manager import tpu_watched_devices

    inv = scan_tpus(v5e8.sysfs, v5e8.dev, env={})
    server = DevicePluginServer(
        resource_name="google.com/tpu",
        state=DeviceState(tpu_watched_devices(inv, v5e8.sysfs)),
        allocator=TpuAllocator(lambda: inv, "google.com", "tpu"),
        socket_dir=kubelet.socket_dir,
    )
    server.start(register=False)
    try:
        ch, stub = kubelet.plugin_stub(server.endpoint)
        with ch:
            v5e8.remove_dev_node("accel2")  # no watcher ran: health still Healthy
            with pytest.raises(grpc.RpcError) as exc:
                stub.Allocate(
                    pb.AllocateRequest(
                        container_requests=[pb.ContainerAllocateRequest(device_ids=["2"])]
                    )
                )
            assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        server.stop()


def test_preferred_allocation_contiguous(manager, kubelet):
    ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
    with ch:
        resp = stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(
                container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_device_ids=["0", "3", "4", "5", "6", "7"],
                        allocation_size=4,
                    )
                ]
            )
        )
        (cresp,) = resp.container_responses
        assert list(cresp.device_ids) == ["4", "5", "6", "7"]  # the free 2x2 box


def test_kubelet_restart_reregisters(manager, kubelet):
    assert kubelet.registered.wait(5)
    plugin = manager.plugins()[0]
    watcher = HealthWatcher([plugin], use_inotify=False)
    os.unlink(plugin.socket_path)  # kubelet wiped its dir
    watcher.evaluate()
    # The manager's own inotify watcher may be mid-restart concurrently with
    # our explicit evaluate(); wait for the re-registration to land.
    deadline = time.time() + 5
    while len(kubelet.registrations) < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert len(kubelet.registrations) >= 2
    assert plugin.serving
    # and the plugin still answers on the re-created socket
    ch, stub = kubelet.plugin_stub(kubelet.registrations[-1].endpoint)
    with ch:
        assert stub.GetDevicePluginOptions(pb.Empty()).get_preferred_allocation_available


def test_cdi_spec_written(manager):
    path = os.path.join(manager.cfg.cdi_dir, "google.com-tpu.yaml")
    spec = cdi.load(path)
    assert spec.device_names() == [str(i) for i in range(8)]
    env_keys = {e.split("=")[0] for e in spec.container_edits.env}
    assert "TPU_ACCELERATOR_TYPE" in env_keys
    assert "TPU_CHIPS_PER_HOST_BOUNDS" in env_keys
    node = spec.devices[0].container_edits.device_nodes[0]
    assert node.path == "/dev/accel0"  # in-guest path, not the fake root
    assert node.host_path.endswith("/dev/accel0")


def test_rescan_picks_up_new_chip(kubelet, short_dir):
    fake = FakeSysfsBuilder(root=os.path.join(short_dir, "host"))
    fake.add_accel_chip(0)
    mgr = PluginManager(make_config(fake, kubelet, short_dir))
    mgr.start()
    try:
        assert mgr.plugins()[0].state.ids() == ["0"]
        fake.add_accel_chip(1)
        assert mgr.rescan_once() is True
        assert mgr.plugins()[0].state.ids() == ["0", "1"]
        assert mgr.rescan_once() is False  # idempotent
    finally:
        mgr.stop()


def test_zero_chip_dry_run(kubelet, short_dir):
    # BASELINE configs[0]: node with no TPUs still serves an empty resource.
    fake = FakeSysfsBuilder(root=os.path.join(short_dir, "host"))
    mgr = PluginManager(make_config(fake, kubelet, short_dir))
    mgr.start()
    try:
        assert kubelet.registered.wait(5)
        ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
        with ch:
            stream = stub.ListAndWatch(pb.Empty())
            first = next(stream)
            assert len(first.devices) == 0
            stream.cancel()
        assert not os.path.exists(os.path.join(mgr.cfg.cdi_dir, "google.com-tpu.yaml"))
    finally:
        mgr.stop()


def test_vfio_model_plugin(kubelet, short_dir):
    fake = FakeSysfsBuilder(root=os.path.join(short_dir, "host"))
    fake.add_pci_function("0000:01:00.0", "10de", "2203", driver="vfio-pci", iommu_group="11")
    fake.add_pci_function("0000:02:00.0", "10de", "2203", driver="vfio-pci", iommu_group="12")
    mgr = PluginManager(
        make_config(fake, kubelet, short_dir, vfio_vendors=("10de",))
    )
    mgr.start()
    try:
        names = {r.resource_name for r in kubelet.registrations}
        assert "google.com/tpu" in names
        vfio_res = next(n for n in names if n != "google.com/tpu")
        reg = next(r for r in kubelet.registrations if r.resource_name == vfio_res)
        ch, stub = kubelet.plugin_stub(reg.endpoint)
        with ch:
            stream = stub.ListAndWatch(pb.Empty())
            first = next(stream)
            assert sorted(d.id for d in first.devices) == ["11", "12"]
            stream.cancel()
            resp = stub.Allocate(
                pb.AllocateRequest(
                    container_requests=[pb.ContainerAllocateRequest(device_ids=["11"])]
                )
            )
            (cresp,) = resp.container_responses
            assert cresp.cdi_devices[0].name == "google.com/vfio=11"
        # spec on disk covers the groups
        spec = cdi.load(os.path.join(mgr.cfg.cdi_dir, "google.com-vfio.yaml"))
        assert spec.device_names() == ["11", "12"]
    finally:
        mgr.stop()


def test_vfio_allocate_fails_after_unbind(kubelet, short_dir):
    fake = FakeSysfsBuilder(root=os.path.join(short_dir, "host"))
    fake.add_pci_function("0000:01:00.0", "10de", "2203", driver="vfio-pci", iommu_group="11")
    mgr = PluginManager(make_config(fake, kubelet, short_dir, vfio_vendors=("10de",)))
    mgr.start()
    try:
        reg = next(r for r in kubelet.registrations if r.resource_name != "google.com/tpu")
        # Driver rebound from vfio-pci to nvidia between discovery and Allocate.
        fake.add_pci_function("0000:01:00.0", "10de", "2203", driver="nvidia", iommu_group="11")
        ch, stub = kubelet.plugin_stub(reg.endpoint)
        with ch:
            with pytest.raises(grpc.RpcError) as exc:
                stub.Allocate(
                    pb.AllocateRequest(
                        container_requests=[pb.ContainerAllocateRequest(device_ids=["11"])]
                    )
                )
            assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        mgr.stop()


def test_manager_stop_reaches_restarted_plugin(manager, kubelet):
    # Quirk 2 regression: restart() must not orphan the plugin from stop().
    plugin = manager.plugins()[0]
    plugin.restart()
    assert len(kubelet.registrations) == 2
    manager.stop()
    assert plugin.stopped
    assert not os.path.exists(plugin.socket_path)


def test_envvar_strategy_carries_full_guest_contract(kubelet, v5e8, short_dir):
    # Without CDI, AllocateResponse itself must carry topology env + libtpu.
    libtpu = os.path.join(short_dir, "libtpu.so")
    open(libtpu, "w").close()
    mgr = PluginManager(
        make_config(v5e8, kubelet, short_dir,
                    strategies=("envvar",), libtpu_host_path=libtpu)
    )
    mgr.start()
    try:
        ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
        with ch:
            resp = stub.Allocate(
                pb.AllocateRequest(
                    container_requests=[pb.ContainerAllocateRequest(device_ids=["0", "1"])]
                )
            )
            (cr,) = resp.container_responses
            assert len(cr.devices) == 2 and cr.devices[0].permissions == "rw"
            assert cr.envs["TPU_ACCELERATOR_TYPE"] == "v5litepod-8"
            assert cr.envs["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,4,1"
            assert cr.mounts[0].host_path == libtpu and cr.mounts[0].read_only
            assert not cr.cdi_devices  # cdi-cri not enabled
    finally:
        mgr.stop()


def test_driver_unbind_flips_unhealthy(manager, kubelet, v5e8):
    """SURVEY §7 hard part #4: a vanished /sys/class/accel entry means the
    driver is gone — Unhealthy even though the stale /dev node lingers."""
    import shutil

    plugin = manager.plugins()[0]
    watcher = HealthWatcher([plugin], use_inotify=False)
    watcher.evaluate()
    assert all(d.health == glue.HEALTHY for d in plugin.state.snapshot())
    shutil.rmtree(os.path.join(v5e8.sysfs, "class/accel/accel5"))
    watcher.evaluate()
    health = {d.id: d.health for d in plugin.state.snapshot()}
    assert health["5"] == glue.UNHEALTHY
    assert health["0"] == glue.HEALTHY
    # dev node is still there — existence alone would have said Healthy
    assert os.path.exists(os.path.join(v5e8.dev, "accel5"))


def test_debug_report_snapshots_live_state(manager):
    rep = manager.debug_report()
    (tpu_plugin,) = [p for p in rep["plugins"] if p["resource"] == "google.com/tpu"]
    assert tpu_plugin["serving"] and not tpu_plugin["stopped"]
    assert {d["id"] for d in tpu_plugin["devices"]} == {str(i) for i in range(8)}
    assert rep["tpu"]["chips"] == 8
    assert rep["watcher_alive"]
    import json

    json.dumps(rep)  # must be directly serializable for the SIGUSR1 dump


def test_recovery_requires_live_driver(manager, kubelet, v5e8, monkeypatch):
    """Flipping back to Healthy is gated on the open-probe: a path that
    reappears but whose driver answers ENXIO stays Unhealthy; a guest-held
    node (EBUSY) recovers. Steady-state Healthy never probes (no VMM race)."""
    import errno
    import stat as stat_mod

    from kata_xpu_device_plugin_tpu.plugin import health as H

    # The manager's own inotify watcher wakes on the fs events below and
    # would race the monkeypatched os functions; this test drives health
    # deterministically through its own watcher.
    manager._watcher.stop()
    plugin = manager.plugins()[0]
    watcher = HealthWatcher([plugin], use_inotify=False)
    sys_entry = os.path.join(v5e8.sysfs, "class/accel/accel0")
    shutil.rmtree(sys_entry)
    watcher.evaluate()
    assert {d.id: d.health for d in plugin.state.snapshot()}["0"] == glue.UNHEALTHY

    os.makedirs(sys_entry)  # path is back — recovery now hinges on the probe
    dev0 = os.path.join(v5e8.dev, "accel0")
    real_stat, real_open = os.stat, os.open

    class CharStat:
        st_mode = stat_mod.S_IFCHR | 0o600

    monkeypatch.setattr(
        H.os,
        "stat",
        lambda p, *a, **kw: CharStat() if p == dev0 else real_stat(p, *a, **kw),
    )

    def open_with(err):
        def _open(path, flags, *a):
            if path == dev0:
                raise OSError(err, os.strerror(err), path)
            return real_open(path, flags, *a)

        return _open

    monkeypatch.setattr(H.os, "open", open_with(errno.ENXIO))
    watcher.evaluate()
    assert {d.id: d.health for d in plugin.state.snapshot()}["0"] == glue.UNHEALTHY

    monkeypatch.setattr(H.os, "open", open_with(errno.EBUSY))
    watcher.evaluate()
    assert {d.id: d.health for d in plugin.state.snapshot()}["0"] == glue.HEALTHY


def test_allocate_revalidates_driver_liveness(manager, kubelet, monkeypatch, v5e8):
    """VERDICT r1 #2 acceptance: an Allocate against an orphaned char device
    (open → ENXIO) fails closed, while a guest-held one (EBUSY) allocates."""
    import errno
    import stat as stat_mod

    from kata_xpu_device_plugin_tpu.plugin import health as H

    dev0 = os.path.join(v5e8.dev, "accel0")
    real_stat, real_open = os.stat, os.open

    class CharStat:
        st_mode = stat_mod.S_IFCHR | 0o600

    def fake_stat(path, *a, **kw):
        if path == dev0:
            return CharStat()
        return real_stat(path, *a, **kw)

    def open_with(err):
        def _open(path, flags, *a):
            if path == dev0:
                raise OSError(err, os.strerror(err), path)
            return real_open(path, flags, *a)

        return _open

    monkeypatch.setattr(H.os, "stat", fake_stat)
    req = pb.AllocateRequest(
        container_requests=[pb.ContainerAllocateRequest(device_ids=["0"])]
    )
    ch, stub = kubelet.plugin_stub(kubelet.registrations[0].endpoint)
    with ch:
        monkeypatch.setattr(H.os, "open", open_with(errno.EBUSY))
        resp = stub.Allocate(req)
        assert resp.container_responses[0].cdi_devices

        monkeypatch.setattr(H.os, "open", open_with(errno.ENXIO))
        with pytest.raises(grpc.RpcError) as exc:
            stub.Allocate(req)
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "liveness" in exc.value.details()


def test_node_alive_errno_classification(monkeypatch, tmp_path):
    import errno
    import stat as stat_mod

    from kata_xpu_device_plugin_tpu.plugin import health as H

    # Regular file: existence is the signal.
    f = tmp_path / "plain"
    f.write_text("")
    assert H.node_alive(str(f))
    assert not H.node_alive(str(tmp_path / "missing"))

    # Char devices: openability decides.
    class FakeStat:
        st_mode = stat_mod.S_IFCHR | 0o600

    monkeypatch.setattr(H.os, "stat", lambda p: FakeStat())

    def open_raising(err):
        def _open(path, flags):
            raise OSError(err, os.strerror(err), path)

        return _open

    monkeypatch.setattr(H.os, "open", open_raising(errno.EBUSY))
    assert H.node_alive("/dev/accel0")  # held by a guest: alive
    monkeypatch.setattr(H.os, "open", open_raising(errno.ENXIO))
    assert not H.node_alive("/dev/accel0")  # orphaned inode: dead
    monkeypatch.setattr(H.os, "open", open_raising(errno.ENODEV))
    assert not H.node_alive("/dev/accel0")


def test_vfio_preferred_numa_affinity():
    """preferred() fills from one NUMA node before spilling (the policy the
    ref's stub at generic_device_plugin.go:378-386 never grew)."""
    from kata_xpu_device_plugin_tpu.discovery.vfio import VfioDevice, VfioInventory
    from kata_xpu_device_plugin_tpu.plugin.allocators import VfioAllocator

    inv = VfioInventory()
    for group, node in [("1", 0), ("2", 1), ("3", 0), ("4", 1), ("5", 1)]:
        inv.groups[group] = [
            VfioDevice(
                address=f"0000:0{group}:00.0", vendor="10de", device="2330",
                iommu_group=group, numa_node=node,
            )
        ]
    alloc = VfioAllocator(lambda: inv, "nvidia.com", ("10de", "2330"))

    # Node 1 can satisfy the whole request; node 0 cannot.
    picked = alloc.preferred(["1", "2", "3", "4", "5"], [], 3)
    assert sorted(picked) == ["2", "4", "5"]
    # must_include pins the node: same-node groups fill the remainder.
    picked = alloc.preferred(["1", "2", "3", "4", "5"], ["1"], 2)
    assert picked == ["1", "3"]
    # Larger than any one node: same-node prefix first, then spill.
    picked = alloc.preferred(["1", "2", "3", "4", "5"], [], 4)
    assert len(picked) == 4


# ----- robustness satellites (ISSUE 7) -------------------------------------


class _FlakyPlugin:
    """Stand-in for DevicePluginServer in restart-retry tests: serving,
    socket gone, restart() fails a scripted number of times."""

    def __init__(self, short_dir, fail_times):
        self.resource_name = "google.com/tpu"
        self.serving = True
        self.stopped = False
        self.socket_path = os.path.join(short_dir, "never-created.sock")
        self.fail_times = fail_times
        self.calls = 0
        self.state = type("S", (), {"snapshot": lambda self: []})()

    def restart(self):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise RuntimeError(f"kubelet not back (attempt {self.calls})")


def test_health_watcher_restart_retries_with_backoff(short_dir, tmp_path):
    """A failed plugin.restart() is no longer forgotten until the next
    socket event: every evaluate() pass re-offers it under bounded
    exponential backoff, failures emit plugin_restart_failed events and
    land on plugin_restarts_total{ok="false"}, and success clears the
    backoff state."""
    from prometheus_client import REGISTRY, generate_latest

    from kata_xpu_device_plugin_tpu import obs

    plugin = _FlakyPlugin(short_dir, fail_times=2)
    now = [100.0]
    watcher = HealthWatcher([plugin], use_inotify=False,
                            restart_backoff_s=1.0, restart_backoff_max_s=8.0,
                            clock=lambda: now[0])
    sink = obs.EventSink(str(tmp_path / "ev.jsonl"))
    prev = obs.set_default_sink(sink)
    try:
        watcher.evaluate()  # attempt 1: fails, next not before t+1
        assert plugin.calls == 1
        watcher.evaluate()  # backing off: no attempt
        assert plugin.calls == 1
        now[0] += 1.1
        watcher.evaluate()  # attempt 2: fails, delay doubles to 2 s
        assert plugin.calls == 2
        now[0] += 1.1
        watcher.evaluate()  # still inside the doubled window
        assert plugin.calls == 2
        now[0] += 1.1
        watcher.evaluate()  # attempt 3: succeeds, state cleared
        assert plugin.calls == 3
        now[0] += 0.01
        watcher.evaluate()  # socket still missing: retry IMMEDIATELY
        assert plugin.calls == 4  # (no stale backoff after a success)
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = [e for e in obs.read_events(str(tmp_path / "ev.jsonl"))
           if e.get("name") == "plugin_restart_failed"]
    assert [e["attempt"] for e in evs] == [1, 2]
    assert all(e["retry_in_s"] > 0 and "kubelet" in e["err"] for e in evs)
    text = generate_latest(REGISTRY).decode()
    assert ('plugin_restarts_total{ok="false",resource="google.com/tpu"}'
            in text)
    assert ('plugin_restarts_total{ok="true",resource="google.com/tpu"}'
            in text)


def test_register_exhaustion_emits_event_and_respects_config(short_dir,
                                                             tmp_path):
    """register() policy is configurable (Config.register_attempts /
    register_backoff_s on the daemon path) and exhausting every attempt
    emits a registration_exhausted obs event before raising — no more
    silent permanent give-up after the old hardcoded ladder."""
    from kata_xpu_device_plugin_tpu import obs
    from kata_xpu_device_plugin_tpu.plugin import DevicePluginServer, DeviceState

    server = DevicePluginServer(
        resource_name="google.com/tpu",
        state=DeviceState([]),
        allocator=None,
        socket_dir=short_dir,
        kubelet_socket=os.path.join(short_dir, "no-kubelet.sock"),
        register_attempts=2,
        register_backoff_s=0.01,
        register_dial_timeout_s=0.05,
    )
    sink = obs.EventSink(str(tmp_path / "ev.jsonl"))
    prev = obs.set_default_sink(sink)
    t0 = time.monotonic()
    try:
        with pytest.raises(Exception):
            server.register()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert time.monotonic() - t0 < 5  # the short policy was honored
    (ev,) = [e for e in obs.read_events(str(tmp_path / "ev.jsonl"))
             if e.get("name") == "registration_exhausted"]
    assert ev["resource"] == "google.com/tpu" and ev["attempts"] == 2
    assert ev["err"]


def test_config_register_policy_validation_and_plumbing(v5e8, kubelet,
                                                        short_dir):
    """Config validates the new register knobs and the manager hands them
    to every plugin it builds."""
    with pytest.raises(ValueError, match="register-attempts"):
        make_config(v5e8, kubelet, short_dir, register_attempts=0)
    with pytest.raises(ValueError, match="register-backoff-s"):
        make_config(v5e8, kubelet, short_dir, register_backoff_s=-1.0)

    mgr = PluginManager(make_config(v5e8, kubelet, short_dir,
                                    register_attempts=7,
                                    register_backoff_s=0.25))
    mgr.start()
    try:
        assert kubelet.registered.wait(5)
        plugin = mgr.plugins()[0]
        assert plugin.register_attempts == 7
        assert plugin.register_backoff_s == 0.25
    finally:
        mgr.stop()
