"""Checkpoint/resume on the virtual CPU mesh: a train loop killed mid-run
must resume from disk to bit-identical losses (VERDICT r1 item 7)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import parallel
from kata_xpu_device_plugin_tpu.models import tiny_test_config


def _batch(cfg, mesh, step: int):
    """Deterministic per-step batch so two runs see identical data."""
    toks = jax.random.randint(
        jax.random.PRNGKey(1000 + step), (8, 16), 0, cfg.vocab_size, dtype=jnp.int32
    )
    return parallel.shard_batch(toks, mesh)


@pytest.fixture(scope="module")
def mesh():
    return parallel.build_mesh(parallel.default_mesh_shape(8))


def test_kill_and_resume_bit_identical(tmp_path, mesh):
    cfg = tiny_test_config()
    init_state, step_fn = parallel.make_train_step(cfg, mesh)

    # Uninterrupted run: 6 steps, record losses.
    state = init_state(jax.random.PRNGKey(0))
    full_losses = []
    for i in range(6):
        state, loss = step_fn(state, _batch(cfg, mesh, i))
        full_losses.append(np.asarray(loss))

    # Interrupted run: same init, checkpoint each step, "die" after step 3.
    ckpt_dir = str(tmp_path / "ckpt")
    state = init_state(jax.random.PRNGKey(0))
    with parallel.TrainCheckpointer(ckpt_dir, max_to_keep=2) as ck:
        for i in range(3):
            state, loss = step_fn(state, _batch(cfg, mesh, i))
            assert ck.save(int(state["step"]), state)
            np.testing.assert_array_equal(np.asarray(loss), full_losses[i])

    # "Restart": a fresh checkpointer + a fresh abstract state restores the
    # latest step into the same shardings, and the remaining steps reproduce
    # the uninterrupted losses bit-for-bit.
    with parallel.TrainCheckpointer(ckpt_dir) as ck:
        assert ck.latest_step() == 3
        template = init_state(jax.random.PRNGKey(7))  # different key: values must come from disk
        restored = ck.restore(template)
    assert int(restored["step"]) == 3
    for leaf, ref_leaf in zip(
        jax.tree.leaves(restored), jax.tree.leaves(template)
    ):
        assert leaf.sharding == ref_leaf.sharding
    state = restored
    for i in range(3, 6):
        state, loss = step_fn(state, _batch(cfg, mesh, i))
        np.testing.assert_array_equal(np.asarray(loss), full_losses[i])


def test_max_to_keep_prunes_old_steps(tmp_path, mesh):
    cfg = tiny_test_config()
    init_state, step_fn = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    with parallel.TrainCheckpointer(str(tmp_path), max_to_keep=2) as ck:
        for i in range(4):
            state, _ = step_fn(state, _batch(cfg, mesh, i))
            ck.save(int(state["step"]), state)
        ck.wait()
        assert ck.latest_step() == 4
        assert sorted(ck._mngr.all_steps()) == [3, 4]  # 1 and 2 pruned

    with parallel.TrainCheckpointer(str(tmp_path)) as ck:
        state2 = ck.restore(state)  # live state as template
        assert int(state2["step"]) == 4


def test_restore_empty_dir_raises(tmp_path, mesh):
    cfg = tiny_test_config()
    init_state, _ = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    with parallel.TrainCheckpointer(str(tmp_path)) as ck:
        with pytest.raises(FileNotFoundError):
            ck.restore(state)


def test_moe_state_checkpoint_roundtrip(tmp_path, mesh):
    """MoE train state (expert-major sharded params + opt moments) must
    checkpoint and resume to bit-identical losses like the dense state."""
    from kata_xpu_device_plugin_tpu.models import mixtral_test_config

    cfg = mixtral_test_config(dtype=jnp.float32)
    init_state, step_fn = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    state, l0 = step_fn(state, _batch(cfg, mesh, 0))
    with parallel.TrainCheckpointer(str(tmp_path / "moe")) as ck:
        assert ck.save(int(state["step"]), state)
        template = init_state(jax.random.PRNGKey(9))
        restored = ck.restore(template)
    _, l_resumed = step_fn(restored, _batch(cfg, mesh, 1))
    state, l_direct = step_fn(state, _batch(cfg, mesh, 1))
    np.testing.assert_array_equal(np.asarray(l_resumed), np.asarray(l_direct))


def test_pp_state_checkpoint_roundtrip(tmp_path):
    """Composed pp×fsdp×tp state (stage-major pipe-sharded layers) restores
    into its mesh shardings and reproduces the next loss exactly."""
    from kata_xpu_device_plugin_tpu.parallel import composed

    cfg = tiny_test_config(n_layers=4, dtype=jnp.float32)
    cmesh = composed.composed_mesh(2, 2, 2)
    init_state, step_fn = composed.make_pp_train_step(cfg, cmesh, 2, 4)

    def batch(step):
        toks = jax.random.randint(
            jax.random.PRNGKey(2000 + step), (4, 2, 16), 0, cfg.vocab_size,
            dtype=jnp.int32,
        )
        return composed.shard_microbatches(toks, cmesh)

    state = init_state(jax.random.PRNGKey(0))
    state, _ = step_fn(state, batch(0))
    with parallel.TrainCheckpointer(str(tmp_path / "pp")) as ck:
        assert ck.save(int(state["step"]), state)
        template = init_state(jax.random.PRNGKey(9))
        restored = ck.restore(template)
    lay = restored["params"]["layers"]["wq"]
    assert lay.sharding.spec[0] == "pipe"
    _, l_resumed = step_fn(restored, batch(1))
    state, l_direct = step_fn(state, batch(1))
    np.testing.assert_array_equal(np.asarray(l_resumed), np.asarray(l_direct))
