"""Deterministic resumable data loader (parallel/loader.py).

The reference ships no input pipeline (SURVEY §2: zero ML code); the bar
here is the training-stack contract: determinism, exact resume, disjoint
host shards, mesh placement.
"""
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.parallel import (
    TokenBatchLoader,
    build_mesh,
    make_loader,
)

TOKENS = np.arange(1000, dtype=np.int32) % 251


def _take(loader, n):
    return [next(loader) for _ in range(n)]


def test_shapes_and_coverage():
    ld = TokenBatchLoader(TOKENS, batch=4, seq_len=15, shuffle=False)
    b = next(ld)
    assert b.shape == (4, 16) and b.dtype == np.int32
    # Unshuffled: rows are consecutive windows of the stream.
    np.testing.assert_array_equal(b[0], TOKENS[:16])
    np.testing.assert_array_equal(b[1], TOKENS[16:32])
    assert ld.steps_per_epoch == (1000 // 16) // 4


def test_determinism_same_seed():
    a = _take(TokenBatchLoader(TOKENS, 4, 15, seed=7), 10)
    b = _take(TokenBatchLoader(TOKENS, 4, 15, seed=7), 10)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    c = _take(TokenBatchLoader(TOKENS, 4, 15, seed=8), 10)
    assert any((x != y).any() for x, y in zip(a, c))


def test_epochs_reshuffle_but_cover_same_windows():
    # 64 windows, batch 4 → every window used each epoch (no dropped tail;
    # with a non-divisible count the dropped windows differ per epoch).
    tokens = np.arange(1024, dtype=np.int32) % 251
    ld = TokenBatchLoader(tokens, 4, 15, seed=1)
    per_epoch = ld.steps_per_epoch
    assert per_epoch * 4 == ld.n_windows
    e0 = np.concatenate(_take(ld, per_epoch)).ravel()
    e1 = np.concatenate(_take(ld, per_epoch)).ravel()
    assert ld.epoch == 1
    assert (np.sort(e0) == np.sort(e1)).all()  # same windows...
    assert (e0 != e1).any()  # ...different order


def test_resume_matches_uninterrupted():
    ld = TokenBatchLoader(TOKENS, 4, 15, seed=3)
    _take(ld, 7)  # advance past an epoch boundary (steps_per_epoch=15)
    state = ld.state_dict()
    expected = _take(ld, 12)

    ld2 = TokenBatchLoader(TOKENS, 4, 15, seed=3)
    ld2.load_state_dict(state)
    resumed = _take(ld2, 12)
    for x, y in zip(expected, resumed):
        np.testing.assert_array_equal(x, y)


def test_resume_rejects_mismatched_config():
    ld = TokenBatchLoader(TOKENS, 4, 15, seed=3)
    state = ld.state_dict()
    other = TokenBatchLoader(TOKENS, 4, 15, seed=4)
    with pytest.raises(ValueError, match="seed"):
        other.load_state_dict(state)
    # A grown/swapped corpus changes the permutation — must refuse too.
    grown = TokenBatchLoader(np.concatenate([TOKENS, TOKENS]), 4, 15, seed=3)
    with pytest.raises(ValueError, match="n_windows"):
        grown.load_state_dict(state)


def test_host_shards_disjoint_and_cover():
    full = next(TokenBatchLoader(TOKENS, 8, 15, seed=5))
    shards = [
        next(TokenBatchLoader(TOKENS, 8, 15, seed=5, host_count=4, host_index=i))
        for i in range(4)
    ]
    assert all(s.shape == (2, 16) for s in shards)
    recombined = np.concatenate(shards)
    # Strided assignment: host i takes rows i, i+4 of the global batch.
    np.testing.assert_array_equal(
        np.sort(recombined.ravel()), np.sort(full.ravel())
    )


def test_mesh_placement():
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    ld = make_loader(TOKENS, batch=8, seq_len=15, mesh=mesh)
    b = next(ld)
    assert b.shape == (8, 16)
    # Committed to the mesh with the train step's batch spec.
    assert set(b.sharding.mesh.axis_names) == {"data", "fsdp", "model"}


def test_loader_feeds_train_step():
    # End-to-end: loader batches drive the GSPMD train step with no
    # re-layout (loss finite, step counter advances).
    import jax

    from kata_xpu_device_plugin_tpu.models import llama3_train_test
    from kata_xpu_device_plugin_tpu.parallel import make_train_step

    cfg = llama3_train_test()
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    tokens = np.arange(2048, dtype=np.int32) % cfg.vocab_size
    ld = make_loader(tokens, batch=8, seq_len=31, mesh=mesh, seed=11)
    init_state, step = make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    for _ in range(2):
        state, loss = step(state, next(ld))
    assert np.isfinite(float(loss))
    assert int(state["step"]) == 2


def test_validation():
    with pytest.raises(ValueError, match="divisible"):
        TokenBatchLoader(TOKENS, batch=3, seq_len=15, host_count=2)
    with pytest.raises(ValueError, match="windows"):
        TokenBatchLoader(TOKENS[:40], batch=4, seq_len=15)
    with pytest.raises(ValueError, match="1-D"):
        TokenBatchLoader(TOKENS.reshape(2, -1), batch=2, seq_len=15)
