"""Sampling controls: temperature, top_k, top_p (nucleus).

top_p's oracle is constructed distributions where the nucleus membership
is known exactly.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer, serve_batch
from kata_xpu_device_plugin_tpu.models import generate, tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params, sample_token


def _dist_logits(probs):
    return jnp.log(jnp.asarray([probs], jnp.float32))


def _draws(logits, n=200, **kw):
    return {
        int(sample_token(logits, jax.random.PRNGKey(s),
                         jnp.float32(1.0), **kw)[0])
        for s in range(n)
    }


def test_top_p_nucleus_membership():
    logits = _dist_logits([0.5, 0.3, 0.15, 0.05])
    # top_p=0.6: cumulative-before = [0, .5, .8, .95] → nucleus {0, 1}.
    assert _draws(logits, top_k=0, top_p=0.6) == {0, 1}
    # top_p=0.4: only the argmax survives (nucleus is never empty).
    assert _draws(logits, top_k=0, top_p=0.4) == {0}
    # top_p=1.0: everything stays reachable.
    assert _draws(logits, top_k=0, top_p=1.0) == {0, 1, 2, 3}


def test_top_p_composes_with_top_k():
    logits = _dist_logits([0.4, 0.3, 0.2, 0.1])
    # top_k=3 removes token 3; top_p=0.75 over the REMAINING mass keeps the
    # smallest prefix reaching 0.75 of the renormalized {0,1,2} ≈ {0, 1}.
    assert _draws(logits, top_k=3, top_p=0.75) == {0, 1}


def test_top_p_exact_prefix_under_ties():
    # Flat distribution: 4 tokens at identical logits, top_p=0.3 → the
    # smallest prefix reaching 0.3 is exactly TWO tokens (0.25, then 0.5);
    # a threshold compare at the boundary logit would keep all four ties.
    logits = _dist_logits([0.25, 0.25, 0.25, 0.25])
    assert len(_draws(logits, top_k=0, top_p=0.3)) == 2


def test_top_p_validation():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="top_p must be"):
        generate(params, prompt, cfg, 4, temperature=0.5, top_p=1.5,
                 key=jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="temperature > 0"):
        generate(params, prompt, cfg, 4, top_p=0.9)
    with pytest.raises(ValueError, match="temperature > 0"):
        GenerationServer(params, cfg, top_p=0.9)


def test_generate_and_serving_with_top_p():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab_size)
    out = np.asarray(generate(params, prompt, cfg, 8, max_len=16,
                              temperature=0.8, top_p=0.9,
                              key=jax.random.PRNGKey(2)))
    assert out.shape == (2, 8) and out.dtype == np.int32
    prompts = [np.asarray(prompt[0]), np.asarray(prompt[1, :4])]
    served = serve_batch(params, cfg, prompts, max_new_tokens=6, max_batch=2,
                         max_len=16, temperature=0.8, top_p=0.9, seed=3)
    assert all(len(o) == 6 for o in served)
