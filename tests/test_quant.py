"""Weight-only int8 quantization (ops/quant.py).

Reference context: the reference ships no quantization (or any ML code —
SURVEY §2); this is a perf capability of the TPU-first guest stack, so the
oracle is the framework's own fp/bf16 path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    decode,
    forward,
    fuse_decoder_params,
    init_params,
    prefill,
)
from kata_xpu_device_plugin_tpu.ops.quant import (
    QTensor,
    dequantize,
    params_hbm_bytes,
    quantize,
    quantize_decoder_params,
    weight_matmul,
)


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32) * 3.0
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (1, 32)
    err = np.abs(np.asarray(dequantize(qt) - w))
    # Round-to-nearest: error ≤ scale/2 per element (plus fp slack).
    bound = np.asarray(qt.scale)[0] / 2 + 1e-6
    assert (err <= bound[None, :]).all()


def test_quantize_zero_column_no_nan():
    w = jnp.zeros((16, 4), jnp.float32)
    qt = quantize(w)
    assert np.isfinite(np.asarray(qt.scale)).all()
    np.testing.assert_array_equal(np.asarray(dequantize(qt)), 0.0)


def test_weight_matmul_matches_dequantized():
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (2, 8, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (64, 48), jnp.float32)
    qt = quantize(w)
    out_q = weight_matmul(x, qt)
    out_deq = x @ dequantize(qt)
    # Same math up to (x@q)·s vs x@(q·s) association.
    np.testing.assert_allclose(
        np.asarray(out_q), np.asarray(out_deq), rtol=1e-5, atol=1e-5
    )
    # And close to the unquantized product: per-channel int8 keeps the
    # relative Frobenius error well under 1% (elementwise bounds are brittle
    # in the rounding tail, so bound the norm).
    ref = np.asarray(x @ w)
    rel = np.linalg.norm(np.asarray(out_q) - ref) / np.linalg.norm(ref)
    assert rel < 0.01, rel


def test_weight_matmul_plain_array_passthrough():
    x = jnp.ones((1, 4, 8), jnp.float32)
    w = jnp.ones((8, 16), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(weight_matmul(x, w)), np.asarray(x @ w)
    )


def test_stacked_layer_axis_quantizes_per_layer():
    # [L, in, out] stacked weights: scales must be per (layer, out) — one
    # layer's outliers must not coarsen another's resolution.
    w = jnp.stack(
        [jnp.ones((8, 4), jnp.float32), 100.0 * jnp.ones((8, 4), jnp.float32)]
    )
    qt = quantize(w)
    assert qt.scale.shape == (2, 1, 4)
    np.testing.assert_allclose(np.asarray(dequantize(qt)), np.asarray(w), rtol=1e-2)


@pytest.fixture(scope="module")
def quant_setup():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    qparams = quantize_decoder_params(fuse_decoder_params(params))
    return cfg, params, qparams


def test_quantize_decoder_params_layout(quant_setup):
    _, params, qparams = quant_setup
    layers = qparams["layers"]
    assert isinstance(layers["wqkv"], QTensor)
    assert isinstance(layers["w_gateup"], QTensor)
    assert isinstance(layers["w_down"], QTensor)
    assert isinstance(layers["wo"], QTensor)
    # Norms and the embedding stay full precision.
    assert not isinstance(layers["attn_norm"], QTensor)
    assert not isinstance(qparams["embed"], QTensor)
    # Idempotent.
    again = quantize_decoder_params(qparams)
    assert again["layers"]["wqkv"].q is layers["wqkv"].q
    # The byte accounting sees the int8 payloads.
    assert params_hbm_bytes(qparams) < params_hbm_bytes(
        fuse_decoder_params(params)
    )


def test_quantize_before_fuse_rejected(quant_setup):
    _, params, _ = quant_setup
    with pytest.raises(ValueError):
        fuse_decoder_params(quantize_decoder_params(params))


def test_quantized_forward_close(quant_setup):
    cfg, params, qparams = quant_setup
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, cfg.vocab_size)
    ref = np.asarray(forward(params, tokens, cfg))
    out = np.asarray(forward(qparams, tokens, cfg))
    assert out.shape == ref.shape
    # Per-channel int8 keeps tiny-model logits within a few percent of the
    # logit scale; the bound is loose but would catch any wiring bug (wrong
    # scale axis, scale applied twice, dropped scale) by orders of magnitude.
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() <= 0.05 * scale + 1e-3


def test_quantized_decode_runs_and_tracks_reference(quant_setup):
    cfg, params, qparams = quant_setup
    fparams = fuse_decoder_params(params)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 0, cfg.vocab_size)
    max_len = 16

    def gen(p):
        caches, last, pos = prefill(p, prompt, cfg, max_len)
        toks = decode(p, caches, last, int(pos), cfg, 8)
        return np.asarray(toks)

    out_ref = gen(fparams)
    out_q = gen(qparams)
    assert out_q.shape == out_ref.shape == (2, 8)
    assert out_q.dtype == np.int32
    # Greedy argmax under random weights is not bit-stable to quantization;
    # require broad agreement, not identity.
    agreement = (out_q == out_ref).mean()
    assert agreement >= 0.5, f"token agreement {agreement}"


def test_w8a8_optin_tracks_weight_only(monkeypatch, quant_setup):
    # KATA_TPU_W8A8=1: int8×int8 dots with per-vector activation scales.
    # Adds activation-quant error on top of weight-only — bounded, and the
    # full decode path still produces mostly the same greedy tokens.
    from kata_xpu_device_plugin_tpu.ops.quant import set_w8a8

    cfg, params, qparams = quant_setup
    x = jax.random.normal(jax.random.PRNGKey(11), (2, 4, cfg.d_model))
    w = qparams["layers"]["wqkv"][0]
    ref = np.asarray(weight_matmul(x, w))
    set_w8a8(True)  # explicit toggle: the env snapshot is import-time only
    try:
        out = np.asarray(weight_matmul(x, w))
        scale = np.abs(ref).max()
        assert np.abs(out - ref).max() <= 0.05 * scale + 1e-3

        # Batch 3 is a shape no earlier test traced: the decode scan is
        # jitted and the flag binds at TRACE time, so a cached executable
        # from a weight-only test would silently bypass the W8A8 path.
        prompt = jax.random.randint(jax.random.PRNGKey(12), (3, 8), 0,
                                    cfg.vocab_size)
        caches, last, pos = prefill(qparams, prompt, cfg, 16)
        toks = np.asarray(decode(qparams, caches, last, int(pos), cfg, 8))
        assert toks.shape == (3, 8) and toks.dtype == np.int32
    finally:
        set_w8a8(False)


def test_quantized_moe_experts_per_expert_scales():
    # MoE expert stacks quantize with per-expert per-output-channel scales
    # ([L, E, 1, f]); the router stays fp so routing decisions (and the
    # load-balancing aux) are untouched by quantization.
    from kata_xpu_device_plugin_tpu.models import mixtral_test_config

    cfg = mixtral_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(6), cfg, dtype=jnp.float32)
    qparams = quantize_decoder_params(params)
    layers = qparams["layers"]
    for k in ("moe_w_gate", "moe_w_in", "moe_w_out"):
        qt = layers[k]
        assert isinstance(qt, QTensor), k
        L, E = qt.q.shape[:2]
        assert qt.scale.shape == (L, E, 1, qt.q.shape[-1]), k
    assert not isinstance(layers["router"], QTensor)
    # ~2x byte shrink on the expert stacks (fp32 → int8 + fp32 scales).
    assert params_hbm_bytes(qparams) < 0.5 * params_hbm_bytes(params)

    # Op-level bound with FIXED routing: the router (fp, identical inputs)
    # picks the same experts either way, so the only delta is the expert
    # MLP's int8 error — bounded like the dense layers. (A full-model
    # forward bound would be meaningless here: upstream perturbation flips
    # top-k choices, a discontinuity no elementwise bound survives.)
    from kata_xpu_device_plugin_tpu.ops import moe_ffn

    mcfg = cfg.moe_cfg()
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 16, cfg.d_model))
    moe_keys = {"moe_w_gate": "w_gate", "moe_w_in": "w_in", "moe_w_out": "w_out"}
    fp = {"router": params["layers"]["router"][0],
          **{v: params["layers"][k][0] for k, v in moe_keys.items()}}
    qt = {"router": layers["router"][0],
          **{v: QTensor(layers[k].q[0], layers[k].scale[0])
             for k, v in moe_keys.items()}}
    ref, _ = moe_ffn(fp, x, mcfg)
    out, _ = moe_ffn(qt, x, mcfg)
    ref, out = np.asarray(ref), np.asarray(out)
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() <= 0.05 * scale + 1e-3


def test_quantized_mixtral_decode_runs_and_tracks_reference():
    # int8 Mixtral-style decode (VERDICT r3: "Mixtral has no quant story"):
    # the full prefill+decode path over quantized experts.
    from kata_xpu_device_plugin_tpu.models import mixtral_test_config

    cfg = mixtral_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(8), cfg, dtype=jnp.float32)
    qparams = quantize_decoder_params(params)
    prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 8), 0, cfg.vocab_size)

    def gen(p):
        caches, last, pos = prefill(p, prompt, cfg, 16)
        return np.asarray(decode(p, caches, last, int(pos), cfg, 8))

    out_ref, out_q = gen(params), gen(qparams)
    assert out_q.shape == out_ref.shape == (2, 8)
    agreement = (out_q == out_ref).mean()
    assert agreement >= 0.5, f"token agreement {agreement}"


def test_eval_quality_harness_runs_and_reports():
    """The quantization quality harness (scripts/eval_quality.py — the
    tool W8A8's docstring prescribes) runs the full ladder and emits one
    parseable JSON line per variant with the go/no-go fields."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "eval_quality.py"),
         "--cpu", "--batch", "2", "--seq-len", "32", "--decode-steps", "8"],
        capture_output=True, text=True, timeout=480, cwd=repo,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(l) for l in r.stdout.splitlines() if l.startswith("{")]
    variants = {l["variant"] for l in lines}
    assert variants == {"baseline", "int8", "w8a8", "int8_kv"}, variants
    by = {l["variant"]: l for l in lines}
    assert by["baseline"]["max_logit_drift"] == 0.0
    for v in ("int8", "w8a8"):
        assert 0.0 <= by[v]["top1_agree"] <= 1.0
        assert by[v]["max_logit_drift"] > 0.0  # quantization is not a no-op
    assert 0.0 <= by["int8_kv"]["kv_agree"] <= 1.0
